"""Shard router: one URL for an N-process store mesh.

Mesh-aware clients don't need this tier — ``RemoteStore`` learns the
shard map from ``/healthz`` and ships each sub-segment straight to its
shard's process.  The router exists for everything else: legacy
single-URL clients (vtctl, the mirror, curl), the merged ``/watch``
stream, and the audit/debug surfaces that must present the mesh as ONE
store.  It is deliberately stateless — every request is answered from
the shards' current state, so a router restart loses nothing and two
routers over one mesh agree by construction.

The merged ``/watch`` is the part with teeth.  Each shard's reply
carries the per-shard watermark ``next`` (the shared-line high-water
mark taken under that shard's lock — seqbus.py's completeness
invariant).  The router fans one poll to every shard in parallel and
computes ``W = min(next_i)``: every event with ``seq <= W`` has been
observed SOMEWHERE (its owner either returned it or returned a
watermark above it), so emitting the union of returned events at or
below W, sorted by seq, reproduces the single-process stream — events
above W are dropped, not buffered (the client's next poll re-reads them
from the shard logs; statelessness again).

Cross-shard ordering needs no new machinery: seqs come off one shared
line, the audit root is a modular sum of disjoint shard roots
(``vtaudit.merge_digest_payloads``), and ``vtctl audit`` against a
router walks the same three tiers it walks against one process.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, quote, urlparse

from volcano_tpu import timeseries, trace, vtaudit, vtfleet, vtprof
from volcano_tpu.locksan import make_lock
from volcano_tpu.store.partition import (
    shard_of, shard_of_key, split_segment, wal_shard,
)

#: slack added to a forwarded long-poll's socket timeout so the shard's
#: own deadline (the client's ``timeout`` param) always fires first
_POLL_SLACK = 10.0


def _merge_wal_stats(per: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "shards": len(per),
        "records": sum(p.get("records", 0) for p in per),
        "fsync_total": sum(p.get("fsync_total", 0) for p in per),
        "fsync_s": round(sum(p.get("fsync_s", 0.0) for p in per), 4),
        "replayed_records": sum(p.get("replayed_records", 0) for p in per),
        "torn_tails": sum(p.get("torn_tails", 0) for p in per),
        "per_shard": per,
    }


class ShardRouter:
    """Thin stateless HTTP tier over ``shard_map`` (leader URL per
    shard, mesh order).  ``supervisor`` (optional) serves
    ``/procmesh/shards`` with live member status; without one the
    route reports the static map."""

    def __init__(self, shard_map: List[str], supervisor=None,
                 host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 30.0):
        self.shard_map = [u.rstrip("/") for u in shard_map]
        self.nshards = len(self.shard_map)
        self.supervisor = supervisor
        self.timeout = timeout
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: D102 - quiet like StoreServer
                pass

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_raw(self, code: int, body: bytes,
                           ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> Dict[str, Any]:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                try:
                    router._handle(self, "get", router._get)
                except Exception as e:  # noqa: BLE001 - wire boundary
                    self._reply(500, {"error": repr(e)})

            def do_POST(self):
                try:
                    router._handle(self, "post", router._post)
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": repr(e)})

            def do_PUT(self):
                try:
                    router._handle(
                        self, "put",
                        lambda h: router._forward_object_write(h, "PUT"))
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": repr(e)})

            def do_PATCH(self):
                try:
                    router._handle(
                        self, "patch",
                        lambda h: router._forward_key_write(h, "PATCH"))
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": repr(e)})

            def do_DELETE(self):
                try:
                    router._handle(self, "delete", router._delete)
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": repr(e)})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ShardRouter":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- request tracing -----------------------------------------------------

    def _handle(self, h, verb: str, fn) -> None:
        """Continue a client's ``X-Volcano-Trace`` context around one
        routed request, exactly like the store server's ``_traced``:
        disarmed or uncontexted costs one attribute check, and the
        admin/forensics surfaces are never traced (reading the flight
        recorder must not write to it)."""
        if trace.TRACER is None:
            return fn(h)
        path = h.path
        if path.startswith("/chaos") or path.startswith("/debug/") \
                or path.startswith("/metrics") \
                or path.startswith("/procmesh"):
            return fn(h)
        header = h.headers.get(trace.HEADER, "")
        if not header:
            return fn(h)
        trace.set_component("router")
        with trace.request_context(
            header, f"router.{verb}", path=path.split("?", 1)[0],
        ):
            return fn(h)

    def _delete(self, h) -> None:
        u = urlparse(h.path)
        if u.path == "/chaos":
            return self._chaos_fan(h, "DELETE")
        return self._forward_key_write(h, "DELETE")

    # -- shard http ----------------------------------------------------------

    def _shard_req(self, shard: int, method: str, path: str,
                   payload: Optional[dict] = None,
                   timeout: Optional[float] = None
                   ) -> Tuple[int, Dict[str, Any]]:
        data = json.dumps(payload).encode() if payload is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        if trace.TRACER is not None:
            # forward the routed request's ambient context so the shard
            # process's store.* span parents under the router span — the
            # router -> shard leg of the fleet timeline
            tid, sid = trace.current()
            if tid:
                headers[trace.HEADER] = trace.format_header(tid, sid)
        req = urllib.request.Request(
            self.shard_map[shard] + path, data=data, method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout or self.timeout
            ) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001
                body = {"error": str(e)}
            return e.code, body

    def _fan(self, method: str, path: str, payload: Optional[dict] = None,
             timeout: Optional[float] = None
             ) -> List[Tuple[int, Dict[str, Any]]]:
        """One request to EVERY shard, in parallel (a serial fan would
        stack shard long-polls end to end).  Transport failures become
        599 entries — callers decide whether partial coverage is fatal."""
        out: List[Any] = [None] * self.nshards

        def one(i: int) -> None:
            try:
                out[i] = self._shard_req(i, method, path, payload, timeout)
            except Exception as e:  # noqa: BLE001
                out[i] = (599, {"error": repr(e)})

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(self.nshards)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    @staticmethod
    def _first_error(replies) -> Optional[Tuple[int, Dict[str, Any]]]:
        for code, body in replies:
            if code != 200:
                return code, body
        return None

    # -- GET routes ----------------------------------------------------------

    def _get(self, h) -> None:
        u = urlparse(h.path)
        q = parse_qs(u.query)
        parts = [p for p in u.path.split("/") if p]
        if u.path == "/healthz":
            return self._healthz(h)
        if u.path == "/watch":
            return self._watch(h, q)
        if u.path in ("/debug/trace", "/debug/prof", "/debug/timeseries",
                      "/debug/digest", "/metrics"):
            proc = (q.get("proc") or [None])[0]
            if proc is not None:
                # exact-match passthrough: one front URL reaches ANY
                # process in the mesh (leaders, followers via "N.rM",
                # the router's own process via "router")
                return self._proc_passthrough(h, u.path, q, proc)
        if u.path == "/debug/digest":
            return self._digest(h, q)
        if u.path in ("/debug/trace", "/debug/prof", "/debug/timeseries"):
            # fleet-merged forensics: every member's ring plus the
            # router's own, clock-aligned, with per-proc provenance
            return self._debug_fleet(h, u.path)
        if u.path == "/metrics":
            return self._metrics_fleet(h)
        if u.path == "/procmesh/shards":
            if self.supervisor is not None:
                return h._reply(200, self.supervisor.status())
            return h._reply(200, {
                "shards": self.nshards,
                "members": [
                    {"shard": i, "replica": 0, "role": "leader", "url": url}
                    for i, url in enumerate(self.shard_map)
                ],
            })
        if u.path == "/chaos":
            return self._chaos_fan(h, "GET")
        if u.path in ("/repl/status", "/repl/feed"):
            # the mesh replicates PER SHARD behind the supervisor; the
            # router is not a feed source — same reply as an
            # unreplicated server
            return h._reply(404, {"error": "replication not armed"})
        if len(parts) == 2 and parts[0] == "apis":
            replies = self._fan("GET", h.path)
            err = self._first_error(replies)
            if err is not None:
                return h._reply(*err)
            items: List[Any] = []
            for _, body in replies:
                items.extend(body.get("items") or [])
            # the watch-bootstrap floor: a follow-up watch from ``seq``
            # must cover everything newer than EVERY shard's list read,
            # so the merged stamp is the minimum (over-delivery side)
            seq = min(int(body.get("seq", 0)) for _, body in replies)
            return h._reply(200, {"items": items, "seq": seq})
        if len(parts) == 3 and parts[0] == "apis" and parts[2] == "obj":
            key = q.get("key", [""])[0]
            s = shard_of_key(key, self.nshards)
            code, body = self._shard_req(s, "GET", h.path)
            return h._reply(code, body)
        return h._reply(404, {"error": f"no route {u.path}"})

    def _healthz(self, h) -> None:
        replies = self._fan("GET", "/healthz")
        err = self._first_error(replies)
        if err is not None:
            return h._reply(*err)
        bodies = [b for _, b in replies]
        payload: Dict[str, Any] = {
            "ok": all(b.get("ok") for b in bodies),
            # shard 0 anchors the mesh lineage id; per-member uids are a
            # /procmesh/shards detail
            "uid": bodies[0].get("uid"),
            # the partitioned-bus contract: clients split segments N
            # ways exactly as against an in-process shards=N server
            "shards": self.nshards,
            "proc_shards": self.nshards,
            "shard_map": list(self.shard_map),
            "hwm": max(int(b.get("hwm", 0)) for b in bodies),
        }
        digests = [b.get("digest") for b in bodies]
        if all(d is not None for d in digests):
            root = 0
            per = []
            for d in digests:
                shard_entry = (d.get("shards") or [{}])[0]
                r = int(str(shard_entry.get("digest",
                                            d.get("root", "0"))), 16)
                root = (root + r) & vtaudit._MASK
                per.append({"digest": vtaudit.hexd(r),
                            "seq": int(shard_entry.get("seq", 0))})
            payload["digest"] = {
                "root": vtaudit.hexd(root),
                "seq": max(int(b.get("digest", {}).get("seq", 0))
                           for b in bodies),
                "shards": per,
            }
        wals = [b.get("wal") for b in bodies]
        if all(w is not None for w in wals):
            payload["wal"] = _merge_wal_stats(wals)
        return h._reply(200, payload)

    def _watch(self, h, q) -> None:
        shard_q = q.get("shard", [None])[0]
        timeout = float(q.get("timeout", ["0"])[0])
        if shard_q is not None:
            # per-shard fan-out consumer: verbatim passthrough (a
            # shards=1 server serves its untagged entries to any
            # shard-scoped watcher)
            code, body = self._shard_req(
                int(shard_q) % self.nshards, "GET", h.path,
                timeout=timeout + _POLL_SLACK,
            )
            return h._reply(code, body)
        replies = self._fan("GET", h.path, timeout=timeout + _POLL_SLACK)
        err = self._first_error(replies)
        if err is not None:
            return h._reply(*err)
        bodies = [b for _, b in replies]
        # W = min per-shard watermark: complete at or below W by the
        # seqbus invariant — each shard's ``next`` was read under its
        # own lock, so a seq <= next_i owned by shard i was in its reply
        w = min(int(b.get("next", 0)) for b in bodies)
        epochs = [b["epoch"] for b in bodies if "epoch" in b]
        if any(b.get("relist") for b in bodies):
            payload: Dict[str, Any] = {
                "events": None, "next": w, "relist": True}
        else:
            evs = [e for b in bodies for e in b["events"]
                   if int(e.get("seq", 0)) <= w]
            evs.sort(key=lambda e: int(e.get("seq", 0)))
            payload = {"events": evs, "next": w}
        if epochs:
            # per-shard serving epochs collapse to their sum: ANY shard
            # failover/resync moves the merged epoch, and the client's
            # fence (epoch changed -> relist) fires exactly then
            payload["epoch"] = sum(int(e) for e in epochs)
        return h._reply(200, payload)

    def _digest(self, h, q) -> None:
        rec = (q.get("recompute") or [None])[0] not in (None, "", "0")
        fwd = "/debug/digest" + ("?recompute=1" if rec else "")
        kind = (q.get("kind") or [None])[0]
        if kind is not None:
            ns = (q.get("namespace") or [""])[0]
            s = shard_of(ns, self.nshards)
            sep = "&" if rec else "?"
            code, body = self._shard_req(
                s, "GET",
                f"{fwd}{sep}kind={quote(kind, safe='')}"
                f"&namespace={quote(ns, safe='')}")
            return h._reply(code, body)
        sh = (q.get("shard") or [None])[0]
        if (q.get("detail") or [None])[0] == "buckets" or sh is not None:
            sep = "&" if rec else "?"
            if sh is not None:
                # one shard's whole table IS that shard's bucket slice —
                # the shard param must NOT forward (a shards=1 server
                # would filter on shard_of(ns, 1) == sh: empty for sh>0)
                code, body = self._shard_req(
                    int(sh) % self.nshards, "GET", f"{fwd}{sep}detail=buckets")
                return h._reply(code, body)
            replies = self._fan("GET", f"{fwd}{sep}detail=buckets")
            err = self._first_error(replies)
            if err is not None:
                return h._reply(*err)
            buckets: Dict[str, str] = {}
            for _, body in replies:
                # namespace->shard is a partition: bucket keys are
                # disjoint across shards, the union is the mesh table
                buckets.update(body.get("buckets") or {})
            return h._reply(200, {
                "seq": max(int(b.get("seq", 0)) for _, b in replies),
                "recompute": rec,
                "buckets": buckets,
            })
        replies = self._fan("GET", fwd)
        err = self._first_error(replies)
        if err is not None:
            return h._reply(*err)
        bodies = [b for _, b in replies]
        out: Dict[str, Any] = {
            "enabled": all(b.get("enabled") for b in bodies),
            "seq": max(int(b.get("seq", 0)) for b in bodies),
            "recompute": rec,
            # per-shard LOCAL seqs: the mesh skew surface (each shards=1
            # member reports one-element shard_seq == its seq)
            "shard_seq": [int(b.get("seq", 0)) for b in bodies],
        }
        if all(b.get("root") is not None for b in bodies):
            out.update(vtaudit.merge_digest_payloads(bodies))
        return h._reply(200, out)

    # -- fleet observability surfaces ----------------------------------------

    _LOCAL_PROCS = ("router", "self")

    def _local_debug(self, path: str) -> Dict[str, Any]:
        """The router's OWN process view of one debug surface."""
        if path == "/debug/trace":
            return trace.debug_payload()
        if path == "/debug/timeseries":
            return timeseries.debug_payload()
        if path == "/debug/prof":
            return vtprof.debug_payload()
        return vtaudit.debug_payload()

    def _proc_url(self, proc: str) -> str:
        """Resolve a ``proc=`` selector (``N`` leader / ``N.rM``
        follower) to a member URL; raises ``KeyError`` for an unknown
        member."""
        stem, _, rep = proc.partition(".r")
        shard = int(stem)
        replica = int(rep) if rep else 0
        if self.supervisor is not None:
            for m in self.supervisor.status()["members"]:
                if m["shard"] == shard and m["replica"] == replica:
                    return m["url"]
            raise KeyError(proc)
        if replica == 0 and 0 <= shard < self.nshards:
            return self.shard_map[shard]
        raise KeyError(proc)

    def _proc_passthrough(self, h, path: str, q, proc: str) -> None:
        rest = "&".join(f"{k}={quote(v, safe='')}"
                        for k, vs in sorted(q.items()) if k != "proc"
                        for v in vs)
        if proc in self._LOCAL_PROCS:
            from volcano_tpu.scheduler import metrics as _metrics

            if path == "/metrics":
                return h._reply_raw(200, _metrics.expose_text().encode(),
                                    "text/plain; version=0.0.4")
            return h._reply(200, self._local_debug(path))
        try:
            url = self._proc_url(proc)
        except (KeyError, ValueError):
            return h._reply(404, {"error": f"no proc {proc}"})
        fwd = path + (f"?{rest}" if rest else "")
        try:
            with urllib.request.urlopen(
                url + fwd, timeout=self.timeout
            ) as resp:
                return h._reply_raw(
                    resp.status, resp.read(),
                    resp.headers.get("Content-Type", "application/json"))
        except urllib.error.HTTPError as e:
            return h._reply_raw(
                e.code, e.read() or b"{}",
                e.headers.get("Content-Type", "application/json"))

    def _fleet_snapshot(self) -> Dict[str, Any]:
        """One harvest round over the mesh: every member's surfaces in
        parallel plus the router's own process, vtfleet-shaped.  A dead
        member degrades to an ``unreachable`` entry."""
        mesh: Optional[Dict[str, Any]] = None
        if self.supervisor is not None:
            mesh = self.supervisor.status()
            targets = [
                (vtfleet.member_name(m["shard"], m["replica"]), m["url"])
                for m in mesh["members"]
            ]
        else:
            targets = [(vtfleet.member_name(i), url)
                       for i, url in enumerate(self.shard_map)]
        procs: Dict[str, Any] = {}
        unreachable: List[str] = []
        mu = make_lock("ShardRouter.fleet_harvest")

        def one(name: str, url: str) -> None:
            try:
                snap = vtfleet.harvest_proc(name, url, timeout=self.timeout)
            except Exception:  # noqa: BLE001 - partial harvest reports
                with mu:
                    unreachable.append(name)
                return
            with mu:
                procs[name] = snap

        threads = [threading.Thread(target=one, args=t, daemon=True)
                   for t in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        procs["router"] = vtfleet.local_proc("router")
        return {"procs": procs, "unreachable": sorted(unreachable),
                "mesh": mesh}

    def _debug_fleet(self, h, path: str) -> None:
        snap = self._fleet_snapshot()
        if path == "/debug/trace":
            return h._reply(200, vtfleet.merge_trace(snap))
        if path == "/debug/timeseries":
            return h._reply(200, vtfleet.merge_timeseries(snap))
        return h._reply(200, vtfleet.merge_prof(snap))

    def _metrics_fleet(self, h) -> None:
        """Federated ``/metrics``: each member's exposition under its
        ``proc=`` label plus the router's own, histogram families
        rolled up bucket-wise under ``proc="fleet"``."""
        from volcano_tpu.scheduler import metrics as _metrics

        texts: Dict[str, Optional[str]] = {}
        mu = make_lock("ShardRouter.metrics_fan")
        if self.supervisor is not None:
            targets = [
                (vtfleet.member_name(m["shard"], m["replica"]), m["url"])
                for m in self.supervisor.status()["members"]
            ]
        else:
            targets = [(vtfleet.member_name(i), url)
                       for i, url in enumerate(self.shard_map)]

        def one(name: str, url: str) -> None:
            try:
                with urllib.request.urlopen(
                    url + "/metrics", timeout=self.timeout
                ) as resp:
                    body = resp.read().decode("utf-8", "replace")
            except Exception:  # noqa: BLE001 - dead member: skip series
                return
            with mu:
                texts[name] = body

        threads = [threading.Thread(target=one, args=t, daemon=True)
                   for t in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        texts["router"] = _metrics.expose_text()
        body = vtfleet.merge_metrics(texts).encode()
        return h._reply_raw(200, body, "text/plain; version=0.0.4")

    # -- mutation routes ------------------------------------------------------

    def _post(self, h) -> None:
        u = urlparse(h.path)
        parts = [p for p in u.path.split("/") if p]
        if u.path == "/chaos":
            return self._chaos_fan(h, "POST", h._body())
        if u.path == "/bulk":
            return self._bulk(h, h._body())
        if len(parts) == 2 and parts[0] == "apis":
            return self._forward_object_write(h, "POST")
        return h._reply(404, {"error": "no route"})

    def _forward_object_write(self, h, method: str) -> None:
        """POST/PUT ``/apis/{kind}``: route by the object's namespace —
        the same hash that placed every other record of that namespace
        on its shard."""
        body = h._body()
        enc = body.get("object") or {}
        meta = enc.get("meta") or {}
        s = shard_of(str(meta.get("namespace") or ""), self.nshards)
        code, reply = self._shard_req(s, method, h.path, body)
        return h._reply(code, reply)

    def _forward_key_write(self, h, method: str) -> None:
        u = urlparse(h.path)
        q = parse_qs(u.query)
        key = q.get("key", [""])[0]
        s = shard_of_key(key, self.nshards)
        body = h._body() if method == "PATCH" else None
        code, reply = self._shard_req(s, method, h.path, body)
        return h._reply(code, reply)

    def _chaos_fan(self, h, method: str, body: Optional[dict] = None) -> None:
        """Chaos admin fans to every shard (one plan arms the whole
        mesh); the reply carries each shard's status."""
        replies = self._fan(method, "/chaos", body)
        err = self._first_error(replies)
        if err is not None:
            return h._reply(*err)
        return h._reply(200, {
            "armed": any(b.get("armed") for _, b in replies),
            "shards": [b for _, b in replies],
        })

    # -- /bulk: split, forward, reassemble ------------------------------------

    def _bulk(self, h, body: Dict[str, Any]) -> None:
        """Group a legacy client's mixed op list into per-shard
        sub-bulks (per-shard ORDER preserved — that is the WAL/replay
        order contract), forward them in parallel, and reassemble the
        per-op results in the original order.  Ops that themselves span
        shards (untagged segments, columnar patch runs over mixed
        namespaces) split into per-shard sub-ops with their row/key
        results remapped back."""
        ops = body.get("ops") or []
        n = self.nshards
        shard_ops: Dict[int, List[dict]] = {}
        slots: List[Tuple[str, Any]] = []

        def push(s: int, op: dict) -> int:
            lst = shard_ops.setdefault(s, [])
            lst.append(op)
            return len(lst) - 1

        for op in ops:
            verb = op.get("op")
            if verb == "segment" and "shard" not in op:
                parts = self._split_segment_op(op)
                slots.append(("seg", [
                    (s, push(s, sub), brows, erows)
                    for s, sub, brows, erows in parts
                ]))
            elif verb == "patch_col":
                keys = op.get("keys") or []
                by_shard: Dict[int, List[int]] = {}
                for j, key in enumerate(keys):
                    by_shard.setdefault(shard_of_key(key, n), []).append(j)
                if len(by_shard) <= 1:
                    s = next(iter(by_shard), 0)
                    slots.append(("one", (s, push(s, op))))
                else:
                    placed = []
                    for s, rows in sorted(by_shard.items()):
                        sub: Dict[str, Any] = {
                            "op": "patch_col", "kind": op["kind"],
                            "keys": [keys[j] for j in rows],
                        }
                        if op.get("columns"):
                            sub["columns"] = {
                                f: [col[j] for j in rows]
                                for f, col in op["columns"].items()
                            }
                        if op.get("const"):
                            sub["const"] = op["const"]
                        if "when" in op:
                            sub["when"] = op["when"]
                        placed.append((s, push(s, sub), rows))
                    slots.append(("pcol", (placed, len(keys))))
            else:
                s = wal_shard(op, n)
                slots.append(("one", (s, push(s, op))))
        fan_out: Dict[int, List[Any]] = {}
        errors: List[Tuple[int, Dict[str, Any]]] = []
        lock = make_lock("ShardRouter.bulk_fan")

        def ship(s: int) -> None:
            code, reply = self._shard_req(
                s, "POST", "/bulk", {"ops": shard_ops[s]})
            with lock:
                if code != 200:
                    errors.append((code, reply))
                else:
                    fan_out[s] = reply.get("results") or []

        threads = [threading.Thread(target=ship, args=(s,), daemon=True)
                   for s in shard_ops]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            return h._reply(*errors[0])
        results: List[Any] = []
        for tag, info in slots:
            if tag == "one":
                s, idx = info
                results.append(fan_out[s][idx])
            elif tag == "pcol":
                placed, nkeys = info
                out: List[Any] = [None] * nkeys
                for s, idx, rows in placed:
                    r = fan_out[s][idx]
                    vals = r if isinstance(r, list) else [r] * len(rows)
                    for j, v in zip(rows, vals):
                        out[j] = v
                results.append(out)
            else:  # seg
                merged: Dict[str, List[Any]] = {"binds": [], "evicts": []}
                op_err: Optional[str] = None
                for s, idx, brows, erows in info:
                    r = fan_out[s][idx]
                    if not isinstance(r, dict):
                        op_err = str(r) if r else "segment op dropped"
                        continue
                    for row, err in r.get("binds") or []:
                        merged["binds"].append([brows[int(row)], err])
                    for row, err in r.get("evicts") or []:
                        merged["evicts"].append([erows[int(row)], err])
                if op_err is not None:
                    results.append(op_err)
                else:
                    merged["binds"].sort(key=lambda t: t[0])
                    merged["evicts"].sort(key=lambda t: t[0])
                    results.append(merged)
        return h._reply(200, {"results": results})

    def _split_segment_op(self, op: Dict[str, Any]):
        """An UNTAGGED segment (a pre-split client that believes the
        store is one shard) re-splits here by namespace hash — the same
        ``split_segment`` the mesh-aware applier runs client-side.  Row
        maps (sub-row -> original row) come from the split's order
        guarantee: relative order within a shard is preserved."""
        from volcano_tpu.store.segment import DecisionSegment

        seg = DecisionSegment.from_wire(op)
        subs = split_segment(seg, self.nshards)
        bind_rows: Dict[int, List[int]] = {}
        for j, key in enumerate(seg.bind_keys):
            bind_rows.setdefault(
                shard_of_key(key, self.nshards), []).append(j)
        evict_rows: Dict[int, List[int]] = {}
        for j, key in enumerate(seg.evict_keys):
            evict_rows.setdefault(
                shard_of_key(key, self.nshards), []).append(j)
        out = []
        for s, sub in subs:
            wire = sub.to_wire()
            wire["shard"] = s
            out.append((s, wire, bind_rows.get(s, []), evict_rows.get(s, [])))
        return out
