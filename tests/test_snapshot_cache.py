"""Cross-cycle incremental snapshot (SURVEY §7 hard part (e)).

The SnapshotCache keeps the O(classes x nodes) static-predicate sweep, the
node-static arrays, and the host->device uploads out of steady-state cycles:
while the node epoch (names + resource_versions) is unchanged, rebuilt
snapshots reuse the same numpy objects, and `to_device` skips the upload by
object identity. Node mutations (labels, taints, capacity) roll the epoch
and invalidate everything.
"""

import numpy as np
import pytest

from volcano_tpu.api.job import Job, JobSpec, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobPhase
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.framework import open_session
from volcano_tpu.scheduler.snapshot import SnapshotCache, build_tensor_snapshot
from volcano_tpu.sim import Cluster


def mk_job(name, replicas, req, selector=None):
    tmpl = PodSpec(image="busybox",
                   resources=Resource.from_resource_list(req))
    if selector:
        tmpl.node_selector = dict(selector)
    return Job(
        meta=Metadata(name=name, namespace="test"),
        spec=JobSpec(
            min_available=replicas,
            tasks=[TaskSpec(name="main", replicas=replicas, template=tmpl)],
            queue="default",
        ),
    )


@pytest.fixture
def cluster():
    c = Cluster(scheduler_conf=full_conf("tpu"))
    c.add_queue("default", weight=1)
    for i in range(4):
        c.add_node(
            f"n{i}", {"cpu": "8", "memory": "16Gi", "pods": 110},
            labels={"zone": f"z{i % 2}"},
        )
    return c


def _session(cluster):
    return open_session(cluster.scheduler.cache, cluster.scheduler.conf.tiers)


def test_class_rows_and_node_static_reused_across_cycles(cluster):
    cache = SnapshotCache()
    cluster.store.create("Job", mk_job("a", 2, {"cpu": "1", "memory": "1Gi"},
                                       selector={"zone": "z0"}))
    cluster.run_until_idle()
    # keep a pending job so classes are non-empty in both builds
    cluster.store.create("Job", mk_job("b", 2, {"cpu": "1", "memory": "1Gi"},
                                       selector={"zone": "z0"}))
    for _ in range(6):
        cluster.pump_controller()
        cluster.scheduler.run_once()
        cluster.kubelet_step()

    s1 = build_tensor_snapshot(_session(cluster), cache=cache)
    s2 = build_tensor_snapshot(_session(cluster), cache=cache)
    # identical pending set across the two builds -> assembled arrays must
    # be the same objects (the cache's whole point); assert, don't branch
    assert tuple(np.nonzero(s1.task_valid)[0]) == tuple(np.nonzero(s2.task_valid)[0])
    assert s2.class_node_mask is s1.class_node_mask
    assert s2.class_node_score is s1.class_node_score
    assert s2.node_alloc is s1.node_alloc
    assert s2.node_max_tasks is s1.node_max_tasks


def test_node_mutation_rolls_epoch(cluster):
    cache = SnapshotCache()
    cluster.store.create("Job", mk_job("a", 1, {"cpu": "1", "memory": "1Gi"},
                                       selector={"zone": "z0"}))
    for _ in range(6):
        cluster.pump_controller()
    s1 = build_tensor_snapshot(_session(cluster), cache=cache)

    node = cluster.store.get("Node", "/n1")
    node.labels["zone"] = "z0"
    cluster.store.update("Node", node)

    s2 = build_tensor_snapshot(_session(cluster), cache=cache)
    assert s2.class_node_mask is not s1.class_node_mask
    # n1 (row 1) now matches the z0 selector in the fresh build
    if s2.class_node_mask.shape[0] >= 1 and len(np.nonzero(s2.task_valid)[0]):
        c = int(s2.task_class[np.nonzero(s2.task_valid)[0][0]])
        assert bool(s2.class_node_mask[c, 1])
        assert not bool(s1.class_node_mask[c, 1])


def test_to_device_memoizes_by_identity(cluster):
    cache = SnapshotCache()
    arr = np.arange(16, dtype=np.float32)
    d1 = cache.to_device(arr)
    d2 = cache.to_device(arr)
    assert d1 is d2
    d3 = cache.to_device(arr.copy())
    assert d3 is not d1


def test_scheduler_with_cache_matches_behavior(cluster):
    """End-to-end: the tpu-backend scheduler with its persistent cache
    schedules a selector-constrained gang correctly across cycles."""
    assert cluster.scheduler.snapshot_cache is not None
    cluster.store.create("Job", mk_job("g1", 3, {"cpu": "1", "memory": "1Gi"},
                                       selector={"zone": "z0"}))
    cluster.run_until_idle()
    job = cluster.store.get("Job", "test/g1")
    assert job.status.state.phase == JobPhase.RUNNING
    pods = cluster.store.list("Pod")
    assert len(pods) == 3
    assert all(p.node_name in ("n0", "n2") for p in pods)  # the z0 nodes

    # second wave reuses cached class rows (epoch unchanged)
    cluster.store.create("Job", mk_job("g2", 2, {"cpu": "1", "memory": "1Gi"},
                                       selector={"zone": "z1"}))
    cluster.run_until_idle()
    job2 = cluster.store.get("Job", "test/g2")
    assert job2.status.state.phase == JobPhase.RUNNING
    pods2 = [p for p in cluster.store.list("Pod") if "g2" in p.meta.name]
    assert pods2 and all(p.node_name in ("n1", "n3") for p in pods2)
