"""The continuous perf-regression gate (bench.py --check / --history).

Tier-1 proves the MACHINERY sub-second — trajectory collation across
every BENCH_r0*.json format, same-device band derivation, the
pass/doctored-fail verdict with its per-phase attribution diff, and the
--check exit-code wiring — with the real capture stubbed.  The real
capture runs under `make perfgate` (`python bench.py --check`).
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def _driver_doc(tail_payloads, **extra):
    """The r01–r07 driver capture shape: JSON lines inside ``tail``."""
    return {
        "n": 1, "cmd": "python bench.py", "rc": 0,
        "tail": "noise line\n" + "\n".join(
            json.dumps(p) for p in tail_payloads),
        **extra,
    }


CFG5 = "e2e_schedule_cycle_100k_tasks_10k_nodes"


def _payload(metric=CFG5, value=1.0, device="TFRT_CPU_0", phases=None,
             **extra):
    return {"metric": metric, "value": value, "unit": "s",
            "vs_baseline": 60.0 / value,
            "extra": {"device": device,
                      **({"phases_s": phases} if phases else {}), **extra}}


# -- trajectory collation -----------------------------------------------------


def test_history_collates_every_bench_format_and_is_idempotent(tmp_path):
    # r01: driver form, metric only in the tail; a later tail line for
    # the same metric wins (the driver transcript repeats sweeps)
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_driver_doc([
        _payload(value=2.0), _payload(value=1.5),
    ])))
    # r02: driver form with a parsed payload AND a tail line
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_driver_doc(
        [_payload(value=1.2, phases={"solve": 0.6, "publish": 0.3})],
        parsed=_payload(metric="cfg7_x", value=9.0),
    )))
    # r03: the r08 bare-payload form
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        _payload(metric="cfg8_open_loop_first_seen_to_bind", value=0.02,
                 p99_ms=30.0)))
    # a non-bench json must be ignored
    (tmp_path / "OTHER.json").write_text("{}")

    rounds = bench.load_bench_rounds(str(tmp_path))
    assert [n for n, _ in rounds] == [1, 2, 3]
    assert rounds[0][1][CFG5]["value"] == 1.5  # last tail line wins
    assert set(rounds[1][1]) == {CFG5, "cfg7_x"}

    baseline = tmp_path / "BASELINE.md"
    baseline.write_text("# BASELINE\n\nprose stays.\n")
    bench.cmd_history(directory=str(tmp_path),
                      baseline_md=str(baseline))
    traj = json.load(open(tmp_path / "BENCH_TRAJECTORY.json"))
    assert [r["round"] for r in traj["rounds"]] == [1, 2, 3]
    assert traj["rounds"][1]["metrics"][CFG5]["phases_s"]["solve"] == 0.6
    text = baseline.read_text()
    assert "prose stays." in text
    assert text.count("## Bench trajectory") == 1
    assert "| `cfg7_x` |" in text
    # idempotent: a second run REPLACES the generated section in place
    bench.cmd_history(directory=str(tmp_path), baseline_md=str(baseline))
    assert baseline.read_text().count("## Bench trajectory") == 1


# -- band derivation ----------------------------------------------------------


def test_derive_bands_same_device_class_only():
    traj = bench.build_trajectory([
        (5, {CFG5: _payload(value=0.66, device="TPU v5e",
                            phases={"solve": 0.25})}),
        (6, {CFG5: _payload(value=2.4, device="TFRT_CPU_0",
                            phases={"solve": 1.8})}),
    ])
    cpu = bench.derive_bands(traj, "TFRT_CPU_0")
    tpu = bench.derive_bands(traj, "TPU v5e lite")
    assert cpu[CFG5]["source_round"] == 6
    assert cpu[CFG5]["max_s"] == pytest.approx(2.4 * bench.VALUE_SLACK)
    assert cpu[CFG5]["phases_max_s"]["solve"] == pytest.approx(
        1.8 * bench.PHASE_SLACK + bench.PHASE_FLOOR_S)
    assert tpu[CFG5]["source_round"] == 5
    # no same-device history -> no band for that metric
    assert bench.derive_bands(bench.build_trajectory([]), "TFRT_CPU_0") == {}
    # a device-less reading matches NO class (it must not slip into the
    # accelerator pool just because '' contains no 'cpu')
    traj_nodev = bench.build_trajectory([
        (7, {CFG5: _payload(value=0.1, device=None)}),
    ])
    assert bench.derive_bands(traj_nodev, "TPU v5e") == {}
    assert bench.derive_bands(traj_nodev, "TFRT_CPU_0") == {}


# -- the verdict --------------------------------------------------------------


def _bands():
    return {CFG5: {"max_s": 2.0, "phases_max_s": {"solve": 1.0,
                                                  "publish": 0.5}}}


def test_check_results_passes_inside_bands():
    ok, lines = bench.check_results(
        [_payload(value=1.5, phases={"solve": 0.8, "publish": 0.3})],
        _bands())
    assert ok
    assert any(line.startswith("ok   " + CFG5) for line in lines)


def test_check_results_fails_with_per_phase_attribution_diff():
    ok, lines = bench.check_results(
        [_payload(value=1.5, phases={"solve": 1.4, "publish": 0.05})],
        _bands())
    assert not ok
    joined = "\n".join(lines)
    assert f"FAIL {CFG5}" in joined
    assert "phase solve" in joined and "BREACH" in joined
    assert "phase publish" in joined  # the full diff prints, not just hits
    # value breach alone also fails
    ok2, lines2 = bench.check_results([_payload(value=9.9)], _bands())
    assert not ok2 and "value 9.9000s > band 2.0000s" in "\n".join(lines2)
    # a crashed capture is a gate failure, not a silent pass
    ok3, lines3 = bench.check_results(
        [{"metric": "config5", "value": None, "error": "boom"}], _bands())
    assert not ok3 and "no result captured" in "\n".join(lines3)
    # no bands at all must fail loudly (a vacuous gate is worse than none)
    ok4, lines4 = bench.check_results([], {})
    assert not ok4 and "no bands resolved" in "\n".join(lines4)


# -- --check wiring (capture stubbed: the sub-second tier-1 smoke) ------------


def test_cmd_check_smoke_exit_codes_with_stubbed_capture(tmp_path,
                                                         monkeypatch):
    def fake_smoke():
        bench._print_json(_payload(
            metric="perfgate_smoke_small_cycle", value=0.4,
            phases={"solve": 0.2, "publish": 0.1}))

    monkeypatch.setattr(bench, "config_smoke", fake_smoke)
    assert bench.cmd_check(smoke=True) == 0
    # a doctored band file must flip the verdict (nonzero exit)
    doctored = tmp_path / "bands.json"
    doctored.write_text(json.dumps({
        "perfgate_smoke_small_cycle": {
            "max_s": 1e-6, "phases_max_s": {"solve": 1e-6}},
    }))
    assert bench.cmd_check(smoke=True, bands_path=str(doctored)) == 1


def test_cmd_check_skips_configs_without_same_device_band(tmp_path,
                                                          monkeypatch,
                                                          capsys):
    # trajectory knows cfg5 only on another device class -> the gate
    # must skip it (no wasted capture) and fail for want of bands
    (tmp_path / bench.TRAJECTORY_FILE).write_text(json.dumps(
        bench.build_trajectory([
            (5, {CFG5: _payload(value=0.66, device="TPU v5e")}),
        ])))
    monkeypatch.setattr(
        bench, "config5",
        lambda **kw: (_ for _ in ()).throw(AssertionError("ran anyway")))
    import jax

    if "cpu" not in str(jax.devices()[0]).lower():
        pytest.skip("needs a CPU device to mismatch the TPU-only history")
    rc = bench.cmd_check(configs=(5,), directory=str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 1
    assert "skipping config(s) [5]" in out
    assert "no bands resolved" in out


def test_cmd_check_bands_file_gates_only_requested_configs(tmp_path,
                                                           monkeypatch,
                                                           capsys):
    """Review hardening: an explicit --bands file carrying cfg7/cfg8
    bands must not fail a cfg5-only run as 'missing', and a config the
    file has no band for is skipped, not captured pointlessly."""
    bands = tmp_path / "bands.json"
    bands.write_text(json.dumps({
        CFG5: {"max_s": 2.0},
        "e2e_http_schedule_cycle_100k_tasks_10k_nodes": {"max_s": 3.0},
        "cfg8_open_loop_first_seen_to_bind": {"max_s": 0.1},
    }))
    monkeypatch.setattr(
        bench, "config5",
        lambda **kw: bench._print_json(_payload(value=1.0)))
    monkeypatch.setattr(
        bench, "config7",
        lambda: (_ for _ in ()).throw(AssertionError("cfg7 ran anyway")))
    assert bench.cmd_check(configs=(5,), bands_path=str(bands)) == 0
    out = capsys.readouterr().out
    assert f"ok   {CFG5}" in out
    assert "no result captured" not in out
    # a config with no band in the file is skipped loudly
    bands2 = tmp_path / "bands2.json"
    bands2.write_text(json.dumps({CFG5: {"max_s": 2.0}}))
    assert bench.cmd_check(configs=(5, 7), bands_path=str(bands2)) == 0
    assert "skipping config(s) [7]" in capsys.readouterr().out


def test_cmd_check_surfaces_capture_exception_in_verdict(tmp_path,
                                                         monkeypatch,
                                                         capsys):
    """Review hardening: a crashed capture records its error under the
    GATED metric name, so the FAIL line carries the real exception."""
    bands = tmp_path / "bands.json"
    bands.write_text(json.dumps({CFG5: {"max_s": 2.0}}))
    monkeypatch.setattr(
        bench, "config5",
        lambda **kw: (_ for _ in ()).throw(RuntimeError("kaboom")))
    assert bench.cmd_check(configs=(5,), bands_path=str(bands)) == 1
    out = capsys.readouterr().out
    assert f"FAIL {CFG5}" in out and "kaboom" in out
