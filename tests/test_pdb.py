"""PodDisruptionBudget shadow gangs (reference setPDB,
KB/pkg/scheduler/cache/event_handlers.go:494-510): plain controller-owned
pods grouped into one shadow job whose MinAvailable comes from the budget.
"""

from volcano_tpu.api.objects import Metadata
from volcano_tpu.api.resource import Resource

def test_pdb_gangs_plain_pods():
    """A PodDisruptionBudget groups its controller's plain pods into one
    shadow job with MinAvailable from the budget (reference setPDB,
    KB cache/event_handlers.go:494-510): when the gang can't fully fit,
    nothing binds; without the budget, whatever fits binds."""
    from volcano_tpu.api.objects import Pod, PodDisruptionBudget, PodSpec as PS
    from volcano_tpu.sim import Cluster

    def run(with_pdb):
        c = Cluster(with_controller=False)
        c.add_queue("default", weight=1)
        c.add_node("n0", {"cpu": "2", "memory": "4Gi", "pods": 110})
        if with_pdb:
            c.store.create(
                "PodDisruptionBudget",
                PodDisruptionBudget(
                    meta=Metadata(name="budget", namespace="d",
                                  owner=("ReplicaSet", "rs-a")),
                    min_available=3,
                ),
            )
        for i in range(3):  # 3 x 1cpu pods, only 2 cpu available
            c.store.create(
                "Pod",
                Pod(meta=Metadata(name=f"p{i}", namespace="d",
                                  owner=("ReplicaSet", "rs-a")),
                    spec=PS(resources=Resource.from_resource_list(
                        {"cpu": "1", "memory": "1Gi"}))),
            )
        c.scheduler.run_once()
        return [p for p in c.store.list("Pod") if p.node_name]

    assert len(run(with_pdb=False)) == 2   # plain pods bind individually
    assert len(run(with_pdb=True)) == 0    # gang of 3 can't fit -> nothing
