"""Partitioned store bus (store/partition.py + StoreServer shards=N).

The gate for ROADMAP item 1's store half:

  * the shard hash is stable and the segment split is a partition
    (row union preserved, within-shard order preserved, node tables
    re-interned per shard);
  * a partitioned server fed the SAME sub-segment sequence as a
    single-shard server produces a BYTE-IDENTICAL merged watch stream
    (frozen uid/clock — the PR-6 proof pattern), and each
    ``/watch?shard=i`` slice is exactly the merged stream filtered to
    that shard's namespaces;
  * the async applier splits a cycle's segment by namespace shard,
    ships the sub-segments concurrently, and the store converges to
    the unsplit outcome with per-shard drain attribution;
  * the PR-7 zero-acked-loss gate holds on the partitioned WAL: kill a
    ``shards=4`` server with acked sub-segments in four WAL files,
    reboot, and every ACKed mutation is back bit-for-bit (the merged
    per-shard replay); a WAL-off boot absorbs a partitioned life's
    leftover tails.
"""

import json
import time

import pytest

from volcano_tpu.api import objects as api_objects
from volcano_tpu.api.objects import Metadata, Queue
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.store.client import RemoteStore
from volcano_tpu.store.partition import (
    ShardedWAL,
    leftover_shard_dirs,
    shard_of,
    shard_of_key,
    split_segment,
    wal_shard,
)
from volcano_tpu.store.segment import DecisionSegment
from volcano_tpu.store.server import StoreServer

from tests.helpers import build_pod

NSHARDS = 4

#: namespaces spread across every shard (asserted below)
_NAMESPACES = [f"team{i}" for i in range(8)]


def _seed_pods(create, n, namespaces=_NAMESPACES, nodes=("n0", "n1")):
    for i in range(n):
        create("Pod", build_pod(f"p{i}", namespace=namespaces[i % len(namespaces)]))


def _mixed_segment(n=24, n_evict=4):
    """One cycle-shaped segment whose rows span every shard."""
    bind_keys, bind_nodes, table = [], [], ["n0", "n1", "n2"]
    for i in range(n):
        bind_keys.append(f"{_NAMESPACES[i % len(_NAMESPACES)]}/p{i}")
        bind_nodes.append(i % len(table))
    evicts = [
        (f"{_NAMESPACES[i % len(_NAMESPACES)]}/p{n + i}", "preempt")
        for i in range(n_evict)
    ]
    return DecisionSegment.build(bind_keys, bind_nodes, table, evicts)


# -- the hash + split --------------------------------------------------------


def test_shard_of_is_stable_and_covers_all_shards():
    # crc32 is process-independent: pin a few values so a hash change
    # (which would orphan per-shard WAL/watch streams) fails loudly
    assert shard_of("team0", 4) == shard_of("team0", 4)
    assert shard_of_key("team0/p1", 4) == shard_of("team0", 4)
    assert shard_of_key("/cluster-scoped", 4) == shard_of("", 4)
    assert shard_of("anything", 1) == 0
    seen = {shard_of(ns, NSHARDS) for ns in _NAMESPACES}
    assert seen == set(range(NSHARDS)), (
        "test namespaces must cover every shard; adjust _NAMESPACES"
    )


def test_split_segment_is_a_partition_preserving_order():
    seg = _mixed_segment(n=24, n_evict=4)
    subs = split_segment(seg, NSHARDS)
    assert {s for s, _ in subs} <= set(range(NSHARDS))
    # union of rows == original rows; within-shard order preserved
    all_binds = []
    all_evicts = []
    for shard, sub in subs:
        for k in sub.bind_keys:
            assert shard_of_key(k, NSHARDS) == shard
        for k in sub.evict_keys:
            assert shard_of_key(k, NSHARDS) == shard
        # node table re-interned per shard: only referenced hosts
        assert set(sub.node_table) == set(sub.bind_hosts)
        all_binds.extend(zip(sub.bind_keys, sub.bind_hosts))
        all_evicts.extend(sub.evict_pairs())
        # each sub-segment reserved its OWN event uid block
        assert len(sub.bind_keys) + len(sub.evict_keys) >= 1
    assert sorted(all_binds) == sorted(zip(seg.bind_keys, seg.bind_hosts))
    assert sorted(all_evicts) == sorted(seg.evict_pairs())
    orig_order = {k: i for i, k in enumerate(seg.bind_keys)}
    for _, sub in subs:
        idxs = [orig_order[k] for k in sub.bind_keys]
        assert idxs == sorted(idxs)
    # splitting on one shard is the identity
    assert split_segment(seg, 1) == [(0, seg)]


def test_wal_shard_routes_every_record_shape():
    assert wal_shard({"op": "segment", "shard": 3}, 4) == 3
    assert wal_shard({"op": "patch", "kind": "Pod", "key": "team0/p0"}, 4) \
        == shard_of("team0", 4)
    assert wal_shard(
        {"op": "patch_col", "kind": "Pod", "keys": ["team1/p0", "team1/p1"]},
        4,
    ) == shard_of("team1", 4)
    assert wal_shard(
        {"op": "create", "kind": "Pod",
         "object": {"meta": {"namespace": "team2", "name": "x"}}}, 4
    ) == shard_of("team2", 4)
    assert wal_shard({"op": "delete", "kind": "Node", "key": "/n0"}, 1) == 0


# -- watch-stream byte identity vs the single-shard server -------------------


def _run_stream(monkeypatch, shards):
    """Apply the SAME deterministic sub-segment sequence (frozen uid
    counter + clock) and return (server, merged watch events)."""
    monkeypatch.setattr(api_objects, "_uid_token", "t0")
    monkeypatch.setattr(api_objects, "_uid_next", 1000)
    monkeypatch.setattr(time, "time", lambda: 1234.5)
    srv = StoreServer(shards=shards).start()
    _seed_pods(srv.store.create, 32)
    with srv.lock:
        srv._pump_log()  # seed events drain with deterministic seqs
    seg = _mixed_segment(n=24, n_evict=4)
    for shard, sub in split_segment(seg, NSHARDS):
        # sequential, in shard order: both servers see the identical op
        # sequence, so seq/rv assignment matches exactly
        res = srv._apply_segment(dict(sub.to_wire(), shard=shard))
        assert not res["binds"] and not res["evicts"]
    return srv, srv.watch_since(0, set(), 0)["events"]


def test_partitioned_watch_stream_byte_identical_to_single_shard(monkeypatch):
    srv1, stream1 = _run_stream(monkeypatch, shards=1)
    srvN, streamN = _run_stream(monkeypatch, shards=NSHARDS)
    try:
        assert json.dumps(streamN) == json.dumps(stream1)
        # per-shard fan-out: each shard's slice is exactly the merged
        # stream filtered to that shard's namespaces, order preserved
        covered = 0

        def shard_of_event(e):
            # a segment-born Event is cluster-scoped (namespace "") but
            # belongs to its segment's shard — the involved pod's
            # namespace; everything else shards by its own namespace
            if e["kind"] == "Event":
                return shard_of_key(e["object"]["involved"][1], NSHARDS)
            return shard_of(e["object"]["meta"].get("namespace") or "",
                            NSHARDS)

        for s in range(NSHARDS):
            slice_s = srvN.watch_since(0, set(), 0, shard=s)["events"]
            expect = [e for e in stream1 if shard_of_event(e) == s]
            assert json.dumps(slice_s) == json.dumps(expect), f"shard {s}"
            covered += len(slice_s)
        assert covered == len(stream1)  # the slices partition the stream
    finally:
        srv1.stop()
        srvN.stop()


def test_shard_scoped_remote_watcher_sees_only_its_namespaces(monkeypatch):
    srv = StoreServer(shards=NSHARDS).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 8)
        target = shard_of("team0", NSHARDS)
        watcher = RemoteStore(srv.url, shard=target)
        q = watcher.watch("Pod")
        seg = _mixed_segment(n=8, n_evict=0)
        for shard, sub in split_segment(seg, NSHARDS):
            rs.apply_segment(sub, shard=shard)
        watcher.poll()
        got = []
        while q:
            got.append(q.popleft())
        assert got, "shard watcher saw nothing"
        assert all(
            shard_of(e.obj.meta.namespace, NSHARDS) == target for e in got
        )
        expect = sum(
            1 for k in seg.bind_keys
            if shard_of_key(k, NSHARDS) == target
        )
        assert len(got) == expect
    finally:
        srv.stop()


# -- the applier's concurrent split-ship -------------------------------------


def test_applier_splits_and_ships_concurrently_with_attribution():
    srv = StoreServer(shards=NSHARDS).start()
    try:
        rs = RemoteStore(srv.url)
        rs.create("Queue", Queue(meta=Metadata(name="default", namespace="")))
        _seed_pods(rs.create, 32)
        assert rs.segment_shards == NSHARDS
        cache = SchedulerCache(rs, async_apply=True)
        seg = _mixed_segment(n=24, n_evict=4)
        try:
            assert cache.publish_segment(seg)
            assert cache.applier.flush(timeout=30.0)
            assert cache.err_log == []
        finally:
            cache.applier.stop(flush=False)
        # every bind landed, exactly the unsplit outcome
        for i, key in enumerate(seg.bind_keys):
            assert rs.get("Pod", key).node_name == seg.bind_hosts[i]
        for key in seg.evict_keys:
            assert rs.get("Pod", key).deleting is True
        # one Scheduled/Evict event per row, across all sub-blocks
        evs = rs.list("Event")
        assert len(evs) == len(seg.bind_keys) + len(seg.evict_keys)
        # per-shard drain attribution rode the stats dict
        stats = cache.applier.drain_stats
        shard_keys = [k for k in stats if k.startswith("shard")]
        assert shard_keys, stats
        assert {f"shard{s:02d}_s"
                for s, _ in split_segment(seg, NSHARDS)} == set(shard_keys)
    finally:
        srv.stop()


def test_unsharded_server_keeps_single_segment_path():
    """A shards=1 server advertises 1 and the applier ships ONE segment
    — no shardNN attribution keys, the pre-partition wire exactly."""
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 8)
        assert rs.segment_shards == 1
        cache = SchedulerCache(rs, async_apply=True)
        seg = _mixed_segment(n=8, n_evict=0)
        try:
            assert cache.publish_segment(seg)
            assert cache.applier.flush(timeout=30.0)
            assert cache.err_log == []
            assert not any(k.startswith("shard")
                           for k in cache.applier.drain_stats)
        finally:
            cache.applier.stop(flush=False)
    finally:
        srv.stop()


# -- the PR-7 zero-acked-loss gate on the partitioned WAL --------------------


def _boot(tmp_path, shards, port=0):
    return StoreServer(
        state_path=str(tmp_path / "state.json"), wal=True, shards=shards,
        save_interval=3600, port=port,
    ).start()


def test_partitioned_wal_zero_acked_loss_after_kill(tmp_path):
    """Acked sub-segments in FOUR shard WALs; SIGKILL-shaped death; the
    reboot merges the shard tails by seq and recovers every ACKed
    mutation bit-for-bit — the PR-7 gate, partitioned."""
    srv = _boot(tmp_path, NSHARDS)
    rs = RemoteStore(srv.url)
    _seed_pods(rs.create, 32)
    seg = _mixed_segment(n=24, n_evict=4)
    subs = split_segment(seg, NSHARDS)
    for shard, sub in subs:
        res = rs.apply_segment(sub, shard=shard)
        assert not res["binds"] and not res["evicts"]
    # per-shard WAL files really exist and each got its shard's record
    wal_dir = str(tmp_path / "state.json.wal")
    assert len(leftover_shard_dirs(wal_dir)) == NSHARDS
    stats = srv.wal.stats()
    assert stats["shards"] == NSHARDS
    per_shard_records = [p["records"] for p in stats["per_shard"]]
    for shard, _ in subs:
        assert per_shard_records[shard] >= 1
    acked = {p.meta.key: (p.node_name, p.deleting, p.meta.resource_version)
             for p in rs.list("Pod")}
    acked_events = {e.meta.name for e in rs.list("Event")}
    seq, rv = srv.seq, srv.store._rv
    srv.kill()

    srv2 = _boot(tmp_path, NSHARDS, port=srv.port)
    try:
        rs2 = RemoteStore(srv2.url)
        after = {p.meta.key: (p.node_name, p.deleting,
                              p.meta.resource_version)
                 for p in rs2.list("Pod")}
        assert after == acked
        assert {e.meta.name for e in rs2.list("Event")} == acked_events
        assert srv2.seq == seq and srv2.store._rv == rv
    finally:
        srv2.stop()


def test_partitioned_wal_checkpoint_carries_per_shard_floors(tmp_path):
    srv = _boot(tmp_path, NSHARDS)
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 8)
        for shard, sub in split_segment(_mixed_segment(n=8, n_evict=0),
                                        NSHARDS):
            rs.apply_segment(sub, shard=shard)
        srv.flush_state(force=True)
        with open(tmp_path / "state.json") as f:
            data = json.load(f)
        floors = data["wal_floor"]
        assert isinstance(floors, list) and len(floors) == NSHARDS
        assert all(isinstance(f, int) and f >= 2 for f in floors)
    finally:
        srv.stop()


def test_partitioned_crash_kill_storm_keeps_gate_green(tmp_path):
    """Seeded kill storm against the partitioned WAL store: repeated
    kill+reboot cycles with acked decision traffic in between — every
    reboot recovers exactly the acked state (no loss, no resurrection),
    the PR-7 storm shape on the sharded bus."""
    port = 0
    expect = {}
    srv = _boot(tmp_path, NSHARDS)
    port = srv.port
    rs = RemoteStore(srv.url)
    _seed_pods(rs.create, 40, namespaces=_NAMESPACES)
    for p in rs.list("Pod"):
        expect[p.meta.key] = ""
    for round_ in range(3):
        seg = DecisionSegment.build(
            [f"{_NAMESPACES[(round_ * 5 + i) % len(_NAMESPACES)]}"
             f"/p{(round_ * 5 + i) % 40}" for i in range(5)],
            [0] * 5, [f"n{round_}"],
        )
        for shard, sub in split_segment(seg, NSHARDS):
            res = rs.apply_segment(sub, shard=shard)
            assert not res["binds"]
        for k, h in zip(seg.bind_keys, seg.bind_hosts):
            expect[k] = h
        srv.kill()
        srv = _boot(tmp_path, NSHARDS, port=port)
        rs = RemoteStore(srv.url)
        got = {p.meta.key: p.node_name for p in rs.list("Pod")}
        assert got == expect, f"round {round_}"
    srv.stop()


def test_wal_off_boot_absorbs_partitioned_leftover_tail(tmp_path):
    """Dropping from a partitioned WAL-on life to a WAL-off boot must
    absorb every shard's acked tail (merged by seq), snapshot it, and
    retire the shard segments — the PR-7 lineage rule, sharded."""
    srv = _boot(tmp_path, NSHARDS)
    rs = RemoteStore(srv.url)
    _seed_pods(rs.create, 16)
    seg = _mixed_segment(n=12, n_evict=0)
    for shard, sub in split_segment(seg, NSHARDS):
        rs.apply_segment(sub, shard=shard)
    acked = {p.meta.key: p.node_name for p in rs.list("Pod")}
    srv.kill()

    srv2 = StoreServer(state_path=str(tmp_path / "state.json"),
                       save_interval=3600, port=srv.port).start()
    try:
        rs2 = RemoteStore(srv2.url)
        assert {p.meta.key: p.node_name
                for p in rs2.list("Pod")} == acked
        # shard tails retired after absorption
        wal_dir = str(tmp_path / "state.json.wal")
        import os

        for d in leftover_shard_dirs(wal_dir):
            assert [n for n in os.listdir(d) if n.endswith(".wal")] == []
    finally:
        srv2.stop()


def test_sharded_wal_independent_group_commit(tmp_path):
    """Each shard has its own fsync leader: records appended to two
    shards fsync through two independent commits, and a shard with no
    pending appends never fsyncs at all."""
    wal = ShardedWAL(str(tmp_path / "w"), 4)
    wal.append({"op": "patch", "kind": "Pod", "key": "team0/p0",
                "fields": {}, "seq": 1})
    wal.append({"op": "patch", "kind": "Pod", "key": "team1/p0",
                "fields": {}, "seq": 2})
    wal.commit()
    stats = wal.stats()
    assert stats["records"] == 2
    touched = [p for p in stats["per_shard"] if p["records"]]
    assert len(touched) == 2
    assert all(p["fsync_total"] == 1 for p in touched)
    untouched = [p for p in stats["per_shard"] if not p["records"]]
    assert all(p["fsync_total"] == 0 for p in untouched)
    # replay merges across shards in seq order
    wal.sync_close()
    wal2 = ShardedWAL(str(tmp_path / "w"), 4)
    seqs = [rec["seq"] for rec in wal2.replay([0, 0, 0, 0])]
    assert seqs == [1, 2]
    wal2.sync_close()


# -- review hardening (PR 11 code review) ------------------------------------


@pytest.mark.parametrize("old_shards,new_shards", [(4, 1), (1, 4), (4, 2)])
def test_shard_count_change_across_kill_keeps_acked_records(
    tmp_path, old_shards, new_shards
):
    """The zero-acked-loss contract survives an operator re-partitioning
    the bus across a crash: records fsynced under one shard layout must
    replay on a boot with ANY other layout (orphaned-layout tails are
    absorbed seq-merged, snapshotted, and retired)."""
    srv = _boot(tmp_path, old_shards)
    rs = RemoteStore(srv.url)
    _seed_pods(rs.create, 16)
    seg = _mixed_segment(n=12, n_evict=0)
    for shard, sub in split_segment(seg, old_shards):
        res = rs.apply_segment(sub, shard=shard)
        assert not res["binds"]
    acked = {p.meta.key: p.node_name for p in rs.list("Pod")}
    srv.kill()

    srv2 = _boot(tmp_path, new_shards, port=srv.port)
    try:
        rs2 = RemoteStore(srv2.url)
        after = {p.meta.key: p.node_name for p in rs2.list("Pod")}
        assert after == acked, f"{old_shards}->{new_shards} lost acked state"
        # kill AGAIN without new traffic: the absorbed tail must have
        # been made durable (snapshot) before the orphaned segments died
        srv2.kill()
        srv3 = _boot(tmp_path, new_shards, port=srv.port)
        try:
            rs3 = RemoteStore(srv3.url)
            assert {p.meta.key: p.node_name
                    for p in rs3.list("Pod")} == acked
        finally:
            srv3.stop()
    finally:
        if not srv2._killed:
            srv2.stop()


def test_untagged_segment_reaches_every_shard_watcher():
    """A segment shipped WITHOUT a shard tag (pre-partition client /
    failed healthz probe) must reach shard-scoped watchers of every
    shard — over-delivery, never a silent per-shard gap."""
    srv = StoreServer(shards=NSHARDS).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 8)
        watchers = []
        for s in range(NSHARDS):
            w = RemoteStore(srv.url, shard=s)
            watchers.append((s, w, w.watch("Pod")))
        seg = _mixed_segment(n=8, n_evict=0)
        rs.apply_segment(seg)  # whole segment, no shard tag
        for s, w, q in watchers:
            w.poll()
            got = []
            while q:
                got.append(q.popleft().obj.meta.key)
            assert got == seg.bind_keys, f"shard {s} watcher missed rows"
    finally:
        srv.stop()


def test_sharded_fanout_wire_attribution_not_inflated():
    """wire_s accounts the fan-out ONCE (wall minus server sections),
    not the sum of overlapping per-ship walls — it must stay comparable
    with the single-segment path's reading (and can never exceed the
    whole drain's wall-clock)."""
    import time as _time

    srv = StoreServer(shards=NSHARDS).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 32)
        cache = SchedulerCache(rs, async_apply=True)
        seg = _mixed_segment(n=24, n_evict=0)
        t0 = _time.perf_counter()
        try:
            assert cache.publish_segment(seg)
            assert cache.applier.flush(timeout=30.0)
            wall = _time.perf_counter() - t0
            assert cache.err_log == []
            stats = cache.applier.drain_stats
            assert stats["wire_s"] <= wall + 0.05, (stats["wire_s"], wall)
        finally:
            cache.applier.stop(flush=False)
    finally:
        srv.stop()


# -- per-shard digest surface (PR 13: vtaudit) --------------------------------


def test_healthz_carries_per_shard_digest_and_seq():
    """/healthz exposes the maintained digest per shard next to that
    shard's newest seq — shard skew and divergence at a glance; the
    per-shard digests must roll up to the root exactly."""
    import urllib.request

    from volcano_tpu import vtaudit

    if not vtaudit.enabled():
        pytest.skip("digest auditing disarmed in env")
    srv = StoreServer(shards=NSHARDS).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 16)
        with urllib.request.urlopen(srv.url + "/healthz", timeout=10) as r:
            hz = json.load(r)
        dg = hz["digest"]
        assert dg["seq"] == srv.seq
        assert len(dg["shards"]) == NSHARDS
        total = sum(int(s["digest"], 16) for s in dg["shards"]) % (1 << 64)
        assert vtaudit.hexd(total) == dg["root"]
        # every seeded namespace's shard saw traffic; no shard seq can
        # exceed the global seq
        touched = {shard_of(ns, NSHARDS) for ns in _NAMESPACES}
        for s, entry in enumerate(dg["shards"]):
            assert entry["seq"] <= dg["seq"]
            if s in touched:
                assert entry["seq"] > 0
        # the rollup agrees with /debug/digest's maintained tier
        with urllib.request.urlopen(
            srv.url + "/debug/digest", timeout=10
        ) as r:
            dbg = json.load(r)
        assert dbg["root"] == dg["root"]
        assert dbg["shards"] == [e["digest"] for e in dg["shards"]]
        assert dbg["shard_seq"] == [e["seq"] for e in dg["shards"]]
        # one more namespace-scoped write moves EXACTLY that shard's
        # digest and seq
        before = dg["shards"]
        rs.create("Pod", build_pod("extra", namespace=_NAMESPACES[0]))
        with urllib.request.urlopen(srv.url + "/healthz", timeout=10) as r:
            after = json.load(r)["digest"]["shards"]
        hot = shard_of(_NAMESPACES[0], NSHARDS)
        for s in range(NSHARDS):
            if s == hot:
                assert after[s] != before[s]
            else:
                assert after[s]["digest"] == before[s]["digest"]
    finally:
        srv.stop()
