"""Array-native preempt/reclaim in the fast cycle (fast_victims.py):
decision parity against the object path on contended clusters, and the
guarded fallbacks for the kernel-inexpressible cases."""

import random

import numpy as np
import pytest

from volcano_tpu.api.objects import Metadata, PriorityClass
from volcano_tpu.api.types import PodPhase
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import (
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)


def _prio_classes(store):
    store.create("PriorityClass", PriorityClass(
        meta=Metadata(name="urgent", namespace=""), value=10))
    store.create("PriorityClass", PriorityClass(
        meta=Metadata(name="low", namespace=""), value=1))


def preempt_store():
    """Full cluster of low-priority singleton gangs + one starving
    high-priority gang in the same queue: allocate finds nothing, preempt
    must evict."""
    nodes = [build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(4)]
    queues = [build_queue("qa", weight=1), build_queue("default")]
    podgroups, pods = [], []
    for i in range(8):
        pg = build_podgroup(f"low{i}", min_member=1, queue="qa")
        pg.priority_class_name = "low"
        podgroups.append(pg)
        p = build_pod(f"low{i}-0", group=f"low{i}", cpu="2", memory="2Gi",
                      priority=1)
        p.node_name = f"n{i % 4}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
    hi = build_podgroup("hi", min_member=2, queue="qa")
    hi.priority_class_name = "urgent"
    podgroups.append(hi)
    for t in range(2):
        pods.append(build_pod(f"hi-{t}", group="hi", cpu="2", memory="2Gi",
                              priority=10))
    store = make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                      pods=pods)
    _prio_classes(store)
    return store


def reclaim_store():
    """Weighted queues qa(3):qb(1); qb's running pods overuse its deserved
    share while qa starves: reclaim must evict qb residents."""
    nodes = [build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(4)]
    queues = [build_queue("qa", weight=3), build_queue("qb", weight=1),
              build_queue("default")]
    podgroups, pods = [], []
    for i in range(8):
        pg = build_podgroup(f"b{i}", min_member=1, queue="qb")
        podgroups.append(pg)
        p = build_pod(f"b{i}-0", group=f"b{i}", cpu="2", memory="2Gi")
        p.node_name = f"n{i % 4}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
    for j in range(2):
        pg = build_podgroup(f"a{j}", min_member=1, queue="qa")
        podgroups.append(pg)
        pods.append(build_pod(f"a{j}-0", group=f"a{j}", cpu="2",
                              memory="2Gi"))
    store = make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                      pods=pods)
    _prio_classes(store)
    return store


def random_contended_store(seed):
    """Randomized overcommitted cluster: running singleton gangs fill most
    capacity; pending gangs at mixed priorities across two weighted
    queues."""
    rng = random.Random(seed)
    n_nodes = rng.choice([3, 5])
    nodes = [build_node(f"n{i:02d}", cpu="4", memory="8Gi")
             for i in range(n_nodes)]
    queues = [build_queue("qa", weight=2), build_queue("qb", weight=1),
              build_queue("default")]
    podgroups, pods = [], []
    for i in range(2 * n_nodes):
        q = rng.choice(["qa", "qb"])
        pg = build_podgroup(f"run{i}", min_member=1, queue=q)
        pg.priority_class_name = rng.choice(["low", ""])
        podgroups.append(pg)
        p = build_pod(f"run{i}-0", group=f"run{i}", cpu="2", memory="2Gi",
                      priority=1)
        p.node_name = f"n{i % n_nodes:02d}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
    for j in range(rng.randint(1, 3)):
        q = rng.choice(["qa", "qb"])
        n_tasks = rng.randint(1, 2)
        pg = build_podgroup(f"pend{j}", min_member=n_tasks, queue=q)
        pg.priority_class_name = "urgent"
        podgroups.append(pg)
        for t in range(n_tasks):
            pods.append(build_pod(
                f"pend{j}-{t}", group=f"pend{j}", cpu="2", memory="2Gi",
                priority=10,
            ))
    store = make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                      pods=pods)
    _prio_classes(store)
    return store


def _outcome(store, fast: bool, solve_mode=None):
    conf = full_conf("tpu")
    if not fast:
        conf.fast_path = "off"
    if solve_mode is not None:
        conf.solve_mode = solve_mode
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    pods = {
        p.meta.key: (p.node_name, p.deleting) for p in store.items("Pod")
    }
    pgs = {
        pg.meta.key: (
            pg.status.phase,
            sorted(c.kind for c in pg.status.conditions),
        )
        for pg in store.items("PodGroup")
    }
    evicts = sorted(k for k, _ in sched.cache.evict_log)
    return sched, {"pods": pods, "pgs": pgs, "evicts": evicts}


def _fast_used(sched):
    return sched.fast_cycle is not None and sched.fast_cycle.mirror is not None


def test_preempt_parity_and_fast_path_used():
    s_fast, fast = _outcome(preempt_store(), True)
    s_obj, obj = _outcome(preempt_store(), False)
    assert _fast_used(s_fast)
    assert fast == obj
    assert fast["evicts"], "scenario must actually preempt"


def test_reclaim_parity_and_fast_path_used():
    s_fast, fast = _outcome(reclaim_store(), True)
    s_obj, obj = _outcome(reclaim_store(), False)
    assert _fast_used(s_fast)
    assert fast == obj
    assert fast["evicts"], "scenario must actually reclaim"


@pytest.mark.parametrize("seed", range(8))
def test_random_contention_parity(seed):
    s_fast, fast = _outcome(random_contended_store(seed), True)
    _, obj = _outcome(random_contended_store(seed), False)
    assert _fast_used(s_fast)
    assert fast == obj


def test_best_effort_preemptor_evicts_on_fast_path():
    """Without backfill in the conf a pending BE task reaches preempt;
    the fast path re-packs it into the task arrays and the DO-while core
    takes exactly one victim for it — parity with the object path, no
    fallback."""
    def build():
        store = preempt_store()
        store.create("Pod", build_pod("hi-be", group="hi", cpu="0", memory="0"))
        return store

    def outcome(store, fast):
        conf = full_conf("tpu")
        conf.actions = ["enqueue", "allocate", "preempt"]
        if not fast:
            conf.fast_path = "off"
        sched = Scheduler(store, conf=conf)
        called = []
        sched.run_object_residue = lambda *a, **k: called.append(a)
        sched.run_once()
        state = {
            "pods": {p.meta.key: (p.node_name, p.deleting)
                     for p in store.items("Pod")},
            "evicts": sorted(k for k, _ in sched.cache.evict_log),
        }
        return sched, called, state

    s_fast, called, fast = outcome(build(), True)
    _, _, obj = outcome(build(), False)
    assert _fast_used(s_fast)
    assert not called, "BE preemptor fell back to the object sub-cycle"
    assert fast == obj
    # 2 victims for the express gang tasks + 1 for the BE task
    assert len(fast["evicts"]) == 3, fast["evicts"]


def test_best_effort_repack_does_not_shift_published_binds():
    """Spare capacity + a best-effort preemptor: allocate places express
    tasks, then the BE re-pack rebuilds the task arrays BEFORE publish —
    binds must keep indexing the solve's layout (the re-pack inserts the
    BE row mid-array when its job is not last)."""
    def build():
        nodes = [build_node(f"n{i}", cpu="4", memory="8Gi")
                 for i in range(2)]
        # job "a..." sorts first; its BE row lands between a's and z's
        # express rows after the re-pack
        pga = build_podgroup("aaa", min_member=1, queue="qa")
        pgz = build_podgroup("zzz", min_member=2, queue="qa")
        pods = [build_pod("aaa-0", group="aaa", cpu="1", memory="1Gi",
                          priority=5)]
        be = build_pod("aaa-be", group="aaa", cpu="0", memory="0")
        be.spec.node_selector = {"zone": "nowhere"}
        pods.append(be)
        pods += [build_pod(f"zzz-{t}", group="zzz", cpu="1", memory="1Gi")
                 for t in range(2)]
        store = make_store(
            nodes=nodes, queues=[build_queue("qa"), build_queue("default")],
            podgroups=[pga, pgz], pods=pods)
        _prio_classes(store)
        return store

    s_fast, fast = _outcome(build(), True)
    _, obj = _outcome(build(), False)
    assert _fast_used(s_fast)
    assert fast == obj
    bound = {k: v[0] for k, v in fast["pods"].items() if v[0]}
    assert set(bound) == {"default/aaa-0", "default/zzz-0", "default/zzz-1"}


def test_two_cycle_convergence():
    """After the kubelet reaps evicted victims, the next cycle binds the
    pipelined preemptors — end-to-end over the fast path."""
    store = preempt_store()
    conf = full_conf("tpu")
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    evicted = [k for k, _ in sched.cache.evict_log]
    assert evicted
    # sim kubelet: reap deleting pods
    for key in evicted:
        pod = store.get("Pod", key)
        assert pod.deleting
        store.delete("Pod", key)
    sched.run_once()
    hi_nodes = [store.get("Pod", f"default/hi-{t}").node_name
                for t in range(2)]
    assert all(hi_nodes), hi_nodes


def test_batched_rounds_equivalence_on_simple_storm():
    """solve_mode: batch forces the batched-rounds contention kernel even
    below the auto threshold.  Like the batched allocate solve, node
    choice diverges on score ties (the reference randomizes those), so
    the contract is outcome equivalence, not bit parity: same eviction
    count from the same victim class, and the gang converges."""
    store = preempt_store()
    conf = full_conf("tpu")
    conf.solve_mode = "batch"
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    assert _fast_used(sched)
    _, obj = _outcome(preempt_store(), False)
    evicted = [k for k, _ in sched.cache.evict_log]
    assert len(evicted) == len(obj["evicts"]) == 2
    for key in evicted:
        assert key.startswith("default/low"), key
        pod = store.get("Pod", key)
        assert pod.deleting
        store.delete("Pod", key)
    sched.run_once()
    hi_nodes = [store.get("Pod", f"default/hi-{t}").node_name
                for t in range(2)]
    assert all(hi_nodes), hi_nodes


def test_batched_rounds_storm_above_threshold():
    """A storm wider than CONTENTION_BATCH_THRESHOLD takes the rounds
    kernel on the auto path; every gang must be served (enough victims
    exist), nothing may be over-evicted, and the next cycle must bind the
    preemptors."""
    from volcano_tpu.scheduler import fast_victims

    n_nodes, per_node = 12, 8
    nodes = [build_node(f"n{i:02d}", cpu=str(2 * per_node), memory="64Gi")
             for i in range(n_nodes)]
    queues = [build_queue("qa", weight=1), build_queue("default")]
    podgroups, pods = [], []
    for i in range(n_nodes * per_node):
        pg = build_podgroup(f"low{i:03d}", min_member=1, queue="qa")
        pg.priority_class_name = "low"
        podgroups.append(pg)
        p = build_pod(f"low{i:03d}-0", group=f"low{i:03d}", cpu="2",
                      memory="2Gi", priority=1)
        p.node_name = f"n{i % n_nodes:02d}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
    # 24 urgent gangs x 3 tasks = 72 preemptors > threshold (64); each
    # task displaces exactly one resident
    n_gangs, gang_size = 24, 3
    for g in range(n_gangs):
        pg = build_podgroup(f"hot{g:02d}", min_member=gang_size, queue="qa")
        pg.priority_class_name = "urgent"
        podgroups.append(pg)
        for t in range(gang_size):
            pods.append(build_pod(f"hot{g:02d}-{t}", group=f"hot{g:02d}",
                                  cpu="2", memory="2Gi", priority=10))
    store = make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                       pods=pods)
    _prio_classes(store)

    assert n_gangs * gang_size > fast_victims.CONTENTION_BATCH_THRESHOLD
    conf = full_conf("tpu")
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    assert _fast_used(sched)
    evicted = [k for k, _ in sched.cache.evict_log]
    assert len(evicted) == n_gangs * gang_size, len(evicted)
    for key in evicted:
        pod = store.get("Pod", key)
        assert pod.deleting
        store.delete("Pod", key)
    sched.run_once()
    for g in range(n_gangs):
        for t in range(gang_size):
            p = store.get("Pod", f"default/hot{g:02d}-{t}")
            assert p.node_name, f"hot{g:02d}-{t} unbound"


def test_batched_rounds_never_evicts_cross_queue():
    """Phase-1 preemption is strictly same-queue; the rounds kernel's
    capacity curves are per-(node, queue), so a qa storm must never be
    funded by qb residents — even when qb's pods sort earlier in the
    node's eviction order (lower priority)."""
    nodes = [build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(4)]
    queues = [build_queue("qa", weight=1), build_queue("qb", weight=1),
              build_queue("default")]
    podgroups, pods = [], []
    for i in range(4):
        pg = build_podgroup(f"a{i}", min_member=1, queue="qa")
        pg.priority_class_name = "low"
        podgroups.append(pg)
        p = build_pod(f"a{i}-0", group=f"a{i}", cpu="2", memory="2Gi",
                      priority=1)
        p.node_name = f"n{i}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
        # qb resident on the same node, LOWER priority: first in the
        # node's pooled eviction order, must still be untouchable
        pg = build_podgroup(f"b{i}", min_member=1, queue="qb")
        podgroups.append(pg)
        p = build_pod(f"b{i}-0", group=f"b{i}", cpu="2", memory="2Gi",
                      priority=0)
        p.node_name = f"n{i}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
    pg = build_podgroup("hi", min_member=2, queue="qa")
    pg.priority_class_name = "urgent"
    podgroups.append(pg)
    for t in range(2):
        pods.append(build_pod(f"hi-{t}", group="hi", cpu="2", memory="2Gi",
                              priority=10))
    store = make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                       pods=pods)
    _prio_classes(store)
    conf = full_conf("tpu")
    conf.solve_mode = "batch"
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    preempted = [k for k, r in sched.cache.evict_log if r == "preempt"]
    assert preempted, "storm must preempt"
    # cross-queue eviction is reclaim's (proportion-gated) domain only;
    # the preempt action must never touch qb residents
    assert all("/a" in k for k in preempted), preempted


def test_best_effort_preemptor_served_by_fast_path():
    """An empty-request pending task among the preemptors used to force
    the O(cluster) object sub-cycle; the DO-while victim core (one victim
    for an empty request, host rule) makes it array-native.  Parity must
    hold AND the object machinery must never run."""
    def build():
        store = preempt_store()
        store.create("Pod", build_pod("hi-be", group="hi", cpu="0", memory="0"))
        return store

    conf = full_conf("tpu")
    store = build()
    sched = Scheduler(store, conf=conf)
    called = []
    sched.run_object_residue = lambda *a, **k: called.append(a)
    sched.run_once()
    assert _fast_used(sched)
    assert not called, "BE preemptor fell back to the object sub-cycle"
    fast = {
        "pods": {p.meta.key: (p.node_name, p.deleting)
                 for p in store.items("Pod")},
        "pgs": {pg.meta.key: (pg.status.phase,
                              sorted(c.kind for c in pg.status.conditions))
                for pg in store.items("PodGroup")},
        "evicts": sorted(k for k, _ in sched.cache.evict_log),
    }
    _, obj = _outcome(build(), False)
    assert fast == obj
