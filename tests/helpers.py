"""Shared builders for scheduler tests (the FakeBinder/BuildNode/BuildPod
pattern of reference KB/pkg/scheduler/util/test_utils.go)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from volcano_tpu.api import (
    POD_GROUP_KEY,
    PodGroup,
    Queue,
    Resource,
)
from volcano_tpu.api.objects import Metadata, Node, Pod, PodSpec
from volcano_tpu.api.types import PodPhase
from volcano_tpu.store import Store
# the shared deadline-bounded readiness probe for server-backed tests —
# use this instead of ad-hoc /healthz polling loops
from volcano_tpu.store.client import wait_healthy  # noqa: F401


def build_node(name: str, cpu="4", memory="8Gi", pods: int = 110, labels=None, **scalars) -> Node:
    rl = {"cpu": cpu, "memory": memory, "pods": pods, **scalars}
    return Node(
        meta=Metadata(name=name, namespace=""),
        allocatable=Resource.from_resource_list(rl),
        labels=dict(labels or {}),
    )


def build_pod(
    name: str,
    group: str = "",
    cpu="1",
    memory="1Gi",
    namespace: str = "default",
    node_name: str = "",
    phase: PodPhase = PodPhase.PENDING,
    priority: int = 0,
    labels=None,
    **scalars,
) -> Pod:
    rl = {"cpu": cpu, "memory": memory, **scalars}
    annotations = {POD_GROUP_KEY: group} if group else {}
    return Pod(
        meta=Metadata(name=name, namespace=namespace, annotations=annotations,
                      labels=dict(labels or {})),
        spec=PodSpec(resources=Resource.from_resource_list(rl), priority=priority),
        phase=phase,
        node_name=node_name,
    )


def build_podgroup(
    name: str,
    min_member: int = 1,
    queue: str = "default",
    namespace: str = "default",
    phase=None,
) -> PodGroup:
    from volcano_tpu.api.types import PodGroupPhase

    pg = PodGroup(
        meta=Metadata(name=name, namespace=namespace),
        min_member=min_member,
        queue=queue,
    )
    pg.status.phase = phase or PodGroupPhase.INQUEUE
    return pg


def build_queue(name: str, weight: int = 1) -> Queue:
    return Queue(meta=Metadata(name=name, namespace=""), weight=weight)


def make_store(
    nodes: List[Node],
    queues: Optional[List[Queue]] = None,
    podgroups: Optional[List[PodGroup]] = None,
    pods: Optional[List[Pod]] = None,
) -> Store:
    store = Store()
    for q in queues if queues is not None else [build_queue("default")]:
        store.create("Queue", q)
    for n in nodes:
        store.create("Node", n)
    for pg in podgroups or []:
        store.create("PodGroup", pg)
    for p in pods or []:
        store.create("Pod", p)
    return store


class FakeBinder:
    """Records binds instead of writing the store (test_utils.go:96-113)."""

    def __init__(self):
        self.binds: Dict[str, str] = {}

    def bind(self, task, hostname: str) -> None:
        self.binds[task.key] = hostname


class FakeEvictor:
    def __init__(self):
        self.evicts: List[str] = []

    def evict(self, task, reason: str) -> None:
        self.evicts.append(task.key)
