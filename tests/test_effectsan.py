"""effectsan: the runtime effect-order sanitizer (volcano_tpu/effectsan.py).

The dynamic twin of the static `wal-effect-order` rule: with
VOLCANO_TPU_EFFECT_SANITIZER=1 the store/replica hot paths record the
(mutate, append, beacon, ship, ack) sequence per thread and any
observable effect over an un-appended mutation raises EffectOrderError
at the offending site.  These tests drive the hooks directly with
deliberately reordered sequences (the unit-level "reordered fixture"),
then prove the instrumented server stays green end-to-end under the
flag — the same legs `make sanitize` runs at full suite scale.
"""

import threading

import pytest

from volcano_tpu import effectsan
from volcano_tpu.effectsan import EffectOrderError


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv(effectsan.ENV_FLAG, "1")
    effectsan._reset()
    yield
    effectsan._reset()


def test_disabled_hooks_are_noops(monkeypatch):
    monkeypatch.delenv(effectsan.ENV_FLAG, raising=False)
    effectsan.note_mutate("m")
    effectsan.note_beacon("b")  # would raise if armed: mutate is pending
    effectsan.note_ack("a")
    assert effectsan.pending_count() == 0


def test_canonical_order_is_clean(armed):
    effectsan.note_mutate("StoreServer.create")
    assert effectsan.pending_count() == 1
    effectsan.note_append("StoreServer._wal_append")
    assert effectsan.pending_count() == 0
    effectsan.note_beacon("Replicator.log_beacon")
    effectsan.note_ship("Replicator.log_append")
    effectsan.note_ack("StoreServer._commit_ack")


@pytest.mark.parametrize("observable,site", [
    (effectsan.note_beacon, "Replicator.log_beacon"),
    (effectsan.note_ship, "Replicator.log_append"),
    (effectsan.note_ack, "StoreServer._commit_ack"),
])
def test_reordered_sequence_raises_at_offending_site(armed, observable, site):
    """The deliberately reordered fixture: an observable effect fired
    while the mutation's WAL append has not happened — the error names
    BOTH the offending site and the un-appended mutation."""
    effectsan.note_mutate("StoreServer.update")
    with pytest.raises(EffectOrderError) as e:
        observable(site)
    msg = str(e.value)
    assert site in msg
    assert "StoreServer.update" in msg
    # the raise resets the thread's state so a caught error cannot
    # cascade into unrelated requests on the same handler thread
    assert effectsan.pending_count() == 0


def test_second_mutation_before_append_still_one_window(armed):
    effectsan.note_mutate("a")
    effectsan.note_mutate("b")
    assert effectsan.pending_count() == 2
    effectsan.note_append("wal")
    assert effectsan.pending_count() == 0
    effectsan.note_ack("ack")  # both covered by the single append


def test_abandon_clears_pending_for_reused_handler_thread(armed):
    """The except-Exception 500-reply shape: the failed request is never
    acked, so its pending mutation must not leak into the next request
    served by the same keep-alive thread."""
    effectsan.note_mutate("StoreServer.patch")
    effectsan.abandon("Handler.500")
    assert effectsan.pending_count() == 0
    effectsan.note_ack("StoreServer._commit_ack")  # next request: clean


def test_pending_state_is_thread_local(armed):
    effectsan.note_mutate("main-thread")
    seen = {}

    def other():
        seen["pending"] = effectsan.pending_count()
        effectsan.note_ack("other-thread")  # no pending HERE: clean

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["pending"] == 0
    assert effectsan.pending_count() == 1
    effectsan.note_append("wal")


def test_instrumented_server_is_clean_under_the_flag(monkeypatch, tmp_path):
    """End-to-end leg: the real StoreServer's instrumented verb paths
    (create / update / patch / delete / ack) run green with the sanitizer
    armed — the production ordering satisfies its own runtime check."""
    monkeypatch.setenv(effectsan.ENV_FLAG, "1")
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.server import StoreServer

    from tests.helpers import build_pod

    srv = StoreServer(state_path=str(tmp_path / "state.json"),
                      save_interval=3600, wal=True).start()
    try:
        rs = RemoteStore(srv.url)
        rs.create("Pod", build_pod("p0"))
        rs.create("Pod", build_pod("p1"))
        rs.patch("Pod", "default/p0", {"node_name": "n0"})
        rs.delete("Pod", "default/p1")
    finally:
        srv.stop()
