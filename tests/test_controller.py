"""Job controller lifecycle through the simulated cluster.

Mirrors the reference controller path (SURVEY.md §3.3/3.4): Job created ->
PodGroup -> enqueue -> pods -> gang bind -> Running; plus failure policies
(RestartJob with MaxRetry), abort/resume commands, and TaskCompleted.
"""

import pytest

from volcano_tpu.api.job import (
    Job,
    JobSpec,
    LifecyclePolicy,
    TaskSpec,
)
from volcano_tpu.api.objects import Command, Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase, PodPhase
from volcano_tpu.sim import Cluster


def mk_job(name, tasks, min_available=None, policies=None, plugins=None,
           max_retry=3, queue="default"):
    specs = [
        TaskSpec(
            name=tname,
            replicas=replicas,
            template=PodSpec(image="busybox",
                             resources=Resource.from_resource_list(req)),
            policies=tpolicies or [],
        )
        for tname, replicas, req, tpolicies in tasks
    ]
    total = sum(t.replicas for t in specs)
    return Job(
        meta=Metadata(name=name, namespace="test"),
        spec=JobSpec(
            min_available=min_available if min_available is not None else total,
            tasks=specs,
            policies=policies or [],
            plugins=plugins or {},
            queue=queue,
            max_retry=max_retry,
        ),
    )


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(2):
        c.add_node(f"n{i}", {"cpu": "4", "memory": "8Gi", "pods": 110})
    return c


def test_restarted_controller_finishes_partial_gang(cluster):
    """Regression (found by the chaos soak's mid-body-cut plan): a
    controller that crashed after the PodGroup went Inqueue but before the
    gang's pods were all created must FINISH the gang on restart — the
    Pending->Inqueue transition event is gone, so the rebuilt controller's
    list+watch seed has to drive the enqueue-sync from the PodGroup's
    current phase."""
    from volcano_tpu.controller import JobController

    job = mk_job("partial", [("main", 2, {"cpu": "1", "memory": "1Gi"}, None)])
    cluster.store.create("Job", job)
    cluster.pump_controller()      # create_job: PodGroup appears
    cluster.scheduler.run_once()   # enqueue action: PodGroup -> Inqueue

    # the next pump creates the gang's pods; cut the bus after the FIRST
    # pod commits (what a mid-body response cut does over HTTP) — the
    # sync aborts with half a gang and the job still Pending
    real_create = cluster.store.create

    def cut_after_commit(kind, obj):
        out = real_create(kind, obj)
        if kind == "Pod":
            raise ConnectionResetError("chaos: response cut after commit")
        return out

    cluster.store.create = cut_after_commit
    with pytest.raises(ConnectionResetError):
        cluster.pump_controller()
    cluster.store.create = real_create
    assert len(cluster.store.list("Pod")) == 1
    assert cluster.store.get("Job", "test/partial").status.state.phase \
        == JobPhase.PENDING

    cluster.controller = JobController(cluster.store)  # fresh process
    cluster.run_until_idle()
    job = cluster.store.get("Job", "test/partial")
    assert job.status.state.phase == JobPhase.RUNNING
    assert job.status.running == 2


def test_job_reaches_running(cluster):
    job = mk_job("j1", [("main", 3, {"cpu": "1", "memory": "1Gi"}, None)])
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.RUNNING
    assert job.status.running == 3
    pods = cluster.store.list("Pod")
    assert len(pods) == 3
    assert all(p.phase == PodPhase.RUNNING and p.node_name for p in pods)
    # PodGroup created by the controller with gang minMember
    pg = cluster.store.get("PodGroup", "test/j1")
    assert pg is not None and pg.min_member == 3


def test_gang_insufficient_stays_pending(cluster):
    # 2 nodes x 4 cpu; gang of 5 x 2cpu can never fully fit
    job = mk_job("big", [("w", 5, {"cpu": "2", "memory": "1Gi"}, None)])
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    assert job.status.state.phase in (JobPhase.INQUEUE, JobPhase.PENDING)
    pods = cluster.store.list("Pod")
    # no partial gang binding
    assert all(not p.node_name for p in pods)


def test_pod_failure_restart_policy(cluster):
    job = mk_job(
        "r1",
        [("main", 2, {"cpu": "1", "memory": "1Gi"}, None)],
        policies=[LifecyclePolicy(action=JobAction.RESTART_JOB,
                                  event=JobEvent.POD_FAILED)],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING
    version_before = job.status.version

    cluster.fail_pod("test/r1-main-0", exit_code=137)
    cluster.run_until_idle()

    # job was killed (version bump) and came back to Running with fresh pods
    assert job.status.version > version_before
    assert job.status.state.phase == JobPhase.RUNNING
    assert job.status.retry_count >= 1
    pods = cluster.store.list("Pod")
    assert len(pods) == 2
    assert all(p.phase == PodPhase.RUNNING for p in pods)


def test_max_retry_leads_to_failed(cluster):
    job = mk_job(
        "r2",
        [("main", 1, {"cpu": "1", "memory": "1Gi"}, None)],
        policies=[LifecyclePolicy(action=JobAction.RESTART_JOB,
                                  event=JobEvent.POD_FAILED)],
        max_retry=2,
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    for _ in range(4):
        pods = cluster.store.list("Pod")
        if not pods or job.status.state.phase == JobPhase.FAILED:
            break
        cluster.fail_pod(pods[0].meta.key)
        cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.FAILED
    assert job.status.retry_count >= 2


def test_terminate_policy(cluster):
    job = mk_job(
        "t1",
        [("main", 2, {"cpu": "1", "memory": "1Gi"}, None)],
        policies=[LifecyclePolicy(action=JobAction.TERMINATE_JOB,
                                  event=JobEvent.POD_FAILED)],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()
    cluster.fail_pod("test/t1-main-1")
    cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.TERMINATED
    assert cluster.store.list("Pod") == []
    assert cluster.store.get("PodGroup", "test/t1") is None


def test_task_completed_completes_job(cluster):
    job = mk_job(
        "c1",
        [("main", 2, {"cpu": "1", "memory": "1Gi"}, None)],
        policies=[LifecyclePolicy(action=JobAction.COMPLETE_JOB,
                                  event=JobEvent.TASK_COMPLETED)],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    cluster.complete_pod("test/c1-main-0")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING  # task not yet complete

    cluster.complete_pod("test/c1-main-1")
    cluster.run_until_idle()
    assert job.status.state.phase in (JobPhase.COMPLETING, JobPhase.COMPLETED)
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.COMPLETED


def test_abort_resume_via_command(cluster):
    job = mk_job("a1", [("main", 2, {"cpu": "1", "memory": "1Gi"}, None)])
    cluster.store.create("Job", job)
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING

    cluster.store.create(
        "Command",
        Command(
            meta=Metadata(name="abort-a1", namespace="test"),
            action=JobAction.ABORT_JOB.value,
            target=("Job", "a1"),
        ),
    )
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED
    assert cluster.store.list("Pod") == []
    # command executes at most once: it is deleted on receipt
    assert cluster.store.list("Command") == []

    cluster.store.create(
        "Command",
        Command(
            meta=Metadata(name="resume-a1", namespace="test"),
            action=JobAction.RESUME_JOB.value,
            target=("Job", "a1"),
        ),
    )
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING
    assert len(cluster.store.list("Pod")) == 2


def test_version_fencing_drops_stale_pod_events(cluster):
    """Events carrying an old job version must map to SyncJob, not their
    policy action (job_controller_util.go:145-148)."""
    job = mk_job(
        "v1",
        [("main", 1, {"cpu": "1", "memory": "1Gi"}, None)],
        policies=[LifecyclePolicy(action=JobAction.ABORT_JOB,
                                  event=JobEvent.POD_EVICTED)],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    from volcano_tpu.controller.cache import Request
    from volcano_tpu.controller.controller import apply_policies

    stale = Request("test", "v1", task_name="main",
                    event=JobEvent.POD_EVICTED, job_version=job.status.version - 1)
    live = Request("test", "v1", task_name="main",
                   event=JobEvent.POD_EVICTED, job_version=job.status.version)
    assert apply_policies(job, stale) == JobAction.SYNC_JOB
    assert apply_policies(job, live) == JobAction.ABORT_JOB


def test_volume_claims_stable_across_restarts(cluster):
    from volcano_tpu.api.job import VolumeSpec

    job = mk_job(
        "vol1",
        [("main", 1, {"cpu": "1", "memory": "1Gi"}, None)],
        policies=[LifecyclePolicy(action=JobAction.RESTART_JOB,
                                  event=JobEvent.POD_FAILED)],
    )
    job.spec.volumes = [VolumeSpec(mount_path="/data", size="10Gi")]
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    pvcs = cluster.store.list("PVC")
    assert len(pvcs) == 1
    claim = pvcs[0].meta.name
    pod = cluster.store.list("Pod")[0]
    assert claim in pod.volumes

    cluster.fail_pod("test/vol1-main-0")
    cluster.run_until_idle()
    # restart must reuse the same claim, not mint orphans
    assert [p.meta.name for p in cluster.store.list("PVC")] == [claim]


def test_quiesces_without_controller():
    # no watcher on PodGroup: no-op status writes must still be suppressed
    c = Cluster(with_controller=False)
    c.add_queue("default")
    c.add_node("n0", {"cpu": "4", "memory": "8Gi"})
    from volcano_tpu.api.objects import Metadata, PodGroup

    c.store.create("PodGroup", PodGroup(meta=Metadata(name="pg", namespace="test")))
    c.run_until_idle()


def test_unknown_command_action_ignored(cluster):
    job = mk_job("u1", [("main", 1, {"cpu": "1", "memory": "1Gi"}, None)])
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    cluster.store.create(
        "Command",
        Command(meta=Metadata(name="bogus", namespace="test"),
                action="NotAnAction", target=("Job", "u1")),
    )
    cluster.run_until_idle()  # must not raise
    assert job.status.state.phase == JobPhase.RUNNING


def test_svc_ssh_env_plugins(cluster):
    job = mk_job(
        "p1",
        [("ps", 1, {"cpu": "1", "memory": "1Gi"}, None),
         ("worker", 2, {"cpu": "1", "memory": "1Gi"}, None)],
        plugins={"svc": [], "ssh": [], "env": []},
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    hostfile = cluster.store.get("ConfigMap", "test/p1-svc")
    assert hostfile is not None
    assert hostfile.data["ps.host"] == "p1-ps-0.p1"
    assert hostfile.data["worker.host"] == "p1-worker-0.p1\np1-worker-1.p1"
    assert cluster.store.get("Service", "test/p1") is not None

    sshcm = cluster.store.get("ConfigMap", "test/p1-ssh")
    assert sshcm is not None
    assert {"id_rsa", "id_rsa.pub", "authorized_keys", "config"} <= set(sshcm.data)

    pods = {p.meta.name: p for p in cluster.store.list("Pod")}
    assert pods["p1-worker-1"].env["VT_TASK_INDEX"] == "1"
    assert pods["p1-worker-1"].hostname == "p1-worker-1"
    assert pods["p1-worker-1"].subdomain == "p1"
    assert "p1-svc" in pods["p1-ps-0"].volumes
    assert "p1-ssh" in pods["p1-ps-0"].volumes

    # teardown removes plugin resources
    cluster.store.create(
        "Command",
        Command(meta=Metadata(name="kill-p1", namespace="test"),
                action=JobAction.TERMINATE_JOB.value, target=("Job", "p1")),
    )
    cluster.run_until_idle()
    assert cluster.store.get("ConfigMap", "test/p1-svc") is None
    assert cluster.store.get("Service", "test/p1") is None
