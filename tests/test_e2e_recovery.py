"""Full-occupancy gang scheduling and control-plane restart recovery.

Mirrors the reference e2e scenarios the suite didn't yet cover:
  * "Gang scheduling: full occupied" (test/e2e/job_scheduling.go:118) — a
    gang sized exactly to cluster capacity fills it completely;
  * checkpoint/resume (SURVEY.md §5): both binaries rebuild all in-memory
    state from the store on restart (the reference's WaitForCacheSync
    warm-up from etcd/informers) — a mid-flight workload finishes after
    the scheduler and controller are replaced by fresh instances.
"""

import pytest

from volcano_tpu.api.job import Job, JobSpec, LifecyclePolicy, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase, PodPhase
from volcano_tpu.controller import JobController
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.sim import Cluster


def mk_job(name, replicas, cpu="1", min_available=None, policies=None):
    return Job(
        meta=Metadata(name=name, namespace="test"),
        spec=JobSpec(
            min_available=min_available if min_available is not None else replicas,
            tasks=[
                TaskSpec(
                    name="main",
                    replicas=replicas,
                    template=PodSpec(
                        image="busybox",
                        resources=Resource.from_resource_list(
                            {"cpu": cpu, "memory": "1Gi"}
                        )
                    ),
                )
            ],
            policies=policies or [],
            queue="default",
        ),
    )


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(2):
        c.add_node(f"n{i}", {"cpu": "4", "memory": "16Gi", "pods": 110})
    return c


def test_gang_full_occupied(cluster):
    """A gang sized exactly to cluster CPU capacity (8 x 1cpu on 2 x 4cpu)
    binds completely — no deadlock at 100% occupancy (job_scheduling.go:118)."""
    cluster.store.create("Job", mk_job("occupy", 8))
    cluster.run_until_idle()

    job = cluster.store.get("Job", "test/occupy")
    assert job.status.state.phase == JobPhase.RUNNING
    pods = cluster.store.list("Pod")
    assert len(pods) == 8 and all(p.phase == PodPhase.RUNNING for p in pods)
    # capacity is genuinely exhausted: a 1-cpu follow-up stays pending
    cluster.store.create("Job", mk_job("late", 1))
    cluster.run_until_idle()
    late_pods = [p for p in cluster.store.list("Pod") if "late" in p.meta.name]
    assert all(not p.node_name for p in late_pods)


def test_control_plane_restart_mid_flight(cluster):
    """Kill and replace scheduler + controller while a job is half-created:
    the fresh instances rebuild state from the store and finish the job."""
    cluster.store.create("Job", mk_job("resume", 4))
    # advance only until the PodGroup is Inqueue and pods exist, stopping
    # before the gang binds (pump controller + one scheduler cycle, no kubelet)
    cluster.pump_controller()
    cluster.scheduler.run_once()
    cluster.pump_controller()

    # "crash": brand-new processes — all in-memory state lost
    cluster.scheduler = Scheduler(cluster.store, conf=full_conf())
    cluster.controller = JobController(cluster.store)

    cluster.run_until_idle()
    job = cluster.store.get("Job", "test/resume")
    assert job.status.state.phase == JobPhase.RUNNING
    assert job.status.running == 4


def test_restarted_controller_still_applies_policies(cluster):
    """Version fencing and lifecycle policies survive a controller restart
    because Job.status (version, retries) lives in the store."""
    job = mk_job(
        "pol", 2,
        policies=[LifecyclePolicy(event=JobEvent.POD_FAILED,
                                  action=JobAction.RESTART_JOB)],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()
    assert cluster.store.get("Job", "test/pol").status.state.phase == JobPhase.RUNNING

    cluster.controller = JobController(cluster.store)  # restart

    victim = cluster.store.list("Pod")[0]
    cluster.fail_pod(victim.meta.key, exit_code=137)
    cluster.run_until_idle()

    job = cluster.store.get("Job", "test/pol")
    assert job.status.state.phase == JobPhase.RUNNING  # restarted and recovered
    assert job.status.retry_count >= 1
    # the restart bumped the fencing version
    assert job.status.version >= 1


def test_scheduler_restart_keeps_full_occupancy_consistent(cluster):
    """After a scheduler restart at 100% occupancy, the fresh cache must
    see all capacity used (state rebuilt from pods) and bind nothing new."""
    cluster.store.create("Job", mk_job("full", 8))
    cluster.run_until_idle()
    assert cluster.store.get("Job", "test/full").status.state.phase == JobPhase.RUNNING

    cluster.scheduler = Scheduler(cluster.store, conf=full_conf())
    cluster.store.create("Job", mk_job("waiting", 2))
    cluster.run_until_idle()

    waiting = [p for p in cluster.store.list("Pod") if "waiting" in p.meta.name]
    assert all(not p.node_name for p in waiting)
    # no double-booking: resident pods unchanged
    running = [p for p in cluster.store.list("Pod") if p.phase == PodPhase.RUNNING]
    assert len(running) == 8
