"""Enqueue action admission gate (reference
KB/pkg/scheduler/actions/enqueue/enqueue.go:42-128): Pending PodGroups move
to Inqueue only when cluster idle capacity with the 1.2x overcommit factor
covers their MinResources; admitted groups consume from the budget within
the cycle.
"""

from volcano_tpu.api.objects import Metadata, PodGroup
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import PodGroupPhase, PodPhase
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import build_node, build_pod, build_queue, make_store


def mk_pg(name, min_cpu):
    pg = PodGroup(
        meta=Metadata(name=name, namespace="default"),
        min_member=1,
        queue="default",
        min_resources=Resource.from_resource_list({"cpu": str(min_cpu)}),
    )
    pg.status.phase = PodGroupPhase.PENDING
    return pg


def run_enqueue(podgroups, running_cpu=8):
    # one 10-cpu node with `running_cpu` already used:
    # overcommit budget = 10 * 1.2 - running_cpu
    pods = [
        build_pod(f"busy-{i}", group="busy", cpu="1",
                  phase=PodPhase.RUNNING, node_name="n0")
        for i in range(running_cpu)
    ]
    busy = PodGroup(meta=Metadata(name="busy", namespace="default"),
                    min_member=1, queue="default")
    busy.status.phase = PodGroupPhase.RUNNING
    store = make_store(
        nodes=[build_node("n0", cpu="10", memory="64Gi")],
        queues=[build_queue("default")],
        podgroups=[busy, *podgroups],
        pods=pods,
    )
    conf = full_conf()
    conf.actions = ["enqueue"]
    Scheduler(store, conf=conf).run_once()
    return {pg.meta.name: pg.status.phase for pg in store.list("PodGroup")}


def test_min_resources_within_overcommit_enqueues():
    # budget = 10 * 1.2 - 8 = 4 cpu
    phases = run_enqueue([mk_pg("fits", 4)])
    assert phases["fits"] == PodGroupPhase.INQUEUE


def test_min_resources_beyond_overcommit_stays_pending():
    phases = run_enqueue([mk_pg("too-big", 5)])
    assert phases["too-big"] == PodGroupPhase.PENDING


def test_admitted_group_consumes_budget():
    # 3 + 3 fits within the 4-cpu budget only once: first (by creation
    # order) admits, second waits
    phases = run_enqueue([mk_pg("first", 3), mk_pg("second", 3)])
    assert phases["first"] == PodGroupPhase.INQUEUE
    assert phases["second"] == PodGroupPhase.PENDING


def test_empty_min_resources_always_enqueues():
    phases = run_enqueue([mk_pg("free", 0)], running_cpu=10)
    assert phases["free"] == PodGroupPhase.INQUEUE
