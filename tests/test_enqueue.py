"""Enqueue action admission gate (reference
KB/pkg/scheduler/actions/enqueue/enqueue.go:42-128): Pending PodGroups move
to Inqueue only when cluster idle capacity with the 1.2x overcommit factor
covers their MinResources; admitted groups consume from the budget within
the cycle.
"""

from volcano_tpu.api.objects import Metadata, PodGroup
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import PodGroupPhase, PodPhase
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import build_node, build_pod, build_queue, make_store


def mk_pg(name, min_cpu):
    pg = PodGroup(
        meta=Metadata(name=name, namespace="default"),
        min_member=1,
        queue="default",
        min_resources=Resource.from_resource_list({"cpu": str(min_cpu)}),
    )
    pg.status.phase = PodGroupPhase.PENDING
    return pg


def run_enqueue(podgroups, running_cpu=8):
    # one 10-cpu node with `running_cpu` already used:
    # overcommit budget = 10 * 1.2 - running_cpu
    pods = [
        build_pod(f"busy-{i}", group="busy", cpu="1",
                  phase=PodPhase.RUNNING, node_name="n0")
        for i in range(running_cpu)
    ]
    busy = PodGroup(meta=Metadata(name="busy", namespace="default"),
                    min_member=1, queue="default")
    busy.status.phase = PodGroupPhase.RUNNING
    store = make_store(
        nodes=[build_node("n0", cpu="10", memory="64Gi")],
        queues=[build_queue("default")],
        podgroups=[busy, *podgroups],
        pods=pods,
    )
    conf = full_conf()
    conf.actions = ["enqueue"]
    Scheduler(store, conf=conf).run_once()
    return {pg.meta.name: pg.status.phase for pg in store.list("PodGroup")}


def test_min_resources_within_overcommit_enqueues():
    # budget = 10 * 1.2 - 8 = 4 cpu
    phases = run_enqueue([mk_pg("fits", 4)])
    assert phases["fits"] == PodGroupPhase.INQUEUE


def test_min_resources_beyond_overcommit_stays_pending():
    phases = run_enqueue([mk_pg("too-big", 5)])
    assert phases["too-big"] == PodGroupPhase.PENDING


def test_admitted_group_consumes_budget():
    # 3 + 3 fits within the 4-cpu budget only once: first (by creation
    # order) admits, second waits
    phases = run_enqueue([mk_pg("first", 3), mk_pg("second", 3)])
    assert phases["first"] == PodGroupPhase.INQUEUE
    assert phases["second"] == PodGroupPhase.PENDING


def test_empty_min_resources_always_enqueues():
    phases = run_enqueue([mk_pg("free", 0)], running_cpu=10)
    assert phases["free"] == PodGroupPhase.INQUEUE


def test_unconditional_jobs_occupy_round_robin_turns():
    """An unconditionally-admitted group (empty MinResources) still
    occupies its queue's turn in the budget round-robin: queue A's
    budgeted job is visited in round 1 — AFTER queue B's round-0 job has
    consumed the budget — on both the object and fast paths (enqueue.go
    pops one group per queue per round regardless of admission class)."""
    def mk(name, queue, min_cpu):
        pg = PodGroup(
            meta=Metadata(name=name, namespace="default"),
            min_member=1, queue=queue,
            min_resources=Resource.from_resource_list(
                {"cpu": str(min_cpu)} if min_cpu else {}
            ),
        )
        pg.status.phase = PodGroupPhase.PENDING
        return pg

    def run(backend):
        pods = [
            build_pod(f"busy-{i}", group="busy", cpu="1",
                      phase=PodPhase.RUNNING, node_name="n0")
            for i in range(8)
        ]
        busy = PodGroup(meta=Metadata(name="busy", namespace="default"),
                        min_member=1, queue="qa")
        busy.status.phase = PodGroupPhase.RUNNING
        store = make_store(
            nodes=[build_node("n0", cpu="10", memory="64Gi")],
            queues=[build_queue("qa"), build_queue("qb"),
                    build_queue("default")],
            # creation order: ua before ba within qa
            podgroups=[busy, mk("ua", "qa", 0), mk("ba", "qa", 3),
                       mk("bb", "qb", 3)],
            pods=pods,
        )
        conf = full_conf(backend)
        conf.actions = ["enqueue", "allocate"]
        sched = Scheduler(store, conf=conf)
        sched.run_once()
        if backend == "tpu":
            assert sched.fast_cycle and sched.fast_cycle.mirror is not None
        return {pg.meta.name: pg.status.phase
                for pg in store.list("PodGroup")}

    for backend in ("host", "tpu"):
        phases = run(backend)
        # budget = 10*1.2 - 8 = 4 cpu: round 0 visits ua (free) and bb
        # (takes 3); round 1 visits ba (3 > 1 left -> stays Pending)
        assert phases["ua"] == PodGroupPhase.INQUEUE, backend
        assert phases["bb"] == PodGroupPhase.INQUEUE, backend
        assert phases["ba"] == PodGroupPhase.PENDING, backend


def test_shadow_gang_rows_released_on_pod_churn():
    """Plain-pod shadow gang rows are refcounted: deleting the last member
    releases the row (no unbounded mirror growth under churn); a
    PDB-backed gang outlives its pods like the object builder's."""
    from volcano_tpu.api.objects import Metadata as Meta, PodDisruptionBudget
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    store = make_store(nodes=[build_node("n0")],
                       queues=[build_queue("default")],
                       podgroups=[], pods=[])
    m = ArrayMirror(store, "volcano-tpu", "default")
    m.drain()
    store.create("PodDisruptionBudget", PodDisruptionBudget(
        meta=Meta(name="budget", namespace="default",
                  owner=("ReplicaSet", "rs-z")),
        min_available=2,
    ))
    for i in range(3):
        p = build_pod(f"loose-{i}", cpu="100m")
        if i > 0:
            p.meta.owner = ("ReplicaSet", "rs-z")
        store.create("Pod", p)
    m.drain()
    assert "shadow/default/loose-0" in m.jobs.key_row
    assert "shadow/default/rs-z" in m.jobs.key_row
    for i in range(3):
        store.delete("Pod", f"default/loose-{i}")
    m.drain()
    # per-pod shadow released; PDB-backed shadow persists with min intact
    assert "shadow/default/loose-0" not in m.jobs.key_row
    rs_row = m.jobs.key_row["shadow/default/rs-z"]
    assert m.j_live[rs_row] and m.j_min[rs_row] == 2
    store.delete("PodDisruptionBudget", "default/budget")
    m.drain()
    assert "shadow/default/rs-z" not in m.jobs.key_row
