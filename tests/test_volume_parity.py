"""Device volume solve + vectorized residue engine parity (r6).

The r5 host-residue cost curve (BASELINE.md) made volume-constrained
pods the last multi-minute path; r6 moves the count-expressible claim
shapes onto the device (volsolve.py + the allocate kernel's volsel
extension) and vectorizes whatever still falls out
(scheduler/residue.py).  These suites pin both halves to the host
oracle bit-for-bit:

  * device volume solve vs the pure host object-session path — bound-PVC
    pinning, PV nodeAffinity sets, attach-capacity exhaustion,
    WaitForFirstConsumer dynamic classes, and the VolumeBindingError
    concurrent-rebind race;
  * the vectorized residue engine vs the per-task loop on a seeded mixed
    cluster (placements, statuses, fit-error histograms), including the
    >= 10x per-task speedup on a 10k-node cluster;
  * the non-constraining regression: emptyDir-style / dynamic-class
    volumes stay array-native (the fastpath classifier fix).
"""

import time

import pytest

from tests.helpers import (
    FakeBinder,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)
from volcano_tpu.api.objects import (
    Metadata,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler


def _run(store, backend="tpu"):
    conf = default_conf(backend=backend)
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder.binds


def _add_pool(store, class_name, pins, capacity="20Gi", prefix="pool"):
    store.create("StorageClass", StorageClass(
        meta=Metadata(name=class_name, namespace=""), provisioner=""))
    for i, pin in enumerate(pins):
        aff = {"kubernetes.io/hostname": pin} if pin else {}
        store.create("PV", PersistentVolume(
            meta=Metadata(name=f"{prefix}{i}", namespace=""),
            capacity=capacity, storage_class=class_name, node_affinity=aff))


def _vol_job(store, name, n_tasks, claim, min_member=None,
             cpu="1", memory="1Gi"):
    store.create("PodGroup", build_podgroup(
        name, min_member=min_member or n_tasks))
    for t in range(n_tasks):
        p = build_pod(f"{name}-{t}", group=name, cpu=cpu, memory=memory)
        p.volumes = [claim]
        store.create("Pod", p)


# --- device volume solve vs the host oracle ----------------------------------


def _bound_claim_store():
    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(4)]
    store = make_store(nodes=nodes, queues=[build_queue("default")])
    store.create("PV", PersistentVolume(
        meta=Metadata(name="disk2", namespace=""), capacity="20Gi",
        storage_class="net",
        node_affinity={"kubernetes.io/hostname": "n2"},
        claim_ref="default/reused"))
    store.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="reused", namespace="default"), size="5Gi",
        storage_class="net", volume_name="disk2", phase="Bound"))
    _vol_job(store, "pinned", 2, "reused")
    # plus an express job so the solve genuinely mixes partitions
    store.create("PodGroup", build_podgroup("plain", min_member=2))
    for t in range(2):
        store.create("Pod", build_pod(f"plain-{t}", group="plain",
                                      cpu="1", memory="1Gi"))
    return store


def test_bound_pvc_pinning_matches_host_oracle():
    """A gang mounting a claim bound to a node-pinned PV colocates on
    that node, identically on the device path and the host oracle."""
    _, host = _run(_bound_claim_store(), "host")
    sched, fast = _run(_bound_claim_store(), "tpu")
    assert fast == host
    assert {host[f"default/pinned-{t}"] for t in range(2)} == {"n2"}
    # the cycle stayed array-native: no residue sub-cycle phase
    assert "subcycle" not in sched.fast_cycle.phases
    assert "vol_solve" in sched.fast_cycle.phases


def test_pv_node_affinity_set_matches_host_oracle():
    """A bound PV whose affinity is a multi-node ZONE label yields a
    feasible-node SET (not a single pin) — the device bitset must carry
    exactly the matching nodes."""
    def mk():
        nodes = []
        for i in range(6):
            n = build_node(f"n{i}", cpu="8", memory="16Gi",
                           labels={"zone": "a" if i < 2 else "b"})
            nodes.append(n)
        store = make_store(nodes=nodes, queues=[build_queue("default")])
        store.create("PV", PersistentVolume(
            meta=Metadata(name="zoned", namespace=""), capacity="20Gi",
            storage_class="net", node_affinity={"zone": "a"},
            claim_ref="default/zc"))
        store.create("PVC", PersistentVolumeClaim(
            meta=Metadata(name="zc", namespace="default"), size="5Gi",
            storage_class="net", volume_name="zoned", phase="Bound"))
        _vol_job(store, "zj", 3, "zc", min_member=3)
        return store

    _, host = _run(mk(), "host")
    _, fast = _run(mk(), "tpu")
    assert fast == host
    assert all(fast[f"default/zj-{t}"] in ("n0", "n1") for t in range(3))


@pytest.mark.parametrize("network_pool", [False, True])
def test_attach_capacity_exhaustion_matches_host_oracle(network_pool):
    """More claims than pool PVs: exactly pool-many jobs bind, the SAME
    jobs on the SAME nodes as the host oracle — the in-kernel capacity
    decrement replays the binder's assume-cache.  Covers both the
    node-pinned (per-node counts) and network (global count) pools."""
    def mk():
        nodes = [build_node(f"n{i}", cpu="8", memory="16Gi")
                 for i in range(5)]
        store = make_store(nodes=nodes, queues=[build_queue("default")])
        pins = [None, None] if network_pool else ["n1", "n3"]
        _add_pool(store, "local", pins)
        for j in range(3):
            store.create("PVC", PersistentVolumeClaim(
                meta=Metadata(name=f"c{j}", namespace="default"),
                size="5Gi", storage_class="local"))
            _vol_job(store, f"vj{j}", 1, f"c{j}")
        return store

    _, host = _run(mk(), "host")
    sched, fast = _run(mk(), "tpu")
    assert fast == host
    assert len(fast) == 2  # pool of 2 serves exactly 2 single-task gangs
    assert "subcycle" not in sched.fast_cycle.phases


def test_static_shared_claim_colocates_gang_like_host():
    """One pending static claim shared by a whole gang: the first
    placement assumes a node-pinned PV and every sibling must follow to
    its node (the kernel's claim_node state)."""
    def mk():
        nodes = [build_node(f"n{i}", cpu="8", memory="16Gi")
                 for i in range(4)]
        store = make_store(nodes=nodes, queues=[build_queue("default")])
        _add_pool(store, "local", ["n2"])
        store.create("PVC", PersistentVolumeClaim(
            meta=Metadata(name="shared", namespace="default"),
            size="5Gi", storage_class="local"))
        _vol_job(store, "team", 3, "shared")
        return store

    _, host = _run(mk(), "host")
    _, fast = _run(mk(), "tpu")
    assert fast == host
    assert {fast[f"default/team-{t}"] for t in range(3)} == {"n2"}


def test_size_overflow_claim_contends_its_whole_pool():
    """A claim too large for the pool floor goes residue — and every
    DEVICE job competing for the same class pool must follow it there
    (the contention closure): the host oracle serializes both claims'
    assumptions through one session, so a device-side decrement blind to
    the residue side would diverge."""
    def mk():
        nodes = [build_node(f"n{i}", cpu="8", memory="16Gi")
                 for i in range(4)]
        store = make_store(nodes=nodes, queues=[build_queue("default")])
        store.create("StorageClass", StorageClass(
            meta=Metadata(name="local", namespace=""), provisioner=""))
        store.create("PV", PersistentVolume(
            meta=Metadata(name="small", namespace=""), capacity="10Gi",
            storage_class="local",
            node_affinity={"kubernetes.io/hostname": "n1"}))
        store.create("PV", PersistentVolume(
            meta=Metadata(name="big", namespace=""), capacity="50Gi",
            storage_class="local",
            node_affinity={"kubernetes.io/hostname": "n2"}))
        # job A: 5Gi claim (device-expressible on its own)
        store.create("PVC", PersistentVolumeClaim(
            meta=Metadata(name="ca", namespace="default"), size="5Gi",
            storage_class="local"))
        _vol_job(store, "va", 1, "ca")
        # job B: 20Gi claim — only the big PV fits (size > pool floor)
        store.create("PVC", PersistentVolumeClaim(
            meta=Metadata(name="cb", namespace="default"), size="20Gi",
            storage_class="local"))
        _vol_job(store, "vb", 1, "cb")
        return store

    _, host = _run(mk(), "host")
    sched, fast = _run(mk(), "tpu")
    assert fast == host
    # both jobs bound: A on the small PV's node, B on the big PV's
    assert fast["default/va-0"] == "n1" and fast["default/vb-0"] == "n2"
    reasons = sched.fast_cycle.last_residue_reasons
    assert reasons.get("default/vb") == "volume-shape"
    assert reasons.get("default/va") == "contended-claims"


def test_wait_for_first_consumer_dynamic_class_stays_express(monkeypatch):
    """Dynamic-class (WaitForFirstConsumer, provisioner set) claims never
    constrain: the job rides the EXPRESS solve — no residue sub-cycle,
    no dynamic pass — and publish provisions + binds the PV."""
    calls = []
    monkeypatch.setattr(
        Scheduler, "run_object_residue",
        lambda self, keys, preempt: calls.append(set(keys)),
    )

    def mk():
        nodes = [build_node(f"n{i}", cpu="8", memory="16Gi")
                 for i in range(3)]
        store = make_store(nodes=nodes, queues=[build_queue("default")])
        store.create("PVC", PersistentVolumeClaim(
            meta=Metadata(name="dyn", namespace="default"), size="10Gi",
            storage_class="standard"))  # no SC object, no PVs: dynamic
        _vol_job(store, "dj", 2, "dyn")
        return store

    _, host = _run(mk(), "host")
    store = mk()
    conf = default_conf(backend="tpu")
    sched = Scheduler(store, conf=conf)
    sched.run_once()  # real binder: publish writes the store
    binds = {p.meta.key: p.node_name for p in store.list("Pod")
             if p.node_name}
    assert binds == host
    assert calls == []
    assert "dyn_solve" not in sched.fast_cycle.phases
    pvc = store.get("PVC", "default/dyn")
    assert pvc.phase == "Bound" and pvc.volume_name
    pv = store.get("PV", f"/{pvc.volume_name}")
    assert pv is not None and pv.claim_ref == "default/dyn"


def test_thousand_task_job_with_nonconstraining_volumes_stays_array_native(
    monkeypatch,
):
    """The fastpath classifier fix (fastpath.py:_pod_dynamic): a 1k-task
    job whose pods mount claim-less (emptyDir/configMap-style) volumes
    must keep the express path — spied residue set stays empty and every
    pod binds in one array-native cycle."""
    calls = []
    monkeypatch.setattr(
        Scheduler, "run_object_residue",
        lambda self, keys, preempt: calls.append(set(keys)),
    )
    nodes = [build_node(f"n{i}", cpu="64", memory="128Gi", pods=200)
             for i in range(10)]
    store = make_store(nodes=nodes, queues=[build_queue("default")])
    store.create("PodGroup", build_podgroup("big", min_member=1000))
    for t in range(1000):
        p = build_pod(f"big-{t}", group="big", cpu="100m", memory="64Mi")
        p.volumes = ["scratch"]  # no PVC object: never constrains
        store.create("Pod", p)
    sched, binds = _run(store, "tpu")
    assert calls == []
    assert len(binds) == 1000
    fc = sched.fast_cycle
    assert fc.mirror is not None and "subcycle" not in fc.phases
    assert not fc.last_residue_reasons


def test_volume_binding_error_concurrent_rebind_race(monkeypatch):
    """A concurrent writer steals the pool's PV between the device solve
    and publish: allocate_volumes raises VolumeBindingError, the bind is
    DROPPED (validation, not placement), nothing crashes, and the pod
    recovers on a later cycle once capacity returns."""
    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(3)]
    store = make_store(nodes=nodes, queues=[build_queue("default")])
    _add_pool(store, "local", ["n1"])
    store.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="c0", namespace="default"), size="5Gi",
        storage_class="local"))
    _vol_job(store, "racer", 1, "c0")

    from volcano_tpu.scheduler import tensor_actions

    orig = tensor_actions.jax_dynamic_solve
    stolen = []

    def stealing(backend, snap, dyn, n_pending=None):
        out = orig(backend, snap, dyn, n_pending)
        if not stolen:
            pv = store.get("PV", "/pool0")
            pv.claim_ref = "other/claim"  # concurrent rebind
            store.update("PV", pv)
            stolen.append(True)
        return out

    monkeypatch.setattr(tensor_actions, "jax_dynamic_solve", stealing)
    conf = default_conf(backend="tpu")
    sched = Scheduler(store, conf=conf)
    sched.run_once()  # must not raise
    pod = store.get("Pod", "default/racer-0")
    assert pod.node_name == ""
    assert any(op == "bind_volumes" for op, _, _ in sched.cache.err_log)
    pvc = store.get("PVC", "default/c0")
    assert pvc.phase == "Pending" and not pvc.volume_name
    # capacity returns: a later cycle binds cleanly
    store.create("PV", PersistentVolume(
        meta=Metadata(name="fresh", namespace=""), capacity="20Gi",
        storage_class="local",
        node_affinity={"kubernetes.io/hostname": "n2"}))
    sched.run_once()
    sched.run_once()
    assert store.get("Pod", "default/racer-0").node_name == "n2"


def test_batch_wave_demotes_volume_jobs_to_residue_engine():
    """solveMode batch (and auto waves above the batch threshold): volume
    jobs step aside to the vectorized residue engine so the dynamic wave
    keeps the batched-rounds kernel (volsel forces the exact kernel) —
    everything still binds, with the ``batch-wave`` reason class."""
    from volcano_tpu.api.objects import Affinity

    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(4)]
    store = make_store(nodes=nodes, queues=[build_queue("default")])
    _add_pool(store, "local", ["n2"])
    store.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="bc", namespace="default"), size="5Gi",
        storage_class="local"))
    _vol_job(store, "volj", 2, "bc")
    # a port/affinity wave sharing the cycle
    store.create("PodGroup", build_podgroup("wave", min_member=3))
    for t in range(3):
        p = build_pod(f"w{t}", group="wave", cpu="1", memory="1Gi",
                      labels={"app": "w"})
        p.spec.affinity = Affinity(pod_anti_affinity=[{"app": "w"}])
        store.create("Pod", p)
    conf = default_conf(backend="tpu")
    conf.solve_mode = "batch"
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    binds = {p.meta.key: p.node_name for p in store.list("Pod")
             if p.node_name}
    assert {binds[f"default/volj-{t}"] for t in range(2)} == {"n2"}
    assert len({binds[f"default/w{t}"] for t in range(3)}) == 3
    assert sched.fast_cycle.last_residue_reasons == {
        "default/volj": "batch-wave"
    }


def test_no_vol_phase_or_residue_on_volume_free_cycles():
    """cfg5-class regression guard: a cycle with zero volume pods grows
    no vol_solve / residue_vec / subcycle phase."""
    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(3)]
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=[build_podgroup("pg", min_member=2)],
                       pods=[build_pod(f"p{t}", group="pg") for t in range(2)])
    sched, binds = _run(store, "tpu")
    assert len(binds) == 2
    for phase in ("vol_solve", "residue_vec", "subcycle", "dyn_solve"):
        assert phase not in sched.fast_cycle.phases


# --- vectorized residue engine vs the per-task loop --------------------------


def _mixed_residue_store(n_nodes=8, n_jobs=6):
    """Seeded mixed cluster: labeled/tainted nodes, labeled+ported
    residents (one deleting, so a releasing pool exists), and pending
    jobs spanning ports, (anti)affinity, selectors, and inexpressible
    volume shapes — the residue engine's whole predicate surface."""
    import random

    from volcano_tpu.api.objects import Affinity, Taint, Toleration
    from volcano_tpu.api.types import PodPhase

    rng = random.Random(7)
    nodes = []
    for i in range(n_nodes):
        n = build_node(
            f"n{i}", cpu=str(rng.choice([4, 8])),
            memory=f"{rng.choice([8, 16])}Gi",
            labels={"zone": "a" if i % 2 else "b"},
        )
        if i == 0:
            n.taints.append(Taint(key="dedicated", value="x"))
        nodes.append(n)
    store = make_store(nodes=nodes, queues=[build_queue("default"),
                                            build_queue("batch", weight=2)])
    store.create("PodGroup", build_podgroup("res", min_member=1))
    for i in range(5):
        p = build_pod(f"res-{i}", group="res", cpu="1", memory="1Gi",
                      labels=rng.choice([{"app": "web"}, {"app": "db"}, {}]))
        if i % 2 == 0:
            p.spec.host_ports = [8000 + i]
        p.node_name = f"n{rng.randrange(1, n_nodes)}"
        p.phase = PodPhase.RUNNING
        if i == 4:
            p.deleting = True  # releasing resident: pipeline path exists
        store.create("Pod", p)
    # an inexpressible volume shape (mixed pinned+network pool)
    store.create("StorageClass", StorageClass(
        meta=Metadata(name="mixed", namespace=""), provisioner=""))
    store.create("PV", PersistentVolume(
        meta=Metadata(name="mp0", namespace=""), capacity="20Gi",
        storage_class="mixed",
        node_affinity={"kubernetes.io/hostname": "n2"}))
    store.create("PV", PersistentVolume(
        meta=Metadata(name="mp1", namespace=""), capacity="20Gi",
        storage_class="mixed"))
    for j in range(n_jobs):
        kind = ["ports", "aff", "anti", "vol", "sel", "plain"][j % 6]
        n_tasks = rng.randint(1, 3)
        queue = "batch" if j % 3 == 0 else "default"
        store.create("PodGroup", build_podgroup(
            f"rj{j}", min_member=rng.randint(1, n_tasks), queue=queue))
        if kind == "vol":
            store.create("PVC", PersistentVolumeClaim(
                meta=Metadata(name=f"mc{j}", namespace="default"),
                size="5Gi", storage_class="mixed"))
        for t in range(n_tasks):
            p = build_pod(f"rj{j}-{t}", group=f"rj{j}", cpu="1",
                          memory="1Gi",
                          labels=rng.choice([{"app": "web"}, {}]))
            if kind == "ports":
                p.spec.host_ports = [8000 + (t % 3)]
            elif kind == "aff":
                p.spec.affinity = Affinity(pod_affinity=[{"app": "web"}])
            elif kind == "anti":
                p.spec.affinity = Affinity(
                    pod_anti_affinity=[{"app": "db"}])
            elif kind == "vol":
                p.volumes = [f"mc{j}"]
            elif kind == "sel":
                p.spec.node_selector = {"zone": "a"}
                p.spec.tolerations = [
                    Toleration(key="dedicated", operator="Exists")
                ]
            store.create("Pod", p)
    return store


def _residue_pass(store, vectorized):
    from volcano_tpu.scheduler.actions.allocate import AllocateAction
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.framework import open_session

    cache = SchedulerCache(store)
    ssn = open_session(cache, default_conf().tiers)
    stats = {}
    AllocateAction()._execute_host(
        ssn, job_filter=lambda job: True, vectorized=vectorized,
        stats=stats,
    )
    state = {}
    errors = {}
    for job in ssn.jobs.values():
        for task in job.tasks.values():
            state[task.key] = (task.status.name, task.node_name)
        if job.fit_errors:
            errors[job.uid] = dict(job.fit_errors)
    binds = {p.meta.key: p.node_name for p in store.list("Pod")
             if p.node_name}
    return state, errors, binds, stats


def test_vectorized_residue_bit_for_bit_equals_per_task_loop():
    state_v, errors_v, binds_v, stats = _residue_pass(
        _mixed_residue_store(), vectorized=True)
    state_l, errors_l, binds_l, _ = _residue_pass(
        _mixed_residue_store(), vectorized=False)
    assert stats.get("tasks", 0) > 0, "engine did not run"
    assert state_v == state_l
    assert errors_v == errors_l
    assert binds_v == binds_l


def test_vectorized_residue_10x_faster_per_task_at_10k_nodes():
    """The acceptance bar: the remaining host-residue fallback is >= 10x
    faster per task than the r5 per-task loop on a 10k-node cluster.
    Both sides run the same session shape; the loop is measured on a task
    SLICE (it is the slow side) and compared per task."""
    from volcano_tpu.scheduler.actions.allocate import AllocateAction
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.framework import open_session

    n_nodes, n_tasks, loop_tasks = 10_000, 40, 6

    def mk(n):
        nodes = [build_node(f"n{i:05d}", cpu="8", memory="16Gi")
                 for i in range(n_nodes)]
        store = make_store(nodes=nodes, queues=[build_queue("default")])
        store.create("PodGroup", build_podgroup("slow", min_member=1))
        for t in range(n):
            store.create("Pod", build_pod(
                f"s-{t}", group="slow", cpu="500m", memory="512Mi"))
        return store

    def timed(n, vectorized):
        store = mk(n)
        ssn = open_session(SchedulerCache(store), default_conf().tiers)
        t0 = time.perf_counter()
        AllocateAction()._execute_host(
            ssn, job_filter=lambda job: True, vectorized=vectorized)
        elapsed = time.perf_counter() - t0
        placed = sum(1 for p in store.list("Pod") if p.node_name)
        assert placed == n
        return elapsed / n

    per_task_loop = timed(loop_tasks, vectorized=False)
    per_task_vec = timed(n_tasks, vectorized=True)
    assert per_task_vec * 10 <= per_task_loop, (
        f"vectorized {per_task_vec:.4f}s/task vs loop "
        f"{per_task_loop:.4f}s/task — less than 10x"
    )


def test_residue_counter_exposition_and_monotonicity():
    """volcano_residue_tasks_total{class=...}: appears in the Prometheus
    exposition with the right class label and only ever grows."""
    from volcano_tpu.scheduler import metrics

    metrics.reset()
    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(3)]
    store = make_store(nodes=nodes, queues=[build_queue("default")])
    # mixed pinned+network pool: count-inexpressible -> residue class
    store.create("StorageClass", StorageClass(
        meta=Metadata(name="mixed", namespace=""), provisioner=""))
    store.create("PV", PersistentVolume(
        meta=Metadata(name="a", namespace=""), capacity="20Gi",
        storage_class="mixed",
        node_affinity={"kubernetes.io/hostname": "n1"}))
    store.create("PV", PersistentVolume(
        meta=Metadata(name="b", namespace=""), capacity="1Gi",
        storage_class="mixed"))
    store.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="mc", namespace="default"), size="5Gi",
        storage_class="mixed"))
    store.create("PodGroup", build_podgroup("slowjob", min_member=2))
    for t in range(2):
        p = build_pod(f"sj-{t}", group="slowjob", cpu="1", memory="1Gi")
        p.volumes = ["mc"]
        store.create("Pod", p)
    conf = default_conf(backend="tpu")
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    v1 = metrics.get_counter("volcano_residue_tasks_total",
                             **{"class": "volume-shape"})
    assert v1 > 0
    assert 'volcano_residue_tasks_total{class="volume-shape"}' in (
        metrics.expose_text()
    )
    assert sched.fast_cycle.last_residue_reasons == {
        "default/slowjob": "volume-shape"
    }
    assert sched.fast_cycle.phases.get("residue_vec") is not None
    sched.run_once()
    v2 = metrics.get_counter("volcano_residue_tasks_total",
                             **{"class": "volume-shape"})
    assert v2 >= v1
