"""vtload metrics core: bounded histograms + Prometheus text conformance.

The r8 rebuild replaced the unbounded per-sample lists behind
``metrics.observe()`` with fixed-universe log-linear bucket histograms
and a proper text exposition.  This suite holds the three contracts:

* **conformance** — a mini Prometheus text-format parser asserts
  HELP/TYPE presence, ascending ``le`` with monotone cumulative counts,
  ``le="+Inf"`` == ``_count``, and byte-stable output ordering;
* **boundedness** — a series with 10^6 observations occupies the same
  fixed bucket state as one with 10^2 (ISSUE 9 acceptance), and the
  label-cardinality guard caps per-name series with a dropped counter;
* **readout** — p50/p99/p999 quantiles land within one sub-bucket width
  of the exact answer.
"""

import math
import re

import pytest

from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.metrics import (
    MAX_BUCKETS,
    MAX_SERIES_PER_METRIC,
    SUBBUCKETS,
    Histogram,
)


@pytest.fixture(autouse=True)
def _fresh():
    metrics.reset()
    yield
    metrics.reset()


# --- mini Prometheus text-format parser --------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)$"
)


def parse_prometheus(text: str):
    """(families, samples): family name -> {"help": str, "type": str};
    samples = list of (name, labels dict, float value) in file order.
    Raises AssertionError on malformed lines — the parser IS the
    conformance check."""
    families = {}
    samples = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, {})["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            assert mtype in ("counter", "gauge", "histogram"), line
            families.setdefault(name, {})["type"] = mtype
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        labels = {}
        if m.group("labels"):
            for part in m.group("labels").split(","):
                k, _, v = part.partition("=")
                assert v.startswith('"') and v.endswith('"'), line
                labels[k] = v[1:-1]
        samples.append((m.group("name"), labels, m.group("value")))
    return families, samples


def _family_of(sample_name: str, families) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        base = sample_name[: -len(suffix)] if sample_name.endswith(suffix) \
            else None
        if base and families.get(base, {}).get("type") == "histogram":
            return base
    return sample_name


def test_exposition_conformance_help_type_and_bucket_invariants():
    metrics.inc("volcano_conf_total", 3)
    metrics.set_gauge("volcano_conf_gauge", 1.25, pool="a")
    for v in (0.4, 1.0, 8.0, 8.0, 120.0):
        metrics.observe("volcano_conf_latency_seconds", v, op="x")
    text = metrics.expose_text()
    families, samples = parse_prometheus(text)

    # every sample's family carries HELP and TYPE
    for name, _, _ in samples:
        fam = _family_of(name, families)
        assert "help" in families[fam], fam
        assert "type" in families[fam], fam
    assert families["volcano_conf_total"]["type"] == "counter"
    assert families["volcano_conf_gauge"]["type"] == "gauge"
    assert families["volcano_conf_latency_seconds"]["type"] == "histogram"

    # histogram: le ascending, cumulative monotone, +Inf == _count
    buckets = [(ls["le"], float(v)) for n, ls, v in samples
               if n == "volcano_conf_latency_seconds_bucket"]
    les = [math.inf if le == "+Inf" else float(le) for le, _ in buckets]
    counts = [c for _, c in buckets]
    assert les == sorted(les) and len(set(les)) == len(les)
    assert counts == sorted(counts)
    assert les[-1] == math.inf
    count_v = next(float(v) for n, ls, v in samples
                   if n == "volcano_conf_latency_seconds_count")
    sum_v = next(float(v) for n, ls, v in samples
                 if n == "volcano_conf_latency_seconds_sum")
    assert counts[-1] == count_v == 5
    assert sum_v == pytest.approx(137.4)
    # every observation sits at or below its bucket's le
    assert all(c >= 1 for c in counts)


def test_exposition_byte_stable_ordering():
    def record(order):
        metrics.reset()
        for name, kind in order:
            if kind == "c":
                metrics.inc(name, 1, q=name[-1])
            elif kind == "g":
                metrics.set_gauge(name, 2.0)
            else:
                metrics.observe(name, 0.5)
        return metrics.expose_text()

    series = [("volcano_b_total", "c"), ("volcano_a_seconds", "h"),
              ("volcano_c_gauge", "g"), ("volcano_b_total", "c")]
    t1 = record(series)
    t2 = record(list(reversed(series)))
    assert t1 == t2  # insertion order never leaks into the exposition
    assert metrics.expose_text() == metrics.expose_text()  # and stable


def test_histogram_state_is_bounded_by_buckets_not_observations():
    """THE memory-leak fix: 10^6 observations occupy the same fixed
    bucket state as 10^2 (ISSUE 9 acceptance criterion)."""
    vals = [0.001 * (i % 97 + 1) for i in range(100)]
    small = Histogram()
    for v in vals:
        small.observe(v)
    big = Histogram()
    for i in range(10 ** 6):
        big.observe(vals[i % 100])
    assert len(big.buckets) == len(small.buckets)
    assert len(big.buckets) <= MAX_BUCKETS
    assert big.count == 10 ** 6 and small.count == 100
    # and through the module API: same series, a million more samples,
    # identical bucket-universe bound
    for i in range(1000):
        metrics.observe("volcano_bounded_seconds", vals[i % 100])
    snap = metrics.get_histogram("volcano_bounded_seconds")
    assert snap.count == 1000
    assert len(snap.buckets) <= MAX_BUCKETS


def test_quantile_within_one_subbucket():
    h = Histogram()
    for i in range(1, 10001):
        h.observe(i / 1000.0)  # 1ms .. 10s uniform
    rel = 9.0 / SUBBUCKETS
    for q, exact in ((0.5, 5.0), (0.99, 9.9), (0.999, 9.99)):
        got = h.quantile(q)
        assert exact * (1 - 1e-9) <= got <= exact * (1 + rel + 0.01), (q, got)
    assert h.quantile(1.0) <= h.vmax * (1 + rel)


def test_label_cardinality_guard_caps_series_and_counts_drops():
    for i in range(MAX_SERIES_PER_METRIC + 40):
        metrics.register_job_retry(f"default/job-{i:04d}")
    # the cap held: exactly MAX series exist, the overflow was counted
    text = metrics.expose_text()
    n_series = text.count("volcano_job_retry_counts{")
    assert n_series == MAX_SERIES_PER_METRIC
    assert metrics.get_counter(
        "volcano_metrics_dropped_series_total",
        metric="volcano_job_retry_counts") == 40
    # dropped observations are silent: admitted series keep counting
    metrics.register_job_retry("default/job-0000")
    assert metrics.get_counter("volcano_job_retry_counts",
                               job_id="default/job-0000") == 2
    # histograms are guarded too
    for i in range(MAX_SERIES_PER_METRIC + 5):
        metrics.observe("volcano_guarded_seconds", 0.1, job=f"j{i}")
    assert metrics.get_counter("volcano_metrics_dropped_series_total",
                               metric="volcano_guarded_seconds") == 5


def test_snapshot_list_compat_and_empty_series():
    empty = metrics.get_histogram("volcano_never_observed_seconds")
    assert len(empty) == 0 and list(empty) == [] and not empty
    assert empty.quantile(0.99) == 0.0
    metrics.observe("volcano_compat_seconds", 0.25)
    metrics.observe("volcano_compat_seconds", 0.5)
    snap = metrics.get_histogram("volcano_compat_seconds")
    assert len(snap) == 2
    vals = list(snap)
    assert len(vals) == 2 and all(v >= 0.25 for v in vals)
    assert metrics.quantile("volcano_compat_seconds", 0.5) >= 0.25


def test_wal_fsync_seconds_histogram_exposed(tmp_path):
    """Satellite: group-commit fsync latency is a histogram on /metrics
    (the ``_total`` counters only ever showed volume)."""
    from volcano_tpu.store.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "wal"))
    wal.append_commit({"op": "delete", "kind": "Pod", "key": "a/b"})
    wal.append_commit({"op": "delete", "kind": "Pod", "key": "a/c"})
    wal.sync_close()
    snap = metrics.get_histogram("volcano_store_wal_fsync_seconds")
    assert snap.count >= 2
    assert snap.sum >= 0.0
    text = metrics.expose_text()
    assert 'volcano_store_wal_fsync_seconds_bucket{le="+Inf"}' in text
    assert "volcano_store_wal_fsync_seconds_count" in text
    families, _ = parse_prometheus(text)
    assert families["volcano_store_wal_fsync_seconds"]["type"] == "histogram"
    # fsync volume counter still rides alongside, with the new-name
    # recovery counter family registered under the _total discipline
    assert metrics.get_counter("volcano_store_wal_fsync_total") >= 2


def test_counter_and_histogram_monotone_under_interleaving():
    """Monotonicity across the histogram encoding: count/sum/buckets
    only ever grow (the shape the e2e-latency/WAL/residue tests rely
    on)."""
    for i in range(5):
        metrics.observe("volcano_mono_latency_seconds", 0.01 * (i + 1))
    s1 = metrics.get_histogram("volcano_mono_latency_seconds")
    for i in range(5):
        metrics.observe("volcano_mono_latency_seconds", 0.02 * (i + 1))
    s2 = metrics.get_histogram("volcano_mono_latency_seconds")
    assert s2.count == s1.count + 5
    assert s2.sum > s1.sum
    c1 = dict((le, c) for le, c in s1.buckets)
    c2 = dict((le, c) for le, c in s2.buckets)
    for le, c in c1.items():
        assert c2.get(le, 0) >= c  # cumulative counts never shrink
