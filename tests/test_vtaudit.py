"""vtaudit: the incremental state-digest auditor (PR 13 tentpole).

The gate for continuous divergence detection:

  * the digest algebra: order independence (per-bucket sums commute),
    removal as the exact inverse, field-delta patches equal to a full
    re-digest, and version-counter neutrality (``SKIP_LEAVES``) — the
    invariants every maintenance hook relies on;
  * the corruption drill: flip ONE field of ONE stored object behind
    the verbs' back and the maintained-vs-recompute walk must localize
    it to the exact ``(kind, namespace, name)`` — locally, and over a
    partitioned server via the ``?recompute=1`` debug tier;
  * the mirror half: an ``ArrayMirror`` fed the watch stream maintains
    its own table and ``audit_verify`` reaches digest equality with
    the server (beacon-pinned remotely, lock-synchronous in-process),
    detects tampering, and self-heals by resync;
  * the WAL half: ``replay_wal_digest`` folds a snapshot+WAL lineage
    into the same digest the live server reports;
  * the beacon protocol: seq-pinned checkpoints ride the event log to
    every shard watcher without ever surfacing as objects.
"""

import json
import os
import urllib.request

import pytest

from volcano_tpu import vtaudit
from volcano_tpu.api.objects import Metadata, Queue
from volcano_tpu.store import Store
from volcano_tpu.store.client import RemoteStore
from volcano_tpu.store.server import StoreServer

from tests.helpers import build_node, build_pod, build_podgroup

pytestmark = pytest.mark.skipif(
    not vtaudit.enabled(), reason="digest auditing disarmed in env"
)


def _fetch(url, path):
    with urllib.request.urlopen(url.rstrip("/") + path, timeout=10) as r:
        return json.load(r)


# -- the digest algebra -------------------------------------------------------


def test_digest_order_independent_and_removal_inverse():
    pods = [build_pod(f"p{i}", namespace=f"ns{i % 3}") for i in range(12)]
    a = vtaudit.table_from_objects(("Pod", p) for p in pods)
    b = vtaudit.table_from_objects(("Pod", p) for p in reversed(pods))
    assert a.root() == b.root()
    assert a.bucket_payload() == b.bucket_payload()
    # add one more, remove it again: bit-for-bit back where we started
    before = a.payload(4)
    extra = build_pod("extra", namespace="ns1")
    a.set_obj("Pod", extra.meta.key, extra)
    assert a.payload(4) != before
    a.remove("Pod", extra.meta.key)
    assert a.payload(4) == before


def test_field_delta_patch_equals_full_redigest():
    t = vtaudit.DigestTable()
    p = build_pod("p0")
    t.set_obj("Pod", p.meta.key, p)
    old = p.node_name
    p.node_name = "n7"
    t.apply_fields("Pod", p.meta.key, (("node_name", old, "n7"),), obj=p)
    fresh = vtaudit.table_from_objects([("Pod", p)])
    assert t.root() == fresh.root()
    assert t.object_payload("Pod", "default") == fresh.object_payload(
        "Pod", "default")


def test_resource_version_is_digest_neutral():
    """rv bumps on every write by design — digesting it would make every
    no-op-adjacent path a divergence; SKIP_LEAVES drops it."""
    p = build_pod("p0")
    p.meta.resource_version = 1
    d1 = vtaudit.obj_digest("Pod", p)
    p.meta.resource_version = 999
    assert vtaudit.obj_digest("Pod", p) == d1
    # a REAL field flip does move the digest
    p.node_name = "n1"
    assert vtaudit.obj_digest("Pod", p) != d1


def test_store_maintains_digest_through_every_verb():
    """create/update/patch/delete all keep the maintained table equal to
    a ground-truth recompute (the invariant vtlint's digest-maintenance
    rule fences statically)."""
    st = Store()
    st.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    st.create("Node", build_node("n0"))
    for i in range(6):
        st.create("Pod", build_pod(f"p{i}"))
    st.patch("Pod", "default/p1", {"node_name": "n0"})
    p3 = st.get("Pod", "default/p3")
    p3.phase = type(p3.phase)("Running")
    st.update("Pod", p3)
    st.delete("Pod", "default/p4")
    maint = st._digest
    truth = st.recompute_digest()
    assert maint is not None
    assert maint.root() == truth.root()
    assert maint.bucket_payload() == truth.bucket_payload()


# -- the corruption drill -----------------------------------------------------


def test_corruption_localizes_to_exact_object_locally():
    from volcano_tpu.cli import vtctl

    st = Store()
    for i in range(8):
        st.create("Pod", build_pod(f"p{i}", namespace=f"ns{i % 2}"))
    assert "state digest OK" in vtctl.cmd_audit_local(st)
    # flip one byte of one object's state behind the verbs' back
    st._objects["Pod"]["ns1/p5"].node_name = "flipped"
    text = vtctl.cmd_audit_local(st)
    assert "STATE DIGEST DIVERGENCE" in text
    assert "Pod ns1/p5" in text
    # exactly one object implicated
    assert text.count("maintained=") - 1 == 1


def test_corruption_localizes_over_partitioned_server():
    """The remote drill: the maintained rollup vs the server-side
    ``?recompute=1`` tier walks shard -> bucket -> object down to the
    flipped pod, and ``vtctl audit --server`` exits 2."""
    from volcano_tpu.cli import vtctl

    srv = StoreServer(shards=4).start()
    try:
        rs = RemoteStore(srv.url)
        for i in range(10):
            rs.create("Pod", build_pod(f"p{i}", namespace=f"team{i % 4}"))
        assert "state digest OK" in vtctl.cmd_audit_remote(srv.url)
        srv.store._objects["Pod"]["team2/p6"].node_name = "flipped"
        text = vtctl.cmd_audit_remote(srv.url)
        assert "STATE DIGEST DIVERGENCE" in text
        assert "Pod team2/p6" in text
        assert vtctl.main(["audit", "--server", srv.url]) == 2
    finally:
        srv.stop()


def test_debug_digest_recompute_tier_matches_maintained_when_clean():
    srv = StoreServer(shards=4).start()
    try:
        rs = RemoteStore(srv.url)
        for i in range(6):
            rs.create("Pod", build_pod(f"p{i}", namespace=f"team{i % 3}"))
        dbg = _fetch(srv.url, "/debug/digest")
        rec = _fetch(srv.url, "/debug/digest?recompute=1")
        assert dbg["enabled"] and rec["recompute"]
        assert dbg["root"] == rec["root"]
        assert dbg["shards"] == rec["shards"]
        # healthz mirrors the same rollup
        hz = _fetch(srv.url, "/healthz")
        assert hz["digest"]["root"] == dbg["root"]
    finally:
        srv.stop()


# -- the mirror half ----------------------------------------------------------


def _seed_cluster(create):
    create("Queue", Queue(meta=Metadata(name="default", namespace="")))
    create("Node", build_node("n0"))
    create("PodGroup", build_podgroup("pg", min_member=1))
    for i in range(5):
        create("Pod", build_pod(f"p{i}", group="pg"))


def test_mirror_audit_verify_in_process_and_detects_tampering():
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    st = Store()
    _seed_cluster(st.create)
    m = ArrayMirror(st, "volcano-tpu", "default")
    m.drain()
    res = m.audit_verify()
    assert res is not None and res["ok"], res
    assert res["mode"] == "store"
    # keep verifying through incremental traffic
    st.patch("Pod", "default/p1", {"node_name": "n0"})
    st.delete("Pod", "default/p4")
    m.drain()
    res = m.audit_verify()
    assert res is not None and res["ok"], res
    # tamper the MIRROR's table: detection names the kind, resync heals
    m._audit.set_enc("Pod", "default/poison", {"meta": {"name": "poison"}})
    res = m.audit_verify()
    assert res is not None and not res["ok"] and res["kinds"] == ["Pod"]
    assert m.audit_divergences == 1
    m.drain()
    res = m.audit_verify()
    assert res is not None and res["ok"], res


def test_mirror_reaches_digest_equality_with_partitioned_server():
    """The merged watch stream of a shards=4 server drives the mirror's
    independent table to beacon-pinned equality with the server's."""
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    srv = StoreServer(shards=4).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_cluster(rs.create)
        mirror_store = RemoteStore(srv.url)
        m = ArrayMirror(mirror_store, "volcano-tpu", "default")
        m.drain()
        with srv.lock:
            assert srv.stamp_beacon()  # seq-pinned checkpoint, on demand
        m.drain()  # the poll that delivers the beacon
        res = m.audit_verify()
        assert res is not None and res["ok"], res
        assert res["mode"] == "beacon" and res["seq"] == srv.seq
        # more traffic, a new beacon: still equal
        rs.patch("Pod", "default/p2", {"node_name": "n0"})
        rs.delete("Pod", "default/p0")
        with srv.lock:
            assert srv.stamp_beacon()
        m.drain()
        res = m.audit_verify()
        assert res is not None and res["ok"], res
    finally:
        srv.stop()


def test_beacon_rides_every_shard_watch_and_is_not_an_object():
    srv = StoreServer(shards=4).start()
    try:
        rs = RemoteStore(srv.url)
        for i in range(8):
            rs.create("Pod", build_pod(f"p{i}", namespace=f"team{i}"))
        watchers = [RemoteStore(srv.url, shard=s) for s in range(4)]
        queues = [w.watch("Pod") for w in watchers]
        for w in watchers:
            w.poll()
        with srv.lock:
            assert srv.stamp_beacon()
            # the cadence path never re-beacons without seq progress
            # (the just-stamped beacon pinned the current seq)
            assert not srv._maybe_beacon()
        for w, q in zip(watchers, queues):
            while q:
                q.popleft()
            w.poll()
            # the beacon reached this shard's watcher as a beacon, not
            # as a Pod event
            assert not q
            assert w.last_beacon is not None and w.beacon_is_tail
            assert w.last_beacon["seq"] == srv.seq
        dbg = _fetch(srv.url, "/debug/digest")
        assert watchers[0].last_beacon["root"] == dbg["root"]
        # beacons never materialize as listable objects
        assert rs.list("Pod") and len(rs.list("Pod")) == 8
    finally:
        srv.stop()


# -- the WAL half -------------------------------------------------------------


def test_wal_replay_digest_matches_live_server(tmp_path):
    srv = StoreServer(
        state_path=str(tmp_path / "state.json"), save_interval=3600,
        wal=True, shards=4,
    ).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_cluster(rs.create)
        rs.patch("Pod", "default/p3", {"node_name": "n0"})
        rs.delete("Pod", "default/p1")
        live = _fetch(srv.url, "/debug/digest")
        res = vtaudit.replay_wal_digest(str(tmp_path / "state.json"))
        assert res["digest"] is not None
        assert res["digest"]["root"] == live["root"]
        assert res["digest"]["shards"] == live["shards"]
        assert res["seq"] == live["seq"]
        # the CLI wrapper agrees and stamps the verdict
        from volcano_tpu.cli import vtctl

        text = vtctl.cmd_audit_wal(
            str(tmp_path / "state.json.wal"), server_url=srv.url)
        assert "MATCH" in text and "MISMATCH" not in text
        assert vtctl.main([
            "audit", "wal", str(tmp_path / "state.json.wal"),
            "--server", srv.url]) == 0
    finally:
        srv.stop()


def test_wal_replay_digest_survives_kill_and_matches_reboot(tmp_path):
    srv = StoreServer(
        state_path=str(tmp_path / "state.json"), save_interval=3600,
        wal=True, shards=4,
    ).start()
    rs = RemoteStore(srv.url)
    _seed_cluster(rs.create)
    rs.patch("Pod", "default/p2", {"node_name": "n0"})
    srv.kill()  # no flush: the WAL tail is the only record
    res = vtaudit.replay_wal_digest(str(tmp_path / "state.json"))
    srv2 = StoreServer(
        port=srv.port, state_path=str(tmp_path / "state.json"),
        save_interval=3600, wal=True, shards=4,
    ).start()
    try:
        live = _fetch(srv2.url, "/debug/digest")
        assert res["digest"]["root"] == live["root"]
        assert res["digest"]["shards"] == live["shards"]
    finally:
        srv2.stop()


# -- metrics / anomaly wiring -------------------------------------------------


def test_audit_metrics_registered_and_monotonic():
    from volcano_tpu.scheduler import metrics

    c0 = metrics.get_counter("volcano_audit_digest_checks_total")
    d0 = metrics.get_counter("volcano_audit_divergence_total")
    metrics.register_audit_check()
    metrics.register_audit_divergence()
    metrics.observe_beacon_lag(0.25)
    assert metrics.get_counter("volcano_audit_digest_checks_total") == c0 + 1
    assert metrics.get_counter("volcano_audit_divergence_total") == d0 + 1
    text = metrics.expose_text()
    for name in ("volcano_audit_digest_checks_total",
                 "volcano_audit_divergence_total",
                 "volcano_audit_beacon_lag_seconds"):
        assert name in text

def test_audit_verify_survives_stale_watch_during_quiescence_peek():
    """The quiescence peek in ``audit_verify`` polls the wire, so it can
    fall off the server's event log mid-check (cfg7 found this: a long
    solve between drains overflowed the log and the StaleWatch escaped
    ``run_once`` through ``_audit_tick``).  It must recover exactly like
    ``drain()`` — relist, count it, report non-quiescent — and the next
    beacon-pinned check must pass again."""
    from volcano_tpu.scheduler.fastpath import ArrayMirror
    from volcano_tpu.store.client import StaleWatch

    class _StaleQueue:
        def __bool__(self):
            raise StaleWatch("watch cursor fell off the server log")

        def clear(self):
            pass

    srv = StoreServer(shards=2).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_cluster(rs.create)
        m = ArrayMirror(RemoteStore(srv.url), "volcano-tpu", "default")
        m.drain()
        with srv.lock:
            assert srv.stamp_beacon()
        m.drain()
        assert m.audit_verify()["ok"]
        relists = m.stale_relists
        m._watches.insert(0, ("Pod", _StaleQueue()))
        res = m.audit_verify()  # must NOT raise
        assert res is None
        assert m.stale_relists == relists + 1
        # a real post-gap poll stops raising (the cursor advanced past
        # the gap); the injected queue stands in for the raising window
        # only, so retire it and prove the next pinned check converges
        m._watches.remove(("Pod", next(
            q for _, q in m._watches if isinstance(q, _StaleQueue))))
        rs.patch("Pod", "default/p1", {"node_name": "n1"})
        with srv.lock:
            assert srv.stamp_beacon()
        m.drain()
        res = m.audit_verify()
        assert res is not None and res["ok"], res
    finally:
        srv.stop()
