"""Resource arithmetic + epsilon-comparison parity tests.

Scenario sources: reference resource_info.go semantics (LessEqual tolerance
minMilliCPU=10/minMemory=10Mi/minScalar=10, Sub guard, FitDelta epsilon).
"""

import pytest

from volcano_tpu.api import Resource
from volcano_tpu.api.resource import MIN_MEMORY, MIN_MILLI_CPU


def res(cpu=0.0, mem=0.0, **scalars):
    return Resource(cpu, mem, scalars)


class TestComparisons:
    def test_less_equal_exact(self):
        assert res(1000, 2**30).less_equal(res(1000, 2**30))

    def test_less_equal_within_epsilon(self):
        # 9 millicores / <10Mi over still counts as <=
        assert res(1009, 2**30 + MIN_MEMORY - 1).less_equal(res(1000, 2**30))

    def test_less_equal_beyond_epsilon(self):
        assert not res(1011, 0).less_equal(res(1000, 0))
        assert not res(0, 2**30 + MIN_MEMORY).less_equal(res(0, 2**30))

    def test_less_equal_scalar_dims(self):
        assert res(0, 0, accelerator=4000).less_equal(res(0, 0, accelerator=4000))
        assert not res(0, 0, accelerator=4000).less_equal(res(0, 0))

    def test_less_strict(self):
        # Reference quirk (resource_info.go Less): when NEITHER side has
        # scalar resources, Less returns false even for strictly-smaller
        # cpu/mem; it returns true only if the right side has scalars.
        assert not res(999, 2**30 - 1).less(res(1000, 2**30))
        assert res(999, 2**30 - 1).less(res(1000, 2**30, accelerator=1))
        assert not res(1000, 2**30).less(res(1000, 2**30))

    def test_empty(self):
        assert Resource().is_empty()
        assert res(MIN_MILLI_CPU - 1, MIN_MEMORY - 1).is_empty()
        assert not res(MIN_MILLI_CPU, 0).is_empty()


class TestArithmetic:
    def test_add_sub_roundtrip(self):
        a = res(2000, 4 * 2**30, accelerator=1000)
        b = res(500, 2**30, accelerator=1000)
        a.add(b)
        assert a.get("cpu") == 2500
        a.sub(b)
        assert a.get("cpu") == 2000 and a.get("accelerator") == 1000

    def test_sub_insufficient_raises(self):
        with pytest.raises(ValueError):
            res(100, 0).sub(res(200, 0))

    def test_multi(self):
        a = res(1000, 1000, accelerator=10).multi(1.5)
        assert a.get("cpu") == 1500 and a.get("accelerator") == 15

    def test_set_max(self):
        a = res(100, 500)
        a.set_max(res(200, 100, accelerator=7))
        assert (a.get("cpu"), a.get("memory"), a.get("accelerator")) == (200, 500, 7)

    def test_fit_delta_negative_means_insufficient(self):
        idle = res(1000, 0)
        idle.fit_delta(res(1000, 0))
        assert idle.get("cpu") < 0  # exact fit is "insufficient" under FitDelta

    def test_share(self):
        assert Resource.share(0, 0) == 0
        assert Resource.share(5, 0) == 1
        assert Resource.share(1, 4) == 0.25

    def test_dominant_share(self):
        total = res(10000, 100 * 2**30)
        alloc = res(1000, 50 * 2**30)
        assert alloc.dominant_share(total) == 0.5


class TestParsing:
    def test_from_resource_list(self):
        r = Resource.from_resource_list(
            {"cpu": "2", "memory": "4Gi", "accelerator": 1, "pods": "110"}
        )
        assert r.get("cpu") == 2000
        assert r.get("memory") == 4 * 2**30
        assert r.get("accelerator") == 1000  # scalars stored in milli-units
        assert r.max_task_num == 110

    def test_cpu_millis(self):
        assert Resource.from_resource_list({"cpu": "250m"}).get("cpu") == 250

    def test_memory_units(self):
        assert Resource.from_resource_list({"memory": "1G"}).get("memory") == 1e9
        assert Resource.from_resource_list({"memory": "512Mi"}).get("memory") == 512 * 2**20
