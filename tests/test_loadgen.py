"""vtload: the open-loop load harness, the per-cycle time-series
recorder, `vtctl top`, and the SLO chaos gate.

Coverage map (ISSUE 9):

* loadgen determinism — same seed, same schedule and same submitted
  objects, byte for byte (the chaosd determinism contract);
* the tier-1 open-loop smoke — a sub-second run through the real
  Scheduler + Store that must sustain its QPS and report percentiles
  (the fast twin of ``bench.py --open-loop`` / ``make loadtest``);
* the time-series recorder — armed cycles sample phases/backlog/binds,
  disarmed cycles record nothing AND leave the cfg5 phase set unchanged;
  ``/debug/timeseries`` serves the ring on both servers, chaos-exempt;
  ``trace.crash_dump`` artifacts carry the ring; ``vtctl top`` renders;
* THE SLO CHAOS GATE — a lockstep open-loop run through a real
  StoreServer under a seeded 5xx/cut storm must keep a bounded p99 and
  converge to placements bit-for-bit equal to a fault-free run.
"""

import http.client
import json
import urllib.request

import pytest

from volcano_tpu import timeseries, trace
from volcano_tpu.api import Resource
from volcano_tpu.api.objects import Metadata, Node, Queue
from volcano_tpu.backoff import Backoff
from volcano_tpu.loadgen import (
    LoadGen,
    LoadSpec,
    build_schedule,
    run_open_loop,
    saturation_search,
)
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store import Store
from volcano_tpu.store.client import (
    RemoteStore,
    RemoteStoreError,
    wait_healthy,
)
from volcano_tpu.store.server import StoreServer

TRANSIENT = (RemoteStoreError, OSError, http.client.HTTPException)


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    timeseries.disarm()
    yield
    timeseries.disarm()
    metrics.reset()


def _mk_store(n_nodes=6, cpu=8000.0):
    store = Store()
    store.create("Queue", Queue(
        meta=Metadata(name="default", namespace=""), weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i}", namespace=""),
            allocatable=Resource(cpu, 16.0 * (1 << 30), max_task_num=110)))
    return store


# --- loadgen determinism -----------------------------------------------------


def test_schedule_deterministic_per_seed():
    spec = LoadSpec(qps=80, duration_s=1.0, seed=5, dwell_s=2.0)
    s1 = build_schedule(spec)
    s2 = build_schedule(spec)
    assert s1 == s2 and len(s1) > 20
    assert build_schedule(LoadSpec(qps=80, duration_s=1.0, seed=6)) != s1
    # arrivals are time-ordered with materialized shapes
    assert all(a.t <= b.t for a, b in zip(s1, s1[1:]))
    assert all(a.size == len(a.mem_bytes) for a in s1)


def test_generator_submits_identical_objects_per_seed():
    spec = LoadSpec(qps=60, duration_s=0.5, seed=9)

    def submitted(store):
        gen = LoadGen(store, spec)
        gen.submit_due(spec.duration_s)
        return sorted(
            (p.meta.key, p.spec.resources.milli_cpu,
             p.spec.resources.memory)
            for p in store.list("Pod")
        ), sorted(
            (g.meta.key, g.min_member, g.queue)
            for g in store.list("PodGroup")
        )

    assert submitted(Store()) == submitted(Store())


def test_resubmit_after_partial_failure_is_idempotent():
    spec = LoadSpec(qps=200, duration_s=0.05, seed=2)
    store = Store()
    gen = LoadGen(store, spec)
    arr = gen.due(1.0)[0]
    # simulate an earlier cut attempt that committed half the gang
    gen.submit(arr)
    n_pods = len(store.list("Pod"))
    gen._next -= 1  # roll the cursor back as a failed submit would leave it
    del gen.gangs[arr.name]
    gen.submit(arr)  # must not raise, must not duplicate
    assert len(store.list("Pod")) == n_pods


# --- the tier-1 open-loop smoke ---------------------------------------------


def test_open_loop_smoke_sustains_qps_and_reports_percentiles():
    """The seconds-scale twin of `bench.py --open-loop` (make loadtest):
    sustain the arrival process, drain the tail, read p50/p99/p999 from
    the bounded histograms — and route the samples through the PR-4
    reference series."""
    store = _mk_store()
    sched = Scheduler(store, conf=full_conf("host"))
    spec = LoadSpec(qps=60, duration_s=0.5, seed=1,
                    cpu_millis=(100,), mem_mb=(64,), dwell_s=0.4)
    report = run_open_loop(store, spec, sched.run_once, settle_s=20.0)
    assert report.sustained, report.as_dict()
    assert report.submitted_pods > 10
    assert report.bound_pods == report.submitted_pods
    assert 0.0 <= report.p50_ms <= report.p99_ms <= report.p999_ms
    assert report.departed_gangs > 0  # churn ran
    # the samples ALSO landed in the reference first-seen→bind series
    series = metrics.get_histogram(
        "volcano_e2e_job_scheduling_latency_milliseconds")
    assert series.count == report.submitted_pods
    assert metrics.quantile(
        "volcano_e2e_job_scheduling_latency_milliseconds", 0.99) >= 0.0


def test_saturation_search_escalates_until_band_breach():
    calls = []

    def run_at(qps):
        calls.append(qps)
        from volcano_tpu.loadgen.harness import SLOReport

        # synthetic latency curve: p99 grows with qps, breaches at 40
        return SLOReport(
            qps=qps, duration_s=1.0, submitted_pods=10, bound_pods=10,
            unbound_pods=0, p50_ms=qps, p99_ms=qps * 10, p999_ms=qps * 12,
            max_ms=qps * 15, backlog_peak=0, departed_gangs=0, cycles=5,
            wall_s=1.0, sustained=True)

    out = saturation_search(run_at, base_qps=10, band_p99_ms=350.0,
                            max_doublings=4)
    assert calls == [10, 20, 40]
    assert out.sustained_qps == 20 and out.breach_qps == 40
    assert [r.qps for r in out.steps] == calls


# --- the per-cycle time-series recorder --------------------------------------


def _cycle_workload(store, n=4):
    from volcano_tpu.api import POD_GROUP_KEY
    from volcano_tpu.api.objects import Pod, PodGroup, PodSpec
    from volcano_tpu.api.types import PodGroupPhase

    for i in range(n):
        pg = PodGroup(meta=Metadata(name=f"g{i}", namespace="default"),
                      min_member=1, queue="default")
        # default_conf has no enqueue action: admit directly
        pg.status.phase = PodGroupPhase.INQUEUE
        store.create("PodGroup", pg)
        store.create("Pod", Pod(
            meta=Metadata(name=f"p{i}", namespace="default",
                          annotations={POD_GROUP_KEY: f"g{i}"}),
            spec=PodSpec(image="x", resources=Resource(100.0, 1 << 20))))


def test_recorder_samples_fast_cycles_and_disarmed_records_nothing():
    # disarmed: no samples, no stats stash
    store = _mk_store(n_nodes=2)
    _cycle_workload(store)
    sched = Scheduler(store, conf=default_conf("tpu"))
    sched.run_once()
    assert timeseries.samples() == []
    assert sched.fast_cycle.last_cycle_stats == {}

    # armed: every cycle lands one sample with the fast-path fields
    rec = timeseries.arm()
    store2 = _mk_store(n_nodes=2)
    _cycle_workload(store2)
    sched2 = Scheduler(store2, conf=default_conf("tpu"))
    sched2.run_once()
    sched2.run_once()
    samples = rec.samples()
    cycles = [s for s in samples if s["kind"] == "cycle"]
    assert len(cycles) == 2
    first = cycles[0]
    assert first["path"] == "fast"
    assert first["binds"] == 4 and first["backlog"] >= 4
    assert "drain" in first["phases"] and "publish" in first["phases"]
    assert cycles[1]["cycle"] == first["cycle"] + 1
    assert cycles[1]["binds"] == 0  # steady cycle: nothing pending


def test_recorder_arming_leaves_phase_set_unchanged():
    """Acceptance: arming the recorder must not add/remove cycle phases
    (it observes the cycle, never reshapes it)."""
    def phases_with(armed):
        timeseries.disarm()
        if armed:
            timeseries.arm()
        store = _mk_store(n_nodes=2)
        _cycle_workload(store)
        sched = Scheduler(store, conf=default_conf("tpu"))
        sched.run_once()
        sched.run_once()
        return set(sched.fast_cycle.phases)

    assert phases_with(armed=False) == phases_with(armed=True)


def test_object_cycle_binds_delta_survives_fast_cycles():
    """Regression: fast cycles ALSO append to cache.bind_log, so the
    object-path binds delta must not bill a fast->object transition for
    every fast bind since the last object cycle."""
    import time as _time

    rec = timeseries.arm()
    store = _mk_store(n_nodes=2)
    sched = Scheduler(store, conf=default_conf("tpu"))
    # a fast cycle that published 3 binds (bind_log grew underneath)
    sched.cache.bind_log.extend(
        [("default/a", "n0"), ("default/b", "n0"), ("default/c", "n1")])
    sched.fast_cycle.last_cycle_stats = {"binds": 3, "backlog": 3,
                                         "evictions": 0, "residue_jobs": 0}
    sched._record_cycle(_time.perf_counter(), "fast")
    # next cycle falls back to the object path and binds 1 pod
    sched.cache.bind_log.append(("default/d", "n1"))
    sched._record_cycle(_time.perf_counter(), "object")
    cycles = [s for s in rec.samples() if s["kind"] == "cycle"]
    assert cycles[0]["binds"] == 3
    assert cycles[1]["binds"] == 1  # NOT 4: the watermark advanced


def test_store_server_records_flush_samples(tmp_path):
    rec = timeseries.arm()
    srv = StoreServer(state_path=str(tmp_path / "state.json"),
                      wal=True, save_interval=3600.0).start()
    try:
        client = RemoteStore(srv.url)
        client.create("Queue", Queue(
            meta=Metadata(name="q", namespace=""), weight=1))
        srv.flush_state(force=True)
    finally:
        srv.stop()
    stores = [s for s in rec.samples() if s["kind"] == "store"]
    assert stores, rec.samples()
    last = stores[-1]
    assert last["log_seq"] >= 1
    assert last["wal"] is not None and last["wal"]["records"] >= 1


def test_debug_timeseries_endpoint_on_both_servers_and_chaos_exempt():
    from volcano_tpu.chaos import FaultPlan
    from volcano_tpu.scheduler.metrics_server import MetricsServer

    rec = timeseries.arm()
    rec.record("cycle", dur_s=0.01, path="fast", cycle=0)
    srv = StoreServer().start()
    ms = MetricsServer(port=0).start()
    try:
        # every request 5xxs — the debug endpoints must still answer
        srv.arm_chaos(FaultPlan.from_dict({
            "seed": 1,
            "rules": [{"point": "server.request", "action": "http_500",
                       "every": 1, "count": 1000}],
        }))
        for url in (srv.url, f"http://127.0.0.1:{ms.port}"):
            with urllib.request.urlopen(
                url + "/debug/timeseries", timeout=10
            ) as r:
                payload = json.load(r)
            assert payload["armed"] is True
            assert payload["samples"][0]["kind"] == "cycle"
        # disarmed recorder still serves a well-formed (empty) payload
        timeseries.disarm()
        with urllib.request.urlopen(
            srv.url + "/debug/timeseries", timeout=10
        ) as r:
            payload = json.load(r)
        # "now" is the serving process's clock stamp (vtfleet offset
        # estimation) — present even disarmed
        assert payload == {"armed": False, "pid": payload["pid"],
                           "now": payload["now"], "samples": []}
    finally:
        srv.stop()
        ms.stop()


def test_crash_dump_carries_timeseries(tmp_path):
    rec = timeseries.arm()
    rec.record("cycle", dur_s=0.02, path="fast", cycle=7)
    trace.arm(trace.Tracer(dump_dir=str(tmp_path)))
    try:
        with trace.span("scheduler.cycle"):
            pass
        path = trace.crash_dump("unit")
        with open(path, encoding="utf-8") as f:
            dump = json.load(f)
        assert dump["spans"]
        assert dump["timeseries"][0]["cycle"] == 7
    finally:
        trace.disarm()


def test_vtctl_top_renders_ring_and_remote_fetch(capsys):
    from volcano_tpu.cli import cmd_top, main
    from volcano_tpu.scheduler.metrics_server import MetricsServer

    samples = [
        {"seq": 1, "kind": "cycle", "ts": 100.0, "cycle": 3,
         "dur_s": 0.048, "path": "fast", "backlog": 12, "binds": 12,
         "evictions": 0, "drain_pending": 2,
         "phases": {"drain": 0.01, "solve": 0.02, "publish": 0.004}},
        {"seq": 2, "kind": "store", "ts": 100.2, "log_seq": 42,
         "log_rows": 10,
         "wal": {"records": 9, "fsync_total": 3, "fsync_s": 0.01}},
    ]
    text = cmd_top(samples, now=101.0)
    assert "Cycle" in text and "Backlog" in text
    assert "48.0" in text and "solve=0.020" in text
    assert "seq=42" in text and "fsyncs=3" in text
    assert "dur p50" in text
    assert "no time-series samples" in cmd_top([])

    # remote: `vtctl --server ... top` renders the served ring
    rec = timeseries.arm()
    rec.record("cycle", dur_s=0.031, path="fast", cycle=11, backlog=1,
               binds=1, evictions=0, drain_pending=0, phases={})
    ms = MetricsServer(port=0).start()
    try:
        rc = main(["--server", f"http://127.0.0.1:{ms.port}", "top"])
    finally:
        ms.stop()
    assert rc == 0
    out = capsys.readouterr().out
    assert "31.0" in out and "11" in out


# --- subprocess mode ---------------------------------------------------------


@pytest.mark.slow
def test_open_loop_against_real_daemon_processes():
    """Subprocess mode: the SAME generator drives real OS-process
    daemons over HTTP (apiserver + scheduler with the time-series
    recorder armed), and `vtctl top --server` renders the scheduler's
    live /debug/timeseries ring."""
    import os
    import subprocess
    import sys

    ENTRY = [sys.executable, "-m", "volcano_tpu.cli"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VOLCANO_TPU_TIMESERIES": "1"}

    def spawn(args):
        return subprocess.Popen(
            ENTRY + args, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)

    procs = []
    try:
        api = spawn(["apiserver", "--port", "0"])
        procs.append(api)
        line = api.stdout.readline().strip()
        assert "listening on" in line, line
        url = line.rsplit(" ", 1)[-1]
        sch = spawn(["scheduler", "--server", url, "--period", "0.05",
                     "--metrics-port", "0", "--no-leader-elect"])
        procs.append(sch)
        metrics_url = ""
        for _ in range(10):
            line = sch.stdout.readline()
            if "/metrics" in line:
                metrics_url = line.rsplit(" ", 1)[-1].strip()
                metrics_url = metrics_url.rsplit("/metrics", 1)[0]
                break
        assert metrics_url, "scheduler never announced its metrics port"

        client = RemoteStore(url)  # run_apiserver already seeded "default"
        for i in range(4):
            client.create("Node", Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource(8000.0, 16.0 * (1 << 30),
                                     max_task_num=110)))
        spec = LoadSpec(qps=30, duration_s=1.0, seed=3,
                        cpu_millis=(100,), mem_mb=(64,), namespace="sub")
        report = run_open_loop(client, spec, lambda: None, settle_s=60.0,
                               idle_sleep_s=0.02)
        assert report.sustained, report.as_dict()
        assert report.bound_pods == report.submitted_pods > 10

        # the daemon's recorder sampled its cycles; vtctl top renders it
        from volcano_tpu.cli import cmd_top
        from volcano_tpu.cli.vtctl import _fetch_debug_timeseries

        samples = _fetch_debug_timeseries(metrics_url)
        cycles = [s for s in samples if s["kind"] == "cycle"]
        assert cycles and any(s.get("binds", 0) > 0 for s in cycles)
        text = cmd_top(samples)
        assert "Cycle" in text and "dur p50" in text
    finally:
        for p in procs:
            p.send_signal(__import__("signal").SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


# --- THE SLO CHAOS GATE ------------------------------------------------------

#: seeded, bounded request-plane storm: 5xx bursts + mid-body cuts while
#: the open-loop run is live (counts are generous enough to span it)
GATE_PLAN = {
    "seed": 11,
    "rules": [
        {"point": "server.request", "action": "http_500",
         "every": 5, "count": 25},
        {"point": "server.request", "action": "cut_body",
         "after": 7, "every": 9, "count": 8},
    ],
}


def _arm(url, plan):
    data = json.dumps(plan).encode() if plan is not None else None
    req = urllib.request.Request(
        url + "/chaos", data=data,
        method="POST" if plan is not None else "DELETE")
    return json.load(urllib.request.urlopen(req, timeout=10))


def _slo_gate_run(plan, seed=7):
    """One lockstep open-loop run over real HTTP: submit-with-retry per
    virtual tick, pump-with-retry, observe binds.  Returns (placements,
    generator) after convergence."""
    srv = StoreServer().start()
    try:
        assert wait_healthy(srv.url, timeout=10)
        srv.store.create("Queue", Queue(
            meta=Metadata(name="default", namespace=""), weight=1))
        for i in range(6):
            srv.store.create("Node", Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource(8000.0, 16.0 * (1 << 30),
                                     max_task_num=110)))
        client = RemoteStore(srv.url)
        sched = Scheduler(client, conf=full_conf("host"))
        if plan is not None:
            _arm(srv.url, plan)
        spec = LoadSpec(qps=40, duration_s=0.8, seed=seed,
                        cpu_millis=(100,), mem_mb=(64,), namespace="slo")
        gen = LoadGen(client, spec)
        retry = Backoff(base=0.01, cap=0.2, seed=41)
        import time as _time

        deadline = _time.monotonic() + 120
        vnow = 0.0
        while not gen.done:
            assert _time.monotonic() < deadline, "gate never converged"
            for arr in gen.due(vnow):
                while True:
                    try:
                        gen.submit(arr)
                        break
                    except TRANSIENT:
                        retry.sleep()
            while True:
                try:
                    sched.run_once()
                    break
                except TRANSIENT:
                    retry.sleep()
            try:
                gen.observe()
            except TRANSIENT:
                retry.sleep()
            vnow += 0.05
        if plan is not None:
            # read the storm stats BEFORE disarming (disarm clears them)
            status = json.load(urllib.request.urlopen(
                srv.url + "/chaos", timeout=10))
            assert any(s["fires"] > 0 for s in status["stats"]), (
                "the storm never actually fired")
            _arm(srv.url, None)
        return gen.placements(), gen
    finally:
        srv.stop()


def test_slo_chaos_gate_bounded_p99_and_fault_free_placements():
    """ISSUE 9 acceptance: an open-loop run under a seeded chaosd storm
    keeps a bounded p99 first-seen→bind latency and converges to
    placements bit-for-bit equal to a fault-free run — the r2 chaos
    discipline tied to latency, not only convergence."""
    placed_chaos, gen_chaos = _slo_gate_run(GATE_PLAN)
    placed_clean, gen_clean = _slo_gate_run(None)

    assert gen_chaos.submitted_pods == gen_clean.submitted_pods > 20
    assert gen_chaos.bound_pods == gen_chaos.submitted_pods
    # placements: bit-for-bit equal to the fault-free run
    assert placed_chaos == placed_clean
    # bounded latency tail: the storm inflates it but the histogram
    # percentile stays finite and inside the gate band
    p99 = gen_chaos.quantile_ms(0.99)
    assert 0.0 < p99 < 5000.0, p99
    assert gen_chaos.quantile_ms(0.999) < 10000.0
