"""Volume binding through the scheduler's VolumeBinder seam.

WaitForFirstConsumer semantics (reference: VolumeBinder seam
KB/pkg/scheduler/cache/interface.go:83-89 + AllocateVolumes/BindVolumes
call sites session.go:239,263; PV/PVC/StorageClass informers
cache.go:258-278): claims stay Pending until their pod is scheduled,
volume placement constrains node choice, and assumed volumes release when
a gang never dispatches.
"""

import pytest

from volcano_tpu.api.job import Job, JobSpec, TaskSpec, VolumeSpec
from volcano_tpu.api.objects import Metadata, PersistentVolumeClaim, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobPhase, PodPhase
from volcano_tpu.sim import Cluster


def mk_job(name, replicas, req, volumes=None, min_available=None, queue="default"):
    return Job(
        meta=Metadata(name=name, namespace="test"),
        spec=JobSpec(
            min_available=min_available if min_available is not None else replicas,
            tasks=[
                TaskSpec(
                    name="main",
                    replicas=replicas,
                    template=PodSpec(image="busybox",
                                     resources=Resource.from_resource_list(req)),
                )
            ],
            volumes=volumes or [],
            queue=queue,
        ),
    )


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(3):
        c.add_node(f"n{i}", {"cpu": "4", "memory": "8Gi", "pods": 110})
    return c


def test_dynamic_claim_provisions_pv_on_bind(cluster):
    job = mk_job(
        "dyn", 2, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/data", size="10Gi")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.RUNNING
    pvc = cluster.store.get("PVC", "test/dyn-pvc-0")
    assert pvc is not None and pvc.phase == "Bound"
    pv = cluster.store.get("PV", f"/{pvc.volume_name}")
    assert pv is not None and pv.claim_ref == "test/dyn-pvc-0"


def test_static_local_pv_pins_pod_to_its_node(cluster):
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv(
        "pv-n2", capacity="20Gi", storage_class="local",
        node_affinity={"kubernetes.io/hostname": "n2"},
    )
    job = mk_job(
        "pinned", 1, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/scratch", size="10Gi", storage_class="local")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    pods = [p for p in cluster.store.list("Pod")]
    assert len(pods) == 1 and pods[0].node_name == "n2"
    pvc = cluster.store.get("PVC", "test/pinned-pvc-0")
    assert pvc.phase == "Bound" and pvc.volume_name == "pv-n2"


def test_no_available_static_pv_leaves_job_pending(cluster):
    cluster.add_storage_class("local", provisioner="")
    # only PV is too small for the claim
    cluster.add_pv("tiny", capacity="1Gi", storage_class="local")
    job = mk_job(
        "starved", 1, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/x", size="10Gi", storage_class="local")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    pods = cluster.store.list("Pod")
    assert all(not p.node_name for p in pods)
    assert job.status.state.phase != JobPhase.RUNNING


def test_prebound_claim_constrains_to_pv_node(cluster):
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv(
        "disk0", capacity="50Gi", storage_class="local",
        node_affinity={"kubernetes.io/hostname": "n1"},
    )
    # claim already bound to disk0 (e.g. from a previous job run)
    pvc = PersistentVolumeClaim(
        meta=Metadata(name="reused", namespace="test"),
        size="10Gi", storage_class="local", volume_name="disk0", phase="Bound",
    )
    cluster.store.create("PVC", pvc)
    pv = cluster.store.get("PV", "/disk0")
    pv.claim_ref = "test/reused"
    cluster.store.update("PV", pv)

    job = mk_job(
        "reuser", 1, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/x", volume_claim_name="reused")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    pods = cluster.store.list("Pod")
    assert len(pods) == 1 and pods[0].node_name == "n1"


def test_two_tasks_one_pv_only_one_schedules(cluster):
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv("only", capacity="20Gi", storage_class="local")
    # two single-task jobs each wanting their own local claim
    for name in ("a", "b"):
        cluster.store.create(
            "Job",
            mk_job(
                name, 1, {"cpu": "1", "memory": "1Gi"},
                volumes=[VolumeSpec(mount_path="/x", size="5Gi", storage_class="local")],
            ),
        )
    cluster.run_until_idle()

    bound = [p for p in cluster.store.list("Pod") if p.node_name]
    assert len(bound) == 1
    claimed = [
        pvc for pvc in cluster.store.list("PVC") if pvc.phase == "Bound"
    ]
    assert len(claimed) == 1 and claimed[0].volume_name == "only"


def test_gang_never_ready_releases_assumed_volumes(cluster):
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv("solo", capacity="20Gi", storage_class="local")
    # gang of 2, but only one PV of the class exists (per-pod claims via two
    # jobs sharing minAvailable=2 is not expressible; use one job with two
    # volumes so each pod mounts BOTH claims: first pod assumes the PV for
    # claim 0, then fails claim 1 -> nothing binds, PV must stay Available
    job = mk_job(
        "gang", 2, {"cpu": "1", "memory": "1Gi"},
        volumes=[
            VolumeSpec(mount_path="/x", size="5Gi", storage_class="local"),
            VolumeSpec(mount_path="/y", size="5Gi", storage_class="local"),
        ],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    assert all(not p.node_name for p in cluster.store.list("Pod"))
    pv = cluster.store.get("PV", "/solo")
    assert pv.phase == "Available" and not pv.claim_ref
    assert all(pvc.phase == "Pending" for pvc in cluster.store.list("PVC"))


def test_volume_constrained_tasks_fall_back_to_host_solve(cluster):
    """The tensor tier must not claim tasks whose placement depends on
    resident volume state (snapshot marks them dynamic)."""
    from volcano_tpu.scheduler.framework import open_session
    from volcano_tpu.scheduler.snapshot import build_tensor_snapshot

    cluster.add_storage_class("local", provisioner="")
    # no PV large enough: the pod stays pending with a static-class claim
    cluster.add_pv("d0", capacity="1Gi", storage_class="local")
    job = mk_job(
        "vc", 1, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/x", size="5Gi", storage_class="local")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    pods = cluster.store.list("Pod")
    assert pods and all(not p.node_name for p in pods)
    ssn = open_session(cluster.scheduler.cache, cluster.scheduler.conf.tiers)
    snap = build_tensor_snapshot(ssn)
    assert snap.has_dynamic_predicates


def test_gang_shares_one_claim_one_pv(cluster):
    """All pods of a job mount the same job-level claim: the claim's PV is
    assumed once and shared, not grabbed per-task."""
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv("shared", capacity="50Gi", storage_class="local")
    job = mk_job(
        "team", 2, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/x", size="5Gi", storage_class="local")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.RUNNING
    pods = cluster.store.list("Pod")
    assert len(pods) == 2 and all(p.node_name for p in pods)
    pvc = cluster.store.get("PVC", "test/team-pvc-0")
    assert pvc.phase == "Bound" and pvc.volume_name == "shared"
    # exactly one PV bound, to this claim
    bound_pvs = [pv for pv in cluster.store.list("PV") if pv.claim_ref]
    assert [pv.meta.name for pv in bound_pvs] == ["shared"]


def test_node_pinned_shared_claim_colocates_gang(cluster):
    """Once the first task assumes a node-pinned PV for the shared claim,
    siblings must land on nodes that can reach it."""
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv(
        "pinned", capacity="50Gi", storage_class="local",
        node_affinity={"kubernetes.io/hostname": "n1"},
    )
    job = mk_job(
        "colo", 2, {"cpu": "1", "memory": "1Gi"},
        volumes=[VolumeSpec(mount_path="/x", size="5Gi", storage_class="local")],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()

    pods = cluster.store.list("Pod")
    assert len(pods) == 2 and all(p.node_name == "n1" for p in pods)


def test_bound_network_pv_does_not_force_host_fallback(cluster):
    """A claim bound to a PV with empty node affinity can never veto a node,
    so it must not push the tensor tier off the device path."""
    from volcano_tpu.scheduler.framework import open_session

    pvc = PersistentVolumeClaim(
        meta=Metadata(name="net", namespace="test"),
        size="5Gi", volume_name="pv-net", phase="Bound",
    )
    cluster.store.create("PVC", pvc)
    from volcano_tpu.api.objects import PersistentVolume
    cluster.store.create(
        "PV",
        PersistentVolume(meta=Metadata(name="pv-net", namespace=""),
                         capacity="5Gi", claim_ref="test/net"),
    )
    from volcano_tpu.api.objects import Pod, PodSpec as PS
    from volcano_tpu.scheduler.model import TaskInfo

    pod = Pod(
        meta=Metadata(name="p0", namespace="test"),
        spec=PS(resources=Resource.from_resource_list({"cpu": "1"})),
    )
    pod.volumes.append("net")
    task = TaskInfo(pod)
    vb = cluster.scheduler.cache.volume_binder
    assert not vb.task_constrains_nodes(task)


def test_best_effort_with_unsatisfiable_volume_survives_backfill(cluster):
    """VolumeBindingError inside backfill must not crash the cycle."""
    cluster.add_storage_class("local", provisioner="")
    cluster.add_pv("one", capacity="20Gi", storage_class="local")
    job = mk_job(
        "be", 1, {},  # empty request -> BestEffort -> backfill path
        volumes=[
            VolumeSpec(mount_path="/x", size="5Gi", storage_class="local"),
            VolumeSpec(mount_path="/y", size="5Gi", storage_class="local"),
        ],
    )
    cluster.store.create("Job", job)
    cluster.run_until_idle()  # must not raise
    assert all(not p.node_name for p in cluster.store.list("Pod"))
    pv = cluster.store.get("PV", "/one")
    assert pv.phase == "Available"


def test_dynamic_class_not_poisoned_by_provisioned_pv(cluster):
    """A dynamically provisioned (Bound) PV must not flip its class to
    static: a second job with an identical dynamic claim still runs."""
    for name in ("first", "second"):
        cluster.store.create(
            "Job",
            mk_job(
                name, 1, {"cpu": "1", "memory": "1Gi"},
                volumes=[VolumeSpec(mount_path="/x", size="5Gi")],
            ),
        )
        cluster.run_until_idle()
        job = cluster.store.get("Job", f"test/{name}")
        assert job.status.state.phase == JobPhase.RUNNING, name
    assert all(pvc.phase == "Bound" for pvc in cluster.store.list("PVC"))
    assert len([pv for pv in cluster.store.list("PV") if pv.claim_ref]) == 2


def test_classless_static_class_survives_binding_last_pv(cluster):
    """Without a StorageClass object, a class inferred static from its
    pre-created PV must stay static after that PV binds: a second claim
    waits instead of silently dynamic-provisioning."""
    cluster.add_pv("lone", capacity="20Gi", storage_class="local")  # no StorageClass object
    cluster.store.create(
        "Job",
        mk_job("one", 1, {"cpu": "1", "memory": "1Gi"},
               volumes=[VolumeSpec(mount_path="/x", size="5Gi", storage_class="local")]),
    )
    cluster.run_until_idle()
    assert cluster.store.get("Job", "test/one").status.state.phase == JobPhase.RUNNING
    assert cluster.store.get("PV", "/lone").claim_ref

    cluster.store.create(
        "Job",
        mk_job("two", 1, {"cpu": "1", "memory": "1Gi"},
               volumes=[VolumeSpec(mount_path="/x", size="5Gi", storage_class="local")]),
    )
    cluster.run_until_idle()
    # no second PV may appear; the job waits for a pre-created volume
    assert cluster.store.get("Job", "test/two").status.state.phase != JobPhase.RUNNING
    assert len(cluster.store.list("PV")) == 1



def test_assumed_pv_vanishing_before_bind_fails_softly():
    """ADVICE r1: a statically-assumed PV deleted between allocate and bind
    must not wedge the claim as Bound-to-nothing, and must not unwind the
    dispatch loop — the bind is skipped and retried next cycle."""
    from tests.helpers import build_node, build_pod, build_podgroup, make_store
    from volcano_tpu.api.objects import (
        Metadata, PersistentVolume, PersistentVolumeClaim, StorageClass,
    )
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.conf import default_conf
    from volcano_tpu.scheduler.session import Session

    store = make_store([build_node("n1")])
    store.create("StorageClass", StorageClass(
        meta=Metadata(name="local", namespace=""), provisioner=""))
    store.create("PV", PersistentVolume(
        meta=Metadata(name="pv1", namespace=""), capacity="1Gi",
        storage_class="local"))
    store.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="c1", namespace="default"), size="1Gi",
        storage_class="local"))
    store.create("PodGroup", build_podgroup("pg1", min_member=1))
    pod = build_pod("p0", group="pg1")
    pod.volumes = ["c1"]
    store.create("Pod", pod)
    cache = SchedulerCache(store)
    snap = cache.snapshot()
    task = next(t for j in snap.jobs.values() for t in j.tasks.values())
    cache.allocate_volumes(task, "n1")
    store.delete("PV", "/pv1")  # vanishes between allocate and bind
    ssn = Session(cache, default_conf().tiers, snap)
    task.node_name = "n1"
    ssn.dispatch(task)  # must not raise
    assert [(op, key) for op, key, _ in cache.err_log] == [
        ("bind_volumes", "default/p0")
    ]
    pvc = store.get("PVC", "default/c1")
    assert pvc.volume_name == "" and pvc.phase == "Pending"
    assert store.get("Pod", "default/p0").node_name == ""


def test_missing_bound_pv_makes_claim_unschedulable():
    """ADVICE r1: a pod mounting a claim whose bound PV was deleted is
    unschedulable (k8s semantics), not free to land anywhere."""
    from tests.helpers import build_node, build_pod, build_podgroup, make_store
    from volcano_tpu.api.objects import Metadata, PersistentVolumeClaim
    from volcano_tpu.scheduler.cache import SchedulerCache

    store = make_store([build_node("n1")])
    store.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="c1", namespace="default"), size="1Gi",
        storage_class="fast", volume_name="gone-pv", phase="Bound"))
    store.create("PodGroup", build_podgroup("pg1", min_member=1))
    pod = build_pod("p0", group="pg1")
    pod.volumes = ["c1"]
    store.create("Pod", pod)
    cache = SchedulerCache(store)
    snap = cache.snapshot()
    task = next(t for j in snap.jobs.values() for t in j.tasks.values())
    reason = cache.volume_fit(task, snap.nodes["n1"])
    assert reason is not None and "gone-pv not found" in reason
