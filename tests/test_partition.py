"""Dynamic-predicate partition: jobs with resident-state-dependent tasks
(host ports, pod affinity, constraining volumes) are excluded from the
device arrays and host-solved AFTER the device pass, instead of flipping the
whole cycle to the host path (VERDICT r1 weak #3).

Ordering note: the residue runs after the device pass, so under node
contention a dynamic job that would have ordered before an express job can
see different leftovers than the pure-host interleave — the same class of
ordering approximation the reference tolerates (stale heap comparisons,
randomized ties). Capacity invariants and gang atomicity always hold.
"""

import pytest

from tests.helpers import (
    FakeBinder,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler


def _mixed_store(n_express_jobs=4, tasks_per_job=3, n_nodes=8):
    nodes = [
        build_node(f"n{i:02d}", cpu="8", memory="16Gi") for i in range(n_nodes)
    ]
    podgroups, pods = [], []
    for j in range(n_express_jobs):
        podgroups.append(build_podgroup(f"ej{j}", min_member=tasks_per_job))
        for t in range(tasks_per_job):
            pods.append(build_pod(f"ej{j}-{t}", group=f"ej{j}", cpu="1",
                                  memory="1Gi"))
    # one dynamic job: host ports make it class-inexpressible
    podgroups.append(build_podgroup("dyn", min_member=2))
    for t in range(2):
        p = build_pod(f"dyn-{t}", group="dyn", cpu="1", memory="1Gi")
        p.spec.host_ports = [8080]
        pods.append(p)
    return make_store(nodes=nodes, queues=[build_queue("default")],
                      podgroups=podgroups, pods=pods)


def _run(store, backend, spy=None):
    sched = Scheduler(store, conf=default_conf(backend=backend))
    binder = FakeBinder()
    sched.cache.binder = binder
    if spy is not None:
        spy(sched)
    sched.run_once()
    return binder.binds


def test_mixed_cycle_stays_on_tensor_path_and_matches_host(monkeypatch):
    """One host-port job among expressible ones: the device solve still runs
    (no whole-cycle fallback) and, without cross-partition contention, the
    binds equal the pure host path exactly."""
    host = _run(_mixed_store(), "host")

    full_fallbacks = []
    from volcano_tpu.scheduler import tensor_actions

    orig = tensor_actions._host_allocate
    monkeypatch.setattr(
        tensor_actions, "_host_allocate",
        lambda ssn: (full_fallbacks.append(1), orig(ssn)),
    )
    tpu = _run(_mixed_store(), "tpu")
    assert full_fallbacks == [], "device pass fell back to whole-cycle host"
    assert tpu == host
    # the dynamic gang landed, each port-pod on its own node
    dyn_nodes = [n for k, n in tpu.items() if k.startswith("default/dyn")]
    assert len(dyn_nodes) == 2 and len(set(dyn_nodes)) == 2


def test_partition_respects_host_port_conflicts_with_residents():
    """The residue pass sees resident pods: a node already running a pod on
    the port is excluded."""
    from volcano_tpu.api.types import PodPhase

    nodes = [build_node("n0", cpu="8", memory="16Gi"),
             build_node("n1", cpu="8", memory="16Gi")]
    resident = build_pod("res", group="rg", cpu="1", memory="1Gi",
                         node_name="n0", phase=PodPhase.RUNNING)
    resident.spec.host_ports = [8080]
    podgroups = [build_podgroup("rg", min_member=1),
                 build_podgroup("dyn", min_member=1),
                 build_podgroup("ej", min_member=2)]
    newpod = build_pod("dyn-0", group="dyn", cpu="1", memory="1Gi")
    newpod.spec.host_ports = [8080]
    pods = [resident, newpod] + [
        build_pod(f"ej-{t}", group="ej", cpu="1", memory="1Gi") for t in range(2)
    ]
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=podgroups, pods=pods)
    binds = _run(store, "tpu")
    assert binds["default/dyn-0"] == "n1"
    assert len(binds) == 3  # dynamic + 2 express


def test_partition_capacity_invariants_under_contention():
    """Tight cluster, express and dynamic jobs competing: whatever the
    interleave, no node is over-allocated and gangs stay atomic."""
    nodes = [build_node(f"n{i}", cpu="2", memory="4Gi") for i in range(3)]
    podgroups, pods = [], []
    for j in range(3):
        podgroups.append(build_podgroup(f"ej{j}", min_member=2))
        for t in range(2):
            pods.append(build_pod(f"ej{j}-{t}", group=f"ej{j}", cpu="1",
                                  memory="1Gi"))
    podgroups.append(build_podgroup("dyn", min_member=2))
    for t in range(2):
        p = build_pod(f"dyn-{t}", group="dyn", cpu="1", memory="1Gi")
        p.spec.host_ports = [9090]
        pods.append(p)
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=podgroups, pods=pods)
    binds = _run(store, "tpu")

    per_node = {}
    for key, node in binds.items():
        per_node[node] = per_node.get(node, 0) + 1
    assert all(v <= 2 for v in per_node.values()), per_node  # 2 cpu / 1-cpu pods
    # gang atomicity: each job has 0 or >= min_member binds
    for pg in ("ej0", "ej1", "ej2", "dyn"):
        n = sum(1 for k in binds if k.startswith(f"default/{pg}-"))
        assert n in (0, 2), (pg, n)
    # dynamic pods on distinct nodes (port conflict)
    dyn_nodes = [n for k, n in binds.items() if k.startswith("default/dyn")]
    assert len(set(dyn_nodes)) == len(dyn_nodes)


def test_partition_bulk_mode_accounts_nodes_for_residue(monkeypatch):
    """Force the bulk apply path (threshold 0) with a residue present: host
    NodeInfo accounting and fair-share state must be maintained so the
    residue pass cannot over-allocate."""
    from volcano_tpu.scheduler import tensor_backend as tb

    orig_init = tb.TensorBackend.__init__

    def patched(self, ssn, **kw):
        kw["bulk_threshold"] = 0
        orig_init(self, ssn, **kw)

    monkeypatch.setattr(tb.TensorBackend, "__init__", patched)
    nodes = [build_node(f"n{i}", cpu="2", memory="4Gi") for i in range(2)]
    podgroups, pods = [], []
    podgroups.append(build_podgroup("ej", min_member=3))
    for t in range(3):
        pods.append(build_pod(f"ej-{t}", group="ej", cpu="1", memory="1Gi"))
    podgroups.append(build_podgroup("dyn", min_member=1))
    p = build_pod("dyn-0", group="dyn", cpu="1", memory="1Gi")
    p.spec.host_ports = [9090]
    pods.append(p)
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=podgroups, pods=pods)
    sched = Scheduler(store, conf=default_conf(backend="tpu"))
    binder = FakeBinder()
    sched.cache.binder = binder
    # bulk path picks bulk_threshold off the backend built per-cycle; the
    # monkeypatched module constant flows through TensorBackend.__init__
    sched.run_once()
    binds = binder.binds
    per_node = {}
    for key, node in binds.items():
        per_node[node] = per_node.get(node, 0) + 1
    assert sum(per_node.values()) == 4  # 3 express + 1 dynamic, full cluster
    assert all(v <= 2 for v in per_node.values()), per_node


def test_partition_unsafe_when_dynamic_job_outranks_express():
    """A dynamic job with higher (job-level) priority than an express job
    in the same queue must take the exact host path — device-first would
    hand contested capacity to the lower-priority job."""
    from volcano_tpu.api.objects import Metadata, PriorityClass

    def store_mk():
        hi_pg = build_podgroup("hi", min_member=1)
        hi_pg.priority_class_name = "high"
        store = make_store(
            nodes=[build_node("n0", cpu="1", memory="2Gi")],  # ONE pod fits
            queues=[build_queue("default")],
            podgroups=[build_podgroup("lo", min_member=1), hi_pg],
            pods=[build_pod("lo-0", group="lo", cpu="1", memory="1Gi")],
        )
        store.create("PriorityClass", PriorityClass(
            meta=Metadata(name="high", namespace=""), value=10))
        hi = build_pod("hi-0", group="hi", cpu="1", memory="1Gi")
        hi.spec.host_ports = [8080]  # dynamic
        store.create("Pod", hi)
        return store

    binds = _run(store_mk(), "tpu")
    assert binds == {"default/hi-0": "n0"}  # priority respected
    assert _run(store_mk(), "host") == binds


def test_bulk_apply_forces_exact_replay_for_foreign_handlers():
    """An event handler registered by anything other than the device-modeled
    plugins (drf/proportion) must see every allocate decision: the bulk
    apply path (which skips per-task events) is bypassed in favor of exact
    replay even above the bulk threshold."""
    from volcano_tpu.scheduler import tensor_actions
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.framework import open_session
    from volcano_tpu.scheduler.session import EventHandler
    from volcano_tpu.scheduler.tensor_backend import TensorBackend

    nodes = [build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(2)]
    podgroups = [build_podgroup("ej", min_member=3)]
    pods = [build_pod(f"ej-{t}", group="ej", cpu="1", memory="1Gi")
            for t in range(3)]
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=podgroups, pods=pods)
    cache = SchedulerCache(store)
    ssn = open_session(cache, default_conf(backend="tpu").tiers)
    ssn.tensor_backend = TensorBackend(ssn, bulk_threshold=0)
    seen = []
    ssn.add_event_handler(
        EventHandler(allocate_func=lambda e: seen.append(e.task.key))
    )
    tensor_actions.allocate(ssn)
    assert sorted(seen) == [f"default/ej-{t}" for t in range(3)]
