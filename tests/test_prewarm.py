"""Compile-stall mitigation: Scheduler.prewarm + the persistent XLA
compilation cache (VERDICT r1 weak #4 / next #3).

The deployed contract: a restarted scheduler pays cache deserialization in
prewarm() — before its first cycle — instead of recompiling device solves
inside the 1 s scheduling period.
"""

import os

from helpers import build_node, build_pod, build_podgroup, make_store
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import (
    Scheduler,
    enable_persistent_compilation_cache,
)


def _store(n_nodes=3, n_tasks=4):
    return make_store(
        nodes=[build_node(f"n{i}") for i in range(n_nodes)],
        podgroups=[build_podgroup("pg", min_member=n_tasks)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(n_tasks)],
    )


def test_prewarm_compiles_current_and_next_bucket():
    sched = Scheduler(_store(), conf=default_conf("tpu"))
    spent = sched.prewarm(bucket_levels=1)
    assert spent > 0.0
    # prewarm must not bind, evict, or write anything
    assert sched.cache.bind_log == [] and sched.cache.evict_log == []
    # the real cycle after prewarm schedules normally
    sched.run_once()
    assert len(sched.cache.bind_log) == 4


def test_prewarm_covers_victim_solves_under_full_conf():
    sched = Scheduler(_store(), conf=full_conf("tpu"))
    assert sched.prewarm() > 0.0
    sched.run_once()
    assert len(sched.cache.bind_log) == 4


def test_prewarm_noop_for_host_backend():
    sched = Scheduler(_store(), conf=default_conf("host"))
    assert sched.prewarm() == 0.0


def test_persistent_cache_dir_populated(tmp_path):
    """With VOLCANO_TPU_XLA_CACHE set, compiled solves land on disk (the
    artifact a restarted process deserializes instead of recompiling).
    Run in a subprocess: the cache dir is process-global and this process's
    jit cache may already hold the solves (nothing new would be written)."""
    import subprocess
    import sys

    cache_dir = str(tmp_path / "xla")
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    repo_dir = os.path.dirname(tests_dir)
    code = """
import sys
sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
from helpers import build_node, build_pod, build_podgroup, make_store
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import (
    Scheduler, enable_persistent_compilation_cache,
)
assert enable_persistent_compilation_cache() == {cache!r}
store = make_store(
    nodes=[build_node("n0")],
    podgroups=[build_podgroup("pg", min_member=1)],
    pods=[build_pod("p0", group="pg", cpu="1")],
)
sched = Scheduler(store, conf=default_conf("tpu"))
spent = sched.prewarm(bucket_levels=0)
assert spent > 0.0
""".format(repo=repo_dir, tests=tests_dir, cache=cache_dir)
    env = dict(os.environ, VOLCANO_TPU_XLA_CACHE=cache_dir,
               JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=300)
    assert os.listdir(cache_dir), "no compilation cache entries written"


def test_enable_cache_off_switch(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_XLA_CACHE", "off")
    assert enable_persistent_compilation_cache() is None


def test_prewarm_queueless_and_empty_cluster_do_not_crash():
    """Bootstrapping clusters: no queues yet (the fast snapshot builder
    returns (None, {})) or nothing at all — prewarm must fall back to the
    object-session shapes without raising (a KeyError here kills the
    daemon at startup, review r4)."""
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.store import Store

    # no queues, but nodes/pods exist
    store = make_store(nodes=[build_node("n0")], queues=[],
                       podgroups=[build_podgroup("pg", min_member=1)],
                       pods=[build_pod("p0", group="pg", cpu="1")])
    for q in list(store.items("Queue")):
        store.delete("Queue", q.meta.key)
    sched = Scheduler(store, conf=full_conf("tpu"))
    sched.prewarm(bucket_levels=0)

    # completely empty store
    sched = Scheduler(Store(), conf=full_conf("tpu"))
    sched.prewarm(bucket_levels=0)
