"""Compile-stall mitigation: Scheduler.prewarm + the persistent XLA
compilation cache (VERDICT r1 weak #4 / next #3).

The deployed contract: a restarted scheduler pays cache deserialization in
prewarm() — before its first cycle — instead of recompiling device solves
inside the 1 s scheduling period.
"""

import os

from helpers import build_node, build_pod, build_podgroup, make_store
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import (
    Scheduler,
    enable_persistent_compilation_cache,
)


def _store(n_nodes=3, n_tasks=4):
    return make_store(
        nodes=[build_node(f"n{i}") for i in range(n_nodes)],
        podgroups=[build_podgroup("pg", min_member=n_tasks)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(n_tasks)],
    )


def test_prewarm_compiles_current_and_next_bucket():
    sched = Scheduler(_store(), conf=default_conf("tpu"))
    spent = sched.prewarm(bucket_levels=1)
    assert spent > 0.0
    # prewarm must not bind, evict, or write anything
    assert sched.cache.bind_log == [] and sched.cache.evict_log == []
    # the real cycle after prewarm schedules normally
    sched.run_once()
    assert len(sched.cache.bind_log) == 4


def test_prewarm_covers_victim_solves_under_full_conf():
    sched = Scheduler(_store(), conf=full_conf("tpu"))
    assert sched.prewarm() > 0.0
    sched.run_once()
    assert len(sched.cache.bind_log) == 4


def test_prewarm_noop_for_host_backend():
    sched = Scheduler(_store(), conf=default_conf("host"))
    assert sched.prewarm() == 0.0


def test_persistent_cache_dir_populated(tmp_path):
    """With VOLCANO_TPU_XLA_CACHE set, compiled solves land on disk (the
    artifact a restarted process deserializes instead of recompiling).
    Run in a subprocess: the cache dir is process-global and this process's
    jit cache may already hold the solves (nothing new would be written)."""
    import subprocess
    import sys

    cache_dir = str(tmp_path / "xla")
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    repo_dir = os.path.dirname(tests_dir)
    code = """
import sys
sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
from helpers import build_node, build_pod, build_podgroup, make_store
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import (
    Scheduler, enable_persistent_compilation_cache,
)
assert enable_persistent_compilation_cache() == {cache!r}
store = make_store(
    nodes=[build_node("n0")],
    podgroups=[build_podgroup("pg", min_member=1)],
    pods=[build_pod("p0", group="pg", cpu="1")],
)
sched = Scheduler(store, conf=default_conf("tpu"))
spent = sched.prewarm(bucket_levels=0)
assert spent > 0.0
""".format(repo=repo_dir, tests=tests_dir, cache=cache_dir)
    env = dict(os.environ, VOLCANO_TPU_XLA_CACHE=cache_dir,
               JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=300)
    assert os.listdir(cache_dir), "no compilation cache entries written"


def test_enable_cache_off_switch(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_XLA_CACHE", "off")
    assert enable_persistent_compilation_cache() is None


def test_prewarm_queueless_and_empty_cluster_do_not_crash():
    """Bootstrapping clusters: no queues yet (the fast snapshot builder
    returns (None, {})) or nothing at all — prewarm must fall back to the
    object-session shapes without raising (a KeyError here kills the
    daemon at startup, review r4)."""
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.store import Store

    # no queues, but nodes/pods exist
    store = make_store(nodes=[build_node("n0")], queues=[],
                       podgroups=[build_podgroup("pg", min_member=1)],
                       pods=[build_pod("p0", group="pg", cpu="1")])
    for q in list(store.items("Queue")):
        store.delete("Queue", q.meta.key)
    sched = Scheduler(store, conf=full_conf("tpu"))
    sched.prewarm(bucket_levels=0)

    # completely empty store
    sched = Scheduler(Store(), conf=full_conf("tpu"))
    sched.prewarm(bucket_levels=0)


def _bigger_store(n_nodes=12, n_jobs=8, tasks=3):
    pods, pgs = [], []
    for j in range(n_jobs):
        pgs.append(build_podgroup(f"pg{j}", min_member=tasks))
        pods.extend(
            build_pod(f"p{j}-{t}", group=f"pg{j}", cpu="500m")
            for t in range(tasks)
        )
    return make_store(
        nodes=[build_node(f"n{i}") for i in range(n_nodes)],
        podgroups=pgs, pods=pods,
    )


def test_mirror_checkpoint_restore_reconciles_deltas(tmp_path):
    """Warm restart (VERDICT r4 next #5): a restored mirror + delta
    reconcile produces the same snapshot as a full list sync, across
    binds, deletions, additions, and PodGroup updates that happened while
    the checkpoint was cold."""
    import numpy as np

    from volcano_tpu.api.types import PodPhase
    from volcano_tpu.scheduler.fastpath import ArrayMirror, build_fast_snapshot

    store = _bigger_store()
    m = ArrayMirror(store, "volcano-tpu", "default")
    m.drain()
    ckpt = str(tmp_path / "mirror.ckpt")
    m.save_checkpoint(ckpt)

    # cold-window mutations: a bind, a delete, a new pod, a pg update
    store.patch("Pod", "default/p0-0", {"node_name": "n0",
                                        "phase": PodPhase.RUNNING})
    store.delete("Pod", "default/p1-0")
    store.create("Pod", build_pod("late", group="pg2", cpu="250m"))
    store.patch("PodGroup", "default/pg3", {"min_member": 1})

    restored = ArrayMirror(store, "volcano-tpu", "default")
    assert restored.try_restore_checkpoint(ckpt)
    fresh = ArrayMirror(store, "volcano-tpu", "default")
    fresh.drain()

    s1, a1 = build_fast_snapshot(restored)
    s2, a2 = build_fast_snapshot(fresh)
    for field in (
        "node_used", "node_idle", "node_task_count", "task_req", "task_job",
        "task_valid", "job_queue", "job_min_available", "job_ready_init",
        "job_schedulable", "job_start", "job_ntasks", "queue_alloc_init",
        "queue_request",
    ):
        np.testing.assert_array_equal(
            getattr(s1, field), getattr(s2, field), err_msg=field
        )
    assert s1.job_uids == s2.job_uids
    assert a1["pe_rows"].size == a2["pe_rows"].size


def test_mirror_checkpoint_rejects_foreign_lineage(tmp_path):
    """A checkpoint from a different store (younger resource version) or
    configuration is refused — the caller falls back to a full sync."""
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    store = _bigger_store()
    m = ArrayMirror(store, "volcano-tpu", "default")
    m.drain()
    ckpt = str(tmp_path / "mirror.ckpt")
    m.save_checkpoint(ckpt)

    fresh_store = _bigger_store(n_nodes=2, n_jobs=1)  # far fewer writes
    m2 = ArrayMirror(fresh_store, "volcano-tpu", "default")
    assert not m2.try_restore_checkpoint(ckpt)
    m3 = ArrayMirror(store, "other-scheduler", "default")
    assert not m3.try_restore_checkpoint(ckpt)
    m4 = ArrayMirror(store, "volcano-tpu", "default")
    assert not m4.try_restore_checkpoint(str(tmp_path / "missing.ckpt"))


def test_scheduler_checkpoint_roundtrip_schedules_identically(tmp_path):
    """Scheduler-level: run a cycle, checkpoint, restart with
    mirrorCheckpoint configured — the restarted scheduler restores (no
    full ingest), then schedules new work exactly like a fresh one."""
    conf = full_conf("tpu")
    conf.mirror_checkpoint = str(tmp_path / "m.ckpt")
    store = _bigger_store()
    sched = Scheduler(store, conf=conf)
    sched.prewarm()
    sched.run_once()
    assert sched.save_mirror_checkpoint()

    store.create("PodGroup", build_podgroup("fresh", min_member=1))
    store.create("Pod", build_pod("fresh-0", group="fresh", cpu="250m"))

    sched2 = Scheduler(store, conf=conf)
    sched2.prewarm()
    assert sched2.fast_cycle.restored_from_checkpoint
    sched2.run_once()
    assert ("default/fresh-0" in dict(sched2.cache.bind_log))
