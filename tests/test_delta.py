"""vtdelta (scheduler/delta/): event-driven incremental scheduling core.

* snapshot-incremental parity: micro-built snapshots are bit-for-bit
  equal to fresh full builds over randomized seeded event streams (the
  oracle runs inside every micro cycle here);
* delta-vs-full fuzz: lockstep schedulers over identical stores produce
  identical bind logs with delta on vs off;
* structural events (node add/remove, job remove, queue move, preempt/
  reclaim waves) force full fallbacks with their trigger reason in the
  cycle's timeseries row, and micro-cycles resume after;
* jit flatness: >= 50 post-warmup micro-cycles with varying dirty sizes
  advance the compile counter by exactly zero;
* admission control: token-bucket holds, watermark shedding to the
  ``Backlogged`` condition (never dropped), sticky re-shed, re-admit on
  recovery;
* metrics exposition, `vtctl top` delta panel, and the chaos-storm /
  crash-kill SLO gates composed with delta mode on.
"""

import http.client
import json
import urllib.request

import numpy as np
import pytest

from volcano_tpu import timeseries
from volcano_tpu.api import Resource
from volcano_tpu.api.objects import Metadata, Node, PriorityClass, Queue
from volcano_tpu.api.types import PodPhase
from volcano_tpu.backoff import Backoff
from volcano_tpu.loadgen import LoadGen, LoadSpec, run_open_loop
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store import Store
from volcano_tpu.store.client import RemoteStore, RemoteStoreError, wait_healthy
from volcano_tpu.store.server import StoreServer

from helpers import (
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)

TRANSIENT = (RemoteStoreError, OSError, http.client.HTTPException)


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    timeseries.disarm()
    yield
    timeseries.disarm()
    metrics.reset()


def _delta_conf(base="default", **kw):
    conf = (default_conf if base == "default" else full_conf)("tpu")
    conf.delta = "on"
    conf.delta_oracle = True  # every micro cycle proves bit-equality
    for k, v in kw.items():
        setattr(conf, k, v)
    return conf


def _sched(store, conf):
    # the default Binder writes placements back to the store, so tests
    # can assert on pod.node_name AND on cache.bind_log
    return Scheduler(store, conf=conf)


def _mixed_store(seed, n_nodes=5, n_jobs=6, running_jobs=2):
    import random

    rng = random.Random(seed)
    nodes = [build_node(f"n{i:02d}", cpu=str(rng.choice([4, 8])),
                        memory=f"{rng.choice([8, 16])}Gi")
             for i in range(n_nodes)]
    queues = [build_queue("qa", weight=2), build_queue("qb", weight=1),
              build_queue("default")]
    podgroups, pods = [], []
    for j in range(n_jobs):
        n_tasks = rng.randint(1, 4)
        pg = build_podgroup(f"job{j}", min_member=rng.randint(1, n_tasks),
                            queue=rng.choice(["qa", "qb"]))
        podgroups.append(pg)
        running = j < running_jobs
        for t in range(n_tasks):
            pod = build_pod(f"job{j}-{t}", group=f"job{j}",
                            cpu=rng.choice(["500m", "1"]),
                            memory=f"{rng.choice([512, 1024])}Mi",
                            priority=rng.choice([0, 5]))
            if running:
                pod.node_name = nodes[t % n_nodes].meta.name
                pod.phase = PodPhase.RUNNING
            pods.append(pod)
    return make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                      pods=pods)


def _fuzz_stream(store, sched, rng, steps):
    """Randomized event stream: gang arrivals, pod deletions, node churn,
    queue moves — pumping after each step.  The engine's oracle asserts
    snapshot-incremental parity inside every micro cycle."""
    created = []
    for step in range(steps):
        ev = rng.random()
        if ev < 0.55 or not created:
            name = f"fz{step:03d}"
            store.create("PodGroup", build_podgroup(
                name, min_member=1, queue=rng.choice(["qa", "qb"])))
            for t in range(rng.randint(1, 3)):
                store.create("Pod", build_pod(
                    f"{name}-{t}", group=name, cpu=rng.choice(["100m", "250m"]),
                    memory="128Mi", priority=rng.choice([0, 5])))
            created.append(name)
        elif ev < 0.75:
            victim = created.pop(rng.randrange(len(created)))
            for p in list(store.list("Pod")):
                if p.meta.name.startswith(victim + "-"):
                    store.delete("Pod", f"{p.meta.namespace}/{p.meta.name}")
            store.delete("PodGroup", f"default/{victim}")
        elif ev < 0.9:
            store.create("Node", build_node(f"nx{step:03d}", cpu="4",
                                            memory="8Gi"))
        else:
            # queue move: a structural job-requeue
            victim = rng.choice(created)
            pg = store.get("PodGroup", f"default/{victim}")
            if pg is not None:
                store.patch("PodGroup", f"default/{victim}",
                            {"queue": "qb" if pg.queue == "qa" else "qa"})
        sched.run_once()


@pytest.mark.parametrize("seed", range(4))
def test_micro_cycle_snapshot_parity_fuzz(seed):
    """The snapshot-incremental oracle over a randomized stream: every
    micro cycle's snapshot is bit-for-bit a fresh full build's (the
    engine raises from inside run_once otherwise), and micro cycles
    actually dominate the steady stream."""
    import random

    store = _mixed_store(seed)
    sched = _sched(store, _delta_conf())
    sched.run_once()
    _fuzz_stream(store, sched, random.Random(1000 + seed), steps=25)
    micro = metrics.get_counter("volcano_delta_micro_cycles_total")
    assert micro >= 10, f"only {micro} micro cycles in a 25-step stream"


@pytest.mark.parametrize("seed", range(4))
def test_delta_binds_equal_full_cycle_replay(seed):
    """Acceptance: micro-cycle placements bit-for-bit equal a full-cycle
    replay — two schedulers, identical stores and event streams, delta
    on vs off, identical bind logs."""
    import random

    logs = []
    for delta_on in (True, False):
        store = _mixed_store(seed)
        conf = _delta_conf() if delta_on else default_conf("tpu")
        sched = _sched(store, conf)
        sched.run_once()
        _fuzz_stream(store, sched, random.Random(2000 + seed), steps=20)
        logs.append(list(sched.cache.bind_log))
    assert logs[0] == logs[1]
    assert len(logs[0]) > 5


def test_structural_events_force_full_with_reason_then_micro_resumes():
    store = _mixed_store(3)
    sched = _sched(store, _delta_conf(base="full"))
    fc_reason = lambda: sched.fast_cycle.delta.last["fallback_reason"]  # noqa: E731
    fc_mode = lambda: sched.fast_cycle.delta.last["mode"]  # noqa: E731
    sched.run_once()
    assert (fc_mode(), fc_reason()) == ("full", "arm")
    sched.run_once()
    assert fc_mode() == "micro"

    store.create("Node", build_node("late", cpu="8", memory="16Gi"))
    sched.run_once()
    assert (fc_mode(), fc_reason()) == ("full", "node-add")
    sched.run_once()
    assert fc_mode() == "micro"

    store.delete("Node", "/late")
    sched.run_once()
    assert (fc_mode(), fc_reason()) == ("full", "node-remove")

    pg5 = store.get("PodGroup", "default/job5")
    store.patch("PodGroup", "default/job5",
                {"queue": "qb" if pg5.queue == "qa" else "qa"})
    sched.run_once()
    assert (fc_mode(), fc_reason()) == ("full", "job-requeue")

    for p in list(store.list("Pod")):
        if p.meta.name.startswith("job5-"):
            store.delete("Pod", f"{p.meta.namespace}/{p.meta.name}")
    store.delete("PodGroup", "default/job5")
    sched.run_once()
    assert (fc_mode(), fc_reason()) == ("full", "job-remove")
    sched.run_once()
    assert fc_mode() == "micro"


def test_dirty_storm_falls_back(monkeypatch):
    from volcano_tpu.scheduler.delta import engine as engine_mod

    store = _mixed_store(0, n_jobs=2, running_jobs=0)
    sched = _sched(store, _delta_conf())
    for _ in range(3):  # arm + drain the first cycle's own bind echoes
        sched.run_once()
    assert sched.fast_cycle.delta.last["mode"] == "micro"
    monkeypatch.setattr(engine_mod, "DIRTY_STORM", 4)
    # one wave dirtying more rows than the (shrunk) storm bound
    for i in range(4):
        store.create("PodGroup", build_podgroup(f"w{i}", min_member=1,
                                                queue="qa"))
        for t in range(2):
            store.create("Pod", build_pod(f"w{i}-{t}", group=f"w{i}",
                                          cpu="100m", memory="128Mi"))
    sched.run_once()
    assert sched.fast_cycle.delta.last["fallback_reason"] == "dirty-storm"
    # the wave's own bind echoes can re-trip the (shrunk) bound once
    # more; after they drain, micro-cycles resume
    for _ in range(3):
        sched.run_once()
    assert sched.fast_cycle.delta.last["mode"] == "micro"


def test_contention_wave_rebuilds_full_with_reason():
    """A preempt wave arriving in steady micro state: the cycle rebuilds
    on the full path (reason ``contention``) before victim pools are
    carved, and the micro-vs-full binds stay equal by replay."""
    store = make_store(
        nodes=[build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(4)],
        queues=[build_queue("qa", weight=1), build_queue("default")],
        podgroups=[], pods=[])
    store.create("PriorityClass", PriorityClass(
        meta=Metadata(name="urgent", namespace=""), value=10))
    store.create("PriorityClass", PriorityClass(
        meta=Metadata(name="low", namespace=""), value=1))
    # the cluster is full of RUNNING low-priority residents (victims
    # must be running — a bound-not-started pod is not preemptible)
    for i in range(8):
        pg = build_podgroup(f"low{i}", min_member=1, queue="qa")
        pg.priority_class_name = "low"
        store.create("PodGroup", pg)
        store.create("Pod", build_pod(
            f"low{i}-0", group=f"low{i}", cpu="2", memory="2Gi", priority=1,
            node_name=f"n{i % 4}", phase=PodPhase.RUNNING))
    sched = _sched(store, _delta_conf(base="full"))
    for _ in range(3):
        sched.run_once()
    assert sched.fast_cycle.delta.last["mode"] == "micro"
    # the starving high-priority gang: preempt work on a dirty-only pump
    hi = build_podgroup("hi", min_member=2, queue="qa")
    hi.priority_class_name = "urgent"
    store.create("PodGroup", hi)
    for t in range(2):
        store.create("Pod", build_pod(f"hi-{t}", group="hi", cpu="2",
                                      memory="2Gi", priority=10))
    sched.run_once()
    assert metrics.get_counter("volcano_delta_full_fallbacks_total",
                               reason="contention") >= 1
    evicted = [k for k, _ in sched.cache.evict_log]
    assert evicted, "the wave must actually preempt"
    for key in evicted:  # play kubelet: reap the evicted victims
        store.delete("Pod", key)
    for _ in range(3):
        sched.run_once()
    # the wave resolved: the urgent gang is placed
    hi_pods = [p for p in store.list("Pod") if p.meta.name.startswith("hi-")]
    assert hi_pods and all(p.node_name for p in hi_pods)


def _trickle_store(n_nodes=10):
    store = Store()
    store.create("Queue", Queue(
        meta=Metadata(name="default", namespace=""), weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i}", namespace=""),
            allocatable=Resource(8000.0, 16.0 * (1 << 30),
                                 max_task_num=200)))
    return store


def _submit_gang(store, name, n, cpu=10.0):
    store.create("PodGroup", build_podgroup(name, min_member=n,
                                            queue="default"))
    for t in range(n):
        p = build_pod(f"{name}-{t}", group=name, cpu=f"{int(cpu)}m",
                      memory="16Mi")
        store.create("Pod", p)


def test_jit_cache_flat_across_50_post_warmup_micro_cycles():
    """Acceptance: >= 50 post-warmup micro-cycles with dirty sizes
    varying 1-3 gangs x 1-5 tasks (inside one task bucket) advance the
    jit compile counter by exactly ZERO — shape-bucketing discipline
    holds under delta mode, admission filter included."""
    from volcano_tpu import vtprof

    prof = vtprof.arm()
    try:
        store = _trickle_store()
        sched = _sched(store, _delta_conf(delta_admit_qps=1e9))
        # 70 initial gangs pin the J bucket at 128: the 50-gang trickle
        # below (70 + 2 + 50 = 122 live jobs) never re-buckets it
        for i in range(70):
            _submit_gang(store, f"w{i:03d}", 1)
        sched.run_once()
        for i in range(2):  # warm the trickle shape itself
            _submit_gang(store, f"t{i:03d}", 1)
            sched.run_once()
        prof.warmup_handshake()
        sched.run_once()
        assert prof.steady
        total_before = prof.compiles_total
        micro_before = metrics.get_counter("volcano_delta_micro_cycles_total")
        for i in range(50):
            # dirty sizes vary 1-5 tasks — all inside the minimum task
            # bucket, so the solve shapes stay pinned
            _submit_gang(store, f"k{i:03d}", 1 + (i % 5), cpu=10.0)
            sched.run_once()
        micro = metrics.get_counter(
            "volcano_delta_micro_cycles_total") - micro_before
        assert micro >= 50, f"only {micro} micro cycles in the trickle"
        assert prof.compiles_total == total_before, (
            "micro-cycle trickle recompiled", prof._cache_seen)
        assert prof.anomalies_snapshot() == []
        assert all(p.node_name for p in store.list("Pod"))
    finally:
        vtprof.disarm()


# -- admission control + shedding ---------------------------------------------


def _starved_store():
    """One tiny node nothing fits on: every gang backlogs."""
    return make_store(
        nodes=[build_node("n0", cpu="1", memory="1Gi")],
        queues=[build_queue("default")], podgroups=[], pods=[])


def _submit_backlog(store, n, cpu="4", prio=None):
    for i in range(n):
        store.create("PodGroup", build_podgroup(f"g{i}", min_member=1,
                                                queue="default"))
        store.create("Pod", build_pod(
            f"g{i}-0", group=f"g{i}", cpu=cpu, memory="4Gi",
            priority=(prio(i) if prio else 0)))


def test_token_bucket_admission_holds_then_drains():
    """rate=2 gangs/s with an injectable clock: the first pump admits
    the burst, holds the rest (filtered from solve, still INQUEUE); as
    virtual time advances, held gangs drain through the gate — one
    batched micro-cycle per pump, tokens charged once per gang."""
    clock = [0.0]
    store = make_store(
        nodes=[build_node("n0", cpu="16", memory="32Gi")],
        queues=[build_queue("default")], podgroups=[], pods=[])
    conf = _delta_conf(delta_admit_qps=2.0, delta_burst=2)
    sched = _sched(store, conf)
    sched.run_once()
    fc = sched.fast_cycle
    fc.delta.admission.bucket._now = lambda: clock[0]
    fc.delta.admission.bucket._last = 0.0
    _submit_backlog(store, 6, cpu="100m")
    sched.run_once()
    assert fc.delta.last["backlog_gangs"] == 6
    assert fc.delta.last["held_gangs"] == 4  # burst=2 admitted
    bound = lambda: sum(1 for p in store.list("Pod") if p.node_name)  # noqa: E731
    assert bound() == 2
    # no time passes -> nothing new admitted, held set stable
    sched.run_once()
    assert fc.delta.last["held_gangs"] == 4
    assert bound() == 2
    clock[0] = 1.0  # +2 tokens
    sched.run_once()
    assert fc.delta.last["held_gangs"] == 2
    assert bound() == 4
    clock[0] = 2.0
    sched.run_once()
    assert fc.delta.last["held_gangs"] == 0
    assert bound() == 6
    # placed gangs left the backlog; admission slots were released
    sched.run_once()
    assert fc.delta.last["backlog_gangs"] == 0


def test_shed_to_backlogged_condition_and_readmit():
    """Above the high watermark the lowest-priority over-quota gangs get
    the ``Backlogged`` condition — pods stay in the store (never
    dropped) — and the condition clears once depth recovers below the
    low watermark."""
    store = _starved_store()
    sched = _sched(store, _delta_conf(delta_high_watermark=4))
    sched.run_once()
    fc = sched.fast_cycle
    # priorities ascending with i: g0..g3 are the lowest -> shed targets
    _submit_backlog(store, 8, prio=lambda i: 8 - i)
    sched.run_once()
    assert fc.delta.last["backlog_gangs"] == 8
    assert fc.delta.last["shed_gangs"] == 4
    conds = {pg.meta.name: [c for c in pg.status.conditions]
             for pg in store.list("PodGroup")}
    shed = {n for n, cs in conds.items()
            if any(c.kind == "Backlogged" for c in cs)}
    assert shed == {"g4", "g5", "g6", "g7"}  # lowest priority (prio=8-i)
    for c in sum(conds.values(), []):
        if c.kind == "Backlogged":
            assert c.reason == "AdmissionShed" and c.status == "True"
    # never dropped: every pod still lives in the store
    assert len(store.list("Pod")) == 8
    assert metrics.get_counter("volcano_delta_shed_gangs_total") == 4
    # sticky: another pump re-sheds the same gangs, counter flat
    sched.run_once()
    assert metrics.get_counter("volcano_delta_shed_gangs_total") == 4
    # recovery: drain to depth 2 (<= low = high//2)
    for i in range(6):
        if f"g{i}" in shed:
            continue
        store.delete("Pod", f"default/g{i}-0")
        store.delete("PodGroup", f"default/g{i}")
    for n in sorted(shed)[:2]:
        store.delete("Pod", f"default/{n}-0")
        store.delete("PodGroup", f"default/{n}")
    sched.run_once()
    assert fc.delta.last["backlog_gangs"] == 2
    assert fc.delta.last["shed_gangs"] == 0
    for pg in store.list("PodGroup"):
        assert not any(c.kind == "Backlogged" for c in pg.status.conditions)


def test_delta_metrics_exposition():
    store = _mixed_store(2)
    sched = _sched(store, _delta_conf(delta_high_watermark=1))
    sched.run_once()
    sched.run_once()
    text = metrics.expose_text()
    assert "volcano_delta_micro_cycles_total" in text
    assert 'volcano_delta_full_fallbacks_total{reason="arm"}' in text
    assert "# HELP volcano_delta_micro_cycles_total" in text


def test_timeseries_rows_carry_mode_and_vtctl_renders_delta_panel():
    from volcano_tpu.cli.vtctl import cmd_top

    timeseries.arm()
    store = _mixed_store(1)
    sched = _sched(store, _delta_conf())
    sched.run_once()
    store.create("PodGroup", build_podgroup("late", min_member=1,
                                            queue="qa"))
    store.create("Pod", build_pod("late-0", group="late", cpu="100m",
                                  memory="128Mi"))
    sched.run_once()
    rows = [s for s in timeseries.samples()
            if s.get("kind") == "cycle"]
    assert rows, "no cycle rows recorded"
    assert rows[0]["mode"] == "full" and rows[0]["fallback_reason"] == "arm"
    assert rows[-1]["mode"] == "micro"
    assert "backlog_gangs" in rows[-1]
    text = cmd_top(timeseries.samples())
    assert "delta:" in text and "micro" in text and "fallbacks:" in text


# -- the SLO gates composed with delta mode on --------------------------------


def _delta_gate_run(plan, seed=7, delta=True):
    """Lockstep open-loop over real HTTP with a delta-mode scheduler,
    optionally under a seeded request-plane chaos storm (the ISSUE-9
    gate recipe with conf.delta flipped on)."""
    srv = StoreServer().start()
    try:
        assert wait_healthy(srv.url, timeout=10)
        srv.store.create("Queue", Queue(
            meta=Metadata(name="default", namespace=""), weight=1))
        for i in range(6):
            srv.store.create("Node", Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource(8000.0, 16.0 * (1 << 30),
                                     max_task_num=110)))
        client = RemoteStore(srv.url)
        conf = full_conf("tpu")
        if delta:
            conf.delta = "on"
            conf.delta_oracle = True
        sched = Scheduler(client, conf=conf)
        if plan is not None:
            data = json.dumps(plan).encode()
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/chaos", data=data, method="POST"), timeout=10)
        spec = LoadSpec(qps=40, duration_s=0.8, seed=seed,
                        cpu_millis=(100,), mem_mb=(64,), namespace="slo")
        gen = LoadGen(client, spec)
        retry = Backoff(base=0.01, cap=0.2, seed=41)
        import time as _time

        deadline = _time.monotonic() + 120
        vnow = 0.0
        while not gen.done:
            assert _time.monotonic() < deadline, "gate never converged"
            for arr in gen.due(vnow):
                while True:
                    try:
                        gen.submit(arr)
                        break
                    except TRANSIENT:
                        retry.sleep()
            while True:
                try:
                    sched.run_once()
                    break
                except TRANSIENT:
                    retry.sleep()
            try:
                gen.observe()
            except TRANSIENT:
                retry.sleep()
            vnow += 0.05
        if plan is not None:
            status = json.load(urllib.request.urlopen(
                srv.url + "/chaos", timeout=10))
            assert any(s["fires"] > 0 for s in status["stats"]), (
                "the storm never actually fired")
        return gen.placements(), gen
    finally:
        srv.stop()


_DELTA_GATE_PLAN = {
    "seed": 11,
    "rules": [
        {"point": "server.request", "action": "http_500",
         "every": 5, "count": 25},
        {"point": "server.request", "action": "cut_body",
         "after": 7, "every": 9, "count": 8},
    ],
}


def test_chaos_storm_slo_gate_with_delta_on():
    """The chaos gate composed with delta mode: bounded tail, full
    convergence, and placements bit-for-bit equal to both the fault-free
    delta run and the fault-free full-cycle run."""
    placed_chaos, gen_chaos = _delta_gate_run(_DELTA_GATE_PLAN)
    placed_clean, gen_clean = _delta_gate_run(None)
    placed_full, _ = _delta_gate_run(None, delta=False)
    assert gen_chaos.submitted_pods == gen_clean.submitted_pods > 20
    assert gen_chaos.bound_pods == gen_chaos.submitted_pods
    assert placed_chaos == placed_clean == placed_full
    p99 = gen_chaos.quantile_ms(0.99)
    assert 0.0 < p99 < 5000.0, p99
    assert metrics.get_counter("volcano_delta_micro_cycles_total") > 0


def test_crash_kill_restart_rearms_delta_and_converges():
    """Crash-kill composed with delta: the scheduler process dies every
    few pumps (rebuilt from scratch — fresh mirror, fresh engine, full
    relist) and the run still converges to exactly the placements of an
    uninterrupted delta run."""
    def run(kill_every):
        store = _mixed_store(5, running_jobs=0)
        sched = _sched(store, _delta_conf())
        for step in range(12):
            if kill_every and step and step % kill_every == 0:
                # crash-kill: the replacement relists everything and
                # re-arms the delta hook from scratch
                sched = _sched(store, _delta_conf())
            if step < 6:
                store.create("PodGroup", build_podgroup(
                    f"ck{step}", min_member=1, queue="qa"))
                store.create("Pod", build_pod(
                    f"ck{step}-0", group=f"ck{step}", cpu="100m",
                    memory="128Mi"))
            sched.run_once()
        return sorted((f"{p.meta.namespace}/{p.meta.name}", p.node_name)
                      for p in store.list("Pod"))

    uninterrupted = run(kill_every=0)
    crashed = run(kill_every=3)
    assert crashed == uninterrupted
    assert len(crashed) > 6
    assert all(node for _, node in crashed)
    # every restart re-armed the hook (structural "arm" fallback)
    assert metrics.get_counter("volcano_delta_full_fallbacks_total",
                               reason="arm") >= 4


def test_lockstep_saturation_sustains_250_gangs_per_second():
    """Acceptance: the lockstep harness sustains >= 250 gangs/s through
    a delta-mode scheduler with bounded p99 (>= 10x the BENCH_r08 breach
    of 25 gangs/s sustained / 100 breach on this CPU container)."""
    store = Store()
    store.create("Queue", Queue(
        meta=Metadata(name="default", namespace=""), weight=1))
    for i in range(8):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i}", namespace=""),
            allocatable=Resource(64000.0, 64.0 * (1 << 30),
                                 max_task_num=500)))
    sched = Scheduler(store, conf=_delta_conf(base="full"))
    spec = LoadSpec(qps=250, duration_s=1.0, seed=3, cpu_millis=(100,),
                    mem_mb=(64,), gang_sizes=((1, 6.0), (2, 3.0)),
                    namespace="sat")
    report = run_open_loop(store, spec, sched.run_once, tick_s=0.05,
                           settle_s=60.0)
    assert report.sustained, report.as_dict()
    assert report.bound_pods == report.submitted_pods > 200
    assert 0.0 < report.p99_ms < 2000.0, report.as_dict()
    assert metrics.get_counter("volcano_delta_micro_cycles_total") > 0
