"""Admission validation/mutation, mirroring reference test/e2e/admission.go
scenarios plus the policy matrix from admit_job.go."""

import pytest

from volcano_tpu.admission import (
    AdmissionError,
    mutate_job,
    validate_job,
    validate_job_update,
)
from volcano_tpu.api.job import Job, JobSpec, LifecyclePolicy, TaskSpec, VolumeSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent
from volcano_tpu.sim import Cluster


def mk_task(name="main", replicas=1, policies=None):
    return TaskSpec(
        name=name,
        replicas=replicas,
        template=PodSpec(image="busybox",
                         resources=Resource.from_resource_list({"cpu": "1"})),
        policies=policies or [],
    )


def mk_job(**spec_kw):
    spec_kw.setdefault("tasks", [mk_task()])
    spec_kw.setdefault("min_available", 1)
    return Job(meta=Metadata(name="j", namespace="test"), spec=JobSpec(**spec_kw))


def test_valid_job_passes():
    ok, msg = validate_job(mk_job())
    assert ok, msg


def test_negative_min_available_rejected():
    ok, msg = validate_job(mk_job(min_available=-1))
    assert not ok and "minAvailable" in msg


def test_no_tasks_rejected():
    ok, msg = validate_job(mk_job(tasks=[]))
    assert not ok and "No task" in msg


def test_nonpositive_replicas_rejected():
    ok, msg = validate_job(mk_job(tasks=[mk_task(replicas=0)]))
    assert not ok and "replicas" in msg


def test_bad_task_name_rejected():
    ok, msg = validate_job(mk_job(tasks=[mk_task(name="Bad_Name")]))
    assert not ok and "DNS-1123" in msg


def test_duplicate_task_name_rejected():
    ok, msg = validate_job(
        mk_job(tasks=[mk_task(name="a"), mk_task(name="a")], min_available=2)
    )
    assert not ok and "duplicated task name" in msg


def test_min_available_exceeds_replicas_rejected():
    ok, msg = validate_job(mk_job(min_available=5))
    assert not ok and "minAvailable" in msg


def test_policy_event_and_exit_code_rejected():
    pol = LifecyclePolicy(
        action=JobAction.RESTART_JOB, event=JobEvent.POD_FAILED, exit_code=3
    )
    ok, msg = validate_job(mk_job(policies=[pol]))
    assert not ok and "simultaneously" in msg


def test_policy_neither_event_nor_exit_code_rejected():
    pol = LifecyclePolicy(action=JobAction.RESTART_JOB)
    ok, msg = validate_job(mk_job(policies=[pol]))
    assert not ok


def test_exit_code_zero_rejected():
    pol = LifecyclePolicy(action=JobAction.RESTART_JOB, exit_code=0)
    ok, msg = validate_job(mk_job(policies=[pol]))
    assert not ok and "0 is not a valid error code" in msg


def test_duplicate_policy_event_rejected():
    pols = [
        LifecyclePolicy(action=JobAction.RESTART_JOB, event=JobEvent.POD_FAILED),
        LifecyclePolicy(action=JobAction.ABORT_JOB, event=JobEvent.POD_FAILED),
    ]
    ok, msg = validate_job(mk_job(policies=pols))
    assert not ok and "duplicated job event policies" in msg


def test_any_event_exclusive():
    pols = [
        LifecyclePolicy(action=JobAction.RESTART_JOB, event=JobEvent.ANY),
        LifecyclePolicy(action=JobAction.ABORT_JOB, event=JobEvent.POD_FAILED),
    ]
    ok, msg = validate_job(mk_job(policies=pols))
    assert not ok and "*" in msg


def test_internal_event_action_rejected():
    ok, msg = validate_job(
        mk_job(policies=[LifecyclePolicy(action=JobAction.SYNC_JOB,
                                         event=JobEvent.POD_FAILED)])
    )
    assert not ok and "invalid policy action" in msg


def test_unknown_plugin_rejected():
    ok, msg = validate_job(mk_job(plugins={"nope": []}))
    assert not ok and "job plugin" in msg


def test_volume_validation():
    ok, msg = validate_job(mk_job(volumes=[VolumeSpec(mount_path="")]))
    assert not ok and "mountPath is required" in msg
    ok, msg = validate_job(
        mk_job(volumes=[VolumeSpec(mount_path="/d"), VolumeSpec(mount_path="/d")])
    )
    assert not ok and "duplicated mountPath" in msg


def test_update_spec_frozen():
    import copy

    old = mk_job()
    new = copy.deepcopy(old)
    ok, _ = validate_job_update(new, old)
    assert ok
    new.spec.min_available = 0
    ok, msg = validate_job_update(new, old)
    assert not ok and "not allowed to modify" in msg


def test_mutate_defaults_queue_and_task_names():
    job = mk_job(tasks=[TaskSpec(name="", replicas=1), TaskSpec(name="", replicas=1)])
    job.spec.queue = ""
    mutate_job(job)
    assert job.spec.queue == "default"
    assert [t.name for t in job.spec.tasks] == ["default0", "default1"]


def test_cluster_submit_path_enforces_admission():
    c = Cluster(with_scheduler=False)
    with pytest.raises(AdmissionError):
        c.submit_job(mk_job(min_available=9))

    job = mk_job()
    job.spec.queue = ""
    c.submit_job(job)
    assert job.spec.queue == "default"
    assert c.store.get("Job", "test/j") is not None


def test_update_exemption_limited_to_generated_claim_names():
    """Filling a previously-empty volume_claim_name is allowed ONLY for the
    controller's generated name; pointing at someone else's claim is a
    frozen-spec violation."""
    import copy

    from volcano_tpu.api.job import Job, JobSpec, TaskSpec, VolumeSpec
    from volcano_tpu.api.objects import Metadata, PodSpec
    from volcano_tpu.admission.admit import validate_job_update

    def mk(claim=""):
        return Job(
            meta=Metadata(name="j", namespace="d"),
            spec=JobSpec(
                min_available=1,
                tasks=[TaskSpec(name="t", replicas=1,
                                template=PodSpec(image="busybox"))],
                volumes=[VolumeSpec(mount_path="/x", size="1Gi",
                                    volume_claim_name=claim)],
            ),
        )

    old = mk("")
    ok, _ = validate_job_update(mk("j-pvc-0"), old)   # controller write-back
    assert ok
    ok, msg = validate_job_update(mk("victim-pvc-0"), old)  # claim hijack
    assert not ok and "not allowed" in msg
    # overwriting an existing name is frozen even if it matches the pattern
    ok, _ = validate_job_update(mk("j-pvc-0"), mk("other"))
    assert not ok


# -- PodTemplate field validation (admit_job.go:160-193) ---------------------

def mk_tmpl_job(**tmpl_kw):
    tmpl_kw.setdefault("image", "busybox")
    tmpl_kw.setdefault("resources", Resource.from_resource_list({"cpu": "1"}))
    return mk_job(tasks=[TaskSpec(name="main", replicas=1,
                                  template=PodSpec(**tmpl_kw))])


def test_template_missing_image_rejected():
    ok, msg = validate_job(mk_tmpl_job(image=""))
    assert not ok and "image: Required value" in msg and "spec.task[0]" in msg


def test_template_bad_restart_policy_rejected():
    ok, msg = validate_job(mk_tmpl_job(restart_policy="WheneverConvenient"))
    assert not ok and "restartPolicy" in msg


def test_template_negative_resource_rejected():
    ok, msg = validate_job(mk_tmpl_job(resources=Resource(-100, 1 << 30)))
    assert not ok and "resources.cpu" in msg and "non-negative" in msg


def test_template_negative_scalar_rejected():
    ok, msg = validate_job(
        mk_tmpl_job(resources=Resource(100, 0, {"tpu.dev/v5e": -1.0}))
    )
    assert not ok and "tpu.dev/v5e" in msg


def test_template_nan_and_inf_rejected():
    ok, msg = validate_job(mk_tmpl_job(resources=Resource(float("nan"), 0)))
    assert not ok and "resources.cpu" in msg
    ok, msg = validate_job(
        mk_tmpl_job(init_resources=Resource(0, float("inf")))
    )
    assert not ok and "initResources.memory" in msg


def test_template_host_port_range_and_duplicates_rejected():
    ok, msg = validate_job(mk_tmpl_job(host_ports=[0]))
    assert not ok and "between 1 and 65535" in msg
    ok, msg = validate_job(mk_tmpl_job(host_ports=[70000]))
    assert not ok
    ok, msg = validate_job(mk_tmpl_job(host_ports=[8080, 8080]))
    assert not ok and "duplicate port 8080" in msg


def test_template_bad_toleration_rejected():
    from volcano_tpu.api.objects import Toleration

    ok, msg = validate_job(
        mk_tmpl_job(tolerations=[Toleration(key="k", operator="Sometimes")])
    )
    assert not ok and "tolerations.operator" in msg
    ok, msg = validate_job(
        mk_tmpl_job(tolerations=[Toleration(key="k", operator="Exists",
                                            value="v")])
    )
    assert not ok and "must be empty" in msg


def test_template_valid_passes():
    from volcano_tpu.api.objects import Toleration

    ok, msg = validate_job(mk_tmpl_job(
        host_ports=[8080, 9090],
        tolerations=[Toleration(key="k", operator="Exists")],
        restart_policy="Never",
    ))
    assert ok, msg
