"""MPI-shaped job end-to-end (reference test/e2e/mpi.go:26 +
example/openmpi-hello.yaml): a master + workers gang with svc/ssh/env
plugins, verifying the full rsh-discovery contract — headless service,
hostfile ConfigMap with worker DNS rows, shared keypair, pod DNS identity
— and job completion when the master's task completes."""

import pytest

from volcano_tpu.api.job import Job, JobSpec, LifecyclePolicy, TaskSpec, make_pod_name
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase, PodPhase
from volcano_tpu.sim import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(2):
        c.add_node(f"n{i}", {"cpu": "8", "memory": "16Gi", "pods": 110})
    return c


def mpi_job(name="mpi-hello", workers=2):
    req = Resource.from_resource_list({"cpu": "1", "memory": "1Gi"})
    return Job(
        meta=Metadata(name=name, namespace="test"),
        spec=JobSpec(
            min_available=1 + workers,
            plugins={"ssh": [], "svc": [], "env": []},
            tasks=[
                TaskSpec(
                    name="mpimaster",
                    replicas=1,
                    template=PodSpec(image="busybox", resources=req.clone()),
                    policies=[
                        LifecyclePolicy(
                            action=JobAction.COMPLETE_JOB,
                            event=JobEvent.TASK_COMPLETED,
                        )
                    ],
                ),
                TaskSpec(
                    name="mpiworker",
                    replicas=workers,
                    template=PodSpec(image="busybox", resources=req.clone()),
                ),
            ],
        ),
    )


def test_mpi_job_end_to_end(cluster):
    job = mpi_job()
    cluster.submit_job(job)
    cluster.run_until_idle()

    # gang is up
    assert job.status.state.phase == JobPhase.RUNNING
    pods = {p.meta.name: p for p in cluster.store.list("Pod")}
    assert len(pods) == 3
    assert all(p.phase == PodPhase.RUNNING for p in pods.values())

    # headless service selects the job's pods
    svc = cluster.store.get("Service", "test/mpi-hello")
    assert svc is not None and svc.cluster_ip == "None"

    # hostfile ConfigMap lists every task replica as <pod>.<job> DNS rows
    hostfile = cluster.store.get("ConfigMap", "test/mpi-hello-svc")
    assert hostfile is not None
    workers = hostfile.data["mpiworker.host"].splitlines()
    assert workers == [
        f"{make_pod_name('mpi-hello', 'mpiworker', i)}.mpi-hello" for i in range(2)
    ]
    assert hostfile.data["mpimaster.host"].splitlines() == [
        f"{make_pod_name('mpi-hello', 'mpimaster', 0)}.mpi-hello"
    ]

    # ssh keypair ConfigMap: private key + authorized_keys must pair up
    ssh = cluster.store.get("ConfigMap", "test/mpi-hello-ssh")
    assert ssh is not None
    assert set(ssh.data) == {"id_rsa", "id_rsa.pub", "authorized_keys", "config"}
    assert ssh.data["authorized_keys"] == ssh.data["id_rsa.pub"]

    # every pod mounts both ConfigMaps and carries DNS identity + task index
    master_name = make_pod_name("mpi-hello", "mpimaster", 0)
    for p in pods.values():
        assert "mpi-hello-svc" in p.volumes and "mpi-hello-ssh" in p.volumes
        assert p.subdomain == "mpi-hello"
        assert p.hostname == p.meta.name
        assert p.env["VT_TASK_INDEX"] in {"0", "1"}

    # master finishes -> TaskCompleted -> CompleteJob; workers get reaped
    cluster.complete_pod(f"test/{master_name}")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.COMPLETED
    assert cluster.store.list("Pod") == []

    # plugin artifacts are cleaned up with the job's pods on delete
    cluster.store.delete("Job", "test/mpi-hello")
    cluster.run_until_idle()
    assert cluster.store.get("Service", "test/mpi-hello") is None
    assert cluster.store.get("ConfigMap", "test/mpi-hello-svc") is None
    assert cluster.store.get("ConfigMap", "test/mpi-hello-ssh") is None


def test_mpi_gang_waits_for_all_replicas(cluster):
    # master+workers gang larger than the cluster: nothing binds
    job = mpi_job(name="mpi-big", workers=20)
    cluster.submit_job(job)
    cluster.run_until_idle()
    assert job.status.state.phase in (JobPhase.PENDING, JobPhase.INQUEUE)
    assert all(not p.node_name for p in cluster.store.list("Pod"))
