"""Chaos soak: seeded fault schedules on the store bus, convergence asserted.

The recovery machinery this suite tortures already exists — daemon outage
guards, StaleWatch relists, lease CAS, gang all-or-nothing — but the plain
suite only ever exercises it with *clean* failures (whole-process restarts
in test_e2e_recovery.py).  Here a deterministic FaultPlan
(volcano_tpu/chaos.py) injects the messy ones: 5xx bursts, responses cut
mid-body, watch-log truncation below live cursors, dropped flushes, and
lease clock skew — and after every storm the system must converge to the
SAME final placements a fault-free run produces, with every invariant the
system promises still holding:

  * no double-bind / node oversubscription (capacity conserved),
  * gang all-or-nothing (a job is fully placed or holds nothing),
  * no orphaned pods (every pod's job exists),
  * every Statement settled (runtime twin of the statement-discipline rule),
  * exactly one leader per component after lease churn.

``make chaos`` runs the whole file; the smoke variant is tier-1 (not
``slow``) so every CI run exercises the injection layer end to end.
"""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from volcano_tpu import trace
from volcano_tpu.api.job import JOB_NAME_KEY, Job, JobSpec, TaskSpec
from volcano_tpu.api.objects import Metadata, Node, PodSpec, Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobPhase, PodPhase
from volcano_tpu.backoff import Backoff
from volcano_tpu.chaos import FaultPlan, chaos_clock
from volcano_tpu.controller import JobController
from volcano_tpu.leader import LeaderElector
from volcano_tpu.scheduler import statement
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store.client import (
    RemoteStore,
    RemoteStoreError,
    StaleWatch,
    wait_healthy,
)
from volcano_tpu.store.server import StoreServer

TRANSIENT = (RemoteStoreError, OSError, http.client.HTTPException)

#: the three acceptance fault plans — seeded, bounded (every storm ends),
#: and aimed at different layers: the request plane, the watch/event
#: plane, and the leader-election plane
PLAN_5XX_BURST = {
    "seed": 101,
    "rules": [
        # every 3rd API request 503s for a while: outage guards + backoff
        {"point": "server.request", "action": "http_500",
         "every": 3, "count": 40},
    ],
}
PLAN_CUT_AND_TRUNCATE = {
    "seed": 202,
    "rules": [
        # responses cut mid-body: IncompleteRead on the client, absorbed
        # by the idempotent-GET retry or surfaced to the outage guards
        {"point": "server.request", "action": "cut_body",
         "after": 5, "every": 7, "count": 15},
        # watch log truncated under live cursors: StaleWatch relists
        {"point": "server.request", "action": "truncate_log",
         "match": {"path": "/watch"}, "after": 3, "every": 11, "count": 5},
    ],
}
#: applied to ONE candidate's clock via chaos_clock, in alternating
#: multi-read BURSTS: a +40s burst makes the healthy holder's lease look
#: expired to the skewed candidate (steal), a -40s burst makes the skewed
#: holder write stale renew timestamps until the healthy candidate steals
#: it back — at least two real lease transitions whichever candidate wins
#: the initial create race, then the plan exhausts and one leader remains
PLAN_LEASE_FLAP = {
    "seed": 303,
    "rules": [
        {"point": "leader.clock", "action": "skew", "arg": 40.0,
         "after": 2, "every": 1, "count": 6},
        {"point": "leader.clock", "action": "skew", "arg": -40.0,
         "after": 12, "every": 1, "count": 6},
        {"point": "leader.clock", "action": "skew", "arg": 40.0,
         "after": 22, "every": 1, "count": 6},
        {"point": "leader.clock", "action": "skew", "arg": -40.0,
         "after": 32, "every": 1, "count": 6},
    ],
}


#: the fourth seeded storm: elastic scale-up under provisioning failures —
#: the first attempts fail outright, later ones are delay-injected; demand
#: persists so elasticd retries, and the pool must still converge to the
#: same placements as a fault-free pre-provisioned run with no orphan
#: Provisioning nodes and the size bounds held throughout
PLAN_PROVISION_FAIL = {
    "seed": 404,
    "rules": [
        {"point": "elastic.provision", "action": "fail",
         "every": 1, "count": 5},
        {"point": "elastic.provision", "action": "delay", "arg": 0.3,
         "after": 5, "every": 2, "count": 4},
    ],
}


def _arm(url: str, plan):
    data = json.dumps(plan).encode() if plan is not None else None
    req = urllib.request.Request(
        url + "/chaos", data=data,
        method="POST" if plan is not None else "DELETE",
    )
    return json.load(urllib.request.urlopen(req, timeout=10))


def _mk_job(name, replicas, cpu="1", queue="default"):
    return Job(
        meta=Metadata(name=name, namespace="soak"),
        spec=JobSpec(
            min_available=replicas,  # strict gang: all-or-nothing
            queue=queue,
            tasks=[TaskSpec(name="w", replicas=replicas,
                            template=PodSpec(
                                image="busybox",
                                resources=Resource.from_resource_list(
                                    {"cpu": cpu, "memory": "1Gi"})))],
        ),
    )


class ControlPlane:
    """Controller + scheduler(s) + kubelet as threads over real HTTP, each
    with the daemon-grade outage discipline (backoff on transients,
    rebuild on StaleWatch) from cli/daemons.py — same wire path as the
    subprocess daemons, but fast and with the electors inspectable."""

    def __init__(self, url, elect=False, flap_plan=None, peers=None):
        self.url = url
        self.stop = threading.Event()
        self.threads = []
        self.electors = {"vk-scheduler": [], "vk-controllers": []}
        self.crashes = []  # unexpected (non-transient) loop deaths
        self._elect = elect
        self._flap_plan = flap_plan
        # replica peer URLs: every loop's RemoteStore re-resolves the
        # leader through these after a NotLeader redirect or leader death
        self.peers = list(peers) if peers else None

    def _store(self):
        return RemoteStore(self.url, peers=self.peers)

    def _elector(self, store, component, ident, flapped):
        if not self._elect:
            return None
        clock = None
        if flapped and self._flap_plan is not None:
            clock = chaos_clock(self._flap_plan)
        # tight candidate pacing so standbys observe even short skew
        # windows; production keeps the 5 s default cap
        e = LeaderElector(store, component, ident, clock=clock,
                          backoff=Backoff(base=0.01, cap=0.05, seed=5))
        self.electors[component].append(e)
        return e

    def _controller_loop(self, ident, flapped):
        trace.set_component("controller")
        retry = Backoff(base=0.02, cap=0.3, seed=21)
        ctl = None
        while not self.stop.is_set():
            try:
                if ctl is None:
                    store = self._store()
                    ctl = JobController(store, elector=self._elector(
                        store, "vk-controllers", ident, flapped))
                ctl.pump()
                retry.reset()
            except StaleWatch:
                ctl = None  # relist via a fresh build, as the daemon does
                continue
            except TRANSIENT:
                ctl = None
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _scheduler_loop(self, ident, flapped):
        trace.set_component("scheduler")
        retry = Backoff(base=0.02, cap=0.3, seed=22)
        sched = None
        while not self.stop.is_set():
            try:
                if sched is None:
                    store = self._store()
                    sched = Scheduler(store, conf=full_conf(),
                                      elector=self._elector(
                                          store, "vk-scheduler", ident,
                                          flapped))
                sched.run_once()
                retry.reset()
            except TRANSIENT:
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _kubelet_loop(self):
        # same pass as the subprocess daemon (cli/daemons.kubelet_step):
        # reap deleting pods, flip bound Pending pods Running (the traced
        # Ready flip), advance Provisioning nodes
        from volcano_tpu.cli.daemons import kubelet_step

        trace.set_component("kubelet")
        store = self._store()
        retry = Backoff(base=0.02, cap=0.3, seed=23)
        while not self.stop.is_set():
            try:
                kubelet_step(store, time.time())
                retry.reset()
            except TRANSIENT:
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _elastic_loop(self, fault_plan):
        """elasticd with the daemon-grade outage discipline, sampling the
        pool-size invariant every pump (``min_size <= size <= max_size``
        must hold THROUGHOUT the storm, not just at the end)."""
        from volcano_tpu.elastic import ElasticController, pool_nodes

        retry = Backoff(base=0.02, cap=0.3, seed=24)
        ctl = None
        while not self.stop.is_set():
            try:
                if ctl is None:
                    store = self._store()
                    ctl = ElasticController(store, chaos=fault_plan)
                ctl.pump()
                for pool in store.list("NodePool"):
                    size = len(pool_nodes(store, pool.meta.name))
                    if not pool.min_size <= size <= pool.max_size:
                        self.crashes.append(
                            f"pool {pool.meta.name} size {size} outside "
                            f"[{pool.min_size}, {pool.max_size}]")
                retry.reset()
            except StaleWatch:
                ctl = None
                continue
            except TRANSIENT:
                ctl = None
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _guard(self, fn, *args):
        def run():
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 — surfaced in teardown
                # failure forensics: the flight recorder's last spans
                # become an artifact before the loop dies (no-op disarmed)
                trace.crash_dump("control-plane-loop")
                self.crashes.append(repr(e))
        return run

    def start(self, schedulers=1, controllers=1, flap_component="",
              elastic_plan=False):
        specs = []
        for i in range(controllers):
            flapped = flap_component == "vk-controllers" and i == 1
            specs.append((self._controller_loop, f"ctl-{i}", flapped))
        for i in range(schedulers):
            flapped = flap_component == "vk-scheduler" and i == 1
            specs.append((self._scheduler_loop, f"sched-{i}", flapped))
        for fn, ident, flapped in specs:
            t = threading.Thread(target=self._guard(fn, ident, flapped),
                                 daemon=True)
            t.start()
            self.threads.append(t)
        t = threading.Thread(target=self._guard(self._kubelet_loop),
                             daemon=True)
        t.start()
        self.threads.append(t)
        if elastic_plan is not False:
            t = threading.Thread(
                target=self._guard(self._elastic_loop, elastic_plan),
                daemon=True)
            t.start()
            self.threads.append(t)
        return self

    def shutdown(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=20)
        assert not self.crashes, f"control-plane loop crashed: {self.crashes}"


def _submit(client, obj, deadline=60.0, kind="Job"):
    """Create through the storm: transient failures retry with backoff; a
    409 means an earlier attempt actually committed (success)."""
    retry = Backoff(base=0.02, cap=0.3, seed=31)
    end = time.monotonic() + deadline
    while True:
        try:
            client.create(kind, obj)
            return
        except KeyError:
            return
        except TRANSIENT:
            if time.monotonic() > end:
                raise
            retry.sleep()


def _wait_running(client, key, deadline=90.0):
    retry = Backoff(base=0.02, cap=0.3, seed=32)
    end = time.monotonic() + deadline
    job = None
    while time.monotonic() < end:
        try:
            job = client.get("Job", key)
            if job is not None and job.status.state.phase == JobPhase.RUNNING:
                return job
            retry.reset()
        except TRANSIENT:
            pass
        retry.sleep()
    raise AssertionError(
        f"{key} never reached Running; last status: {job and job.status}")


def _placements(client):
    return sorted(
        (p.meta.key, p.node_name)
        for p in client.list("Pod") if p.phase == PodPhase.RUNNING
    )


def _check_invariants(client):
    try:
        _check_invariants_inner(client)
    except AssertionError:
        # the flight-recorder contract: an invariant violation dumps the
        # last N spans as a JSON artifact before the storm fails the test
        trace.crash_dump("invariant-violation")
        raise


def _check_invariants_inner(client):
    nodes = {n.meta.name: n for n in client.list("Node")}
    pods = client.list("Pod")
    jobs = client.list("Job")

    # no orphaned pods: every pod belongs to a live job
    job_names = {j.meta.name for j in jobs}
    for p in pods:
        assert p.meta.annotations.get(JOB_NAME_KEY) in job_names, (
            f"orphaned pod {p.meta.key}")

    # no double-bind / oversubscription: resident requests fit every node
    used = {name: Resource() for name in nodes}
    for p in pods:
        if p.node_name and p.phase in (PodPhase.PENDING, PodPhase.RUNNING):
            assert p.node_name in nodes, f"{p.meta.key} bound to ghost node"
            used[p.node_name].add(p.spec.resources)
    for name, u in used.items():
        assert u.less_equal(nodes[name].allocatable), (
            f"node {name} oversubscribed")

    # gang all-or-nothing: a Running job holds its full gang; any other
    # phase holds nothing
    for j in jobs:
        bound = [p for p in pods
                 if p.meta.annotations.get(JOB_NAME_KEY) == j.meta.name
                 and p.node_name]
        if j.status.state.phase == JobPhase.RUNNING:
            assert len(bound) >= j.spec.min_available, (
                f"{j.meta.name}: partial gang {len(bound)}"
                f"/{j.spec.min_available}")
        else:
            assert not bound, (
                f"{j.meta.name} is {j.status.state.phase} but holds "
                f"{len(bound)} bound pods")

    # every Statement settled (in-process schedulers share the counter)
    assert statement.outstanding() == 0, "unsettled scheduler Statements"


def _assert_digest_converged(srv):
    """PR-13 convergence gate: at storm end a mirror fed the merged
    watch stream reaches beacon-pinned digest equality with the server,
    and the server's maintained table equals a raw recompute (no storm
    path ever mutated an object behind the digest hooks)."""
    from volcano_tpu import vtaudit
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    if not vtaudit.enabled():
        return
    m = ArrayMirror(RemoteStore(srv.url), "volcano-tpu", "default")
    res = None
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        m.drain()
        with srv.lock:
            srv.stamp_beacon()
        m.drain()
        res = m.audit_verify()
        if res is not None:
            break  # quiescent: the beacon closed the poll batch
        time.sleep(0.05)
    assert res is not None and res["ok"], res
    truth = srv.store.recompute_digest()
    maint = srv.store.digest_payload(srv.shards)
    assert maint is not None
    assert maint["root"] == vtaudit.hexd(truth.root())
    assert maint["shards"] == truth.payload(srv.shards)["shards"]


def _soak(plan, n_jobs=3, replicas=2, elect=False, flap_component="",
          schedulers=1, controllers=1, queues=("default",),
          trace_ids_out=None):
    """One seeded storm: bring up the control plane, arm the plan, drive
    the workload through it, disarm, converge, check invariants.  Returns
    the final placements for parity against a fault-free run.
    ``trace_ids_out``: a dict — when given, each submission roots a
    vtrace span (the ``vtctl job run`` shape), stamps the gang, and
    records job name -> trace id there."""
    srv = StoreServer().start()
    flap_plan = FaultPlan.from_dict(PLAN_LEASE_FLAP) if flap_component else None
    cp = ControlPlane(srv.url, elect=elect, flap_plan=flap_plan)
    try:
        assert wait_healthy(srv.url, timeout=10)
        for i, qname in enumerate(queues):
            srv.store.create("Queue", Queue(
                meta=Metadata(name=qname, namespace=""), weight=i + 1))
        for i in range(3):
            srv.store.create("Node", Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})))
        cp.start(schedulers=schedulers, controllers=controllers,
                 flap_component=flap_component)
        if plan is not None:
            _arm(srv.url, plan)

        client = RemoteStore(srv.url)
        # sequential gang submission: placement is deterministic, so a
        # faulted run must land exactly where the fault-free run does
        for i in range(n_jobs):
            job = _mk_job(f"cj{i}", replicas,
                          queue=queues[i % len(queues)])
            if trace_ids_out is not None:
                trace.set_component("vtctl")
                with trace.span("vtctl.job.run", job=job.meta.key) as sp:
                    trace.stamp(job.meta)
                    trace_ids_out[f"cj{i}"] = sp.trace_id
                    _submit(client, job)
            else:
                _submit(client, job)
            _wait_running(client, f"soak/cj{i}")

        # storm over (plans are bounded); disarm and let the plane settle
        _arm(srv.url, None)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(client.get("Job", f"soak/cj{i}").status.state.phase
                   == JobPhase.RUNNING for i in range(n_jobs)):
                break
            time.sleep(0.1)

        if flap_plan is not None:
            # the clock-skew bursts are indexed by the flapped candidate's
            # clock READS, which keep accruing while the loops run — hold
            # the plane under churn until every burst has played out, then
            # give the final takeover a moment to land
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                if all(r.hits >= r.after + r.count * r.every
                       for r in flap_plan.rules):
                    break
                time.sleep(0.1)
            time.sleep(1.0)

        _check_invariants(client)
        _assert_digest_converged(srv)

        leases = {}
        if elect:
            # exactly one leader per component survives the churn
            for component, electors in cp.electors.items():
                if not electors:
                    continue
                leaders = [e.identity for e in electors if e.is_leader()]
                assert len(set(leaders)) == 1, (
                    f"{component}: leaders after churn = {leaders}")
                leases[component] = client.get("Lease", f"/{component}")
        placements = _placements(client)
        if plan is not None:
            status = json.load(urllib.request.urlopen(
                srv.url + "/chaos", timeout=10))
            assert not status["armed"]
        return placements, leases
    finally:
        cp.shutdown()
        srv.stop()


# -- chaos primitives (tier-1) -------------------------------------------------


def test_fault_plan_is_deterministic():
    """Two plans with the same seed fire on exactly the same hits — the
    whole determinism contract (counters + per-rule seeded streams)."""
    spec = {"seed": 42, "rules": [
        {"point": "server.request", "action": "http_500",
         "after": 3, "every": 2, "count": 10, "prob": 0.5},
    ]}
    a, b = FaultPlan.from_dict(spec), FaultPlan.from_dict(spec)
    fires_a = [a.fire("server.request", "GET", "/apis/Pod") is not None
               for _ in range(100)]
    fires_b = [b.fire("server.request", "GET", "/apis/Pod") is not None
               for _ in range(100)]
    assert fires_a == fires_b
    assert 1 <= sum(fires_a) <= 10  # count cap respected, prob thinned
    assert not any(fires_a[:3])  # `after` skipped the first hits
    # a different seed shifts the prob draws
    c = FaultPlan.from_dict({**spec, "seed": 43})
    fires_c = [c.fire("server.request", "GET", "/apis/Pod") is not None
               for _ in range(100)]
    assert fires_a != fires_c


def test_fault_plan_overlapping_rules_keep_independent_budgets():
    """A hit consumed by an earlier rule must not burn a later rule's
    fire/count budget — stats stay honest and the later rule still
    delivers its full schedule once the earlier one exhausts."""
    plan = FaultPlan.from_dict({"seed": 1, "rules": [
        {"point": "server.request", "action": "http_500", "count": 2},
        {"point": "server.request", "action": "delay", "count": 3},
    ]})
    actions = [r.action for r in
               (plan.fire("server.request") for _ in range(10)) if r]
    # rule 0 wins its first 2 hits, then rule 1 delivers ALL 3 of its own
    assert actions == ["http_500", "http_500", "delay", "delay", "delay"]
    st = plan.stats()
    assert st[0]["fires"] == 2 and st[1]["fires"] == 3
    assert st[0]["hits"] == st[1]["hits"] == 10


def test_fault_plan_rejects_unknown_points_and_actions():
    from volcano_tpu.chaos import ChaosPlanError

    with pytest.raises(ChaosPlanError):
        FaultPlan.from_dict({"rules": [{"point": "nope", "action": "delay"}]})
    with pytest.raises(ChaosPlanError):
        FaultPlan.from_dict(
            {"rules": [{"point": "server.flush", "action": "http_500"}]})


def test_chaos_endpoint_arm_status_disarm():
    srv = StoreServer().start()
    try:
        status = json.load(urllib.request.urlopen(srv.url + "/chaos"))
        assert status == {"armed": False, "plan": None, "stats": []}
        out = _arm(srv.url, PLAN_5XX_BURST)
        assert out["armed"] and out["plan"]["seed"] == 101
        # a malformed plan is rejected and the old plan stays armed
        req = urllib.request.Request(
            srv.url + "/chaos",
            data=json.dumps({"rules": [{"point": "bogus"}]}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 422
        assert json.load(urllib.request.urlopen(srv.url + "/chaos"))["armed"]
        assert not _arm(srv.url, None)["armed"]
    finally:
        srv.stop()


def test_idempotent_get_retries_connection_cut_once():
    """A single injected reset/cut on a GET is absorbed; two surface; a
    cut POST is never retried (it may have committed server-side)."""
    srv = StoreServer().start()
    try:
        seed = RemoteStore(srv.url)
        seed.create("Queue", Queue(meta=Metadata(name="q", namespace="")))

        one = RemoteStore(srv.url, chaos=FaultPlan.from_dict(
            {"rules": [{"point": "client.request", "action": "os_error",
                        "count": 1}]}))
        assert [q.meta.name for q in one.list("Queue")] == ["q"]

        two = RemoteStore(srv.url, chaos=FaultPlan.from_dict(
            {"rules": [{"point": "client.request", "action": "os_error",
                        "count": 2}]}))
        with pytest.raises(ConnectionResetError):
            two.list("Queue")

        post = RemoteStore(srv.url, chaos=FaultPlan.from_dict(
            {"rules": [{"point": "client.request", "action": "os_error",
                        "match": {"method": "POST"}, "count": 1}]}))
        with pytest.raises(ConnectionResetError):
            post.create("Queue", Queue(meta=Metadata(name="x", namespace="")))
        assert seed.get("Queue", "/x") is None  # nothing committed
    finally:
        srv.stop()


def test_drop_flush_injects_durability_gap(tmp_path):
    """server.flush drop: the acked write is missing from the state file
    until the NEXT flush — the documented crash window, on demand."""
    state = str(tmp_path / "state.json")
    # never started: flushes driven by hand, no HTTP traffic needed
    srv = StoreServer(state_path=state, save_interval=0)
    srv.arm_chaos(FaultPlan.from_dict(
        {"rules": [{"point": "server.flush", "action": "drop_flush",
                    "count": 1}]}))
    srv.store.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    srv.flush_state()  # dropped
    import os
    assert not os.path.exists(state)
    srv.flush_state()  # next flush catches up (kinds stayed dirty)
    assert json.load(open(state))["kinds"]["Queue"]


def test_wait_healthy_deadline_and_recovery():
    assert not wait_healthy("http://127.0.0.1:9", timeout=0.5)
    srv = StoreServer().start()
    try:
        assert wait_healthy(srv.url, timeout=5)
    finally:
        srv.stop()


def test_fastpath_mirror_relists_after_log_truncation():
    """Satellite: the scheduler fastpath mirror's StaleWatch recovery —
    truncate the server log under an ACTIVE mirror (not just a raw
    client) and assert it relists and converges to store truth."""
    from volcano_tpu.scheduler.fastpath import ArrayMirror
    from tests.helpers import build_node, build_pod, build_podgroup

    srv = StoreServer().start()
    try:
        writer = RemoteStore(srv.url)
        writer.create("Queue", Queue(meta=Metadata(name="default",
                                                   namespace="")))
        writer.create("Node", build_node("n0"))
        writer.create("PodGroup", build_podgroup("pg", min_member=1))
        writer.create("Pod", build_pod("p0", group="pg"))

        mirror_store = RemoteStore(srv.url)
        m = ArrayMirror(mirror_store, "volcano-tpu", "default")
        m.drain()  # full sync
        assert int(m.p_live.sum()) == 1 and m.stale_relists == 0

        # mutate while the mirror's cursor lags, then truncate the log
        # under it via the armed faultpoint: the next poll must relist
        writer.create("Pod", build_pod("p1", group="pg"))
        writer.delete("Pod", "default/p0")
        _arm(srv.url, {"seed": 9, "rules": [
            {"point": "server.request", "action": "truncate_log",
             "match": {"path": "/watch"}, "count": 1}]})
        m.drain()
        assert m.stale_relists == 1
        # post-relist state is store truth: p0 gone, p1 live
        assert int(m.p_live.sum()) == 1
        assert "default/p1" in m.pods.key_row
        assert "default/p0" not in m.pods.key_row
        # and the mirror keeps working incrementally afterwards
        writer.create("Pod", build_pod("p2", group="pg"))
        m.drain()
        assert int(m.p_live.sum()) == 2 and m.stale_relists == 1
    finally:
        srv.stop()


# -- tier-1 smoke (slow-exempt): the injection layer end to end ---------------


def test_chaos_smoke_5xx_burst_converges_to_fault_free_placements():
    baseline, _ = _soak(None, n_jobs=2)
    stormy, _ = _soak(PLAN_5XX_BURST, n_jobs=2)
    assert stormy == baseline
    assert len(stormy) == 4  # 2 gangs x 2 replicas, all Running


def test_chaos_smoke_traced_storm_neutral_and_reconstructs_gang(tmp_path):
    """The 5xx storm re-run with vtrace ARMED: (a) final placements are
    bit-for-bit the fault-free DISARMED run's — tracing is
    placement-neutral even mid-storm; (b) the flight-recorder dump
    reconstructs one gang's full lifecycle (submit -> controller ->
    scheduler cycle/bind -> kubelet Ready) across all three daemons under
    the single trace id stamped at submission."""
    baseline, _ = _soak(None, n_jobs=2)  # fault-free, disarmed
    tids = {}
    tracer = trace.arm(trace.Tracer(ring=65536, dump_dir=str(tmp_path)))
    try:
        stormy, _ = _soak(PLAN_5XX_BURST, n_jobs=2, trace_ids_out=tids)
        dump = tracer.dump("soak")
    finally:
        trace.disarm()
    assert stormy == baseline

    tid = tids["cj0"]
    sel = trace.spans_for_trace(dump["spans"], tid)
    comps = {r["component"] for r in sel}
    assert {"controller", "scheduler", "kubelet"} <= comps, comps
    names = {r["name"] for r in sel}
    assert "vtctl.job.run" in names
    assert any(n.startswith("controller.") for n in names), names
    assert "scheduler.bind" in names
    assert "kubelet.ready" in names
    # the linked scheduler cycle reconstructs with its internals: at
    # least one action and one plugin callback inside the cycle tree
    assert "scheduler.cycle" in names
    assert "action" in names and "plugin" in names
    # every bind of the gang carries the trace and names a real node
    binds = [r for r in sel if r["name"] == "scheduler.bind"]
    assert {r["attrs"]["task"] for r in binds} == {
        "soak/cj0-w-0", "soak/cj0-w-1"}
    ready = [r for r in sel if r["name"] == "kubelet.ready"]
    assert {r["attrs"]["pod"] for r in ready} == {
        "soak/cj0-w-0", "soak/cj0-w-1"}


# -- the full seeded storms (make chaos) --------------------------------------


@pytest.mark.slow
def test_chaos_soak_5xx_burst_full():
    baseline, _ = _soak(None, n_jobs=4, queues=("default", "batch"))
    stormy, _ = _soak(PLAN_5XX_BURST, n_jobs=4, queues=("default", "batch"))
    assert stormy == baseline


@pytest.mark.slow
def test_chaos_soak_cut_body_and_log_truncation():
    baseline, _ = _soak(None, n_jobs=4)
    stormy, _ = _soak(PLAN_CUT_AND_TRUNCATE, n_jobs=4)
    assert stormy == baseline


@pytest.mark.slow
def test_real_daemons_survive_env_armed_chaos():
    """The real multi-process model under VOLCANO_TPU_CHAOS: every spawned
    daemon's RemoteStore injects connection resets from the env plan,
    while the apiserver serves a 5xx burst armed over /chaos — the gang
    still reaches Running through the storms."""
    import os
    import signal
    import subprocess
    import sys

    env_plan = {"seed": 17, "rules": [
        {"point": "client.request", "action": "os_error",
         "after": 10, "every": 9, "count": 30},
    ]}
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VOLCANO_TPU_CHAOS": json.dumps(env_plan)}
    entry = [sys.executable, "-m", "volcano_tpu.cli"]
    procs = []
    try:
        api = subprocess.Popen(entry + ["apiserver", "--port", "0"],
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT, text=True, env=env)
        procs.append(api)
        url = api.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert wait_healthy(url, timeout=30)
        for comp in ("controller", "scheduler", "kubelet"):
            extra = (["--period", "0.1", "--metrics-port", "-1"]
                     if comp == "scheduler" else ["--period", "0.05"])
            p = subprocess.Popen(entry + [comp, "--server", url] + extra,
                                 stdout=subprocess.DEVNULL,
                                 stderr=subprocess.STDOUT, env=env)
            procs.append(p)
        _arm(url, {"seed": 18, "rules": [
            {"point": "server.request", "action": "http_500",
             "every": 4, "count": 30},
        ]})

        client = RemoteStore(url)  # this process: no env plan, clean client
        _submit(client, Queue(meta=Metadata(name="default", namespace="")),
                kind="Queue")
        for i in range(2):
            _submit(client, Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})), kind="Node")
        _submit(client, _mk_job("envjob", 2))
        _wait_running(client, "soak/envjob", deadline=120)
        _arm(url, None)
        _check_invariants(client)
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _elastic_soak(provision_plan, n_jobs=3):
    """Elastic scale-up soak: a NodePool at min_size=0 absorbs a gang
    burst through (possibly fault-injected) provisioning.  Returns the
    final placements for parity against a fault-free PRE-PROVISIONED run
    (``_preprovisioned_soak``) — each pod fills a whole template node, so
    gradual arrival and up-front provisioning must land identically."""
    from volcano_tpu.api.objects import NodePool
    from volcano_tpu.chaos import FaultPlan
    from volcano_tpu.elastic import POOL_LABEL, READY, node_state

    srv = StoreServer().start()
    plan = (FaultPlan.from_dict(provision_plan)
            if provision_plan is not None else None)
    cp = ControlPlane(srv.url)
    try:
        assert wait_healthy(srv.url, timeout=10)
        srv.store.create("Queue", Queue(
            meta=Metadata(name="default", namespace="")))
        srv.store.create("NodePool", NodePool(
            meta=Metadata(name="bp", namespace=""),
            resources=Resource.from_resource_list(
                {"cpu": "2", "memory": "8Gi", "pods": 110}),
            min_size=0, max_size=2 * n_jobs,
            provision_delay=0.1, hysteresis=600.0,
        ))
        cp.start(elastic_plan=plan)

        client = RemoteStore(srv.url)
        for i in range(n_jobs):
            _submit(client, _mk_job(f"cj{i}", 2, cpu="2"))
            _wait_running(client, f"soak/cj{i}", deadline=120)

        # every member must settle Ready: an orphan Provisioning node
        # would mean capacity nobody asked for survived the storm
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            members = [n for n in client.list("Node")
                       if n.labels.get(POOL_LABEL) == "bp"]
            if members and all(node_state(n) == READY and n.ready()
                               for n in members):
                break
            time.sleep(0.1)
        members = [n for n in client.list("Node")
                   if n.labels.get(POOL_LABEL) == "bp"]
        assert members and all(node_state(n) == READY for n in members), (
            f"orphan Provisioning nodes: "
            f"{[(n.meta.name, node_state(n)) for n in members]}")
        assert len(members) == 2 * n_jobs  # the bin-pack minimum, exactly
        _check_invariants(client)
        if plan is not None:
            assert any(r["fires"] > 0 for r in plan.stats()), (
                "the provisioning faults never fired")
        return _placements(client)
    finally:
        cp.shutdown()
        srv.stop()


def _preprovisioned_soak(n_jobs=3):
    """The comparator: the same workload against the pool's final shape
    created up front — no NodePool object, no elasticd."""
    from volcano_tpu.elastic import POOL_LABEL

    srv = StoreServer().start()
    cp = ControlPlane(srv.url)
    try:
        assert wait_healthy(srv.url, timeout=10)
        srv.store.create("Queue", Queue(
            meta=Metadata(name="default", namespace="")))
        for i in range(2 * n_jobs):
            srv.store.create("Node", Node(
                meta=Metadata(name=f"bp-{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "2", "memory": "8Gi", "pods": 110}),
                labels={POOL_LABEL: "bp"}))
        cp.start()
        client = RemoteStore(srv.url)
        for i in range(n_jobs):
            _submit(client, _mk_job(f"cj{i}", 2, cpu="2"))
            _wait_running(client, f"soak/cj{i}")
        _check_invariants(client)
        return _placements(client)
    finally:
        cp.shutdown()
        srv.stop()


@pytest.mark.slow
def test_chaos_soak_elastic_provision_failures():
    """Fourth seeded storm: scale-up under elastic.provision failures
    converges to the same final placements as a fault-free
    pre-provisioned run."""
    baseline = _preprovisioned_soak()
    faultfree = _elastic_soak(None)
    stormy = _elastic_soak(PLAN_PROVISION_FAIL)
    assert faultfree == baseline
    assert stormy == baseline
    assert len(stormy) == 6  # 3 gangs x 2 full-node replicas, all Running


@pytest.mark.slow
def test_chaos_soak_lease_flap_single_leader():
    baseline, _ = _soak(None, n_jobs=3, elect=True,
                        schedulers=2, controllers=2)
    stormy, leases = _soak(PLAN_LEASE_FLAP, n_jobs=3, elect=True,
                           schedulers=2, controllers=2,
                           flap_component="vk-scheduler")
    assert stormy == baseline
    # the skewed candidate really did flap the lease back and forth
    lease = leases.get("vk-scheduler")
    assert lease is not None and lease.transitions >= 2, (
        f"lease never churned: {lease}")


# -- the replication storms (repl.* faultpoints; make chaos) -------------------

#: aimed at the WAL-shipping feed itself: replies cut mid-body (the
#: follower pump's torn-tail reconnect), delay-injected feeds (lag accrues
#: then catch-up bursts), and hard 500s (the pump's backoff path) — all
#: while the control plane keeps writing through the leader
PLAN_REPL_FEED_STORM = {
    "seed": 505,
    "rules": [
        {"point": "repl.feed", "action": "cut_body",
         "after": 2, "every": 3, "count": 8},
        {"point": "repl.feed", "action": "delay", "arg": 0.2,
         "after": 1, "every": 4, "count": 6},
        {"point": "repl.feed", "action": "http_500",
         "every": 5, "count": 5},
    ],
}
#: armed on ONE follower: +40s skew makes its local lease copy look
#: expired on every promotion check, so it keeps probing peers — but the
#: live leader answers /repl/status, and the probe must refuse to promote
#: over a living leader every single time (no double promotion)
PLAN_REPL_LEASE_SKEW = {
    "seed": 606,
    "rules": [
        {"point": "repl.lease", "action": "skew", "arg": 40.0,
         "after": 2, "every": 1, "count": 10},
    ],
}


def _repl_boot(tmp_path, name, leader=None, lease=1.0):
    return StoreServer(
        port=0, state_path=str(tmp_path / f"{name}.json"),
        save_interval=3600, wal=True,
        repl={"identity": None, "peers": [], "leader": leader,
              "ack": "async", "lease_duration": lease},
    ).start()


def _wait_repl_converged(live, lead, deadline=30.0):
    """Every live replica applied up to the leader's seq, same epoch."""
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        if all(s.seq >= lead.seq and s.repl.epoch == lead.repl.epoch
               for s in live):
            return
        time.sleep(0.05)
    raise AssertionError(
        "replicas never converged: "
        + str([(s.url, s.seq, s.repl.epoch) for s in live])
        + f" leader {lead.url} seq={lead.seq} epoch={lead.repl.epoch}")


def _repl_soak(tmp_path, feed_plan, kill_leader=False, skew_last=False,
               n_jobs=3):
    """One replication storm: a 3-replica cluster (real HTTP, own WAL
    dirs), the standard gang workload written through peered clients,
    repl.* faults armed over POST /chaos — optionally the leader stopped
    mid-workload (failover) or one follower's promotion clock skewed
    (must NOT promote over the living leader).  Asserts exactly one
    leader, the expected promotion count, zero beacon divergence on
    every live replica (the continuous vtaudit mirror check), identical
    digest roots, and returns the final placements."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    lead = _repl_boot(tmp_path, "L")
    f1 = _repl_boot(tmp_path, "f1", leader=lead.url)
    f2 = _repl_boot(tmp_path, "f2", leader=lead.url)
    servers = [lead, f1, f2]
    urls = [s.url for s in servers]
    for s in servers:
        s.repl.peers = [u for u in urls if u != s.url]
    cp = ControlPlane(lead.url, peers=urls)
    stopped = []
    try:
        assert wait_healthy(lead.url, timeout=10)
        client = RemoteStore(lead.url, peers=urls)
        _submit(client, Queue(meta=Metadata(name="default", namespace="")),
                kind="Queue")
        for i in range(3):
            _submit(client, Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})), kind="Node")
        # both followers past their bootstrap snapshot before the storm:
        # the feed faults must hit the LIVE record tail
        _wait_repl_converged([f1, f2], lead)
        if skew_last:
            _arm(f2.url, PLAN_REPL_LEASE_SKEW)
        if feed_plan is not None:
            _arm(lead.url, feed_plan)
        cp.start()
        for i in range(n_jobs):
            _submit(client, _mk_job(f"cj{i}", 2))
            if kill_leader and i == 0:
                # mid-cycle leader loss: daemons and clients must
                # refollow onto whichever follower promotes
                lead.stop()
                stopped.append(lead)
                end = time.monotonic() + 30
                while time.monotonic() < end:
                    if any(f.repl.role == "leader" for f in (f1, f2)):
                        break
                    time.sleep(0.05)
                else:
                    raise AssertionError("no follower promoted")
            _wait_running(client, f"soak/cj{i}", deadline=120)

        live = [s for s in servers if s not in stopped]
        leaders = [s for s in live if s.repl.role == "leader"]
        assert len(leaders) == 1, (
            f"leaders after the storm: {[s.url for s in leaders]}")
        new_lead = leaders[0]
        promotions = sum(s.repl.promotions for s in live)
        assert promotions == (1 if kill_leader else 0), (
            f"promotions={promotions} (kill_leader={kill_leader})")
        if feed_plan is not None and not kill_leader:
            status = json.load(urllib.request.urlopen(
                lead.url + "/chaos", timeout=10))
            assert any(r["fires"] > 0 for r in status["stats"]), (
                "the repl.feed faults never fired")
            _arm(lead.url, None)
        if skew_last:
            status = json.load(urllib.request.urlopen(
                f2.url + "/chaos", timeout=10))
            assert any(r["fires"] > 0 for r in status["stats"]), (
                "the repl.lease skew never fired")
            assert f2.repl.promotions == 0, (
                "the skewed follower promoted over a living leader")
            _arm(f2.url, None)

        # a fresh beacon through the quiesced pipe, then full convergence
        with new_lead.lock:
            new_lead.stamp_beacon()
        _wait_repl_converged(live, new_lead)
        # continuous divergence detection: every beacon the followers
        # mirrored through the whole storm compared equal
        for s in live:
            assert s.repl.divergence == 0, (
                f"{s.url}: {s.repl.divergence} diverged beacons")
        roots = {s.url: (s.store.digest_payload(s.shards) or {}).get("root")
                 for s in live}
        assert len(set(roots.values())) == 1 and None not in \
            roots.values(), roots
        _check_invariants(client)
        _assert_digest_converged(new_lead)
        return _placements(client)
    finally:
        cp.shutdown()
        for s in servers:
            if s not in stopped:
                s.stop()


@pytest.mark.slow
def test_chaos_soak_repl_feed_storm_failover(tmp_path):
    """The seeded replication failover storm: feed faults + leader loss
    mid-workload must converge to the fault-free run's exact placements,
    with one promotion, one surviving leader, and digest equality."""
    baseline = _repl_soak(tmp_path / "base", None)
    stormy = _repl_soak(tmp_path / "storm", PLAN_REPL_FEED_STORM,
                        kill_leader=True)
    assert stormy == baseline
    assert len(stormy) == 6  # 3 gangs x 2 replicas, all Running


@pytest.mark.slow
def test_chaos_soak_repl_lease_skew_no_double_promotion(tmp_path):
    """Feed faults plus a skewed promotion clock on one follower: its
    lease copy looks expired throughout, but the live leader's
    /repl/status answer must veto every promotion attempt."""
    placements = _repl_soak(tmp_path / "skew", PLAN_REPL_FEED_STORM,
                            skew_last=True)
    assert len(placements) == 6
