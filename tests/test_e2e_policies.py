"""End-to-end LifecyclePolicy matrix through the simulated cluster.

Mirrors reference test/e2e/job_error_handling.go: every meaningful
(event, action) combination — PodFailed/PodEvicted/Any x RestartJob/
TerminateJob/AbortJob (:31-317) — plus exit-code policies (:472) and
task-level overrides. Fault injection goes through the store exactly like
the reference kills pods via the API.
"""

import pytest

from volcano_tpu.api.job import Job, JobSpec, LifecyclePolicy, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase, PodPhase
from volcano_tpu.sim import Cluster


def mk_job(name, replicas=2, policies=None, task_policies=None, max_retry=3):
    return Job(
        meta=Metadata(name=name, namespace="test"),
        spec=JobSpec(
            min_available=replicas,
            tasks=[
                TaskSpec(
                    name="main",
                    replicas=replicas,
                    template=PodSpec(
                        image="busybox",
                        resources=Resource.from_resource_list(
                            {"cpu": "1", "memory": "1Gi"}
                        )
                    ),
                    policies=task_policies or [],
                )
            ],
            policies=policies or [],
            max_retry=max_retry,
        ),
    )


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default", weight=1)
    c.add_node("n0", {"cpu": "4", "memory": "8Gi", "pods": 110})
    return c


def start_running(cluster, job):
    cluster.store.create("Job", job)
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING
    return [p.meta.key for p in cluster.store.list("Pod")]


def first_pod(cluster):
    return sorted(p.meta.key for p in cluster.store.list("Pod"))[0]


# -- event x action matrix (job_error_handling.go:31-317) ---------------------

@pytest.mark.parametrize("event,inject", [
    (JobEvent.POD_FAILED, "fail"),
    (JobEvent.POD_EVICTED, "evict"),
    (JobEvent.ANY, "fail"),
    (JobEvent.ANY, "evict"),
])
def test_restart_job_policy(cluster, event, inject):
    job = mk_job("j", policies=[LifecyclePolicy(action=JobAction.RESTART_JOB, event=event)])
    start_running(cluster, job)
    version_before = job.status.version

    getattr(cluster, f"{inject}_pod")(first_pod(cluster))
    cluster.run_until_idle()

    # restarted: version fence bumped, back to Running with fresh pods
    assert job.status.version > version_before
    assert job.status.retry_count >= 1
    assert job.status.state.phase == JobPhase.RUNNING
    pods = cluster.store.list("Pod")
    assert len(pods) == 2
    assert all(p.phase == PodPhase.RUNNING for p in pods)


@pytest.mark.parametrize("event,inject", [
    (JobEvent.POD_FAILED, "fail"),
    (JobEvent.POD_EVICTED, "evict"),
])
def test_terminate_job_policy(cluster, event, inject):
    job = mk_job("j", policies=[LifecyclePolicy(action=JobAction.TERMINATE_JOB, event=event)])
    start_running(cluster, job)

    getattr(cluster, f"{inject}_pod")(first_pod(cluster))
    cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.TERMINATED
    assert cluster.store.list("Pod") == []
    # terminated jobs stay dead
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.TERMINATED


@pytest.mark.parametrize("event,inject", [
    (JobEvent.POD_FAILED, "fail"),
    (JobEvent.POD_EVICTED, "evict"),
])
def test_abort_job_policy(cluster, event, inject):
    job = mk_job("j", policies=[LifecyclePolicy(action=JobAction.ABORT_JOB, event=event)])
    start_running(cluster, job)

    getattr(cluster, f"{inject}_pod")(first_pod(cluster))
    cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.ABORTED
    assert cluster.store.list("Pod") == []


def test_complete_job_on_task_completed(cluster):
    job = mk_job("j", policies=[
        LifecyclePolicy(action=JobAction.COMPLETE_JOB, event=JobEvent.TASK_COMPLETED)
    ])
    pods = start_running(cluster, job)
    for key in pods:
        cluster.complete_pod(key)
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.COMPLETED


# -- exit-code policies (job_error_handling.go:472) ---------------------------

def test_exit_code_policy_matches(cluster):
    job = mk_job("j", policies=[LifecyclePolicy(action=JobAction.ABORT_JOB, exit_code=3)])
    start_running(cluster, job)

    cluster.fail_pod(first_pod(cluster), exit_code=3)
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED


def test_exit_code_policy_ignores_other_codes(cluster):
    job = mk_job("j", policies=[LifecyclePolicy(action=JobAction.ABORT_JOB, exit_code=3)])
    start_running(cluster, job)

    cluster.fail_pod(first_pod(cluster), exit_code=5)
    cluster.run_until_idle()
    # no policy matched: default sync just recounts — job keeps running
    # with one failed pod
    assert job.status.state.phase == JobPhase.RUNNING
    assert job.status.failed == 1


# -- task-level policy precedence (applyPolicies, job_controller_util.go:136) -

def test_task_policy_overrides_job_policy(cluster):
    job = mk_job(
        "j",
        policies=[LifecyclePolicy(action=JobAction.RESTART_JOB, event=JobEvent.POD_FAILED)],
        task_policies=[LifecyclePolicy(action=JobAction.ABORT_JOB, event=JobEvent.POD_FAILED)],
    )
    start_running(cluster, job)

    cluster.fail_pod(first_pod(cluster))
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED


# -- restart under resource pressure (job_error_handling.go:318) --------------

def test_restart_when_cluster_shrunk_waits_pending(cluster):
    # job restarts on eviction, but the cluster no longer fits the gang:
    # the restarted job parks Pending/Inqueue with no partial binding
    job = mk_job("j", replicas=4,
                 policies=[LifecyclePolicy(action=JobAction.RESTART_JOB,
                                           event=JobEvent.POD_EVICTED)])
    start_running(cluster, job)

    node = cluster.store.get("Node", "/n0")
    node.allocatable = Resource.from_resource_list({"cpu": "2", "memory": "8Gi", "pods": 110})
    cluster.store.update("Node", node)
    cluster.evict_pod(first_pod(cluster))
    cluster.run_until_idle()

    assert job.status.state.phase in (JobPhase.PENDING, JobPhase.INQUEUE)
    assert all(not p.node_name for p in cluster.store.list("Pod"))


def test_max_retry_exhaustion_fails_job(cluster):
    job = mk_job("j", max_retry=2,
                 policies=[LifecyclePolicy(action=JobAction.RESTART_JOB,
                                           event=JobEvent.POD_FAILED)])
    start_running(cluster, job)

    for _ in range(3):
        pods = cluster.store.list("Pod")
        if not pods or job.status.state.phase == JobPhase.FAILED:
            break
        cluster.fail_pod(pods[0].meta.key)
        cluster.run_until_idle()

    assert job.status.state.phase == JobPhase.FAILED
    assert job.status.retry_count >= 2
