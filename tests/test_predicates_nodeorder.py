"""Predicate filtering + node scoring (BASELINE config 3).

Scenario sources: reference test/e2e/predicates.go — NodeAffinity :29,
HostPort :78, Pod Affinity :106, Taints :155 — plus the nodeorder scoring
formulas (KB/pkg/scheduler/plugins/nodeorder/nodeorder.go:99-226).
"""

from volcano_tpu.api.objects import Affinity, Taint, Toleration
from volcano_tpu.api.types import PodPhase
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import FakeBinder, build_node, build_pod, build_podgroup, make_store


def run_cycle(store, conf=None):
    sched = Scheduler(store, conf=conf or default_conf())
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder


def test_node_selector_restricts_placement():
    store = make_store(
        nodes=[
            build_node("plain"),
            build_node("gpu-node", labels={"accelerator": "tpu"}),
        ],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    pod = store.get("Pod", "default/p0")
    pod.spec.node_selector = {"accelerator": "tpu"}
    _, binder = run_cycle(store)
    assert binder.binds == {"default/p0": "gpu-node"}


def test_required_node_affinity():
    # predicates.go:29 — In-operator requiredDuringScheduling term
    store = make_store(
        nodes=[
            build_node("n-east", labels={"zone": "east"}),
            build_node("n-west", labels={"zone": "west"}),
        ],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    pod = store.get("Pod", "default/p0")
    pod.spec.affinity = Affinity(node_terms=[[("zone", "In", ("west",))]])
    _, binder = run_cycle(store)
    assert binder.binds == {"default/p0": "n-west"}


def test_node_affinity_unsatisfiable_binds_nothing():
    store = make_store(
        nodes=[build_node("n0", labels={"zone": "east"})],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    pod = store.get("Pod", "default/p0")
    pod.spec.affinity = Affinity(node_terms=[[("zone", "In", ("mars",))]])
    _, binder = run_cycle(store)
    assert binder.binds == {}


def test_host_port_conflict_spreads_pods():
    # predicates.go:78 — two pods wanting the same host port land on
    # different nodes; a third finds no port-free node and stays pending.
    store = make_store(
        nodes=[build_node("n0"), build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod(f"p{i}", group="pg") for i in range(3)],
    )
    for i in range(3):
        store.get("Pod", f"default/p{i}").spec.host_ports = [8080]
    _, binder = run_cycle(store)
    bound_nodes = sorted(binder.binds.values())
    assert len(binder.binds) == 2
    assert bound_nodes == ["n0", "n1"]


def test_taints_require_toleration():
    # predicates.go:155 — NoSchedule taint repels pods without a toleration
    tainted = build_node("tainted")
    tainted.taints = [Taint(key="dedicated", value="batch", effect="NoSchedule")]
    # separate jobs: an unschedulable head task drops its whole job for the
    # cycle (allocate.go:148), which would mask the tolerant pod
    store = make_store(
        nodes=[tainted],
        podgroups=[
            build_podgroup("pg-plain", min_member=1),
            build_podgroup("pg-tol", min_member=1),
        ],
        pods=[build_pod("plain", group="pg-plain"), build_pod("tolerant", group="pg-tol")],
    )
    store.get("Pod", "default/tolerant").spec.tolerations = [
        Toleration(key="dedicated", operator="Equal", value="batch")
    ]
    _, binder = run_cycle(store)
    assert binder.binds == {"default/tolerant": "tainted"}


def test_pod_affinity_colocates():
    # predicates.go:106 — required pod affinity pulls the follower onto the
    # node already running the matching pod.
    store = make_store(
        nodes=[build_node("n0"), build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[
            build_pod(
                "leader", group="pg", phase=PodPhase.RUNNING, node_name="n1",
                labels={"role": "leader"},
            ),
            build_pod("follower", group="pg"),
        ],
    )
    store.get("Pod", "default/follower").spec.affinity = Affinity(
        pod_affinity=[{"role": "leader"}]
    )
    _, binder = run_cycle(store)
    assert binder.binds == {"default/follower": "n1"}


def test_pod_anti_affinity_separates():
    store = make_store(
        nodes=[build_node("n0"), build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[
            build_pod(
                "a", group="pg", phase=PodPhase.RUNNING, node_name="n0",
                labels={"app": "db"},
            ),
            build_pod("b", group="pg", labels={"app": "db"}),
        ],
    )
    store.get("Pod", "default/b").spec.affinity = Affinity(
        pod_anti_affinity=[{"app": "db"}]
    )
    _, binder = run_cycle(store)
    assert binder.binds == {"default/b": "n1"}


def test_unschedulable_and_notready_nodes_filtered():
    cordoned = build_node("cordoned")
    cordoned.unschedulable = True
    notready = build_node("notready")
    notready.conditions[0].status = "False"
    store = make_store(
        nodes=[cordoned, notready, build_node("good")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    _, binder = run_cycle(store)
    assert binder.binds == {"default/p0": "good"}


def test_max_task_num_per_node():
    # MaxTaskNum predicate (predicates.go:70): the node's "pods" resource
    # bounds resident task count.
    store = make_store(
        nodes=[build_node("n0", pods=2)],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod(f"p{i}", group="pg") for i in range(3)],
    )
    _, binder = run_cycle(store)
    assert len(binder.binds) == 2


def test_least_requested_spreads_load():
    # nodeorder.go LeastRequested: the emptier node scores higher, so two
    # sequential pods spread across the two nodes.
    store = make_store(
        nodes=[build_node("n0", cpu="4", memory="8Gi"), build_node("n1", cpu="4", memory="8Gi")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg", cpu="2"), build_pod("p1", group="pg", cpu="2")],
    )
    _, binder = run_cycle(store)
    assert sorted(binder.binds.values()) == ["n0", "n1"]


def test_preferred_node_affinity_scores():
    # preferred (soft) node affinity steers toward the matching node
    # without filtering the other.
    store = make_store(
        nodes=[
            build_node("n-east", labels={"zone": "east"}),
            build_node("n-west", labels={"zone": "west"}),
        ],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    pod = store.get("Pod", "default/p0")
    pod.spec.affinity = Affinity(
        preferred_node_terms=[(50, [("zone", "In", ("east",))])]
    )
    _, binder = run_cycle(store)
    assert binder.binds == {"default/p0": "n-east"}


def test_nodeorder_weight_arguments():
    # nodeorder.go:99-152 — weights come from plugin arguments. Crank
    # leastrequested.weight and verify the emptier node still wins even
    # against a preferred-affinity pull to the fuller node.
    import yaml

    conf_text = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
    arguments:
      leastrequested.weight: 100
      nodeaffinity.weight: 1
"""
    store = make_store(
        nodes=[
            build_node("busy", cpu="4", memory="8Gi", labels={"zone": "east"}),
            build_node("idle", cpu="4", memory="8Gi"),
        ],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[
            build_pod(
                "resident", group="pg", cpu="3",
                phase=PodPhase.RUNNING, node_name="busy",
            ),
            build_pod("p0", group="pg", cpu="1"),
        ],
    )
    pod = store.get("Pod", "default/p0")
    pod.spec.affinity = Affinity(
        preferred_node_terms=[(5, [("zone", "In", ("east",))])]
    )
    sched = Scheduler.from_conf_yaml(store, conf_text)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    assert binder.binds["default/p0"] == "idle"
