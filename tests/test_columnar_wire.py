"""Columnar store wire (store/segment.py): parity, laziness, atomicity.

The r6 publish path ships a whole cycle as ONE columnar segment and the
server applies it lazily; everything observable must stay EXACTLY what
the r5 per-object path produced:

  * segment decode == per-object encode for every kind the segment
    carries (Pod patch rows, Event rows) — byte-for-byte;
  * a watch client replaying a columnar-fed log sees byte-identical
    events to the per-object log (modulo generated event uids,
    normalized — both runs are otherwise fully controlled);
  * chaos storms on the segment request (cut_body, truncate_log)
    converge to fault-free placements with no half-applied segment;
  * the in-process path still works with columnar publish disabled
    (``columnarPublish: false`` — the fallback flag smoke).
"""

import json
import re
import threading
import time

import pytest

from tests.helpers import build_node, build_pod, build_podgroup, make_store
from volcano_tpu.api import objects as api_objects
from volcano_tpu.api.objects import Metadata, Queue
from volcano_tpu.api.types import PodGroupPhase
from volcano_tpu.events import events_for
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.store import Store
from volcano_tpu.store.client import RemoteStore
from volcano_tpu.store.codec import encode
from volcano_tpu.store.segment import (
    DecisionSegment,
    encode_event_row,
    event_name,
    materialize_event,
)
from volcano_tpu.store.server import StoreServer


def _seg(bind_pairs, evicts=(), node_table=None):
    """Segment from (key, host) pairs — interns hosts like the fast
    cycle's publish tail does with snap.node_names."""
    table = list(node_table) if node_table else sorted(
        {h for _, h in bind_pairs}
    )
    idx = {h: i for i, h in enumerate(table)}
    return DecisionSegment.build(
        [k for k, _ in bind_pairs], [idx[h] for _, h in bind_pairs],
        table, list(evicts),
    )


def _seed_pods(create, n, nodes=("n0", "n1")):
    for name in nodes:
        create("Node", build_node(name, cpu="64", memory="64Gi"))
    pg = build_podgroup("pg1", min_member=1)
    pg.status.phase = PodGroupPhase.INQUEUE
    create("PodGroup", pg)
    for i in range(n):
        create("Pod", build_pod(f"p{i}", group="pg1", cpu="1"))


# -- wire format parity -------------------------------------------------------


def test_segment_wire_roundtrip():
    seg = _seg([("default/p0", "n1"), ("default/p1", "n0")],
               evicts=[("default/p2", "preempt"), ("default/p3", "preempt")])
    back = DecisionSegment.from_wire(json.loads(json.dumps(seg.to_wire())))
    assert back.bind_keys == seg.bind_keys
    assert back.bind_hosts == seg.bind_hosts
    assert back.evict_pairs() == seg.evict_pairs()
    assert (back.ev_token, back.ev_start) == (seg.ev_token, seg.ev_start)
    # reason interning: one table entry for the repeated reason
    assert seg.reason_table == ["preempt"]


def test_segment_event_encoding_matches_codec_byte_for_byte():
    """The hand-built Event row encoding IS codec.encode of the
    materialized ClusterEvent — key order and values, via json bytes."""
    name = event_name("tok", 7)
    args = (name, "default/p0", "Scheduled",
            "Successfully assigned default/p0 to n1", "Normal", 42, 1234.5)
    assert json.dumps(encode_event_row(*args)) == json.dumps(
        encode(materialize_event(*args))
    )
    args = (event_name("tok", 8), "default/p1", "Evict",
            "Evicted for preempt", "Warning", 43, 1234.5)
    assert json.dumps(encode_event_row(*args)) == json.dumps(
        encode(materialize_event(*args))
    )


def test_segment_pod_rows_decode_equal_per_object_encode():
    """Watch-expanded Pod rows from a lazy segment == codec.encode of the
    materialized store objects (segment decode == per-object encode)."""
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 3)
        cursor = rs.resource_version
        rs.apply_segment(_seg(
            [("default/p0", "n0"), ("default/p1", "n1")],
            evicts=[("default/p2", "too-hot")],
        ))
        rows = srv.watch_since(cursor, {"Pod"}, 0)["events"]
        assert [e["type"] for e in rows] == ["Updated"] * 3
        for e in rows:
            obj = srv.store.get("Pod", e["object"]["meta"]["name"] and
                                f"default/{e['object']['meta']['name']}")
            assert json.dumps(e["object"]) == json.dumps(encode(obj))
        # Event rows decode to the exact materialized objects too
        ev_rows = srv.watch_since(cursor, {"Event"}, 0)["events"]
        evs = {e.meta.key: e for e in srv.store.list("Event")}
        assert len(ev_rows) == 3 and len(evs) == 3
        for e in ev_rows:
            key = f"/{e['object']['meta']['name']}"
            assert json.dumps(e["object"]) == json.dumps(encode(evs[key]))
    finally:
        srv.stop()


# -- watch-stream equivalence vs the per-object path --------------------------

_EV_ID = re.compile(r"event-t0-\d{8}(?:-t0-\d{8})?")


def _normalize(stream) -> str:
    """json bytes of an event stream with generated Event identities
    (name + uid — pure opaque ids: the per-object path draws a second
    counter slot for the uid, the segment path reuses the name) replaced
    by first-appearance ordinals.  The ONLY tolerated difference between
    the per-object and columnar paths — both runs are otherwise fully
    controlled: same uid counter, same frozen clock."""
    out = json.loads(json.dumps(stream))
    for e in out:
        if e["kind"] == "Event":
            for side in ("object", "old"):
                o = e.get(side)
                if o:
                    o["meta"]["uid"] = o["meta"]["name"]
    seen = {}

    def sub(m):
        return seen.setdefault(m.group(0), f"EV{len(seen)}")

    return _EV_ID.sub(sub, json.dumps(out))


def _run_publish(monkeypatch, columnar: bool):
    """One controlled publish of 24 binds + 6 evicts through the REAL
    applier against a fresh server; returns the server's full log."""
    monkeypatch.setattr(api_objects, "_uid_token", "t0")
    monkeypatch.setattr(api_objects, "_uid_next", 1000)
    monkeypatch.setattr(time, "time", lambda: 1234.5)
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 30, nodes=("n0", "n1", "n2"))
        cache = SchedulerCache(rs, async_apply=True)
        binds = [(f"default/p{i}", f"n{i % 3}") for i in range(24)]
        evicts = [(f"default/p{24 + i}", "preempt") for i in range(6)]
        applier = cache.applier
        try:
            # drive the drain synchronously (no thread race) so both
            # paths apply as ONE batch, like a cycle's queue drain does
            if columnar:
                applier._apply([("segment", _seg(binds, evicts), None)])
            else:
                applier._apply(
                    [("bind", k, h) for k, h in binds]
                    + [("evict", k, r) for k, r in evicts]
                )
        finally:
            applier.stop(flush=False)
        assert cache.err_log == []
        return srv.watch_since(0, set(), 0)["events"]
    finally:
        srv.stop()


def test_watch_stream_byte_identical_to_per_object_path(monkeypatch):
    per_object = _run_publish(monkeypatch, columnar=False)
    columnar = _run_publish(monkeypatch, columnar=True)
    assert _normalize(columnar) == _normalize(per_object)
    # and the streams actually carried the workload: seeds + 30 pod
    # patches + 30 events
    kinds = [e["kind"] for e in columnar]
    assert kinds.count("Event") == 30
    assert sum(1 for e in columnar
               if e["kind"] == "Pod" and e["type"] == "Updated") == 30


def test_remote_watch_client_decodes_segment_rows(monkeypatch):
    """A RemoteStore watcher drains a columnar-fed log into ordinary
    per-object Events — the mirror/controller contract."""
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 2)
        watcher = RemoteStore(srv.url)
        q = watcher.watch("Pod")
        qe = watcher.watch("Event")
        rs.apply_segment(_seg([("default/p0", "n1"), ("default/p1", "n0")]))
        got = []
        while q:
            got.append(q.popleft())
        assert [(e.obj.meta.name, e.obj.node_name) for e in got] == [
            ("p0", "n1"), ("p1", "n0")]
        assert all(e.old is not None and not e.old.node_name for e in got)
        evs = []
        while qe:
            evs.append(qe.popleft())
        assert [e.obj.reason for e in evs] == ["Scheduled", "Scheduled"]
        assert evs[0].obj.message.endswith("assigned default/p0 to n1")
    finally:
        srv.stop()


# -- lazy materialization semantics ------------------------------------------


def test_lazy_apply_defers_object_writes_until_read():
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 2)
        store = srv.store
        rv_before = store.resource_version
        rs.apply_segment(_seg([("default/p0", "n1"), ("default/p1", "n0")]))
        # rv advanced at ACK (2 patches + 2 events), but the live objects
        # are untouched until a read materializes them
        assert store.resource_version == rv_before + 4
        assert store._objects["Pod"]["default/p0"].node_name == ""
        assert len(store._lazy_patch["Pod"]) == 2
        p0 = store.get("Pod", "default/p0")
        assert p0.node_name == "n1"
        assert p0.meta.resource_version == rv_before + 1
        assert "default/p0" not in store._lazy_patch["Pod"]
        # the no-op-suppression shadow materialized too: re-patching the
        # same value stays quiescent (no event, no rv bump)
        rv = store.resource_version
        store.patch("Pod", "default/p0", {"node_name": "n1"})
        assert store.resource_version == rv
    finally:
        srv.stop()


def test_lazy_events_never_materialize_unless_listed():
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 2)
        rs.apply_segment(_seg([("default/p0", "n1"), ("default/p1", "n0")]))
        store = srv.store
        assert store._objects["Event"] == {}
        assert len(store._lazy_create["Event"]) == 2
        evs = events_for(store, "Pod", "default/p0")  # lists -> materializes
        assert [e.reason for e in evs] == ["Scheduled"]
        assert store._lazy_create["Event"] == {}
        # uid ordering == creation order across the whole block
        ordered = sorted(store.list("Event"), key=lambda e: e.meta.uid)
        assert [e.involved[1] for e in ordered] == [
            "default/p0", "default/p1"]
    finally:
        srv.stop()


def test_later_patch_stacks_on_lazy_row_and_noop_binds_event_only():
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 1)
        cursor = rs.resource_version
        rs.apply_segment(_seg([("default/p0", "n1")]))
        # a normal per-object patch lands on top of the lazy row: the
        # delta chain must keep the segment's node_name
        rs.patch("Pod", "default/p0", {"deleting": True})
        rows = srv.watch_since(cursor, {"Pod"}, 0)["events"]
        assert [e["object"]["node_name"] for e in rows] == ["n1", "n1"]
        assert rows[1]["object"]["deleting"] is True
        assert rows[1]["old"]["node_name"] == "n1"
        # re-binding to the same node is a no-op write: Event, no patch row
        seq = srv.seq
        res = rs.apply_segment(_seg([("default/p0", "n1")]))
        assert res["binds"] == []
        rows = srv.watch_since(seq, set(), 0)["events"]
        assert [e["kind"] for e in rows] == ["Event"]
    finally:
        srv.stop()


def test_segment_row_errors_surface_and_pods_vanish_cleanly():
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 1)
        res = rs.apply_segment(_seg(
            [("default/p0", "n1"), ("default/ghost", "n0")],
            evicts=[("default/gone", "preempt")],
        ))
        assert [row for row, _ in res["binds"]] == [1]
        assert "NotFound" in res["binds"][0][1]
        assert [row for row, _ in res["evicts"]] == [0]
        assert rs.get("Pod", "default/p0").node_name == "n1"
        # only the successful rows produced events
        assert [e.reason for e in srv.store.list("Event")] == ["Scheduled"]
    finally:
        srv.stop()


def test_flush_state_persists_lazy_rows(tmp_path):
    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state, save_interval=3600).start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 1)
        rs.apply_segment(_seg([("default/p0", "n1")]))
        srv.flush_state()
        data = json.load(open(state))
        pods = {p["meta"]["name"]: p for p in data["kinds"]["Pod"]}
        assert pods["p0"]["node_name"] == "n1"
        assert len(data["kinds"]["Event"]) == 1
    finally:
        srv.stop()


def test_log_blocks_trim_partially_and_relist_horizon_holds(monkeypatch):
    from volcano_tpu.store import server as server_mod

    monkeypatch.setattr(server_mod, "LOG_CAP", 10)
    srv = StoreServer().start()
    try:
        rs = RemoteStore(srv.url)
        _seed_pods(rs.create, 8)  # 11 seed events: already over cap
        cursor = rs.resource_version
        rs.apply_segment(_seg(
            [(f"default/p{i}", f"n{i % 2}") for i in range(8)]
        ))  # 8 patch rows + 8 event rows; cap 10 -> patch block trimmed
        assert srv._log_rows == 10
        horizon = srv.seq - srv._log_rows
        assert horizon > cursor  # the trim ate into the patch block
        # a cursor inside the trimmed range must relist
        out = srv.watch_since(horizon - 1, set(), 0)
        assert out.get("relist")
        # a cursor mid-block gets exactly the tail rows, seqs contiguous
        out = srv.watch_since(horizon + 1, set(), 0)
        seqs = [e["seq"] for e in out["events"]]
        assert seqs == list(range(horizon + 2, srv.seq + 1))
        kinds = [e["kind"] for e in out["events"]]
        assert kinds == ["Pod"] * 1 + ["Event"] * 8
    finally:
        srv.stop()


# -- applier integration ------------------------------------------------------


def test_applier_segment_overlay_and_error_retry_semantics():
    store = make_store([])
    _seed_pods(store.create, 2)
    store.delete("Pod", "default/p1")  # vanishes before the drain
    cache = SchedulerCache(store, async_apply=True)
    gate = threading.Event()
    orig = store.apply_segment
    store.apply_segment = lambda seg: (gate.wait(10), orig(seg))[1]
    try:
        seg = _seg([("default/p0", "n0"), ("default/p1", "n1")],
                   evicts=[("default/p0", "late-evict")])
        assert cache.publish_segment(seg)
        # in flight: every key overlaid (bind wins over the queued evict
        # marker for the same key only if the evict came first — here the
        # evict rides the same segment, so both markers show)
        binds, evicts = cache.applier.inflight_view()
        assert binds == {"default/p0": "n0", "default/p1": "n1"}
        assert evicts == {"default/p0": "late-evict"}
    finally:
        gate.set()
    assert cache.applier.flush(10)
    # confirmed: markers gone, failure recorded for the vanished pod only
    assert cache.applier.inflight_view() == ({}, {})
    assert [(op, key) for op, key, _ in cache.err_log] == [
        ("bind", "default/p1")]
    assert store.get("Pod", "default/p0").node_name == "n0"
    assert cache.bind_log == [("default/p0", "n0"), ("default/p1", "n1")]
    assert cache.evict_log == [("default/p0", "late-evict")]


def test_abort_pending_purges_queued_segment_markers():
    store = make_store([])
    _seed_pods(store.create, 2)
    cache = SchedulerCache(store, async_apply=True)
    applier = cache.applier
    gate = threading.Event()
    # first, a blocking op batch occupies the applier thread so the
    # segment stays QUEUED (not applying) when the purge hits
    orig_bulk = store.bulk
    store.bulk = lambda ops: (gate.wait(10), orig_bulk(ops))[1]
    try:
        applier.submit_ops([{"op": "patch", "kind": "Pod",
                             "key": "default/p0", "fields": {}}])
        time.sleep(0.05)  # let the thread pick up the ops batch
        cache.publish_segment(_seg([("default/p0", "n0")]))
        assert applier.inflight_binds == {"default/p0": "n0"}
        dropped = applier.abort_pending()
        assert dropped == 1
        assert applier.inflight_binds == {}
    finally:
        gate.set()
    assert applier.flush(10)
    assert store.get("Pod", "default/p0").node_name == ""  # never applied


def test_repeat_evicts_aggregate_instead_of_duplicating_events():
    """Evict rows keep the r5 count-aggregation semantics: a repeated
    (pod, Evict, message) across segments bumps ONE Event's count, it
    does not mint duplicates forever in a long-lived daemon."""
    store = make_store([])
    _seed_pods(store.create, 1)
    cache = SchedulerCache(store, async_apply=True)
    cache.publish_segment(_seg([], evicts=[("default/p0", "too-hot")]))
    assert cache.applier.flush(10)
    # the pod resurfaces (store writer clears deleting), same verdict
    store.patch("Pod", "default/p0", {"deleting": False})
    cache.publish_segment(_seg([], evicts=[("default/p0", "too-hot")]))
    assert cache.applier.flush(10)
    evs = events_for(store, "Pod", "default/p0")
    assert [(e.reason, e.count) for e in evs] == [("Evict", 2)]
    assert store.get("Pod", "default/p0").deleting is True
    assert cache.err_log == []


def test_restart_seeds_obj_enc_for_segment_delta_bases(tmp_path):
    """A restarted server must not pay a full per-object encode under
    the lock for the first post-restart segment: _load_state seeds the
    per-object cache the segment's delta capture reads."""
    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state, save_interval=0.0).start()
    rs = RemoteStore(srv.url)
    _seed_pods(rs.create, 2)
    srv.stop()
    srv2 = StoreServer(state_path=state, save_interval=0.0).start()
    try:
        assert ("Pod", "default/p0") in srv2._obj_enc
        rs2 = RemoteStore(srv2.url)
        cursor = rs2.resource_version
        rs2.apply_segment(_seg([("default/p0", "n1")]))
        rows = srv2.watch_since(cursor, {"Pod"}, 0)["events"]
        assert rows[0]["object"]["node_name"] == "n1"
        assert rows[0]["old"]["node_name"] == ""
        assert json.dumps(rows[0]["object"]) == json.dumps(
            encode(srv2.store.get("Pod", "default/p0"))
        )
    finally:
        srv2.stop()


# -- fallback flag + end-to-end smoke (tier-1) --------------------------------


def _fast_async_run(columnar: bool, store=None):
    from volcano_tpu.scheduler.scheduler import Scheduler

    store = store or make_store([])
    store.create("Node", build_node("n1", cpu="16", memory="32Gi"))
    pg = build_podgroup("pg1", min_member=3)
    pg.status.phase = PodGroupPhase.INQUEUE
    store.create("PodGroup", pg)
    for i in range(3):
        store.create("Pod", build_pod(f"p{i}", group="pg1", cpu="1"))
    conf = full_conf("tpu")
    conf.apply_mode = "async"
    conf.columnar_publish = columnar
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    assert sched.cache.applier.flush(10)
    assert sched.fast_cycle is not None and sched.fast_cycle.mirror is not None
    placements = sorted(
        (p.meta.key, p.node_name) for p in store.list("Pod")
    )
    events = sorted(
        (e.involved[1], e.reason) for e in store.list("Event")
    )
    assert sched.cache.err_log == []
    sched.cache.applier.stop()
    return placements, events


def test_in_process_fallback_flag_matches_columnar_run():
    """Tier-1 smoke: with ``columnarPublish: false`` the in-process fast
    cycle publishes through the r5 per-object bulk path and produces the
    same placements AND the same event stream as the columnar default."""
    col_p, col_e = _fast_async_run(columnar=True)
    old_p, old_e = _fast_async_run(columnar=False)
    assert col_p == old_p
    assert [p for p, n in col_p if n] != []  # something actually bound
    assert col_e == old_e


def test_conf_loads_columnar_publish_flag():
    from volcano_tpu.scheduler.conf import load_conf

    assert load_conf("applyMode: async\n").columnar_publish is True
    assert load_conf("columnarPublish: false\n").columnar_publish is False


# -- chaos: segment atomicity under storms ------------------------------------


def _storm_run(plan):
    """A fastpath scheduler on RemoteStore publishing columnar segments
    while the server chaos plan fires; returns converged placements."""
    from volcano_tpu.scheduler.scheduler import Scheduler

    srv = StoreServer().start()
    try:
        seeder = RemoteStore(srv.url)
        seeder.create("Queue", Queue(meta=Metadata(name="default",
                                                   namespace="")))
        for n in range(3):
            seeder.create("Node", build_node(f"n{n}", cpu="8",
                                             memory="16Gi"))
        for g in range(4):
            pg = build_podgroup(f"pg{g}", min_member=3)
            pg.status.phase = PodGroupPhase.INQUEUE
            seeder.create("PodGroup", pg)
            for i in range(3):
                seeder.create("Pod", build_pod(f"g{g}-{i}", group=f"pg{g}",
                                               cpu="1"))
        if plan is not None:
            from volcano_tpu.chaos import FaultPlan

            srv.arm_chaos(FaultPlan.from_dict(plan))
        conf = full_conf("tpu")
        conf.apply_mode = "async"
        sched = Scheduler(RemoteStore(srv.url), conf=conf)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                sched.run_once()
            except Exception:  # noqa: BLE001 — storm-side transient
                time.sleep(0.05)
                continue
            sched.cache.applier.flush(10)
            pods = srv.store.list("Pod")
            if all(p.node_name for p in pods):
                break
            time.sleep(0.02)
        srv.arm_chaos(None)
        sched.run_once()
        sched.cache.applier.flush(10)
        pods = srv.store.list("Pod")
        placements = sorted((p.meta.key, p.node_name) for p in pods)
        scheduled = {e.involved[1] for e in srv.store.list("Event")
                     if e.reason == "Scheduled"}
        sched.cache.applier.stop()
        return placements, scheduled
    finally:
        srv.stop()


PLAN_SEGMENT_STORM = {
    "seed": 77,
    "rules": [
        # cut the segment publish's reply mid-body: the segment has
        # APPLIED (atomic under the server lock); the client records
        # errors, and the next cycle's mirror shows the truth
        {"point": "server.request", "action": "cut_body",
         "match": {"path": "/bulk"}, "every": 2, "count": 4},
        # and 5xx some too: consumed BEFORE dispatch — nothing applied,
        # the cycle republishes
        {"point": "server.request", "action": "http_500",
         "match": {"path": "/bulk"}, "after": 8, "every": 2, "count": 3},
        # truncate the watch log under the mirror: StaleWatch relist
        {"point": "server.request", "action": "truncate_log",
         "match": {"path": "/watch"}, "after": 4, "every": 9, "count": 2},
    ],
}


def test_chaos_segment_storm_converges_with_no_half_applied_segment():
    clean_placements, clean_scheduled = _storm_run(None)
    storm_placements, storm_scheduled = _storm_run(PLAN_SEGMENT_STORM)
    assert [k for k, n in clean_placements if n] != []
    assert storm_placements == clean_placements
    # no half-applied segment: every bound pod has its Scheduled Event
    # and no Event names an unbound pod
    bound = {k for k, n in storm_placements if n}
    assert storm_scheduled == bound == clean_scheduled
