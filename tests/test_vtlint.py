"""vtlint: the analyzer itself, every rule's fires/near-miss pair, the
suppression contract, and the zero-findings gate over the real tree.

Tier-1: `python -m volcano_tpu.analysis` must exit 0 on the repo — the
rules encode the hot-path/parity/concurrency disciplines the kernels
depend on (ANALYSIS.md), so a finding here is a real regression, not
style.  Each rule is proven live by a fixture that triggers it and honest
by a near-miss that must stay quiet.
"""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

from volcano_tpu.analysis import all_rules, run_paths
from volcano_tpu.analysis.core import USAGE_RULE


def _lint(tmp_path, relname, source, select=None):
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_paths([str(path)], root=str(tmp_path), select=select)


def _rules_of(findings):
    return [f.rule for f in findings]


# --- the catalog itself ------------------------------------------------------


def test_at_least_eight_rules_registered():
    rules = all_rules()
    assert len(rules) >= 8, sorted(rules)
    for rid, r in rules.items():
        assert r.description, rid


def test_clean_tree_has_zero_findings():
    """THE gate: the analyzer over the real package tree is clean."""
    import volcano_tpu

    pkg = os.path.dirname(os.path.abspath(volcano_tpu.__file__))
    findings = run_paths([pkg], root=os.path.dirname(pkg))
    assert findings == [], "\n".join(f.human() for f in findings)


def test_cli_json_and_exit_codes(tmp_path):
    import json as _json

    bad = tmp_path / "scheduler" / "x.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f():\n    try:\n        g()\n    except Exception:\n        pass\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis", "--json",
         "--root", str(tmp_path), str(bad)],
        capture_output=True, text=True, env=env,
    )
    assert r.returncode == 1, r.stderr
    report = _json.loads(r.stdout)
    assert report["count"] == 1
    assert report["findings"][0]["rule"] == "bare-except"
    # unknown --select is a usage error, not a vacuous pass
    r2 = subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis",
         "--select", "no-such-rule", str(bad)],
        capture_output=True, text=True, env=env,
    )
    assert r2.returncode == 2
    assert "no-such-rule" in r2.stderr


# --- rule 1: hotpath-python-loop --------------------------------------------


def test_hot_loop_fires(tmp_path):
    findings = _lint(tmp_path, "kernels.py", """
        def residue(tasks, nodes):
            for t in tasks:
                for n in nodes:
                    if t[0] < n[0]:
                        return n
    """, select=["hotpath-python-loop"])
    assert _rules_of(findings) == ["hotpath-python-loop"]


def test_hot_loop_near_miss_hierarchical_and_non_twin(tmp_path):
    # a job's OWN tasks: linear, not a product
    assert _lint(tmp_path, "fastpath.py", """
        def walk(jobs):
            total = 0
            for job in jobs:
                for t in job.tasks:
                    total += t
            return total
    """, select=["hotpath-python-loop"]) == []
    # identical product loop OUTSIDE a kernel-twin module: out of scope
    assert _lint(tmp_path, "helpers.py", """
        def residue(tasks, nodes):
            for t in tasks:
                for n in nodes:
                    pass
    """, select=["hotpath-python-loop"]) == []


# --- rule 2: hotpath-host-sync ----------------------------------------------


def test_host_sync_fires(tmp_path):
    findings = _lint(tmp_path, "fast_victims.py", """
        def fetch(out):
            return out.item()
    """, select=["hotpath-host-sync"])
    assert _rules_of(findings) == ["hotpath-host-sync"]
    # float(name) inside a jit body, any module
    findings = _lint(tmp_path, "anything.py", """
        import jax

        @jax.jit
        def f(x):
            return float(x)
    """, select=["hotpath-host-sync"])
    assert _rules_of(findings) == ["hotpath-host-sync"]


def test_host_sync_near_miss(tmp_path):
    assert _lint(tmp_path, "fast_victims.py", """
        def fetch(out):
            return out.sum()
    """, select=["hotpath-host-sync"]) == []


# --- rule 3: hotpath-wallclock ----------------------------------------------


def test_wallclock_fires(tmp_path):
    findings = _lint(tmp_path, "victim_kernels.py", """
        import time

        def stamp():
            return time.time()
    """, select=["hotpath-wallclock"])
    assert _rules_of(findings) == ["hotpath-wallclock"]


def test_wallclock_near_miss_perf_counter(tmp_path):
    assert _lint(tmp_path, "victim_kernels.py", """
        import time

        def phase():
            return time.perf_counter()
    """, select=["hotpath-wallclock"]) == []


# --- rule 4: jit-state-mutation ---------------------------------------------


def test_jit_mutation_fires(tmp_path):
    findings = _lint(tmp_path, "solver.py", """
        import jax

        acc = []

        @jax.jit
        def f(x):
            acc.append(x)
            return x
    """, select=["jit-state-mutation"])
    assert _rules_of(findings) == ["jit-state-mutation"]


def test_jit_mutation_near_miss_local(tmp_path):
    assert _lint(tmp_path, "solver.py", """
        import jax

        @jax.jit
        def f(x):
            tmp = []
            tmp.append(x)
            return x
    """, select=["jit-state-mutation"]) == []


# --- rule 5: jit-unkeyed-random ---------------------------------------------


def test_jit_random_fires(tmp_path):
    findings = _lint(tmp_path, "solver.py", """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return x + np.random.rand()
    """, select=["jit-unkeyed-random"])
    assert _rules_of(findings) == ["jit-unkeyed-random"]


def test_jit_random_near_miss_keyed(tmp_path):
    assert _lint(tmp_path, "solver.py", """
        import jax

        @jax.jit
        def f(key, x):
            return x + jax.random.uniform(key)
    """, select=["jit-unkeyed-random"]) == []


# --- rule 6: resource-raw-compare -------------------------------------------


def test_resource_compare_fires(tmp_path):
    findings = _lint(tmp_path, "someaction.py", """
        def fits(task, node):
            return task.resreq <= node.idle
    """, select=["resource-raw-compare"])
    assert _rules_of(findings) == ["resource-raw-compare"]
    # local taint through Resource()/clone()
    findings = _lint(tmp_path, "other.py", """
        def covered(victims, need):
            total = Resource()
            for v in victims:
                total.add(v.resreq)
            return total == need
    """, select=["resource-raw-compare"])
    assert _rules_of(findings) == ["resource-raw-compare"]


def test_resource_compare_near_miss(tmp_path):
    assert _lint(tmp_path, "someaction.py", """
        def fits(task, node):
            return task.resreq.less_equal(node.idle)
    """, select=["resource-raw-compare"]) == []
    # api/resource.py itself defines the semantics
    assert _lint(tmp_path, "api/resource.py", """
        def less_equal(a, b):
            return a.idle <= b.idle
    """, select=["resource-raw-compare"]) == []


# --- rule 7: parity-citation ------------------------------------------------


def test_parity_citation_fires(tmp_path):
    findings = _lint(tmp_path, "actions/myaction.py", '''
        """An action with no reference citation anywhere."""

        class MyAction(Action):
            name = "my"

            def execute(self, ssn):
                return None
    ''', select=["parity-citation"])
    assert "parity-citation" in _rules_of(findings)


def test_parity_citation_near_miss(tmp_path):
    assert _lint(tmp_path, "actions/myaction.py", '''
        """My action.

        Parity: reference KB/pkg/scheduler/actions/my/my.go:42-128.
        """

        class MyAction(Action):
            name = "my"

            def execute(self, ssn):
                return None
    ''', select=["parity-citation"]) == []


# --- rule 8: session-registry -----------------------------------------------


def test_session_registry_fires(tmp_path):
    findings = _lint(tmp_path, "plugins/myplugin.py", """
        class MyPlugin(Plugin):
            name = "my"

            def on_session_open(self, ssn):
                ssn.add_job_oder_fn(self.name, lambda l, r: 0)
                ssn.add_predicate_fn("other-plugin", lambda t, n: None)
    """, select=["session-registry"])
    assert _rules_of(findings) == ["session-registry", "session-registry"]
    assert "add_job_oder_fn" in findings[0].message
    assert "other than" in findings[1].message


def test_session_registry_near_miss(tmp_path):
    assert _lint(tmp_path, "plugins/myplugin.py", """
        class MyPlugin(Plugin):
            name = "my"

            def on_session_open(self, ssn):
                ssn.add_job_order_fn(self.name, lambda l, r: 0)
                ssn.add_predicate_fn(self.name, lambda t, n: None)
    """, select=["session-registry"]) == []


# --- rule 9: lock-order -----------------------------------------------------


_ABBA = """
    import threading

    class S:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def f(self):
            with self.a:
                with self.b:
                    pass

        def g(self):
            with self.b:
                {body}
"""


def test_lock_order_fires_on_abba(tmp_path):
    findings = _lint(
        tmp_path, "server.py",
        _ABBA.format(body="with self.a:\n                    pass"),
        select=["lock-order"])
    assert _rules_of(findings) == ["lock-order"]
    assert "cycle" in findings[0].message
    # the ABBA through a CALL while holding the lock is caught too
    findings = _lint(tmp_path, "server2.py", """
        import threading

        class S:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def f(self):
                with self.a:
                    with self.b:
                        pass

            def locks_a(self):
                with self.a:
                    pass

            def g(self):
                with self.b:
                    self.locks_a()
    """, select=["lock-order"])
    assert _rules_of(findings) == ["lock-order"]


def test_lock_order_near_miss_consistent(tmp_path):
    findings = _lint(
        tmp_path, "server.py",
        _ABBA.format(body="pass"),
        select=["lock-order"])
    assert findings == []


def test_lock_order_non_reentrant_self_nesting(tmp_path):
    findings = _lint(tmp_path, "server.py", """
        import threading

        class S:
            def __init__(self):
                self.a = threading.Lock()

            def f(self):
                with self.a:
                    with self.a:
                        pass
    """, select=["lock-order"])
    assert _rules_of(findings) == ["lock-order"]
    assert "non-reentrant" in findings[0].message
    # the same shape over an RLock is legal
    findings = _lint(tmp_path, "server2.py", """
        import threading

        class S:
            def __init__(self):
                self.a = threading.RLock()

            def f(self):
                with self.a:
                    with self.a:
                        pass
    """, select=["lock-order"])
    assert findings == []


# --- rule 10: lock-guard ----------------------------------------------------


def test_lock_guard_fires(tmp_path):
    findings = _lint(tmp_path, "daemon.py", """
        import threading

        class S:
            def __init__(self):
                self.mu = threading.Lock()
                self.count = 0

            def bump(self):
                with self.mu:
                    self.count += 1

            def reset(self):
                self.count = 0
    """, select=["lock-guard"])
    assert _rules_of(findings) == ["lock-guard"]
    assert "self.count" in findings[0].message


def test_lock_guard_near_miss(tmp_path):
    assert _lint(tmp_path, "daemon.py", """
        import threading

        class S:
            def __init__(self):
                self.mu = threading.Lock()
                self.count = 0

            def bump(self):
                with self.mu:
                    self.count += 1

            def reset(self):
                with self.mu:
                    self.count = 0
    """, select=["lock-guard"]) == []


# --- rule 11: statement-discipline ------------------------------------------


def test_statement_discipline_fires(tmp_path):
    findings = _lint(tmp_path, "act.py", """
        def act(ssn, jobs):
            for j in jobs:
                stmt = Statement(ssn)
                if j.ok:
                    stmt.commit()
    """, select=["statement-discipline"])
    assert _rules_of(findings) == ["statement-discipline"]


def test_statement_discipline_near_miss(tmp_path):
    assert _lint(tmp_path, "act.py", """
        def act(ssn, jobs):
            for j in jobs:
                stmt = Statement(ssn)
                if j.ok:
                    stmt.commit()
                else:
                    stmt.discard()
                    continue
    """, select=["statement-discipline"]) == []
    # the real preempt shape: break out of an inner loop, settle after
    assert _lint(tmp_path, "act2.py", """
        def act(ssn, jobs):
            while True:
                stmt = Statement(ssn)
                while True:
                    if done():
                        break
                if ok():
                    stmt.commit()
                else:
                    stmt.discard()
                    continue
    """, select=["statement-discipline"]) == []


# --- rule 12: bare-except ---------------------------------------------------


def test_bare_except_fires(tmp_path):
    findings = _lint(tmp_path, "scheduler/thing.py", """
        def f():
            try:
                g()
            except Exception:
                pass
    """, select=["bare-except"])
    assert _rules_of(findings) == ["bare-except"]


def test_bare_except_near_miss(tmp_path):
    # handled broad catch: fine
    assert _lint(tmp_path, "scheduler/thing.py", """
        def f(log):
            try:
                g()
            except Exception as e:
                log.append(e)
    """, select=["bare-except"]) == []
    # silent catch OUTSIDE the hot path trees: out of scope
    assert _lint(tmp_path, "cli/thing.py", """
        def teardown():
            try:
                g()
            except Exception:
                pass
    """, select=["bare-except"]) == []


# --- rule 13: retry-backoff --------------------------------------------------


def test_retry_backoff_fires_on_fixed_sleep_in_handler(tmp_path):
    findings = _lint(tmp_path, "cli/daemons.py", """
        import time

        def run(store):
            while True:
                try:
                    store.pump()
                except OSError:
                    time.sleep(1.0)
    """, select=["retry-backoff"])
    assert _rules_of(findings) == ["retry-backoff"]


def test_retry_backoff_fires_on_fallthrough_to_loop_sleep(tmp_path):
    # the pre-backoff daemons.py shape: handler sets a flag and falls
    # through, so the healthy pump sleep doubles as the retry delay
    findings = _lint(tmp_path, "cli/daemons.py", """
        import time

        def run(store, period, transient):
            down = False
            while True:
                try:
                    store.pump()
                except transient:
                    down = True
                time.sleep(period)
    """, select=["retry-backoff"])
    assert _rules_of(findings) == ["retry-backoff"]


def test_retry_backoff_near_misses(tmp_path):
    # backoff-paced retry + fixed HEALTHY-path period: the sanctioned shape
    assert _lint(tmp_path, "cli/daemons.py", """
        import time
        from volcano_tpu.backoff import Backoff

        def run(store, period):
            retry = Backoff()
            while True:
                try:
                    store.pump()
                    retry.reset()
                except OSError:
                    retry.sleep()
                    continue
                time.sleep(period)
    """, select=["retry-backoff"]) == []
    # time.sleep fed from the backoff stream is equally fine
    assert _lint(tmp_path, "cli/daemons.py", """
        import time
        from volcano_tpu.backoff import Backoff

        def probe(store, deadline):
            retry = Backoff()
            while True:
                try:
                    return store.ping()
                except OSError:
                    time.sleep(min(retry.next(), deadline))
    """, select=["retry-backoff"]) == []
    # non-transient handler falling through: not a retry loop
    assert _lint(tmp_path, "cli/daemons.py", """
        import time

        def run(pids, period):
            while True:
                try:
                    check(pids)
                except ProcessLookupError:
                    pids.clear()
                time.sleep(period)
    """, select=["retry-backoff"]) == []
    # a fixed sleep inside a NON-transient handler is that handler's
    # business — the fall-through pass must not misreport it as the
    # loop-tail retry delay of the (escaping-by-backoff) transient handler
    assert _lint(tmp_path, "cli/daemons.py", """
        import time
        from volcano_tpu.backoff import Backoff

        def run(store):
            retry = Backoff()
            while True:
                try:
                    store.pump()
                except OSError:
                    retry.sleep()
                except ValueError:
                    time.sleep(0.01)
    """, select=["retry-backoff"]) == []
    # identical offending shape OUTSIDE daemon modules: out of scope
    assert _lint(tmp_path, "scheduler/thing.py", """
        import time

        def run(store):
            while True:
                try:
                    store.pump()
                except OSError:
                    time.sleep(1.0)
    """, select=["retry-backoff"]) == []


# --- elastic scope: the daemon-module set and the scan roots include
# --- volcano_tpu/elastic/ (elasticd's reconciler retries against the
# --- store bus exactly like the cli daemons)


def test_retry_backoff_fires_in_elastic_modules(tmp_path):
    findings = _lint(tmp_path, "elastic/controller.py", """
        import time

        def reconcile_loop(store):
            while True:
                try:
                    store.list("NodePool")
                except OSError:
                    time.sleep(0.5)
    """, select=["retry-backoff"])
    assert _rules_of(findings) == ["retry-backoff"]


def test_retry_backoff_elastic_near_miss(tmp_path):
    # backoff-paced retry in an elastic module: the sanctioned shape
    assert _lint(tmp_path, "elastic/controller.py", """
        import time
        from volcano_tpu.backoff import Backoff

        def reconcile_loop(store, period):
            retry = Backoff()
            while True:
                try:
                    store.list("NodePool")
                    retry.reset()
                except OSError:
                    retry.sleep()
                    continue
                time.sleep(period)
    """, select=["retry-backoff"]) == []


def test_retry_backoff_fires_in_replica_module(tmp_path):
    # the follower pump's exact reconnect shape: the leader's feed dies
    # mid-election and every follower re-polls — fixed-sleep retries here
    # synchronize the whole replica fleet onto one reconnect beat
    findings = _lint(tmp_path, "store/replica.py", """
        import time

        def pump(self):
            while not self._stop.is_set():
                try:
                    self._follower_tick()
                except OSError:
                    time.sleep(0.25)
    """, select=["retry-backoff"])
    assert _rules_of(findings) == ["retry-backoff"]


def test_retry_backoff_replica_near_misses(tmp_path):
    # jitter-paced pump retry: the sanctioned shape store/replica.py uses
    assert _lint(tmp_path, "store/replica.py", """
        import time
        from volcano_tpu.backoff import Backoff

        def pump(self):
            retry = Backoff(base=0.05, cap=2.0)
            while not self._stop.is_set():
                try:
                    if self._follower_tick():
                        retry.reset()
                except OSError:
                    retry.sleep()
                    continue
    """, select=["retry-backoff"]) == []
    # the scope is the basename, not the store/ package: the same fixed
    # sleep in another store module (server-side, no reconnect loops
    # against a remote bus) stays out of scope
    assert _lint(tmp_path, "store/server.py", """
        import time

        def pump(self):
            while True:
                try:
                    self.tick()
                except OSError:
                    time.sleep(0.25)
    """, select=["retry-backoff"]) == []


def test_session_registry_scans_elastic_modules(tmp_path):
    # a (hypothetical) elastic plugin registering a typoed Session
    # callback must fire exactly as it would in scheduler/plugins/
    findings = _lint(tmp_path, "elastic/plugin.py", """
        def on_session_open(ssn):
            ssn.add_pool_order_fn("elastic", lambda l, r: 0)
    """, select=["session-registry"])
    assert _rules_of(findings) == ["session-registry"]
    assert _lint(tmp_path, "elastic/plugin.py", """
        def on_session_open(ssn):
            ssn.add_job_order_fn("elastic", lambda l, r: 0)
    """, select=["session-registry"]) == []


def test_lock_rules_scan_elastic_modules(tmp_path):
    # an ABBA pair in an elastic module is flagged like anywhere else
    findings = _lint(tmp_path, "elastic/state.py", """
        import threading

        class PoolState:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def grow(self):
                with self.a:
                    with self.b:
                        pass

            def shrink(self):
                with self.b:
                    with self.a:
                        pass
    """, select=["lock-order"])
    assert _rules_of(findings) == ["lock-order"]
    # consistent order: quiet
    assert _lint(tmp_path, "elastic/state.py", """
        import threading

        class PoolState:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def grow(self):
                with self.a:
                    with self.b:
                        pass

            def shrink(self):
                with self.a:
                    with self.b:
                        pass
    """, select=["lock-order"]) == []


# --- rule: residue-vectorized ------------------------------------------------


def test_residue_vectorized_fires_on_per_task_node_scan(tmp_path):
    findings = _lint(tmp_path, "residue.py", """
        def host_allocate(tasks, nodes):
            for t in tasks:
                for n in nodes:
                    if fits(t, n):
                        place(t, n)
                        break
    """, select=["residue-vectorized"])
    assert _rules_of(findings) == ["residue-vectorized"]


def test_residue_vectorized_fires_through_wrappers_and_while(tmp_path):
    # enumerate(all_nodes) under a while loop is still the per-task scan
    findings = _lint(tmp_path, "tensor_actions.py", """
        def residue(queue, all_nodes):
            while queue:
                t = queue.pop()
                for i, n in enumerate(all_nodes):
                    score(t, n)
    """, select=["residue-vectorized"])
    assert _rules_of(findings) == ["residue-vectorized"]
    # ssn.nodes.values() inside a task loop too
    findings = _lint(tmp_path, "residue.py", """
        def walk(ssn, tasks):
            for t in tasks:
                for n in ssn.nodes.values():
                    probe(t, n)
    """, select=["residue-vectorized"])
    assert _rules_of(findings) == ["residue-vectorized"]


def test_residue_vectorized_near_misses_stay_quiet(tmp_path):
    # a single depth-zero node sweep is the engine's amortized setup
    assert _lint(tmp_path, "residue.py", """
        def build_masks(nodes):
            out = []
            for n in nodes:
                out.append(n.labels)
            return out
    """, select=["residue-vectorized"]) == []
    # hierarchical residents walk: outer over nodes, inner over that
    # node's OWN tasks — linear, and the inner iter is not node-ish
    assert _lint(tmp_path, "residue.py", """
        def sweep(nodes):
            for n in nodes:
                for t in n.tasks.values():
                    note(t)
    """, select=["residue-vectorized"]) == []
    # identical per-task scan OUTSIDE the module set (the oracle loop in
    # actions/allocate.py) is deliberately exempt
    assert _lint(tmp_path, "allocate.py", """
        def oracle(tasks, nodes):
            for t in tasks:
                for n in nodes:
                    fits(t, n)
    """, select=["residue-vectorized"]) == []


# --- rule: columnar-publish --------------------------------------------------


def test_columnar_publish_fires_on_per_object_encode_loop(tmp_path):
    findings = _lint(tmp_path, "store/client.py", """
        def publish(self, binds):
            wire = []
            for key, host in binds:
                wire.append(encode({"key": key, "node_name": host}))
            return wire
    """, select=["columnar-publish"])
    assert _rules_of(findings) == ["columnar-publish"]


def test_columnar_publish_fires_in_comprehension_and_dumps(tmp_path):
    findings = _lint(tmp_path, "scheduler/apply.py", """
        def drain(self, ops):
            return [json.dumps(op) for op in ops]
    """, select=["columnar-publish"])
    assert _rules_of(findings) == ["columnar-publish"]
    # .items() over a decision map in a server bulk handler
    findings = _lint(tmp_path, "store/server.py", """
        def bulk(self, evicts):
            out = []
            for key, reason in evicts.items():
                out.append(encode_fields({"deleting": True}))
            return out
    """, select=["columnar-publish"])
    assert _rules_of(findings) == ["columnar-publish"]


def test_columnar_publish_near_misses_stay_quiet(tmp_path):
    # one whole-payload dumps OUTSIDE any loop is the segment path itself
    assert _lint(tmp_path, "store/client.py", """
        def apply_segment(self, seg):
            return json.dumps(seg.to_wire())
    """, select=["columnar-publish"]) == []
    # a loop over a NON-decision collection (per-field delta apply)
    assert _lint(tmp_path, "store/server.py", """
        def delta(self, enc, fields):
            for k, v in fields.items():
                enc[k] = encode(v)
    """, select=["columnar-publish"]) == []
    # the identical per-op encode loop outside the wire module set
    assert _lint(tmp_path, "scheduler/other.py", """
        def ship(ops):
            return [encode(op) for op in ops]
    """, select=["columnar-publish"]) == []
    # column assembly without any encode stays quiet
    assert _lint(tmp_path, "scheduler/apply.py", """
        def columns(self, binds):
            return [key for key, _ in binds]
    """, select=["columnar-publish"]) == []


def test_columnar_publish_suppressions_carry_justification():
    """The surviving per-op encode sites (client generic bulk, the state-
    flush cache-miss fallback, the replication snapshot's cache-miss
    fallback) are suppressed LINE-BY-LINE — the rule still fires on any
    new decision loop in those files."""
    import volcano_tpu

    pkg = os.path.dirname(os.path.abspath(volcano_tpu.__file__))
    client = open(os.path.join(pkg, "store", "client.py")).read()
    assert client.count("vtlint: disable=columnar-publish") >= 3
    server = open(os.path.join(pkg, "store", "server.py")).read()
    assert server.count("vtlint: disable=columnar-publish") == 2


# --- rule: trace-span-discipline --------------------------------------------


def test_trace_span_fires_outside_with(tmp_path):
    # a bare span() call and an assigned span are both manual pairing:
    # an exception between begin and end leaks the ambient context
    findings = _lint(tmp_path, "scheduler/x.py", """
        from volcano_tpu import trace

        def cycle():
            span = trace.span
            trace.span("cycle")
            s = trace.span("action")
            s.__enter__()
    """, select=["trace-span-discipline"])
    assert _rules_of(findings) == ["trace-span-discipline"] * 2


def test_trace_span_fires_on_manual_begin_end(tmp_path):
    findings = _lint(tmp_path, "scheduler/x.py", """
        def cycle(tr):
            tr.begin_span("cycle")
            work()
            tr.end_span()
    """, select=["trace-span-discipline"])
    assert _rules_of(findings) == ["trace-span-discipline"] * 2


def test_trace_time_in_jit_fires_in_trace_aware_module(tmp_path):
    findings = _lint(tmp_path, "scheduler/x.py", """
        import time

        import jax
        from volcano_tpu import trace

        @jax.jit
        def solve(x):
            t0 = time.perf_counter()
            return x + t0
    """, select=["trace-span-discipline"])
    assert _rules_of(findings) == ["trace-span-discipline"]


def test_trace_span_in_jit_fires_even_without_import(tmp_path):
    findings = _lint(tmp_path, "scheduler/x.py", """
        import jax
        from volcano_tpu.trace import span

        @jax.jit
        def solve(x):
            with span("inner"):
                return x
    """, select=["trace-span-discipline"])
    assert _rules_of(findings) == ["trace-span-discipline"]


def test_trace_span_near_misses(tmp_path):
    # with-scoped spans, annotate on the bound name, time.* outside jit
    # in a trace-aware module, and time-in-jit in a NON-trace module
    # (the generic hot-path rules own that tree) all stay quiet
    assert _lint(tmp_path, "scheduler/x.py", """
        import time

        from volcano_tpu import trace

        def cycle():
            t0 = time.perf_counter()
            with trace.span("cycle") as cyc:
                cyc.annotate(t0=t0)
                with trace.span("action", action="allocate"):
                    work()
    """, select=["trace-span-discipline"]) == []
    assert _lint(tmp_path, "scheduler/y.py", """
        import time

        import jax

        @jax.jit
        def solve(x):
            return x  # time imported but never read under the trace
    """, select=["trace-span-discipline"]) == []


# --- rule: device-sync-discipline --------------------------------------------


def test_device_sync_fires_on_block_until_ready(tmp_path):
    findings = _lint(tmp_path, "tensor_actions.py", """
        def solve(out):
            out.block_until_ready()
            return out
    """, select=["device-sync-discipline"])
    assert _rules_of(findings) == ["device-sync-discipline"]


def test_device_sync_fires_on_raw_device_get(tmp_path):
    findings = _lint(tmp_path, "fast_victims.py", """
        import jax

        def reclaim_pass(state):
            return jax.device_get(state)
    """, select=["device-sync-discipline"])
    assert _rules_of(findings) == ["device-sync-discipline"]


def test_device_sync_fires_on_asarray_and_coercion_of_solve_result(tmp_path):
    # np.asarray of a tracked solve output, float()/bool() of tuple-
    # unpacked victim_step results — the implicit-sync class
    findings = _lint(tmp_path, "tensor_actions.py", """
        import numpy as np

        def attempt(consts, state, req):
            out_state, assigned, nstar, vmask, clean = victim_step(
                consts, state, req)
            if not bool(clean):
                return None
            return np.asarray(vmask)
    """, select=["device-sync-discipline"])
    assert _rules_of(findings) == ["device-sync-discipline"] * 2
    # a jit wrapper created in-function taints its results too
    findings = _lint(tmp_path, "fastpath.py", """
        import jax
        import numpy as np

        def run(args):
            packed = jax.jit(lambda a: a + 1)
            out = packed(args)
            return np.asarray(out)
    """, select=["device-sync-discipline"])
    assert _rules_of(findings) == ["device-sync-discipline"]


def test_device_sync_near_misses_stay_quiet(tmp_path):
    # the sanctioned boundaries themselves: vtprof.fetch / device_get
    assert _lint(tmp_path, "tensor_actions.py", """
        from volcano_tpu import vtprof

        def solve(packed, args):
            out = packed(args)
            flat = vtprof.fetch(out, kernel="allocate_solve", phase="solve")
            return flat
    """, select=["device-sync-discipline"]) == []
    # a device name RE-fetched through vtprof.device_get is host after
    assert _lint(tmp_path, "fast_victims.py", """
        import numpy as np
        from volcano_tpu import vtprof

        def attempt(consts, state, req):
            ok, vmask = victim_step(consts, state, req)
            ok, vmask = vtprof.device_get((ok, vmask), kernel="victim_step")
            return bool(ok), np.asarray(vmask)
    """, select=["device-sync-discipline"]) == []
    # np.asarray of plain host data is not a sync
    assert _lint(tmp_path, "volsolve.py", """
        import numpy as np

        def masks(rows):
            rows = sorted(rows)
            return np.asarray(rows)
    """, select=["device-sync-discipline"]) == []
    # the identical sync OUTSIDE the fastpath-hot module set is exempt
    # (bench drivers / parity suites block on purpose)
    assert _lint(tmp_path, "bench_driver.py", """
        def time_cycle(out):
            out.block_until_ready()
    """, select=["device-sync-discipline"]) == []


def test_device_sync_suppressions_carry_justification():
    """The sanctioned startup syncs (prewarm's device handshake + warm
    blocks) are line-suppressed with their reasons; the rule still fires
    on any NEW sync in scheduler.py."""
    import volcano_tpu

    pkg = os.path.dirname(os.path.abspath(volcano_tpu.__file__))
    sched = open(os.path.join(pkg, "scheduler", "scheduler.py")).read()
    assert sched.count("vtlint: disable=device-sync-discipline") == 2


# --- rule: metric-discipline -------------------------------------------------


def test_metric_discipline_fires_on_unsuffixed_counter(tmp_path):
    findings = _lint(tmp_path, "scheduler/x.py", """
        from volcano_tpu.scheduler import metrics

        def record():
            metrics.inc("volcano_retries")
    """, select=["metric-discipline"])
    assert _rules_of(findings) == ["metric-discipline"]
    assert "_total" in findings[0].message


def test_metric_discipline_fires_on_unitless_duration(tmp_path):
    findings = _lint(tmp_path, "scheduler/x.py", """
        from volcano_tpu.scheduler import metrics

        def record(dur):
            metrics.observe("volcano_bind_latency", dur)
    """, select=["metric-discipline"])
    assert _rules_of(findings) == ["metric-discipline"]
    assert "unit suffix" in findings[0].message


def test_metric_discipline_fires_on_wall_clock_value(tmp_path):
    # time.time() feeding the recorded value — both through the module
    # verbs and through the metrics.* helper wrappers
    findings = _lint(tmp_path, "scheduler/x.py", """
        import time

        from volcano_tpu.scheduler import metrics

        def record(t0):
            metrics.observe("volcano_x_seconds", time.time() - t0)
            metrics.update_pod_e2e_latency((time.time() - t0) * 1e3)
    """, select=["metric-discipline"])
    assert _rules_of(findings) == ["metric-discipline"] * 2
    assert "monotonic" in findings[0].message


def test_metric_discipline_near_misses_stay_quiet(tmp_path):
    # compliant counter/duration names, perf_counter-derived values, a
    # non-volcano literal on a foreign inc(), and wall-clock reads that
    # never feed a metric all pass
    assert _lint(tmp_path, "scheduler/x.py", """
        import time

        from volcano_tpu.scheduler import metrics

        def record(t0):
            metrics.inc("volcano_retries_total")
            metrics.observe("volcano_bind_latency_seconds",
                            time.perf_counter() - t0)
            metrics.set_gauge("volcano_pool_size", 3)
            counter.inc("retries")          # not a volcano series
            stamp = time.time()             # not a metric value
            return stamp
    """, select=["metric-discipline"]) == []


def test_metric_discipline_suppressions_carry_justification():
    """The sanctioned exceptions are line-suppressed with their reasons:
    the two reference-parity counter names in metrics.py and the one
    cross-process epoch edge in cache.py — the rule still fires on any
    NEW violation in those files."""
    import volcano_tpu

    pkg = os.path.dirname(os.path.abspath(volcano_tpu.__file__))
    mx = open(os.path.join(pkg, "scheduler", "metrics.py")).read()
    assert mx.count("vtlint: disable=metric-discipline") == 2
    cache = open(os.path.join(pkg, "scheduler", "cache.py")).read()
    assert cache.count("vtlint: disable=metric-discipline") == 1


def test_metric_discipline_help_coverage_fires_in_vtfleet(tmp_path):
    # a family recorded by vtfleet.py must be HELP'd in the _HELP table
    # of scheduler/metrics.py — it lands on the router's FEDERATED
    # /metrics, where a missing description becomes a placeholder on
    # every dashboard
    findings = _lint(tmp_path, "vtfleet.py", """
        from volcano_tpu.scheduler import metrics

        def record():
            metrics.inc("volcano_fleet_made_up_series_total")
    """, select=["metric-discipline"])
    assert _rules_of(findings) == ["metric-discipline"]
    assert "_HELP" in findings[0].message


def test_metric_discipline_help_coverage_near_misses_stay_quiet(tmp_path):
    # HELP'd families recorded from vtfleet.py pass; the same un-HELP'd
    # family recorded OUTSIDE the scoped module set stays quiet (the
    # sub-check fences the federated exposition, not the whole package)
    assert _lint(tmp_path, "vtfleet.py", """
        from volcano_tpu.scheduler import metrics

        def record():
            metrics.inc("volcano_fleet_harvests_total")
            metrics.inc("volcano_proc_restarts_total", shard="00")
    """, select=["metric-discipline"]) == []
    assert _lint(tmp_path, "other.py", """
        from volcano_tpu.scheduler import metrics

        def record():
            metrics.inc("volcano_fleet_made_up_series_total")
    """, select=["metric-discipline"]) == []


def test_metric_discipline_help_table_covers_fleet_families():
    """The live vtfleet/supervisor families are all registered: the rule
    passing on the real tree means the table kept up."""
    from volcano_tpu.scheduler.metrics import _HELP

    for fam in ("volcano_fleet_harvests_total",
                "volcano_fleet_harvest_errors_total",
                "volcano_proc_restarts_total", "volcano_proc_up"):
        assert fam in _HELP, fam


# --- suppression contract ---------------------------------------------------


def test_file_level_suppression(tmp_path):
    findings = _lint(tmp_path, "scheduler/thing.py", """
        # vtlint: disable=bare-except
        def f():
            try:
                g()
            except Exception:
                pass
    """, select=["bare-except"])
    assert findings == []


def test_line_level_suppression_only_hits_that_line(tmp_path):
    findings = _lint(tmp_path, "scheduler/thing.py", """
        def f():
            try:
                g()
            except Exception:  # vtlint: disable=bare-except
                pass

        def h():
            try:
                g()
            except Exception:
                pass
    """, select=["bare-except"])
    assert len(findings) == 1  # only the unsuppressed handler


def test_unknown_rule_in_suppression_is_an_error(tmp_path):
    findings = _lint(tmp_path, "scheduler/thing.py", """
        # vtlint: disable=not-a-real-rule
        def f():
            return 1
    """)
    assert _rules_of(findings) == [USAGE_RULE]
    assert "not-a-real-rule" in findings[0].message


def test_unknown_select_raises(tmp_path):
    with pytest.raises(ValueError, match="bogus"):
        run_paths([str(tmp_path)], select=["bogus"])


# --- the runtime lock-order sanitizer ---------------------------------------


def test_locksan_disabled_returns_plain_locks(monkeypatch):
    monkeypatch.delenv("VOLCANO_TPU_LOCK_SANITIZER", raising=False)
    from volcano_tpu.analysis import locksan

    assert isinstance(locksan.make_lock("x"), type(threading.Lock()))
    assert not locksan.enabled()


def test_locksan_detects_abba(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_LOCK_SANITIZER", "1")
    from volcano_tpu.analysis import locksan

    locksan.reset_graph()
    try:
        a = locksan.make_lock("san-A")
        b = locksan.make_rlock("san-B")
        with a:
            with b:
                pass
        with pytest.raises(locksan.LockOrderError, match="san-A"):
            with b:
                with a:
                    pass
        # the violating acquisition must not leak a held lock
        with a:
            pass
    finally:
        locksan.reset_graph()


def test_locksan_consistent_order_and_reentrancy_ok(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_LOCK_SANITIZER", "1")
    from volcano_tpu.analysis import locksan

    locksan.reset_graph()
    try:
        a = locksan.make_lock("san-C")
        b = locksan.make_rlock("san-D")
        for _ in range(3):
            with a:
                with b:
                    with b:  # reentrant hold: no new ordering info
                        pass
    finally:
        locksan.reset_graph()


def test_locksan_condition_wait_notify(monkeypatch):
    monkeypatch.setenv("VOLCANO_TPU_LOCK_SANITIZER", "1")
    from volcano_tpu.analysis import locksan

    locksan.reset_graph()
    try:
        cv = locksan.make_condition("san-CV")
        seen = []

        def waiter():
            with cv:
                seen.append(cv.wait(timeout=5))

        t = threading.Thread(target=waiter)
        t.start()
        import time as _time

        _time.sleep(0.1)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert seen == [True]
    finally:
        locksan.reset_graph()


# --- rule: crash-safe-io -----------------------------------------------------


def test_crash_safe_io_fires_on_bare_state_write(tmp_path):
    findings = _lint(tmp_path, "store/server.py", """
        def flush(self, path, payload):
            with open(path, "w") as f:
                json.dump(payload, f)
    """, select=["crash-safe-io"])
    assert _rules_of(findings) == ["crash-safe-io"]
    assert "os.fsync and os.replace" in findings[0].message


def test_crash_safe_io_fires_on_rename_without_fsync(tmp_path):
    # the exact pre-PR-7 flush_state shape: atomic rename, no fsync —
    # a crash can still publish a file whose blocks never hit disk
    findings = _lint(tmp_path, "store/server.py", """
        def flush(self, path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
    """, select=["crash-safe-io"])
    assert _rules_of(findings) == ["crash-safe-io"]
    assert "os.fsync" in findings[0].message
    assert "os.replace" not in findings[0].message.split("without ")[1].split(" in")[0]


def test_crash_safe_io_near_misses_stay_quiet(tmp_path):
    # the full protocol: temp write + fsync + atomic rename
    assert _lint(tmp_path, "store/server.py", """
        def flush(self, path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
    """, select=["crash-safe-io"]) == []
    # append-only WAL segments (per-record CRC protocol) are exempt
    assert _lint(tmp_path, "store/wal.py", """
        def open_segment(self, path):
            self._f = open(path, "ab", buffering=0)
    """, select=["crash-safe-io"]) == []
    # reads are not writes
    assert _lint(tmp_path, "store/server.py", """
        def load(self, path):
            with open(path) as f:
                return json.load(f)
    """, select=["crash-safe-io"]) == []
    # the identical bare write OUTSIDE the store persistence modules
    assert _lint(tmp_path, "scheduler/metrics.py", """
        def dump(path, payload):
            with open(path, "w") as f:
                json.dump(payload, f)
    """, select=["crash-safe-io"]) == []
    # non-literal mode stays quiet (the rule targets bare "w" rewrites)
    assert _lint(tmp_path, "store/server.py", """
        def write(self, path, mode, data):
            with open(path, mode) as f:
                f.write(data)
    """, select=["crash-safe-io"]) == []


def test_crash_safe_io_module_scope_and_suppression(tmp_path):
    # module-level bare write fires too
    findings = _lint(tmp_path, "store/seed.py", """
        with open("state.json", "w") as f:
            f.write("{}")
    """, select=["crash-safe-io"])
    assert _rules_of(findings) == ["crash-safe-io"]
    # ... and a compliant FUNCTION elsewhere in the file must not excuse
    # the module-level write (tails are scoped per level)
    findings = _lint(tmp_path, "store/seed.py", """
        def good(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload)
                os.fsync(f.fileno())
            os.replace(tmp, path)

        with open("state.json", "w") as f:
            f.write("{}")
    """, select=["crash-safe-io"])
    assert _rules_of(findings) == ["crash-safe-io"]
    # a justified line suppression is honored
    assert _lint(tmp_path, "store/seed.py", """
        with open("state.json", "w") as f:  # vtlint: disable=crash-safe-io
            f.write("{}")
    """, select=["crash-safe-io"]) == []


# --- shard-spec-complete (PR 11: mesh-sharded deployed cycle) ----------------


def test_shard_spec_complete_fires_on_undeclared_cycle_arg(tmp_path):
    findings = _lint(tmp_path, "parallel/sharded.py", """
        _SPECS = {"idle": None}
        _REPLICATED = frozenset({"eps"})

        def _cycle(args, w):
            return args["idle"] + args["eps"] + args["node_extra"]
    """, select=["shard-spec-complete"])
    assert _rules_of(findings) == ["shard-spec-complete"]
    assert "node_extra" in findings[0].message


def test_shard_spec_complete_fires_when_spec_table_missing(tmp_path):
    findings = _lint(tmp_path, "parallel/sharded.py", """
        def _cycle(args, w):
            return args["idle"]
    """, select=["shard-spec-complete"])
    assert _rules_of(findings) == ["shard-spec-complete"]


def test_shard_spec_complete_near_misses_stay_quiet(tmp_path):
    # every arg declared (spec'd or explicitly replicated): quiet
    assert _lint(tmp_path, "parallel/sharded.py", """
        _SPECS = {"idle": None, "used": None}
        _REPLICATED = frozenset({"eps", "total"})

        def _cycle(args, w):
            return args["idle"] + args["used"] + args["eps"] + args["total"]
    """, select=["shard-spec-complete"]) == []
    # args[...] reads OUTSIDE a cycle function: out of scope (helper
    # dicts, wire payloads)
    assert _lint(tmp_path, "parallel/sharded.py", """
        _SPECS = {"idle": None}

        def helper(args):
            return args["whatever"]
    """, select=["shard-spec-complete"]) == []
    # same code outside the sharded module set: out of scope
    assert _lint(tmp_path, "scheduler/other.py", """
        def _cycle(args, w):
            return args["undeclared"]
    """, select=["shard-spec-complete"]) == []
    # non-constant subscripts (loops over keys) never fire
    assert _lint(tmp_path, "parallel/sharded.py", """
        _SPECS = {"idle": None}

        def _cycle(args, w):
            return {k: args[k] for k in args}
    """, select=["shard-spec-complete"]) == []


def test_shard_spec_complete_real_module_is_total():
    """The real sharded.py declares a placement for every cycle arg —
    the live proof the deployed mesh path has no silent-default arrays."""
    from volcano_tpu.parallel import sharded

    from volcano_tpu.scheduler.simargs import build_sim_args

    args = build_sim_args(8, 16, 4, 2, seed=0)
    declared = set(sharded._SPECS) | set(sharded._REPLICATED)
    missing = set(args) - declared
    assert not missing, f"undeclared cycle args: {sorted(missing)}"


def test_shard_spec_complete_fires_in_multihost_module(tmp_path):
    # PR 20: the multi-controller module carries the same contract —
    # a cycle arg with no host-axis spec and no replicated declaration
    # fires exactly like in sharded.py
    findings = _lint(tmp_path, "parallel/multihost.py", """
        _SPECS = {"idle": None}
        _REPLICATED = frozenset({"eps"})

        def _cycle(args, w):
            return args["idle"] + args["eps"] + args["task_extra"]
    """, select=["shard-spec-complete"])
    assert _rules_of(findings) == ["shard-spec-complete"]
    assert "task_extra" in findings[0].message


def test_shard_spec_complete_multihost_near_miss_stays_quiet(tmp_path):
    # fully declared multihost cycle: quiet
    assert _lint(tmp_path, "parallel/multihost.py", """
        _SPECS = {"idle": ("hosts", None), "task_req": ("hosts",)}
        _REPLICATED = frozenset({"eps"})

        def _cycle(args, w):
            return args["idle"] + args["task_req"] + args["eps"]
    """, select=["shard-spec-complete"]) == []
    # a multihost-NAMED module elsewhere in the tree is still scoped by
    # basename — but args reads outside a cycle fn stay out of scope
    assert _lint(tmp_path, "parallel/multihost.py", """
        _SPECS = {"idle": None}

        def owned_output_slices(args):
            return args["anything"]
    """, select=["shard-spec-complete"]) == []


def test_shard_spec_complete_real_multihost_module_is_total():
    """The real multihost.py declares a host-axis placement for every
    cycle arg, and the linter finds nothing to say about it."""
    from volcano_tpu.analysis import run_paths
    from volcano_tpu.parallel import multihost

    from volcano_tpu.scheduler.simargs import build_sim_args

    args = build_sim_args(8, 16, 4, 2, seed=0)
    declared = set(multihost._SPECS) | set(multihost._REPLICATED)
    missing = set(args) - declared
    assert not missing, f"undeclared multihost cycle args: {sorted(missing)}"
    findings = [f for f in run_paths([multihost.__file__])
                if f.rule == "shard-spec-complete"]
    assert findings == [], [f.message for f in findings]


# --- rule: digest-maintenance (PR 13: vtaudit state-digest auditor) ----------


def test_digest_maintenance_fires_on_unaudited_mutations(tmp_path):
    """Every mutation class: direct subscript write, alias .pop, in-place
    setattr, lazy-patch staging — all without touching `_digest`."""
    findings = _lint(tmp_path, "store/store.py", """
        class Store:
            def rogue_insert(self, kind, key, obj):
                self._objects[kind][key] = obj

            def rogue_alias_pop(self, kind, key):
                bucket = self._objects[kind]
                return bucket.pop(key, None)

            def rogue_setattr(self, obj, field, v):
                setattr(obj, field, v)

            def rogue_lazy(self, kind, key, fields, rv):
                lp = self._lazy_patch.get(kind)
                lp[key] = (fields, rv)
    """, select=["digest-maintenance"])
    assert _rules_of(findings) == ["digest-maintenance"] * 4
    texts = "\n".join(f.message for f in findings)
    assert "_objects" in texts and "_lazy_patch" in texts
    assert "setattr" in texts


def test_digest_maintenance_near_misses_stay_quiet(tmp_path):
    # the mutation routes through the digest helper: quiet
    assert _lint(tmp_path, "store/store.py", """
        class Store:
            def create(self, kind, key, obj):
                self._objects[kind][key] = obj
                dg = self._digest
                if dg is not None:
                    dg.set_obj(kind, key, obj)
    """, select=["digest-maintenance"]) == []
    # materialization folds values the staging path already digested:
    # structurally exempt, whatever it touches
    assert _lint(tmp_path, "store/store.py", """
        class Store:
            def _materialize(self, kind, key):
                entry = self._lazy_patch[kind].pop(key, None)
                if entry:
                    setattr(self._objects[kind][key], "x", entry)
    """, select=["digest-maintenance"]) == []
    # _lazy_create holds staged Events — unaudited kind, out of scope
    assert _lint(tmp_path, "store/store.py", """
        class Store:
            def stage(self, blk, r):
                self._lazy_create["Event"][blk.key(r)] = (blk, r)
    """, select=["digest-maintenance"]) == []
    # reads never fire
    assert _lint(tmp_path, "store/store.py", """
        class Store:
            def get(self, kind, key):
                lp = self._lazy_patch.get(kind)
                if lp and key in lp:
                    return lp[key]
                return self._objects[kind].get(key)
    """, select=["digest-maintenance"]) == []
    # identical mutation outside the store module set: out of scope
    assert _lint(tmp_path, "scheduler/cache.py", """
        class Cache:
            def rogue_insert(self, kind, key, obj):
                self._objects[kind][key] = obj
    """, select=["digest-maintenance"]) == []


def test_digest_maintenance_real_store_is_clean():
    """The live proof: every mutation verb in the real store keeps the
    digest (or is structurally exempt) — zero findings over store/."""
    import volcano_tpu

    pkg = os.path.dirname(os.path.abspath(volcano_tpu.__file__))
    findings = run_paths(
        [os.path.join(pkg, "store")],
        root=os.path.dirname(pkg),
        select=["digest-maintenance"],
    )
    assert findings == [], "\n".join(f.human() for f in findings)


# --- delta-discipline --------------------------------------------------------


def test_delta_discipline_fires_on_direct_snapshot_writes(tmp_path):
    """Every poke class: subscript store, whole-attribute rebind, and an
    augmented in-place update — all outside a patch_* function."""
    findings = _lint(tmp_path, "scheduler/delta/rogue.py", """
        def shed_tasks(snap, keep):
            snap.task_req[:] = snap.task_req[keep]

        def rebind(snapshot, uids):
            snapshot.task_uids = uids

        def bump(ref_snap):
            ref_snap.job_ntasks[0] += 1
    """, select=["delta-discipline"])
    assert _rules_of(findings) == ["delta-discipline"] * 3
    texts = "\n".join(f.message for f in findings)
    assert "snap.task_req" in texts and "snapshot.task_uids" in texts
    assert "patch_task_planes" in texts


def test_delta_discipline_near_misses_stay_quiet(tmp_path):
    # the sanctioned API's own body: exempt by the patch_* convention
    assert _lint(tmp_path, "scheduler/delta/incr.py", """
        def patch_task_planes(m, snap, aux, pe_rows, w):
            snap.task_req[:] = 0
            snap.task_uids = []
    """, select=["delta-discipline"]) == []
    # reads never fire
    assert _lint(tmp_path, "scheduler/delta/engine.py", """
        def depth(snap):
            t = snap.task_valid.sum()
            return int(t)
    """, select=["delta-discipline"]) == []
    # non-snapshot bindings with snapshot-ish attributes: out of scope
    assert _lint(tmp_path, "scheduler/delta/agg.py", """
        def fold(agg):
            agg.task_req = 0
    """, select=["delta-discipline"]) == []
    # identical poke outside scheduler/delta/: other modules own their
    # snapshots (the fast reclaim pass legitimately re-packs in place)
    assert _lint(tmp_path, "scheduler/fastpath/cycle.py", """
        def repack(snap, keep):
            snap.task_req[:] = snap.task_req[keep]
    """, select=["delta-discipline"]) == []


def test_delta_discipline_real_package_is_clean():
    """The live proof: the real delta package routes every snapshot
    write through the patch API."""
    import volcano_tpu

    pkg = os.path.dirname(os.path.abspath(volcano_tpu.__file__))
    findings = run_paths(
        [os.path.join(pkg, "scheduler", "delta")],
        root=os.path.dirname(pkg),
        select=["delta-discipline"],
    )
    assert findings == [], "\n".join(f.human() for f in findings)


# --- vtflow: wal-effect-order ------------------------------------------------


def _lint_files(tmp_path, sources, select=None, worklist=False):
    """Write a {relname: source} fixture tree and lint it as one project."""
    paths = []
    for relname, source in sources.items():
        path = tmp_path / relname
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
        paths.append(str(path))
    return run_paths(paths, root=str(tmp_path), select=select,
                     worklist=worklist)


def test_wal_effect_order_fires_on_beacon_before_append(tmp_path):
    """The PR-15 regression shape: beacon stamped between the store verb
    and the WAL append."""
    findings = _lint(tmp_path, "store/server.py", """
        class StoreServer:
            def create(self, kind, obj):
                self.store.create(kind, obj)
                self._maybe_beacon()
                self._wal_append({"op": "create"})
    """, select=["wal-effect-order"])
    assert _rules_of(findings) == ["wal-effect-order"]
    assert findings[0].line == 5  # the beacon line, not the verb line


def test_wal_effect_order_fires_on_composed_cross_function_ack(tmp_path):
    """A helper whose first observable effect is an ack, called while the
    caller holds an un-appended mutation: the finding anchors at the CALL
    SITE (the line that composes the violation)."""
    findings = _lint(tmp_path, "store/server.py", """
        class StoreServer:
            def update(self, kind, obj):
                self.store.update(kind, obj)
                self._finish()
                self._wal_append({"op": "update"})

            def _finish(self):
                self._commit_ack()
    """, select=["wal-effect-order"])
    assert _rules_of(findings) == ["wal-effect-order"]
    assert findings[0].line == 5  # `self._finish()` in the caller
    assert "_finish" in findings[0].message


def test_wal_effect_order_fires_on_exception_path_ack(tmp_path):
    """No exception path may ack without the append: the handler inherits
    the pending state a later statement's exception would expose."""
    findings = _lint(tmp_path, "store/server.py", """
        class StoreServer:
            def patch(self, kind, obj):
                try:
                    self.store.patch(kind, obj)
                    self.pump()
                    self._wal_append({"op": "patch"})
                except Exception:
                    self._commit_ack()
    """, select=["wal-effect-order"])
    assert _rules_of(findings) == ["wal-effect-order"]
    assert findings[0].line == 9  # the ack inside the handler


def test_wal_effect_order_near_misses_stay_quiet(tmp_path):
    findings = _lint(tmp_path, "store/server.py", """
        class StoreServer:
            def create(self, kind, obj):
                # the canonical order: mutate -> append -> observable
                self.store.create(kind, obj)
                self._wal_append({"op": "create"})
                self._maybe_beacon()
                self._commit_ack()

            def update(self, kind, obj):
                # wal guard is configuration, not ordering: a wal-less
                # server has no append obligation
                self.store.update(kind, obj)
                if self.wal is not None:
                    self._wal_append({"op": "update"})
                self._commit_ack()

            def delete(self, kind, key):
                # a repl-is-None beacon is local-only (the PR-15 FIX
                # shape) — never an observable effect
                self.store.delete(kind, key)
                if self.repl is None:
                    self._maybe_beacon()
                self._wal_append({"op": "delete"})
    """, select=["wal-effect-order"])
    assert findings == []


def test_wal_effect_order_materialize_is_exempt(tmp_path):
    """Materialization folds state the staging path already logged — a
    reader calling it then replying 200 is not an ordering bug."""
    findings = _lint(tmp_path, "store/server.py", """
        class StoreServer:
            def _materialize(self, kind):
                self.store.update(kind, None)

            def do_GET(self):
                self._materialize("Pod")
                self._reply(200, {})
    """, select=["wal-effect-order"])
    assert findings == []


def test_wal_effect_order_out_of_scope_module_stays_quiet(tmp_path):
    findings = _lint(tmp_path, "elastic/daemon.py", """
        class Daemon:
            def create(self, kind, obj):
                self.store.create(kind, obj)
                self._maybe_beacon()
                self._wal_append({"op": "create"})
    """, select=["wal-effect-order"])
    assert findings == []


def test_wal_effect_order_caller_vs_callee_suppression(tmp_path):
    """Composed findings anchor at the caller's call site; a disable at
    the callee's effect line must NOT suppress them (the callee is
    innocent alone — the composition is the bug)."""
    src_callee_disabled = """
        class StoreServer:
            def update(self, kind, obj):
                self.store.update(kind, obj)
                self._finish()
                self._wal_append({"op": "update"})

            def _finish(self):
                self._commit_ack()  # vtlint: disable=wal-effect-order
    """
    findings = _lint(tmp_path, "store/server.py", src_callee_disabled,
                     select=["wal-effect-order"])
    assert _rules_of(findings) == ["wal-effect-order"]

    src_caller_disabled = """
        class StoreServer:
            def update(self, kind, obj):
                self.store.update(kind, obj)
                self._finish()  # vtlint: disable=wal-effect-order
                self._wal_append({"op": "update"})

            def _finish(self):
                self._commit_ack()
    """
    findings = _lint(tmp_path / "b", "store/server.py",
                     src_caller_disabled, select=["wal-effect-order"])
    assert findings == []


def test_file_level_suppression_of_interprocedural_rule(tmp_path):
    """A file-wide disable covers project-scope findings anchored in that
    file, exactly like file-scope findings."""
    findings = _lint(tmp_path, "store/server.py", """
        # ordering asserted by the runtime sanitizer instead:
        # vtlint: disable=wal-effect-order
        class StoreServer:
            def create(self, kind, obj):
                self.store.create(kind, obj)
                self._maybe_beacon()
                self._wal_append({"op": "create"})
    """, select=["wal-effect-order"])
    assert findings == []


def test_trailing_disable_inside_multiline_statement(tmp_path):
    """A disable trailing ANY physical line of a multi-line statement
    covers the whole logical line — findings anchor at the statement's
    first line, so a closing-paren disable still suppresses them."""
    findings = _lint(tmp_path, "store/locks.py", """
        import threading

        LOCK = threading.Lock(
        )  # vtlint: disable=lock-factory
    """, select=["lock-factory"])
    assert findings == []
    # and the near-miss: the NEXT statement is outside the logical line
    findings = _lint(tmp_path / "b", "store/locks.py", """
        import threading

        A = threading.Lock(
        )  # vtlint: disable=lock-factory
        B = threading.Lock()
    """, select=["lock-factory"])
    assert len(findings) == 1
    assert findings[0].line == 6


# --- vtflow: late-binding ----------------------------------------------------


def test_late_binding_fires_on_attribute_capture(tmp_path):
    """The PR-15 Replicator bug shape: another component's chaos plan
    frozen into an attribute at construction time."""
    findings = _lint(tmp_path, "store/replica.py", """
        class Replicator:
            def __init__(self, srv):
                self.plan = srv.chaos
    """, select=["late-binding"])
    assert _rules_of(findings) == ["late-binding"]
    assert "chaos" in findings[0].message


def test_late_binding_fires_on_closure_default_and_guarded_capture(tmp_path):
    findings = _lint(tmp_path, "store/replica.py", """
        class Replicator:
            def __init__(self, srv, follow):
                def loop(plan=srv.chaos):
                    return plan
                self.loop = loop
                if follow:
                    self.targets = srv.peers
    """, select=["late-binding"])
    assert _rules_of(findings) == ["late-binding", "late-binding"]
    assert "default" in findings[0].message  # closure-default freeze
    assert "peers" in findings[1].message    # capture under an `if`


def test_late_binding_fires_through_self_chain(tmp_path):
    """`self.srv.chaos` at construction time is still another object's
    late state — only BARE self attributes are own-state."""
    findings = _lint(tmp_path, "store/replica.py", """
        class Replicator:
            def __init__(self, srv):
                self.srv = srv
                self.plan = self.srv.chaos
    """, select=["late-binding"])
    assert _rules_of(findings) == ["late-binding"]


def test_late_binding_near_misses_stay_quiet(tmp_path):
    findings = _lint(tmp_path, "store/replica.py", """
        class Replicator:
            def __init__(self, srv):
                # the FIX shape: store the owning object, read per call
                self.srv = srv
                # own construction is ownership, not capture
                self.chaos = build_plan()
                # bare self attribute: own state
                self.role = self.role_hint

            def tick(self):
                # method bodies run per call — late by construction
                plan = self.srv.chaos
                return plan

            def arm(self):
                # nested-def BODIES are exempt (they run later)
                def loop():
                    return self.srv.peers
                return loop
    """, select=["late-binding"])
    assert findings == []


# --- vtflow: proc-isolation --------------------------------------------------


def test_proc_isolation_fires_on_global_mutated_from_verb_path(tmp_path):
    """A module-level mutable written by a helper the verb path reaches:
    in one process shared-for-free, across processes silently forked."""
    findings = _lint(tmp_path, "store/server.py", """
        _CACHE = {}

        class StoreServer:
            def do_POST(self):
                self._handle("Pod")

            def _handle(self, kind):
                _CACHE[kind] = 1
    """, select=["proc-isolation"])
    assert _rules_of(findings) == ["proc-isolation"]
    assert "_CACHE" in findings[0].message
    assert findings[0].line == 9


def test_proc_isolation_fires_on_cross_shard_fanout(tmp_path):
    findings = _lint(tmp_path, "store/server.py", """
        class StoreServer:
            def _append_block(self, blk):
                for s in range(self.shards):
                    self._shard_seq[s] = self.seq
    """, select=["proc-isolation"])
    assert _rules_of(findings) == ["proc-isolation"]
    assert "cross-shard" in findings[0].message


def test_proc_isolation_fires_on_unlocked_rmw(tmp_path):
    findings = _lint(tmp_path, "store/server.py", """
        from volcano_tpu.locksan import make_lock

        class StoreServer:
            def __init__(self):
                self.lock = make_lock("srv")
                self.seq = 0

            def do_POST(self):
                self.seq += 1
    """, select=["proc-isolation"])
    assert _rules_of(findings) == ["proc-isolation"]
    assert "read-modify-write" in findings[0].message


def test_proc_isolation_near_misses_stay_quiet(tmp_path):
    findings = _lint(tmp_path, "store/server.py", """
        from volcano_tpu.locksan import make_lock

        _CACHE = {}

        class StoreServer:
            def __init__(self):
                self.lock = make_lock("srv")
                self.seq = 0
                # construction is single-threaded by contract
                self.seq += 1

            def do_POST(self):
                with self.lock:
                    self.seq += 1       # locked RMW
                    self._bump()        # called-locked helper

            def _bump(self):
                self.seq += 1

            def do_GET(self):
                self._tl.hits += 1      # thread-local by construction

            def _load_wal(self):
                # recovery entry points are single-threaded by contract
                self.seq += 1
                _CACHE["recovered"] = 1

            def _unreachable_helper(self):
                # not reachable from any verb: globals check is scoped to
                # the verb-reachable set
                _CACHE["x"] = 1
    """, select=["proc-isolation"])
    assert findings == []


def test_proc_isolation_out_of_seam_stays_quiet(tmp_path):
    findings = _lint(tmp_path, "scheduler/cache.py", """
        _CACHE = {}

        class Cache:
            def do_POST(self):
                _CACHE["x"] = 1
    """, select=["proc-isolation"])
    assert findings == []


# --- vtflow: digest-reachability ---------------------------------------------


def test_digest_reachability_fires_across_files(tmp_path):
    """A helper OUTSIDE the store module set, reached from an HTTP verb,
    mutating a digested container with no digest touch anywhere in its
    transitive effect set — invisible to per-file digest-maintenance."""
    findings = _lint_files(tmp_path, {
        "store/server.py": """
            from fixup import repair

            class StoreServer:
                def do_POST(self):
                    repair(self.store, "Pod")
        """,
        "fixup.py": """
            def repair(store, kind):
                store._objects[kind] = {}
        """,
    }, select=["digest-reachability"])
    assert _rules_of(findings) == ["digest-reachability"]
    assert findings[0].path == "fixup.py"


def test_digest_reachability_near_misses_stay_quiet(tmp_path):
    findings = _lint_files(tmp_path, {
        "store/server.py": """
            from fixup import repair, compact

            class StoreServer:
                def do_POST(self):
                    repair(self.store, "Pod")
                    compact(self.store)
        """,
        "fixup.py": """
            def repair(store, kind):
                # digest folded under the same hold: transitive effect
                # set includes the digest touch
                store._objects[kind] = {}
                store._digest.fold(kind)

            def compact(store):
                # no digested-container mutation at all
                store.note = 1

            def _orphan(store):
                # mutates, but NOTHING reachable from a verb calls it
                store._objects["X"] = {}
        """,
    }, select=["digest-reachability"])
    assert findings == []


# --- lock-factory ------------------------------------------------------------


def test_lock_factory_fires_on_raw_locks_in_daemon_modules(tmp_path):
    findings = _lint(tmp_path, "elastic/daemon.py", """
        import threading

        class Daemon:
            def __init__(self):
                self.mu = threading.Lock()
                self.cv = threading.Condition()
    """, select=["lock-factory"])
    assert _rules_of(findings) == ["lock-factory", "lock-factory"]
    assert "make_lock" in findings[0].message
    assert "hidden RLock" in findings[1].message


def test_lock_factory_near_misses_stay_quiet(tmp_path):
    findings = _lint(tmp_path, "admission/daemons.py", """
        import threading
        from volcano_tpu.locksan import make_lock

        class Daemon:
            def __init__(self):
                self.mu = make_lock("adm")
                # Condition over an existing factory lock wraps an
                # already-visible lock
                self.cv = threading.Condition(self.mu)
    """, select=["lock-factory"])
    assert findings == []
    # outside the sanitizer-scoped module set raw locks are fine
    findings = _lint(tmp_path / "b", "scheduler/metrics.py", """
        import threading
        MU = threading.Lock()
    """, select=["lock-factory"])
    assert findings == []


# --- worklist mode, stats, determinism ---------------------------------------


def test_worklist_keeps_suppressed_findings_with_justification(tmp_path):
    findings = _lint_files(tmp_path, {
        "store/server.py": """
            class StoreServer:
                def _append_block(self, blk):
                    for s in range(self.shards):
                        # in-process broadcast, deferred to ROADMAP 1
                        self._shard_seq[s] = 0  # vtlint: disable=proc-isolation
        """,
    }, select=["proc-isolation"], worklist=True)
    assert len(findings) == 1
    f = findings[0]
    assert f.suppressed
    assert "proc-isolation" in f.justification
    assert "[suppressed]" in f.human()
    # without worklist the suppressed finding disappears entirely
    findings = _lint_files(tmp_path, {
        "store/server2.py": """
            class StoreServer:
                def _append_block(self, blk):
                    for s in range(self.shards):
                        self._shard_seq[s] = 0  # vtlint: disable=proc-isolation
        """,
    }, select=["proc-isolation"])
    assert findings == []


def test_worklist_cli_exit_zero_when_all_suppressed(tmp_path):
    import json as _json

    bad = tmp_path / "store" / "server.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        class StoreServer:
            def _append_block(self, blk):
                for s in range(self.shards):
                    self._shard_seq[s] = 0  # vtlint: disable=proc-isolation
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis", "--json", "--worklist",
         "--select", "proc-isolation", "--root", str(tmp_path), str(bad)],
        capture_output=True, text=True, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr  # suppressed-only: clean
    report = _json.loads(r.stdout)
    assert report["live_count"] == 0
    assert report["suppressed_count"] == 1
    assert report["findings"][0]["suppressed"] is True


def test_stats_reports_per_rule_counts_and_time(tmp_path):
    import json as _json

    bad = tmp_path / "store" / "server.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        class StoreServer:
            def create(self, kind, obj):
                self.store.create(kind, obj)
                self._maybe_beacon()
                self._wal_append({"op": "create"})
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis", "--json", "--stats",
         "--select", "wal-effect-order", "--root", str(tmp_path), str(bad)],
        capture_output=True, text=True, env=env,
    )
    report = _json.loads(r.stdout)
    stats = report["stats"]
    assert stats["files"] == 1
    assert stats["total_s"] >= 0
    assert stats["project_build_s"] >= 0
    row = stats["rules"]["wal-effect-order"]
    assert row["findings"] == 1
    assert row["time_s"] >= 0


def test_finding_order_is_deterministic(tmp_path):
    """Findings sort by (path, line, rule, message) — two runs over the
    same tree produce byte-identical output."""
    sources = {
        "store/server.py": """
            _CACHE = {}

            class StoreServer:
                def do_POST(self):
                    self.store.create("Pod", None)
                    self._maybe_beacon()
                    self._wal_append({})
                    _CACHE["x"] = 1
        """,
        "store/replica.py": """
            class Replicator:
                def __init__(self, srv):
                    self.plan = srv.chaos
        """,
    }
    first = _lint_files(tmp_path, sources)
    second = _lint_files(tmp_path, sources)
    assert first == second
    assert len(first) >= 3
    keys = [(f.path, f.line, f.rule, f.message) for f in first]
    assert keys == sorted(keys)


def test_registered_rule_count_floor():
    """ISSUE 16 acceptance: >=26 rules active, the four vtflow rules and
    lock-factory among them."""
    rules = all_rules()
    assert len(rules) >= 26, sorted(rules)
    for rid in ("wal-effect-order", "late-binding", "proc-isolation",
                "digest-reachability", "lock-factory"):
        assert rid in rules, rid
