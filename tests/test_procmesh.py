"""Multi-process shard store (store/procmesh): supervisor + router + seqbus.

The gate for the vtproc PR:

  * SeqBus keeps ONE monotone seq/rv line across shard processes
    (block allocation, forward-only recovery CAS);
  * a mesh of N shard PROCESSES behind the router, fed the SAME op
    sequence as a single in-process server, produces a BYTE-IDENTICAL
    ``/watch`` stream — at the zero cursor, mid-cursor, and past-head
    (relist fence) — the PR-6 proof pattern composed across OS
    processes;
  * the router decomposes cross-shard work a disjoint mesh cannot
    share in memory: untagged segments re-split with row maps,
    columnar patches sliced per shard with results reassembled in the
    caller's key order;
  * SIGKILL-a-shard-leader mid-drain storm: the supervisor restarts
    the member, NO acked write is lost, placements land bit-for-bit
    where a fault-free run puts them, and ``vtctl audit`` exits 0
    through the router (PR-7 gate composed with the process seam);
  * the async applier learns the mesh natively (shard map from
    ``/healthz``), ships sub-segments straight to shard processes, and
    attributes drains under ``procNN_s`` keys;
  * the proc-isolation analysis deferral is DRAINED — zero live or
    suppressed findings;
  * a handler 500 on a shard process is absorbed (effect scope
    abandoned under the sanitizer) without a restart.
"""

import json
import multiprocessing
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from volcano_tpu.api.objects import Metadata, Node, Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.cli import vtctl
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.store.client import RemoteStore
from volcano_tpu.store.partition import split_segment
from volcano_tpu.store.procmesh import SeqBus, ShardRouter, ShardSupervisor
from volcano_tpu.store.server import StoreServer

from tests.test_chaos_soak import (
    ControlPlane,
    _check_invariants,
    _mk_job,
    _placements,
    _submit,
    _wait_running,
)
from tests.test_partitioned_store import _NAMESPACES, _mixed_segment, _seed_pods

NPROC = 2


def _mesh(nshards=NPROC, state=None, wal=None, replicas=1):
    sup = ShardSupervisor(
        nshards, state=state, wal=wal, replicas=replicas).start()
    router = ShardRouter(sup.shard_map, supervisor=sup).start()
    return sup, router


# -- the shared line ----------------------------------------------------------


def test_seqbus_alloc_blocks_peek_and_forward_only_advance():
    bus = SeqBus(multiprocessing.get_context("spawn"))
    assert bus.peek_seq() == 0
    assert bus.alloc_seq(3) == 3  # block [1..3], LAST returned
    assert bus.alloc_seq(1) == 4
    assert bus.peek_seq() == 4  # peek never consumes
    assert bus.alloc_rv(2) == 2
    bus.advance_to(10, 7)  # recovering shard CASes forward
    assert bus.snapshot() == (10, 7)
    bus.advance_to(5, 3)  # ...but never backward: siblings ran ahead
    assert bus.snapshot() == (10, 7)
    assert bus.alloc_seq(1) == 11  # allocation continues past the CAS


# -- watch-stream byte identity vs the single-process server ------------------


def _drive_ops(url):
    """The SAME deterministic op sequence against any server: uids and
    creation stamps ride the wire pre-set (the cross-process analogue of
    the frozen-clock monkeypatch — child processes can't be patched), so
    every server-assigned value left is seq/rv, which the shared line
    must make identical."""
    rs = RemoteStore(url)
    for i in range(8):
        rs.create("Queue", Queue(
            meta=Metadata(name=f"q{i}", namespace=_NAMESPACES[i % 4],
                          uid=f"uid-{i:04d}", creation_timestamp=1234.5),
            weight=i + 1))
    for i in range(4):
        rs.patch("Queue", f"{_NAMESPACES[i % 4]}/q{i}", {"weight": 100 + i})
    for i in (6, 7):
        rs.delete("Queue", f"{_NAMESPACES[i % 4]}/q{i}")


def _watch_bytes(url, since):
    return urllib.request.urlopen(
        f"{url}/watch?since={since}&timeout=0", timeout=10).read()


@pytest.mark.parametrize("nproc", [1, 2])
def test_mesh_watch_stream_byte_identical_to_single_process(nproc, monkeypatch):
    # digest beacons consume seqs on a WALL-CLOCK cadence — two servers
    # started milliseconds apart would interleave them at different
    # points.  Pin the cadence past the test (the env rides into the
    # spawned shard processes) so every seq is op-determined.
    monkeypatch.setenv("VOLCANO_TPU_AUDIT_BEACON_S", "3600")
    srv = StoreServer().start()
    sup = router = None
    try:
        sup, router = _mesh(nproc)
        _drive_ops(srv.url)
        _drive_ops(router.url)
        # zero cursor, a mid-stream cursor, and a cursor past the head
        # (the relist fence) — raw bytes, no normalization
        assert _watch_bytes(router.url, 0) == _watch_bytes(srv.url, 0)
        assert _watch_bytes(router.url, 5) == _watch_bytes(srv.url, 5)
        assert _watch_bytes(router.url, 10_000) == \
            _watch_bytes(srv.url, 10_000)
    finally:
        srv.stop()
        if router is not None:
            router.stop()
        if sup is not None:
            sup.stop()


# -- router decomposition of cross-shard work ---------------------------------


def test_router_splits_untagged_segment_and_columnar_patch():
    sup, router = _mesh(NPROC)
    try:
        rs = RemoteStore(router.url)
        _seed_pods(rs.create, 12)
        # a pre-partition client's wire: NO shard tag.  The in-process
        # bus routed this to shard 0's lock; disjoint processes can't —
        # the router must re-split it and stitch per-row results back
        # into the original row order.
        seg = _mixed_segment(n=8, n_evict=4)
        code, body = rs._request("POST", "/bulk", {"ops": [seg.to_wire()]})
        assert code == 200
        res = body["results"][0]
        assert res["binds"] == [] and res["evicts"] == []
        for i, key in enumerate(seg.bind_keys):
            assert rs.get("Pod", key).node_name == seg.bind_hosts[i]
        for key in seg.evict_keys:
            assert rs.get("Pod", key).deleting is True
        # columnar patch spanning shards: keys slice per shard, value
        # columns slice WITH them, per-key results reassemble in the
        # caller's key order
        keys = [f"{_NAMESPACES[i % len(_NAMESPACES)]}/p{i}" for i in range(8)]
        op = {"op": "patch_col", "kind": "Pod", "keys": keys,
              "columns": {"node_name": [f"h{i}" for i in range(8)]}}
        code, body = rs._request("POST", "/bulk", {"ops": [op]})
        assert code == 200
        assert body["results"][0] == [None] * 8
        for i, k in enumerate(keys):
            assert rs.get("Pod", k).node_name == f"h{i}"
        # per-key errors keep their row: one missing key among eight
        op = {"op": "patch_col", "kind": "Pod",
              "keys": keys[:3] + ["team9/ghost"] + keys[3:6],
              "columns": {"node_name": ["x"] * 7}}
        code, body = rs._request("POST", "/bulk", {"ops": [op]})
        assert code == 200
        out = body["results"][0]
        assert len(out) == 7
        assert out[3] and "NotFound" in out[3]
        assert [e for i, e in enumerate(out) if i != 3] == [None] * 6
    finally:
        router.stop()
        sup.stop()


def _stable_digest_pair(url):
    """Maintained + recompute digest rollups pinned to the SAME per-shard
    seqs.  With replication armed the lease renewals keep mutating state,
    so a non-atomic read pair can legitimately disagree — retry until
    both reads land on identical shard seqs (i.e. the same state)."""
    for _ in range(50):
        maint = json.load(urllib.request.urlopen(
            url + "/debug/digest", timeout=10))
        truth = json.load(urllib.request.urlopen(
            url + "/debug/digest?recompute=1", timeout=10))
        if maint.get("shard_seq") == truth.get("shard_seq"):
            return maint, truth
        time.sleep(0.05)
    raise AssertionError("digest reads never landed on a stable seq")


# -- the SIGKILL storm (PR-7 gate composed across processes) ------------------


def _mesh_storm(tmp_path, kill, fleet_dir=None):
    """One storm against a 2-shard mesh with per-shard replica groups
    (WAL + sync-ack replication armed): control plane over the router,
    three gangs submitted sequentially; with ``kill`` each shard leader
    is SIGKILLed once mid-drain (right after an ACKed submit).  With
    ``fleet_dir`` the vtfleet collector is armed: the supervisor caches
    member rings each monitor tick and each SIGKILL must leave an
    incident bundle holding the dying process's final flight-recorder
    ring.  Returns the final placements for parity against the
    fault-free run."""
    from volcano_tpu import vtfleet

    root = tmp_path / ("kill" if kill else "clean")
    root.mkdir()
    state = str(root / "state.json")
    if fleet_dir is not None:
        vtfleet.arm(incident_dir=fleet_dir)
    sup, router = _mesh(NPROC, state=state, wal=state + ".wal",
                        replicas=2)
    cp = ControlPlane(router.url)
    try:
        client = RemoteStore(router.url)
        client.create("Queue", Queue(meta=Metadata(name="default",
                                                   namespace="")))
        for i in range(3):
            client.create("Node", Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})))
        cp.start(schedulers=1, controllers=1)
        for i in range(3):
            _submit(client, _mk_job(f"cj{i}", 2))
            if kill and i < NPROC:
                # the submit above was ACKed — the WAL fsynced it, so
                # the record must be back bit-for-bit after the
                # supervisor's restart (zero acked loss)
                sup.kill_shard(i)
            _wait_running(client, f"soak/cj{i}")
        _check_invariants(client)
        if kill:
            st = sup.status()
            assert sum(m["restarts"] for m in st["members"]) >= NPROC
            assert all(m["alive"] for m in st["members"])
        if kill and fleet_dir is not None:
            # crash-forensics acceptance: the respawn counter on the
            # router's MERGED /metrics equals the supervisor's own
            # count, and each SIGKILLed leader left an incident bundle
            # with its final trace ring and profile
            mt = urllib.request.urlopen(
                router.url + "/metrics", timeout=10).read().decode()
            rows = [line for line in mt.splitlines()
                    if line.startswith("volcano_proc_restarts_total{")
                    and 'proc="fleet"' not in line]
            assert sum(int(float(line.rsplit(" ", 1)[1]))
                       for line in rows) == st["restarts"], (rows, st)
            bundles = sorted(os.listdir(fleet_dir))
            for name in (f"shard{i:02d}" for i in range(NPROC)):
                mine = [b for b in bundles
                        if b.startswith(f"incident-{name}-")
                        and not b.endswith(".tmp")]
                assert mine, (name, bundles)
                d = os.path.join(fleet_dir, mine[-1])
                assert {"meta.json", "trace.json", "prof.json",
                        "timeseries.json", "digest.json"} <= set(
                            os.listdir(d))
                with open(os.path.join(d, "trace.json")) as f:
                    tr = json.load(f)
                # the final ring: harvested while the process lived,
                # kept across its death (children armed via env)
                assert tr and tr.get("armed") and tr.get("spans"), tr
                with open(os.path.join(d, "prof.json")) as f:
                    assert json.load(f) is not None
                with open(os.path.join(d, "meta.json")) as f:
                    meta = json.load(f)
                assert meta["proc"] == name and meta["reason"] \
                    == "proc-exit" and meta["pid"]
        # maintained digest through the router converges to a full
        # recompute — the cross-shard rollup is honest after the storm
        maint, truth = _stable_digest_pair(router.url)
        assert maint["enabled"] and maint["root"] == truth["root"], (
            maint, truth)
        assert maint["shards"] == truth["shards"]
        assert vtctl.main(["audit", "--server", router.url]) == 0
        return _placements(client)
    finally:
        from volcano_tpu import vtfleet

        if fleet_dir is not None:
            vtfleet.disarm()
        cp.shutdown()
        router.stop()
        sup.stop()


def test_mesh_kill_shard_storm_matches_fault_free(tmp_path, monkeypatch):
    # composed stack: sharded WAL + per-shard replication (sync ack)
    # under the mesh, delta micro-cycles (with the bit-equality oracle)
    # in the scheduler loop — the PR-7 gate across every tier at once
    import tests.test_chaos_soak as soak

    base_conf = soak.full_conf

    def delta_conf(*args, **kwargs):
        conf = base_conf(*args, **kwargs)
        conf.delta = "on"
        conf.delta_oracle = True
        return conf

    monkeypatch.setattr(soak, "full_conf", delta_conf)
    # clean run fully disarmed; kill run with fleet forensics armed and
    # child tracing on (the env rides into the spawned shard processes,
    # so the incident bundles capture real span rings) — placements must
    # STILL match bit-for-bit: observability never steers a decision
    clean = _mesh_storm(tmp_path, kill=False)
    monkeypatch.setenv("VOLCANO_TPU_TRACE", "1")
    stormy = _mesh_storm(tmp_path, kill=True,
                         fleet_dir=str(tmp_path / "incidents"))
    assert stormy == clean
    assert clean, "storm placed nothing — the parity check is vacuous"


# -- the applier's native mesh path -------------------------------------------


def test_applier_ships_direct_to_shards_with_proc_attribution():
    sup, router = _mesh(NPROC)
    try:
        rs = RemoteStore(router.url)
        rs.create("Queue", Queue(meta=Metadata(name="default",
                                               namespace="")))
        _seed_pods(rs.create, 32)
        # the mesh advertises its topology: split factor AND the shard
        # map, so sub-segments skip the router hop entirely
        assert rs.segment_shards == NPROC
        pm = rs.proc_shard_map
        assert pm is not None and len(pm) == NPROC
        cache = SchedulerCache(rs, async_apply=True)
        seg = _mixed_segment(n=24, n_evict=4)
        try:
            assert cache.publish_segment(seg)
            assert cache.applier.flush(timeout=30.0)
            assert cache.err_log == []
        finally:
            cache.applier.stop(flush=False)
        for i, key in enumerate(seg.bind_keys):
            assert rs.get("Pod", key).node_name == seg.bind_hosts[i]
        for key in seg.evict_keys:
            assert rs.get("Pod", key).deleting is True
        # drain attribution names the deployment shape: procNN_s keys
        # for a process mesh, never the in-process shardNN_s ones
        stats = cache.applier.drain_stats
        proc_keys = {k for k in stats if k.startswith("proc")}
        assert proc_keys == {
            f"proc{s:02d}_s" for s, _ in split_segment(seg, NPROC)}
        assert not any(k.startswith("shard") for k in stats), stats
        assert stats.get("wire_s", 0.0) >= 0.0
    finally:
        router.stop()
        sup.stop()


# -- the drained analysis deferral --------------------------------------------


def test_proc_isolation_worklist_is_drained():
    """PR 17 fenced the multi-process seam by DEFERRING one finding
    (the `_shard_seq` broadcast in `_append_block`).  This PR converts
    that broadcast into watermark messages — the finding must be GONE,
    not suppressed."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "volcano_tpu.analysis", "--worklist",
         "--json"],
        capture_output=True, text=True, cwd=repo, timeout=300)
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout)
    findings = [f for f in report.get("findings", [])
                if f.get("rule") == "proc-isolation"]
    assert findings == [], findings


# -- handler faults stay inside the process -----------------------------------


def test_shard_handler_500_is_absorbed_without_restart():
    """A malformed request 500s on the shard process (its effect scope
    abandoned under the sanitizer) — the process must survive, the
    supervisor must NOT restart it, and the mesh stays consistent."""
    sup, router = _mesh(NPROC)
    try:
        rs = RemoteStore(router.url)
        rs.create("Queue", Queue(meta=Metadata(name="ok", namespace="")))
        pids = {m["pid"] for m in sup.status()["members"]}
        req = urllib.request.Request(
            sup.shard_map[0] + "/bulk", data=b"{not json",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 500
        # alive, same pids, zero restarts — the 500 stayed a reply
        st = sup.status()
        assert {m["pid"] for m in st["members"]} == pids
        assert all(m["alive"] for m in st["members"])
        assert sum(m["restarts"] for m in st["members"]) == 0
        rs.create("Queue", Queue(meta=Metadata(name="after",
                                               namespace="team1")))
        assert len(rs.list("Queue")) == 2
        maint, truth = _stable_digest_pair(router.url)
        assert maint["root"] == truth["root"]
    finally:
        router.stop()
        sup.stop()
