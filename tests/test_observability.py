"""Events, metrics endpoint, and leader election.

Parity sources: event recorders (KB/pkg/scheduler/cache/cache.go:443,401,
467; pkg/controllers/job/job_controller.go:115), /metrics endpoint
(KB/cmd/kube-batch/app/server.go:86-89), leader election
(cmd/controllers/app/server.go:103-125).
"""

import urllib.request

from volcano_tpu import events
from volcano_tpu.api.types import JobPhase, PodPhase
from volcano_tpu.leader import LeaderElector
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.metrics_server import MetricsServer
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store import Store

from helpers import build_node, build_pod, build_podgroup, make_store


def test_scheduled_event_on_bind():
    store = make_store(
        nodes=[build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    Scheduler(store, conf=default_conf()).run_once()
    evs = events.events_for(store, "Pod", "default/p0")
    assert any(e.reason == "Scheduled" and "n1" in e.message for e in evs)


def test_evict_event_and_aggregation():
    store = Store()
    events.record(store, "Pod", "default/x", "Evict", "Evicted for preempt",
                  type=events.WARNING)
    events.record(store, "Pod", "default/x", "Evict", "Evicted for preempt",
                  type=events.WARNING)
    evs = events.events_for(store, "Pod", "default/x")
    assert len(evs) == 1
    assert evs[0].count == 2
    assert evs[0].type == events.WARNING


def test_unschedulable_event_on_gang_failure():
    store = make_store(
        nodes=[build_node("n1", cpu="1", memory="2Gi")],
        podgroups=[build_podgroup("pg", min_member=3)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(3)],
    )
    Scheduler(store, conf=default_conf()).run_once()
    evs = events.events_for(store, "PodGroup", "default/pg")
    assert any(e.reason == "Unschedulable" for e in evs)


def test_unschedulable_condition_clears_and_reevents_on_repeat_episode():
    # fails -> schedules -> fails again: the stale condition is cleared on
    # success, so the second episode records a fresh event (count bump)
    store = make_store(
        nodes=[build_node("n1", cpu="1", memory="2Gi")],
        podgroups=[build_podgroup("pg", min_member=2)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(2)],
    )
    sched = Scheduler(store, conf=default_conf())
    sched.run_once()
    pg = store.get("PodGroup", "default/pg")
    assert any(c.kind == "Unschedulable" for c in pg.status.conditions)

    # grow the node so the gang schedules; condition must clear
    node = store.get("Node", "/n1")
    node.allocatable = node.allocatable.clone()
    node.allocatable.milli_cpu = 4000.0
    store.update("Node", node)
    sched.run_once()
    assert not any(c.kind == "Unschedulable" for c in pg.status.conditions)

    # shrink again + new identical-shape failure -> event count grows
    before = events.events_for(store, "PodGroup", "default/pg")[0].count
    for p in store.list("Pod"):
        p.node_name = ""
        p.phase = PodPhase.PENDING
        store.update("Pod", p)
    node.allocatable.milli_cpu = 1000.0
    store.update("Node", node)
    sched.run_once()
    after = events.events_for(store, "PodGroup", "default/pg")[0].count
    assert after == before + 1


def test_fit_error_aggregate_in_gang_condition():
    """Gang's Unschedulable condition carries the aggregated fit-error
    message (gang.go:138-139 + job_info.go:338-373): every node failing
    resource fit is histogrammed per insufficient dimension."""
    store = make_store(
        nodes=[build_node(f"n{i}", cpu="1", memory="2Gi") for i in range(3)],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg", cpu="2")],
    )
    Scheduler(store, conf=default_conf()).run_once()
    pg = store.get("PodGroup", "default/pg")
    cond = next(c for c in pg.status.conditions if c.kind == "Unschedulable")
    assert "0/3 nodes are available" in cond.message, cond.message
    assert "3 insufficient cpu" in cond.message, cond.message


def test_fit_error_mixes_predicate_and_resource_reasons():
    """Predicate failures and resource shortfalls aggregate into one
    histogram, k8s-scheduler style."""
    n_sel = build_node("sel", cpu="8", memory="16Gi", labels={"zone": "a"})
    small = [build_node(f"small{i}", cpu="1", memory="2Gi") for i in range(2)]
    pod = build_pod("p0", group="pg", cpu="2")
    pod.spec.node_selector = {"zone": "b"}
    store = make_store(
        nodes=[n_sel] + small,
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[pod],
    )
    Scheduler(store, conf=default_conf()).run_once()
    pg = store.get("PodGroup", "default/pg")
    cond = next(c for c in pg.status.conditions if c.kind == "Unschedulable")
    assert "0/3 nodes are available" in cond.message, cond.message
    assert "2 insufficient cpu" in cond.message, cond.message
    assert "1 node(s) didn't match node selector" in cond.message, cond.message


def test_fit_error_aggregate_tensor_path():
    """The device solve leaves unplaced jobs with a lazy fit-error producer
    rendering the same aggregate shape as the host path."""
    from volcano_tpu.scheduler.conf import default_conf as dc

    store = make_store(
        nodes=[build_node(f"n{i}", cpu="1", memory="2Gi") for i in range(3)],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg", cpu="2")],
    )
    Scheduler(store, conf=dc("tpu")).run_once()
    pg = store.get("PodGroup", "default/pg")
    cond = next(c for c in pg.status.conditions if c.kind == "Unschedulable")
    assert "0/3 nodes are available" in cond.message, cond.message
    assert "insufficient cpu" in cond.message, cond.message


def test_backfill_unschedulable_event_carries_fit_error():
    """A best-effort task with no feasible node records a Warning event on
    its PodGroup with the aggregated reasons (the backfill analogue of
    RecordJobStatusEvent, cache.go:622-638)."""
    pod = build_pod("p0", group="pg", cpu="0", memory="0")
    pod.spec.node_selector = {"zone": "nowhere"}
    store = make_store(
        nodes=[build_node("n1"), build_node("n2")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[pod],
    )
    Scheduler(store, conf=default_conf()).run_once()
    evs = events.events_for(store, "PodGroup", "default/pg")
    ev = next(e for e in evs if e.reason == "Unschedulable")
    assert "0/2 nodes are available" in ev.message, ev.message
    assert "2 node(s) didn't match node selector" in ev.message, ev.message


def test_command_issued_event():
    from volcano_tpu.cli.vtctl import cmd_run, cmd_suspend
    from volcano_tpu.sim import Cluster

    c = Cluster()
    c.add_queue("default", weight=1)
    c.add_node("n0", {"cpu": "4", "memory": "8Gi"})
    cmd_run(c.store, name="j1")
    c.run_until_idle()
    cmd_suspend(c.store, "default", "j1")
    c.run_until_idle()
    evs = events.events_for(c.store, "Job", "default/j1")
    assert any(e.reason == "CommandIssued" for e in evs)


def test_metrics_endpoint_serves_reference_series():
    metrics.reset()
    store = make_store(
        nodes=[build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    Scheduler(store, conf=default_conf()).run_once()

    srv = MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics") as r:
            body = r.read().decode()
        assert "volcano_e2e_scheduling_latency_milliseconds" in body
        assert "volcano_action_scheduling_latency_microseconds" in body
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/healthz") as r:
            assert r.read() == b"ok\n"
    finally:
        srv.stop()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_leader_election_single_winner():
    store = Store()
    clock = FakeClock()
    a = LeaderElector(store, "vt-scheduler", "a", lease_duration=15, clock=clock)
    b = LeaderElector(store, "vt-scheduler", "b", lease_duration=15, clock=clock)
    assert a.try_acquire()
    assert not b.try_acquire()
    assert a.is_leader() and not b.is_leader()
    # renewal keeps the lease
    clock.t = 10
    assert a.try_acquire()
    clock.t = 20
    assert not b.try_acquire()  # renewed at t=10, expires at t=25


def test_leader_election_takeover_after_expiry():
    store = Store()
    clock = FakeClock()
    a = LeaderElector(store, "vt-scheduler", "a", lease_duration=15, clock=clock)
    b = LeaderElector(store, "vt-scheduler", "b", lease_duration=15, clock=clock)
    assert a.try_acquire()
    clock.t = 16  # a stopped renewing; lease expired
    assert b.try_acquire()
    assert b.is_leader() and not a.is_leader()
    assert store.get("Lease", "/vt-scheduler").transitions == 1


def test_leader_election_release_hands_off():
    store = Store()
    clock = FakeClock()
    a = LeaderElector(store, "s", "a", clock=clock)
    b = LeaderElector(store, "s", "b", clock=clock)
    assert a.try_acquire()
    a.release()
    assert b.try_acquire()


def test_standby_scheduler_does_not_bind():
    clock = FakeClock()
    store = make_store(
        nodes=[build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    leader = Scheduler(store, conf=default_conf(),
                       elector=LeaderElector(store, "sched", "leader", clock=clock))
    standby = Scheduler(store, conf=default_conf(),
                        elector=LeaderElector(store, "sched", "standby", clock=clock))
    leader.run_once()
    standby.run_once()
    assert leader.cache.bind_log and not standby.cache.bind_log

    # leader dies; standby takes over next cycle after expiry
    store2 = make_store(
        nodes=[build_node("n1")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg")],
    )
    clock2 = FakeClock()
    dead = LeaderElector(store2, "sched", "dead", clock=clock2)
    assert dead.try_acquire()
    standby2 = Scheduler(store2, conf=default_conf(),
                         elector=LeaderElector(store2, "sched", "standby",
                                               clock=clock2))
    standby2.run_once()
    assert not standby2.cache.bind_log
    clock2.t = 20.0
    standby2.run_once()
    assert standby2.cache.bind_log


def test_failed_bind_recorded_not_raised():
    """A pod deleted between snapshot and bind must not crash the cycle:
    the failure lands in the cache's err log (the reference's errTasks
    resync queue, cache.go:512-533) and the next cycle's fresh snapshot
    simply no longer sees the task."""
    from volcano_tpu.scheduler.cache import SchedulerCache

    from helpers import build_node, build_pod, build_podgroup, make_store

    store = make_store(
        nodes=[build_node("n0")],
        podgroups=[build_podgroup("g", min_member=1)],
        pods=[build_pod("p0", group="g", cpu="1")],
    )
    cache = SchedulerCache(store)
    cluster = cache.snapshot()
    task = next(
        t for j in cluster.jobs.values() for t in j.tasks.values()
    )
    store.delete("Pod", "default/p0")  # vanishes mid-cycle

    cache.bind(task, "n0")  # must not raise
    assert cache.bind_log == []
    assert cache.err_log and cache.err_log[0][0] == "bind"

    cache.evict(task, "test")  # evictor tolerates missing pods already
    assert cache.evict_log == [(task.key, "test")]


def test_profiler_hook_traces_each_cycle(tmp_path, monkeypatch):
    """VOLCANO_TPU_PROFILE wraps every cycle in a JAX profiler trace with a
    per-cycle subdirectory (same-second cycles must not clobber)."""
    import glob
    import os

    monkeypatch.setenv("VOLCANO_TPU_PROFILE", str(tmp_path))
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.sim import Cluster

    c = Cluster(scheduler_conf=full_conf("tpu"))
    c.add_queue("default")
    c.add_node("n0", {"cpu": "4", "memory": "8Gi", "pods": 110})
    c.scheduler.run_once()
    c.scheduler.run_once()  # back-to-back, same wall-clock second

    dirs = sorted(os.listdir(tmp_path))
    assert dirs == ["cycle-000000", "cycle-000001"], dirs
    for d in dirs:
        assert glob.glob(str(tmp_path / d / "**" / "*"), recursive=True), d
