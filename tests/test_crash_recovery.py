"""Crash-kill chaos + the zero-acked-loss recovery gate (store/wal.py).

PR 2's chaosd injected transient faults (5xx, cuts, truncation) but never
killed a process; the durability story under real crashes was untested —
and with interval snapshots it was actually WRONG (acked writes died with
the process).  This suite is the gate for the segment WAL:

  * WAL primitives: CRC framing, torn-tail tolerance, group-commit
    amortization, checkpoint rotation + truncation.
  * Zero acked loss: every 2xx-replied mutation is present after a
    kill+recover — including a whole decision segment as ONE record.
  * Segment atomicity: a crash can never leave an observable
    half-applied segment, and re-submitting a segment (cut reply,
    crash retry) is idempotent via its reserved-uid block.
  * Seeded crash-kill storms (``crash.*`` faultpoints): the control
    plane is killed at the server's pre/post-fsync windows, the
    scheduler mid-drain, the controller mid-gang-create, the kubelet
    mid-ready-flip — and every storm must converge to placements
    bit-for-bit equal to a fault-free run.  Tier-1 runs the in-process
    storms (InjectedCrash aborts); ``make crash-soak`` adds the real
    SIGKILL subprocess storms.
"""

import glob
import json
import os
import threading
import time

import pytest

from volcano_tpu import chaos, trace
from volcano_tpu.api.objects import Metadata, Node, Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobPhase
from volcano_tpu.backoff import Backoff
from volcano_tpu.chaos import FaultPlan, InjectedCrash
from volcano_tpu.controller import JobController
from volcano_tpu.scheduler import statement
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store.client import RemoteStore, StaleWatch, wait_healthy
from volcano_tpu.store.segment import DecisionSegment
from volcano_tpu.store.server import StoreServer
from volcano_tpu.store.wal import WriteAheadLog, frame_record, read_records

from tests.helpers import build_pod
from tests.test_chaos_soak import (
    TRANSIENT,
    _check_invariants,
    _mk_job,
    _placements,
    _submit,
    _wait_running,
)


# -- WAL primitives (tier-1) ---------------------------------------------------


def test_wal_framing_roundtrip(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    recs = [{"op": "patch", "kind": "Pod", "key": f"/p{i}",
             "fields": {"node_name": f"n{i}"}} for i in range(10)]
    for r in recs:
        wal.append(r)
    wal.commit()
    wal.sync_close()
    wal2 = WriteAheadLog(str(tmp_path / "wal"))
    assert list(wal2.replay(0)) == recs
    assert wal2.torn_tails == 0
    wal2.sync_close()


def test_wal_torn_tail_truncated_and_crc_corrupt_discarded(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog(d)
    for i in range(5):
        wal.append({"i": i})
    wal.commit()
    wal.sync_close()
    seg = sorted(glob.glob(os.path.join(d, "*.wal")))[0]

    # physically truncate mid-record: the final record is discarded, the
    # prefix survives, nothing raises
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 3)
    recs, torn = read_records(seg)
    assert torn and [r["i"] for r in recs] == [0, 1, 2, 3]

    # flip a byte inside the last INTACT record's payload: CRC kills it
    # (and everything after it stays discarded)
    rec_size = len(frame_record({"i": 0}))
    with open(seg, "r+b") as f:
        f.seek(4 * rec_size - 2)
        b = f.read(1)
        f.seek(4 * rec_size - 2)
        f.write(bytes([b[0] ^ 0xFF]))
    recs, torn = read_records(seg)
    assert torn and [r["i"] for r in recs] == [0, 1, 2]

    w2 = WriteAheadLog(d)
    assert [r["i"] for r in w2.replay(0)] == [0, 1, 2]
    assert w2.torn_tails == 1
    w2.sync_close()


def test_wal_torn_mid_log_segment_does_not_drop_later_segments(tmp_path):
    """A torn tail in an EARLIER segment (life A crashed mid-append, life
    B appended a whole new segment on top of the repaired prefix) must
    not discard life B's acked records — torn bytes were never ACKed,
    later segments were."""
    d = str(tmp_path / "wal")
    a = WriteAheadLog(d)
    a.append({"life": "A", "i": 0})
    a.append({"life": "A", "i": 1})
    a.commit()
    a.kill()  # crash
    seg_a = sorted(glob.glob(os.path.join(d, "*.wal")))[0]
    with open(seg_a, "r+b") as f:
        f.truncate(os.path.getsize(seg_a) - 2)  # tear A's last record

    b = WriteAheadLog(d)
    assert [r.get("i") for r in b.replay(0)] == [0]
    b.append({"life": "B", "i": 2})
    b.commit()
    b.kill()

    c = WriteAheadLog(d)
    recs = list(c.replay(0))
    assert [(r["life"], r["i"]) for r in recs] == [("A", 0), ("B", 2)]
    c.sync_close()


def test_wal_group_commit_amortizes_fsync(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    # N appends, one commit: exactly one fsync covers them all
    for i in range(100):
        wal.append({"i": i})
    wal.commit()
    assert wal.fsync_total == 1 and wal.appended_records == 100

    # concurrent committers: every commit() returns only once its record
    # is synced, but the leader batches — far fewer fsyncs than commits
    def worker(k):
        for i in range(20):
            wal.commit(wal.append({"w": k, "i": i}))

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert wal.appended_records == 100 + 160
    assert wal.fsync_total < 1 + 160, wal.fsync_total
    wal.sync_close()
    w2 = WriteAheadLog(str(tmp_path / "wal"))
    assert sum(1 for _ in w2.replay(0)) == 260
    w2.sync_close()


def test_wal_checkpoint_rotates_and_drops_covered_segments(tmp_path):
    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state, save_interval=3600, wal=True)
    srv.store.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    srv.flush_state()  # pumps, rotates, snapshots, truncates
    data = json.load(open(state))
    assert data["wal_floor"] == 2  # records live in seg 1, floor moved past
    assert data["rv"] == srv.store._rv
    # covered segment gone; only the fresh live segment remains
    assert srv.wal.segment_indices() == [2]
    srv.wal.sync_close()

    # recovery from snapshot alone replays nothing
    srv2 = StoreServer(state_path=state, save_interval=3600, wal=True)
    assert srv2.store.get("Queue", "/q") is not None
    assert srv2.wal.replayed_records == 0
    srv2.wal.sync_close()


# -- zero acked loss + atomicity (tier-1) --------------------------------------


def _boot(tmp_path, port=0, save_interval=3600):
    return StoreServer(
        port=port, state_path=str(tmp_path / "state.json"),
        save_interval=save_interval, wal=True,
    ).start()


def test_acked_mutations_survive_kill_bit_for_bit(tmp_path):
    """The gate, distilled: a sequential client ACKs creates, updates,
    patches, bulk ops, and a whole decision segment; the server is killed
    with NO flush; the recovered server must show every 2xx-replied
    mutation with the exact rvs the client saw."""
    srv = _boot(tmp_path)
    rs = RemoteStore(srv.url)
    rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    for i in range(6):
        rs.create("Pod", build_pod(f"p{i}"))
    node = Node(meta=Metadata(name="n0", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi"}))
    rs.create("Node", node)
    n2 = rs.get("Node", "/n0")
    n2.labels["zone"] = "z1"
    rs.update("Node", n2)
    rs.patch("Pod", "default/p5", {"node_name": "n0"})
    assert rs.bulk(
        [{"op": "patch", "kind": "Pod", "key": f"default/p{i}",
          "fields": {"node_name": "n0"}} for i in range(3)]
    ) == [None] * 3
    seg = DecisionSegment.build(
        ["default/p3", "default/p4"], [0, 0], ["n0"],
        evicts=[("default/p0", "Preempted")])
    res = rs.apply_segment(seg)
    assert not res["binds"] and not res["evicts"]
    rs.delete("Pod", "default/p1")
    acked = {p.meta.key: (p.node_name, p.deleting, p.meta.resource_version)
             for p in rs.list("Pod")}
    acked_events = {e.meta.name for e in rs.list("Event")}
    seq, rv = srv.seq, srv.store._rv
    srv.kill()

    srv2 = _boot(tmp_path, port=srv.port)
    try:
        rs2 = RemoteStore(srv2.url)
        after = {p.meta.key: (p.node_name, p.deleting,
                              p.meta.resource_version)
                 for p in rs2.list("Pod")}
        assert after == acked
        assert {e.meta.name for e in rs2.list("Event")} == acked_events
        assert rs2.get("Node", "/n0").labels["zone"] == "z1"
        assert srv2.seq == seq and srv2.store._rv == rv
        # CAS continuity: an update against the pre-crash rv still works
        n3 = rs2.get("Node", "/n0")
        rs2.update_cas("Node", n3, n3.meta.resource_version)
    finally:
        srv2.stop()


def test_no_observable_half_applied_segment_across_crash(tmp_path):
    """Atomicity both ways: a segment whose WAL record survived recovers
    FULLY (every bind, every Event); one whose record was lost recovers
    NOT AT ALL — no prefix of binds, no stray Events."""
    srv = _boot(tmp_path)
    rs = RemoteStore(srv.url)
    for i in range(8):
        rs.create("Pod", build_pod(f"p{i}"))
    seg = DecisionSegment.build(
        [f"default/p{i}" for i in range(8)], [0] * 8, ["n0"])
    assert not rs.apply_segment(seg)["binds"]
    srv.kill()

    # record survived (it was written before the ACK): fully applied
    srv2 = _boot(tmp_path, port=srv.port)
    rs2 = RemoteStore(srv2.url)
    assert all(p.node_name == "n0" for p in rs2.list("Pod"))
    assert len(rs2.list("Event")) == 8

    # now ship a second segment and physically lose its record (the
    # pre-fsync crash where the page cache dies too, e.g. power loss):
    # recovery must show NO trace of it
    seg2 = DecisionSegment.build(
        [f"default/p{i}" for i in range(8)], [0] * 8, ["m1"],
        evicts=[("default/p7", "Preempted")])
    assert not rs2.apply_segment(seg2)["binds"]
    srv2.kill()
    live = sorted(glob.glob(str(tmp_path / "state.json.wal" / "*.wal")))[-1]
    records, _ = read_records(live)
    assert records, "segment record should be in the newest live segment"
    with open(live, "r+b") as f:
        f.truncate(os.path.getsize(live) - 10)  # tear the segment record

    srv3 = _boot(tmp_path, port=srv.port)
    try:
        rs3 = RemoteStore(srv3.url)
        pods = rs3.list("Pod")
        # all-or-nothing: every pod still shows segment 1's world
        assert all(p.node_name == "n0" and not p.deleting for p in pods)
        assert len(rs3.list("Event")) == 8
    finally:
        srv3.stop()


def test_segment_resubmit_is_idempotent_on_uid_block(tmp_path):
    """A cut reply leaves a shipped segment's outcome unknown; the
    applier re-ships the SAME segment (same reserved-uid block) — the
    server must dedupe: no duplicate Events, no extra patch events, and
    the final state identical to a single apply."""
    srv = _boot(tmp_path)
    try:
        rs = RemoteStore(srv.url)
        for i in range(4):
            rs.create("Pod", build_pod(f"p{i}"))
        seg = DecisionSegment.build(
            [f"default/p{i}" for i in range(4)], [0] * 4, ["n0"],
            evicts=[("default/p3", "Overcommit")])
        watcher = RemoteStore(srv.url)
        q = watcher.watch("Event")
        assert not rs.apply_segment(seg)["binds"]
        watcher.poll()
        first = len(q)
        assert first == 5
        once = {(p.meta.key, p.node_name, p.deleting,
                 p.meta.resource_version) for p in rs.list("Pod")}
        events_once = sorted(e.meta.name for e in rs.list("Event"))

        res = rs.apply_segment(seg)  # the retry
        assert not res["binds"] and not res["evicts"]
        watcher.poll()
        assert len(q) == first, "resubmit fanned out duplicate events"
        assert {(p.meta.key, p.node_name, p.deleting,
                 p.meta.resource_version)
                for p in rs.list("Pod")} == once
        assert sorted(e.meta.name for e in rs.list("Event")) == events_once
    finally:
        srv.stop()


def test_applier_reships_segment_through_one_connection_cut():
    """The scheduler half of idempotent resubmission: a connection-level
    cut during the segment ship triggers ONE re-ship of the same segment
    instead of dropping the cycle's decisions to the err_log."""
    from volcano_tpu.scheduler.apply import AsyncApplier

    class _Cache:
        def __init__(self, store):
            self.store = store
            self.errs = []

        def _record_err(self, verb, key, e):
            self.errs.append((verb, key, repr(e)))

    class _CutOnceStore:
        """Store façade whose first apply_segment dies mid-connection."""

        def __init__(self, inner):
            self._inner = inner
            self.calls = 0

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def apply_segment(self, seg):
            self.calls += 1
            if self.calls == 1:
                raise ConnectionResetError("cut mid-request")
            return self._inner.apply_segment(seg)

    from volcano_tpu.store.store import Store

    inner = Store()
    for i in range(3):
        inner.create("Pod", build_pod(f"p{i}"))
    store = _CutOnceStore(inner)
    cache = _Cache(store)
    applier = AsyncApplier(cache)
    try:
        seg = DecisionSegment.build(
            [f"default/p{i}" for i in range(3)], [0] * 3, ["n0"])
        applier.submit_segment(seg)
        assert applier.flush(timeout=10)
        assert store.calls == 2
        assert cache.errs == []
        assert all(p.node_name == "n0" for p in inner.list("Pod"))
        assert len(inner.list("Event")) == 3  # no dup events either
    finally:
        applier.stop()


def test_restarted_server_relists_watchers_and_mirror_converges(tmp_path):
    """Satellite: the restart twin of the chaos truncation test — an
    ACTIVE ArrayMirror behind a crash/restart must StaleWatch-relist and
    converge to store truth, then keep working incrementally."""
    from volcano_tpu.scheduler.fastpath import ArrayMirror
    from tests.helpers import build_node, build_podgroup

    srv = _boot(tmp_path)
    writer = RemoteStore(srv.url)
    writer.create("Queue", Queue(meta=Metadata(name="default",
                                               namespace="")))
    writer.create("Node", build_node("n0"))
    writer.create("PodGroup", build_podgroup("pg", min_member=1))
    writer.create("Pod", build_pod("p0", group="pg"))

    mirror_store = RemoteStore(srv.url)
    m = ArrayMirror(mirror_store, "volcano-tpu", "default")
    m.drain()
    assert int(m.p_live.sum()) == 1 and m.stale_relists == 0

    # mutate while the mirror's cursor lags, then kill + recover
    writer.create("Pod", build_pod("p1", group="pg"))
    writer.delete("Pod", "default/p0")
    srv.kill()
    srv2 = _boot(tmp_path, port=srv.port)
    try:
        m.drain()
        assert m.stale_relists == 1
        assert int(m.p_live.sum()) == 1
        assert "default/p1" in m.pods.key_row
        assert "default/p0" not in m.pods.key_row
        w2 = RemoteStore(srv2.url)
        w2.create("Pod", build_pod("p2", group="pg"))
        m.drain()
        assert int(m.p_live.sum()) == 2 and m.stale_relists == 1
    finally:
        srv2.stop()


# -- observability satellites (tier-1) -----------------------------------------


def test_wal_metrics_monotonic_and_exposed(tmp_path):
    from volcano_tpu.scheduler import metrics

    a0 = metrics.get_counter("volcano_store_wal_appended_records_total")
    f0 = metrics.get_counter("volcano_store_wal_fsync_total")
    r0 = metrics.get_counter("volcano_store_wal_recovery_replayed_records_total")
    srv = _boot(tmp_path)
    rs = RemoteStore(srv.url)
    rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    rs.create("Queue", Queue(meta=Metadata(name="r", namespace="")))
    a1 = metrics.get_counter("volcano_store_wal_appended_records_total")
    f1 = metrics.get_counter("volcano_store_wal_fsync_total")
    assert a1 >= a0 + 2 and f1 >= f0 + 1
    srv.kill()
    srv2 = _boot(tmp_path, port=srv.port)
    try:
        r1 = metrics.get_counter(
            "volcano_store_wal_recovery_replayed_records_total")
        assert r1 >= r0 + 2
        # counters only ever grow
        assert metrics.get_counter(
            "volcano_store_wal_appended_records_total") >= a1
        text = metrics.expose_text()
        for name in ("volcano_store_wal_appended_records_total",
                     "volcano_store_wal_fsync_total",
                     "volcano_store_wal_recovery_replayed_records_total"):
            assert name in text
    finally:
        srv2.stop()


def test_recovery_emits_store_recover_span(tmp_path):
    srv = _boot(tmp_path)
    rs = RemoteStore(srv.url)
    rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    rs.create("Queue", Queue(meta=Metadata(name="r", namespace="")))
    srv.kill()
    tracer = trace.arm(trace.Tracer(ring=1024))
    try:
        srv2 = _boot(tmp_path, port=srv.port)
        srv2.stop()
        spans = [r for r in tracer.records()
                 if r.get("name") == "store.recover"]
        assert spans, "recovery did not trace store.recover"
        attrs = spans[-1]["attrs"]
        assert attrs["replayed"] == 2 and attrs["torn_tails"] == 0
    finally:
        trace.disarm()


# -- graceful shutdown satellite (real subprocess) -----------------------------


def test_sigterm_flushes_state_and_wal_before_exit(tmp_path):
    """Satellite regression: run_apiserver must flush state (and fsync
    the WAL tail) on SIGTERM, not only on clean ``vtctl down`` — and a
    write ACKed moments before the signal must be in the state file."""
    import signal
    import subprocess
    import sys

    state = str(tmp_path / "state.json")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.cli", "apiserver",
         "--port", "0", "--state", state, "--wal"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        url = p.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert wait_healthy(url, timeout=30)
        rs = RemoteStore(url)
        rs.create("Queue", Queue(meta=Metadata(name="sigterm-q",
                                               namespace="")))
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=30) == 0
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    data = json.load(open(state))
    names = [q["meta"]["name"] for q in data["kinds"]["Queue"]]
    assert "sigterm-q" in names
    # the shutdown checkpoint truncated the WAL: recovery replays nothing
    srv = StoreServer(state_path=state, wal=True)
    assert srv.store.get("Queue", "/sigterm-q") is not None
    assert srv.wal.replayed_records == 0
    srv.wal.sync_close()


# -- the seeded in-process crash-kill storms (tier-1 gate) ---------------------


def _raise_injected(point, rule):
    raise InjectedCrash(f"chaos abort at {point}")


@pytest.fixture
def injected_aborts():
    chaos.set_abort_handler(_raise_injected)
    try:
        yield
    finally:
        chaos.set_abort_handler(None)
        chaos.arm_crash_plan(None)


class CrashPlane:
    """Controller + scheduler + kubelet threads over real HTTP with the
    daemon outage discipline, PLUS crash-kill semantics: a component that
    dies of InjectedCrash is rebuilt from scratch (fresh RemoteStore,
    full relist) — the in-process analogue of systemd restarting a
    SIGKILLed unit."""

    def __init__(self, url):
        self.url = url
        self.stop = threading.Event()
        self.threads = []
        self.crashes = []  # unexpected deaths (fail the test)
        self.restarts = {"controller": 0, "scheduler": 0, "kubelet": 0}

    def _controller_loop(self):
        retry = Backoff(base=0.02, cap=0.3, seed=41)
        ctl = None
        while not self.stop.is_set():
            try:
                if ctl is None:
                    ctl = JobController(RemoteStore(self.url))
                ctl.pump()
                retry.reset()
            except InjectedCrash:
                ctl = None  # killed mid-gang: restart and relist
                self.restarts["controller"] += 1
                continue
            except StaleWatch:
                ctl = None
                continue
            except TRANSIENT:
                ctl = None
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _scheduler_loop(self):
        retry = Backoff(base=0.02, cap=0.3, seed=42)
        sched = None
        while not self.stop.is_set():
            try:
                if sched is None:
                    conf = full_conf()
                    # deployed default (run_scheduler): async batched
                    # application — the drain crash point lives in the
                    # applier thread
                    conf.apply_mode = "async"
                    sched = Scheduler(RemoteStore(self.url), conf=conf)
                sched.run_once()
                retry.reset()
                # the drain crash kills the APPLIER thread (the
                # scheduler's in-process "process"): treat a dead applier
                # as a dead scheduler and restart the whole unit, exactly
                # what systemd does to the real daemon
                applier = getattr(sched.cache, "applier", None)
                if applier is not None and not applier._thread.is_alive():
                    sched = None
                    self.restarts["scheduler"] += 1
                    continue
            except InjectedCrash:
                sched = None
                self.restarts["scheduler"] += 1
                continue
            except TRANSIENT:
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _kubelet_loop(self):
        from volcano_tpu.cli.daemons import kubelet_step

        retry = Backoff(base=0.02, cap=0.3, seed=43)
        store = None
        while not self.stop.is_set():
            try:
                if store is None:
                    store = RemoteStore(self.url)
                kubelet_step(store, time.time())
                retry.reset()
            except InjectedCrash:
                store = None  # killed mid-ready-flip: restart
                self.restarts["kubelet"] += 1
                continue
            except TRANSIENT:
                retry.sleep()
                continue
            self.stop.wait(0.02)

    def _guard(self, fn):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — surfaced in teardown
                trace.crash_dump("crash-plane-loop")
                self.crashes.append(repr(e))
        return run

    def start(self):
        for fn in (self._controller_loop, self._scheduler_loop,
                   self._kubelet_loop):
            t = threading.Thread(target=self._guard(fn), daemon=True)
            t.start()
            self.threads.append(t)
        return self

    def shutdown(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=30)
        assert not self.crashes, f"crash-plane loop died: {self.crashes}"


def _crash_storm(tmp_path, server_plan=None, process_plan=None,
                 expect_fire=None, n_jobs=2):
    """One seeded crash-kill storm over a WAL-backed apiserver.

    ``server_plan``: crash.server.* rules armed ON the server — when one
    fires (the handler thread dies of InjectedCrash), the harness kills
    the server process-style and boots a replacement on the same
    port/state/WAL.  ``process_plan``: crash.{scheduler,controller,
    kubelet}.* rules armed in-process — the component dies and the
    CrashPlane restarts it.  Returns final placements.
    """
    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state, save_interval=0.25,
                      wal=True).start()
    port = srv.port
    plan = None
    if server_plan is not None:
        plan = FaultPlan.from_dict(server_plan)
        srv.arm_chaos(plan)
    if process_plan is not None:
        plan = chaos.arm_crash_plan(FaultPlan.from_dict(process_plan))
    cp = CrashPlane(srv.url)
    try:
        assert wait_healthy(srv.url, timeout=10)
        seed_rs = RemoteStore(srv.url)
        _submit(seed_rs, Queue(meta=Metadata(name="default",
                                             namespace="")), kind="Queue")
        for i in range(3):
            _submit(seed_rs, Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})),
                kind="Node")
        cp.start()

        client = RemoteStore(srv.url)
        acked_jobs = []
        for i in range(n_jobs):
            job = _mk_job(f"cj{i}", 2)
            _submit(client, job)
            acked_jobs.append(f"soak/cj{i}")
            if server_plan is not None and plan is not None:
                # the seeded server kill may land while this gang is in
                # flight: poll for the fire and crash-restart the server
                deadline = time.monotonic() + 30
                while (time.monotonic() < deadline
                       and srv is not None
                       and not any(r["fires"] for r in plan.stats())):
                    if _job_running(client, f"soak/cj{i}"):
                        break
                    time.sleep(0.02)
                if srv is not None and any(
                        r["fires"] for r in plan.stats()):
                    srv.kill()
                    srv = StoreServer(port=port, state_path=state,
                                      save_interval=0.25, wal=True).start()
                    assert wait_healthy(srv.url, timeout=10)
            _wait_running(client, f"soak/cj{i}", deadline=120)

        if expect_fire:
            assert plan is not None and any(
                r["fires"] for r in plan.stats()), (
                "the seeded crash never fired: " + repr(plan.stats()))

        # every acked submission survived the storm
        for key in acked_jobs:
            job = client.get("Job", key)
            assert job is not None
            assert job.status.state.phase == JobPhase.RUNNING
        _check_invariants(client)
        assert statement.outstanding() == 0
        _assert_digests_converged(srv, state)
        return _placements(client)
    finally:
        cp.shutdown()
        if srv is not None:
            srv.stop()
        chaos.arm_crash_plan(None)


def _job_running(client, key):
    try:
        job = client.get("Job", key)
    except TRANSIENT:
        return False
    return job is not None and job.status.state.phase == JobPhase.RUNNING


def _assert_digests_converged(srv, state_path):
    """PR-13 convergence gate for crash storms: at storm end the mirror
    (fed the merged watch stream), every shard's maintained digest, a
    raw recompute, and a scratch WAL-lineage replay all agree — a crash
    or restart anywhere in the storm can not have forked the state."""
    from volcano_tpu import vtaudit
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    if not vtaudit.enabled():
        return
    m = ArrayMirror(RemoteStore(srv.url), "volcano-tpu", "default")
    res = None
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        m.drain()
        with srv.lock:
            srv.stamp_beacon()
        m.drain()
        res = m.audit_verify()
        if res is not None:
            break  # quiescent: the beacon closed the poll batch
        time.sleep(0.05)
    assert res is not None and res["ok"], res
    truth = srv.store.recompute_digest()
    maint = srv.store.digest_payload(srv.shards)
    assert maint is not None
    assert maint["root"] == vtaudit.hexd(truth.root())
    # the durable lineage replays to the same digest the live server
    # maintains — checkpoint + WAL tails cover every acked mutation
    srv.flush_state()
    replay = vtaudit.replay_wal_digest(state_path)
    assert replay["digest"] is not None
    assert replay["digest"]["root"] == maint["root"], replay


def _assert_digests_converged_remote(url, state_path):
    """The OS-process twin of ``_assert_digests_converged``: the server
    is a subprocess, so every surface is driven over HTTP — the full
    ``vtctl audit`` walk (maintained vs server-side recompute vs wire
    lists), a beacon-pinned mirror verify (seq advanced by a digest-
    neutral create+delete pair so the cadence path stamps one), and a
    scratch replay of the on-disk WAL lineage."""
    import urllib.request

    from volcano_tpu import vtaudit
    from volcano_tpu.cli import vtctl
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    if not vtaudit.enabled():
        return
    text = vtctl.cmd_audit_remote(url)
    assert "state digest OK" in text, text
    m = ArrayMirror(RemoteStore(url), "volcano-tpu", "default")
    poke = RemoteStore(url)
    res = None
    deadline = time.monotonic() + 30
    n = 0
    while time.monotonic() < deadline and res is None:
        m.drain()
        poke.create("Queue", Queue(
            meta=Metadata(name=f"audit-poke-{n}", namespace="")))
        poke.delete("Queue", f"/audit-poke-{n}")
        n += 1
        time.sleep(1.1)  # the beacon cadence (VOLCANO_TPU_AUDIT_BEACON_S)
        m.drain()
        res = m.audit_verify()
    assert res is not None and res["ok"], res
    live = json.load(urllib.request.urlopen(url + "/debug/digest",
                                            timeout=10))
    replay = vtaudit.replay_wal_digest(state_path)
    assert replay["digest"] is not None
    assert replay["digest"]["root"] == live["root"], replay


PLAN_SERVER_PRE_FSYNC = {
    "seed": 701,
    "rules": [{"point": "crash.server.pre_fsync", "action": "abort",
               "after": 6, "count": 1}],
}
PLAN_SERVER_POST_FSYNC = {
    "seed": 702,
    "rules": [{"point": "crash.server.post_fsync", "action": "abort",
               "after": 9, "count": 1}],
}
PLAN_SCHED_DRAIN = {
    "seed": 703,
    "rules": [{"point": "crash.scheduler.drain", "action": "abort",
               "count": 1}],
}
PLAN_CTL_GANG = {
    "seed": 704,
    "rules": [{"point": "crash.controller.gang_create", "action": "abort",
               "after": 1, "count": 1}],
}
PLAN_KUBELET_READY = {
    "seed": 705,
    "rules": [{"point": "crash.kubelet.ready", "action": "abort",
               "after": 1, "count": 1}],
}


#: the aborted thread dying of InjectedCrash IS the storm's mechanism —
#: pytest's thread-exception watcher would report it as noise
_expected_thread_death = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")


@_expected_thread_death
def test_crash_storm_server_pre_and_post_fsync(tmp_path, injected_aborts):
    baseline = _crash_storm(tmp_path / "base")
    pre = _crash_storm(tmp_path / "pre",
                       server_plan=PLAN_SERVER_PRE_FSYNC, expect_fire=True)
    post = _crash_storm(tmp_path / "post",
                        server_plan=PLAN_SERVER_POST_FSYNC, expect_fire=True)
    assert pre == baseline
    assert post == baseline
    assert len(baseline) == 4  # 2 gangs x 2 replicas, all Running


@_expected_thread_death
def test_crash_storm_scheduler_mid_drain(tmp_path, injected_aborts):
    baseline = _crash_storm(tmp_path / "base")
    stormy = _crash_storm(tmp_path / "storm",
                          process_plan=PLAN_SCHED_DRAIN, expect_fire=True)
    assert stormy == baseline


@_expected_thread_death
def test_crash_storm_controller_mid_gang_and_kubelet_mid_ready(
        tmp_path, injected_aborts):
    baseline = _crash_storm(tmp_path / "base")
    gang = _crash_storm(tmp_path / "gang",
                        process_plan=PLAN_CTL_GANG, expect_fire=True)
    ready = _crash_storm(tmp_path / "ready",
                         process_plan=PLAN_KUBELET_READY, expect_fire=True)
    assert gang == baseline
    assert ready == baseline


# -- the real-subprocess SIGKILL storms (make crash-soak) ----------------------


def _spawn_daemon(entry, comp, url, env, extra=()):
    import subprocess

    args = {"controller": ["--period", "0.05"],
            "scheduler": ["--period", "0.1", "--metrics-port", "-1"],
            "kubelet": ["--period", "0.05"]}[comp]
    return subprocess.Popen(
        entry + [comp, "--server", url] + args + list(extra),
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)


def _sigkill_storm(tmp_path, crash_env_for=None, crash_plan=None,
                   n_jobs=2):
    """Real OS processes, real SIGKILL: the component named by
    ``crash_env_for`` boots with a ``crash.*`` abort plan in
    VOLCANO_TPU_CHAOS (default abort handler = SIGKILL self); the
    harness restarts any dead component, server included, and the
    workload must converge.  Returns final placements."""
    import signal
    import subprocess
    import sys

    state = str(tmp_path / "state.json")
    base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                "VOLCANO_TPU_BACKEND": "host"}
    base_env.pop("VOLCANO_TPU_CHAOS", None)
    entry = [sys.executable, "-m", "volcano_tpu.cli"]

    def env_for(comp):
        if comp == crash_env_for and crash_plan is not None:
            return {**base_env, "VOLCANO_TPU_CHAOS": json.dumps(crash_plan)}
        return dict(base_env)

    def start_api(port):
        p = subprocess.Popen(
            entry + ["apiserver", "--port", str(port), "--state", state,
                     "--wal"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env_for("apiserver"))
        url = p.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert wait_healthy(url, timeout=30)
        return p, url

    procs = {}
    api, url = start_api(0)
    port = int(url.rsplit(":", 1)[1])
    procs["apiserver"] = api
    try:
        for comp in ("controller", "scheduler", "kubelet"):
            procs[comp] = _spawn_daemon(entry, comp, url, env_for(comp))

        client = RemoteStore(url)
        _submit(client, Queue(meta=Metadata(name="default", namespace="")),
                kind="Queue")
        for i in range(3):
            _submit(client, Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})),
                kind="Node")

        kills = 0
        acked = []
        for i in range(n_jobs):
            _submit(client, _mk_job(f"kj{i}", 2))
            acked.append(f"soak/kj{i}")
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                # restart anything the seeded abort SIGKILLed — the
                # harness IS the process supervisor here
                for comp, p in list(procs.items()):
                    if p.poll() is not None:
                        kills += 1
                        if comp == "apiserver":
                            # post-SIGKILL recovery on the same state+WAL
                            procs[comp], url2 = start_api(port)
                            assert url2 == url
                        else:
                            procs[comp] = _spawn_daemon(
                                entry, comp, url, dict(base_env))
                if _job_running(client, f"soak/kj{i}"):
                    break
                time.sleep(0.1)
            _wait_running(client, f"soak/kj{i}", deadline=60)

        if crash_plan is not None:
            assert kills >= 1, "the seeded SIGKILL never landed"
        for key in acked:
            job = client.get("Job", key)
            assert job is not None
            assert job.status.state.phase == JobPhase.RUNNING
        _check_invariants(client)
        _assert_digests_converged_remote(url, state)
        return _placements(client)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


SIGKILL_SERVER_PLAN = {
    "seed": 801,
    "rules": [
        {"point": "crash.server.pre_fsync", "action": "abort",
         "after": 10, "count": 1},
        {"point": "crash.server.post_fsync", "action": "abort",
         "after": 25, "count": 1},
    ],
}
SIGKILL_SCHED_PLAN = {
    "seed": 802,
    "rules": [{"point": "crash.scheduler.drain", "action": "abort",
               "count": 1}],
}
SIGKILL_CTL_PLAN = {
    "seed": 803,
    "rules": [{"point": "crash.controller.gang_create", "action": "abort",
               "after": 1, "count": 1}],
}


@pytest.mark.slow
def test_sigkill_storm_server_pre_and_post_fsync(tmp_path):
    baseline = _sigkill_storm(tmp_path / "base")
    stormy = _sigkill_storm(tmp_path / "storm",
                            crash_env_for="apiserver",
                            crash_plan=SIGKILL_SERVER_PLAN)
    assert stormy == baseline
    assert len(baseline) == 4


@pytest.mark.slow
def test_sigkill_storm_scheduler_mid_drain(tmp_path):
    baseline = _sigkill_storm(tmp_path / "base")
    stormy = _sigkill_storm(tmp_path / "storm",
                            crash_env_for="scheduler",
                            crash_plan=SIGKILL_SCHED_PLAN)
    assert stormy == baseline


@pytest.mark.slow
def test_sigkill_storm_controller_mid_gang(tmp_path):
    baseline = _sigkill_storm(tmp_path / "base")
    stormy = _sigkill_storm(tmp_path / "storm",
                            crash_env_for="controller",
                            crash_plan=SIGKILL_CTL_PLAN)
    assert stormy == baseline


# -- review-hardening regressions ----------------------------------------------


def test_failed_fsync_does_not_mark_records_synced(tmp_path, monkeypatch):
    """A failed group-commit fsync must NOT advance the synced watermark:
    the leader's caller sees the error, and a follower (or a retry)
    re-fsyncs the range instead of treating it as durable."""
    wal = WriteAheadLog(str(tmp_path / "wal"))
    t = wal.append({"i": 0})
    real_fsync = os.fsync
    calls = {"n": 0}

    def flaky(fd):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(5, "injected EIO")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", flaky)
    with pytest.raises(OSError):
        wal.commit(t)
    assert wal.fsync_total == 0  # nothing durable yet
    wal.commit(t)  # retry succeeds and covers the range
    assert wal.fsync_total == 1
    monkeypatch.setattr(os, "fsync", real_fsync)
    wal.sync_close()
    w2 = WriteAheadLog(str(tmp_path / "wal"))
    assert [r["i"] for r in w2.replay(0)] == [0]
    w2.sync_close()


def test_wal_off_boot_absorbs_acked_tail_and_retires_segments(tmp_path):
    """Dropping back to interval persistence must not silently lose the
    acked WAL tail of a crashed WAL-on life: a WAL-OFF boot replays the
    leftover segments, snapshots immediately, and retires them; a later
    WAL-on boot starts clean and stamps a floored checkpoint before
    serving (so a floorless snapshot + segments can only ever mean
    already-absorbed staleness — safe to drop)."""
    state = str(tmp_path / "state.json")
    # life 1: WAL-on, ACKs a create, crashes without ever checkpointing
    srv1 = StoreServer(state_path=state, save_interval=3600, wal=True)
    srv1.store.create("Queue", Queue(meta=Metadata(name="acked",
                                                   namespace="")))
    with srv1.lock:
        srv1._pump_log()
        srv1._wal_append({
            "op": "create", "kind": "Queue",
            "object": {"meta": {"name": "acked", "namespace": "",
                                "resource_version": 1}},
        })
    srv1.wal.commit()
    srv1.kill()
    assert not os.path.exists(state)  # nothing but the WAL survived
    # life 2: WAL-off — the acked tail is absorbed, made durable, and
    # the segments retired
    srv2 = StoreServer(state_path=state, save_interval=3600)
    assert srv2.store.get("Queue", "/acked") is not None
    assert json.load(open(state))["kinds"]["Queue"]
    assert glob.glob(str(tmp_path / "state.json.wal" / "*.wal")) == []
    srv2.store.create("Queue", Queue(meta=Metadata(name="newer",
                                                   namespace="")))
    srv2.flush_state()
    srv2._killed = True  # abandon
    # life 3: WAL-on again — clean directory, nothing to replay, and the
    # boot stamps a floored checkpoint before serving
    srv3 = StoreServer(state_path=state, save_interval=3600, wal=True)
    assert srv3.store.get("Queue", "/acked") is not None
    assert srv3.store.get("Queue", "/newer") is not None
    assert srv3.wal.replayed_records == 0
    assert "wal_floor" in json.load(open(state))
    srv3.wal.sync_close()


def test_drop_below_never_unlinks_the_live_segment(tmp_path):
    """A snapshot restored from backup can carry a wal_floor ABOVE a
    rebuilt directory's indices: recovery must not unlink its own live
    segment, or every later acked append lands in an anonymous inode."""
    d = str(tmp_path / "wal")
    wal = WriteAheadLog(d)  # live segment index 1
    wal.drop_below(7)  # floor far beyond this life's index
    assert wal.segment_indices() == [1]
    wal.append({"i": 0})
    wal.commit()
    wal.sync_close()
    w2 = WriteAheadLog(d)
    assert [r["i"] for r in w2.replay(0)] == [0]
    w2.sync_close()


def test_floor_stamp_written_even_for_empty_inherited_snapshot(tmp_path):
    """The boot-time wal_floor stamp must not be skipped when the
    inherited floorless snapshot has no objects at all — the floor, not
    the kinds, is what makes the next crash recoverable."""
    state = str(tmp_path / "state.json")
    with open(state, "w") as f:
        json.dump({"seq": 5, "rv": 5, "store_uid": "u", "kinds": {}}, f)
    srv = StoreServer(state_path=state, save_interval=3600, wal=True)
    data = json.load(open(state))
    assert "wal_floor" in data and data["seq"] == 5
    srv.wal.sync_close()


# -- the corruption drill (PR 13: vtaudit) ------------------------------------


def test_corruption_drill_flipped_byte_detected_and_localized(tmp_path):
    """Flip one field of one stored object BEHIND the mutation verbs
    (simulated memory/state corruption) on a WAL-backed server: the
    audit walk must name exactly that (kind, namespace, name), and the
    WAL-replay digest must side with the maintained table — the durable
    history describes the acked writes, not the corrupted raw state."""
    from volcano_tpu import vtaudit
    from volcano_tpu.cli import vtctl

    if not vtaudit.enabled():
        pytest.skip("digest auditing disarmed in env")
    srv = _boot(tmp_path)
    try:
        rs = RemoteStore(srv.url)
        rs.create("Queue", Queue(meta=Metadata(name="default",
                                               namespace="")))
        for i in range(8):
            rs.create("Pod", build_pod(f"p{i}", namespace=f"ns{i % 2}"))
        assert "state digest OK" in vtctl.cmd_audit_remote(srv.url)
        maint_root = srv.store.digest_payload(srv.shards)["root"]

        srv.store._objects["Pod"]["ns1/p3"].node_name = "flipped"

        text = vtctl.cmd_audit_remote(srv.url)
        assert "STATE DIGEST DIVERGENCE" in text
        assert "Pod ns1/p3" in text
        # exactly one object implicated in the maintained-vs-raw walk
        assert text.count("maintained=") - 1 == 1
        assert vtctl.main(["audit", "--server", srv.url]) == 2
        # the durable lineage agrees with the MAINTAINED digest: the
        # acked history never contained the flipped byte
        srv.flush_state()
        replay = vtaudit.replay_wal_digest(str(tmp_path / "state.json"))
        assert replay["digest"]["root"] == maint_root
        truth = srv.store.recompute_digest()
        assert vtaudit.hexd(truth.root()) != maint_root
    finally:
        srv.stop()
