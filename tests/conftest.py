"""Test config: force JAX onto 8 virtual CPU devices before anything imports jax.

Multi-chip sharding is exercised on this virtual mesh (the driver separately
dry-runs the multichip path); real-TPU numbers come from bench.py only.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# daemon subprocesses (test_daemons etc.) default to the host backend so
# every spawned scheduler doesn't pay a jax import + XLA compile; the
# deployed default is tpu (daemons.run_scheduler), covered explicitly by
# tests that set this to "tpu"
os.environ.setdefault("VOLCANO_TPU_BACKEND", "host")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# a sitecustomize may have pre-registered a TPU backend plugin, in which
# case the env var alone is ignored — pin the platform via jax.config too
import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # backend already initialized before conftest ran
