"""Native (C++/OpenMP) backend parity vs the host object path.

The native tier mirrors the reference's 16-goroutine CPU loops
(scheduler_helper.go:32-106); decisions must match the host path
bit-for-bit on identical snapshots, like the JAX kernels do.
"""

import numpy as np
import pytest

from volcano_tpu import native
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import FakeBinder, build_node, build_pod, build_podgroup, build_queue, make_store

pytestmark = pytest.mark.skipif(
    native.load() is None, reason=f"native solver unavailable: {native.build_error()}"
)


def run_backend(make_store_fn, backend, actions=("allocate", "backfill")):
    store = make_store_fn()
    conf = default_conf(backend=backend)
    conf.actions = list(actions)
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return dict(binder.binds)


def test_native_simple_gang():
    def build():
        return make_store(
            nodes=[build_node("n1"), build_node("n2")],
            podgroups=[build_podgroup("pg1", min_member=3)],
            pods=[build_pod(f"p{i}", group="pg1") for i in range(3)],
        )

    host = run_backend(build, "host")
    nat = run_backend(build, "native")
    assert host == nat and len(nat) == 3


def test_native_gang_atomicity():
    def build():
        return make_store(
            nodes=[build_node("n1", cpu="2", memory="4Gi")],
            podgroups=[build_podgroup("pg1", min_member=3)],
            pods=[build_pod(f"p{i}", group="pg1", cpu="1") for i in range(3)],
        )

    assert run_backend(build, "native") == run_backend(build, "host") == {}


def test_native_multi_queue_fair_share():
    def build():
        return make_store(
            nodes=[build_node("n0", cpu="4", memory="8Gi")],
            queues=[build_queue("q1", weight=3), build_queue("q2", weight=1)],
            podgroups=[
                build_podgroup("pg-1", min_member=1, queue="q1"),
                build_podgroup("pg-2", min_member=1, queue="q2"),
            ],
            pods=[
                *[build_pod(f"q1-{i}", group="pg-1", cpu="1", memory="2Gi") for i in range(4)],
                *[build_pod(f"q2-{i}", group="pg-2", cpu="1", memory="2Gi") for i in range(4)],
            ],
        )

    host = run_backend(build, "host")
    nat = run_backend(build, "native")
    assert host == nat


@pytest.mark.parametrize("seed", list(range(6)))
def test_native_parity_random(seed):
    def build():
        rng = np.random.default_rng(seed)
        n_nodes = int(rng.integers(2, 6))
        n_jobs = int(rng.integers(1, 6))
        nodes = [
            build_node(f"n{i}", cpu=str(int(rng.integers(2, 8))), memory="16Gi")
            for i in range(n_nodes)
        ]
        pgs, pods = [], []
        for j in range(n_jobs):
            replicas = int(rng.integers(1, 5))
            minm = int(rng.integers(1, replicas + 1))
            pgs.append(build_podgroup(f"pg{j}", min_member=minm))
            for k in range(replicas):
                pods.append(
                    build_pod(
                        f"p{j}-{k}", group=f"pg{j}",
                        cpu=str(int(rng.integers(1, 4))),
                        memory=f"{int(rng.integers(1, 4))}Gi",
                        priority=int(rng.integers(0, 3)),
                    )
                )
        return make_store(nodes=nodes, podgroups=pgs, pods=pods)

    host = run_backend(build, "host")
    nat = run_backend(build, "native")
    assert host == nat


def test_native_threads_reported():
    assert native.num_threads() >= 1


def test_native_parity_cordoned_and_provisioning_mix():
    """Elastic capacity: cordoned and Provisioning nodes must be masked
    from the native solver's decisions exactly as from the host path."""
    from test_tensor_parity import _elastic_mix_store

    host = run_backend(_elastic_mix_store, "host")
    nat = run_backend(_elastic_mix_store, "native")
    assert host == nat
    assert host and set(host.values()) == {"n0", "n2"}
