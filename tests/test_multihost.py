"""Multi-controller process-mode smoke: 2 local mesh hosts, one clean
cycle, clean shutdown — and the coordinator-death fallback contract.

The in-process parity legs (``--mesh-hosts 1`` bit-for-bit vs the
sharded path, 2-host lockstep merge) live in tests/test_parallel.py;
this file drives the actual OS-process seam the deployment uses: a
coordinator spawning one worker process per extra host, the rendezvous
dir, and the degrade-don't-wedge rules.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

TASKS, NODES, JOBS = 256, 64, 16


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


def _run(extra, timeout=300):
    cmd = [sys.executable, "-m", "volcano_tpu.parallel.multihost",
           "--nodes", str(NODES), "--tasks", str(TASKS),
           "--jobs", str(JOBS), "--seed", "3"] + extra
    return subprocess.run(cmd, env=_env(), capture_output=True,
                          text=True, timeout=timeout)


def _payload(proc):
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert lines, (proc.returncode, proc.stdout, proc.stderr[-800:])
    return json.loads(lines[-1])


def test_two_host_coordinator_runs_one_clean_cycle(tmp_path):
    """`--mesh-hosts 2`: the coordinator spawns one worker process,
    both run the lockstep cycle, the worker ships its owned slices
    through the rendezvous dir, the coordinator verifies them against
    its merged outputs, and everything exits 0 — one clean cycle, clean
    shutdown, nothing degraded."""
    proc = _run(["--mesh-hosts", "2", "--outdir", str(tmp_path)])
    assert proc.returncode == 0, proc.stderr[-800:]
    summary = _payload(proc)
    assert summary["ok"] is True
    assert summary["hosts"] == 2
    assert summary["degraded"] is False, summary
    assert [w["ok"] for w in summary["workers"]] == [True]
    assert summary["workers"][0]["rc"] == 0
    assert summary["binds"] > 0
    assert len(summary["per_host"]) == 2
    # the worker's shipped slice really is the owned half, not a stub
    shipped = np.load(tmp_path / "host01.npz")
    assert shipped["task_node"].shape[0] == TASKS // 2
    assert shipped["idle"].shape[0] == NODES // 2


def test_worker_degrades_to_full_cycle_when_coordinator_dies(tmp_path):
    """A worker whose coordinator is dead must not wedge waiting on the
    rendezvous: it degrades to a FULL single-host cycle, ships full
    planes, flags ``fallback``, and exits cleanly."""
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait(timeout=30)
    proc = _run(["--mesh-hosts", "2", "--host-id", "1",
                 "--outdir", str(tmp_path),
                 "--coordinator-pid", str(dead.pid)])
    assert proc.returncode == 0, proc.stderr[-800:]
    payload = _payload(proc)
    assert payload["fallback"] is True
    shipped = np.load(tmp_path / "host01.npz")
    # full planes, not the host-1 slice: the degraded cycle can carry
    # the whole cluster on its own
    assert shipped["task_node"].shape[0] == TASKS
    assert shipped["idle"].shape[0] == NODES
    assert (shipped["task_kind"] == 1).sum() > 0


def test_mesh_hosts_conf_validation():
    """meshHosts/meshHostId parse and validate at load; the storm-action
    and backend guards trip at Scheduler construction."""
    import jax
    from volcano_tpu.scheduler.conf import load_conf

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    conf = load_conf("backend: tpu\nmeshHosts: 2\nmeshHostId: 1\n")
    assert conf.mesh_hosts == 2 and conf.mesh_host_id == 1
    with pytest.raises(ValueError):
        load_conf("meshHosts: 0\n")
    with pytest.raises(ValueError):
        load_conf("meshHosts: 2\nmeshHostId: 2\n")

    from volcano_tpu.scheduler.scheduler import Scheduler
    from helpers import build_node, make_store

    store = make_store(nodes=[build_node("n0")])
    with pytest.raises(ValueError, match="backend"):
        Scheduler(store, conf=load_conf(
            "backend: native\nmeshHosts: 2\n"))
    with pytest.raises(ValueError, match="preempt"):
        Scheduler(store, conf=load_conf(
            "backend: tpu\nmeshHosts: 2\n"
            "actions: allocate,preempt\n"))


def test_deployed_coordinator_worker_publish_split():
    """The deployed seam: a coordinator-conf'd scheduler and a
    worker-conf'd scheduler (each over its own copy of the same store)
    publish DISJOINT bind sets whose union equals the single-host run —
    each host binds only its owned express block, nothing is double-
    published at the host seam."""
    import jax
    from volcano_tpu.scheduler.conf import load_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from helpers import build_node, build_pod, build_podgroup, make_store

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    def run(mesh_lines):
        conf = load_conf(
            "backend: tpu\nsolveMode: batch\nexactTopK: true\n"
            + mesh_lines
        )
        store = make_store(
            nodes=[build_node(f"n{i}", cpu="4") for i in range(16)],
            podgroups=[build_podgroup(f"pg{j}", min_member=2)
                       for j in range(4)],
            pods=[build_pod(f"p{j}-{i}", group=f"pg{j}", cpu="1")
                  for j in range(4) for i in range(2)],
        )
        sched = Scheduler(store, conf=conf)
        sched.run_once()
        return dict(sched.cache.bind_log)

    single = run("")
    coord = run("meshHosts: 2\nmeshHostId: 0\n")
    worker = run("meshHosts: 2\nmeshHostId: 1\n")
    assert set(coord) | set(worker) == set(single)
    assert not set(coord) & set(worker)
    for name in coord:
        assert coord[name] == single[name], name
    for name in worker:
        assert worker[name] == single[name], name
    assert coord and worker


def test_degenerate_single_host_cli(tmp_path):
    """`--mesh-hosts 1` is one full in-process cycle — the deployed
    single-host shape, no subprocesses, no rendezvous."""
    proc = _run(["--mesh-hosts", "1"])
    assert proc.returncode == 0, proc.stderr[-800:]
    payload = _payload(proc)
    assert payload["ok"] is True
    assert payload["hosts"] == 1
    assert payload["binds"] > 0
    assert not list(tmp_path.iterdir())
