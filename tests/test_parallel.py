"""Sharded cycle parity: mesh-sharded solve == single-device solve."""

import jax
import numpy as np
import pytest

from volcano_tpu.parallel import make_mesh, make_sharded_cycle, run_cycle_reference
from volcano_tpu.scheduler.simargs import build_sim_args

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _outputs(out):
    return [np.asarray(jax.device_get(x)) for x in out]


def test_sharded_cycle_matches_reference():
    args = build_sim_args(n_nodes=32, n_tasks=64, n_jobs=16, n_queues=2, seed=3)
    ref = _outputs(run_cycle_reference(args, m_chunk=8, p_chunk=4))

    mesh = make_mesh(8)
    fn, dev_args = make_sharded_cycle(args=args, mesh=mesh, m_chunk=8, p_chunk=4)
    got = _outputs(fn(dev_args))

    names = [
        "task_node", "task_kind", "task_seq", "ready", "job_alloc",
        "queue_alloc", "idle", "releasing", "used", "dropped", "rounds",
    ]
    for name, r, g in zip(names, ref, got):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-3, err_msg=name)


def test_sharded_cycle_respects_capacity():
    args = build_sim_args(n_nodes=16, n_tasks=128, n_jobs=8, n_queues=2, seed=7)
    mesh = make_mesh(8)
    fn, dev_args = make_sharded_cycle(args=args, mesh=mesh, m_chunk=8, p_chunk=4)
    out = _outputs(fn(dev_args))
    task_node, task_kind = out[0], out[1]
    used = out[8]
    alloc = args["node_alloc"]
    eps = args["eps"]
    assert (used <= alloc + eps[None, :]).all()
    # every allocated task points at a valid node
    placed = task_kind == 1
    assert (task_node[placed] >= 0).all()
    assert args["node_valid"][task_node[placed]].all()


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, ex = ge.entry()
    out = jax.jit(fn)(*ex)
    jax.block_until_ready(out)
    placed = int((np.asarray(out[1]) > 0).sum())
    assert placed > 0


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_exact_topk_mesh_sweep_bitwise_parity():
    """exact_topk=True makes the sharded batch solve layout-independent:
    every mesh size (1/2/4/8 devices) reproduces the single-device run
    BIT-FOR-BIT at an N large enough that approx_max_k's bucketed
    reduction is layout-sensitive (VERDICT r1 next #8)."""
    args = build_sim_args(n_nodes=512, n_tasks=2048, n_jobs=128,
                          n_queues=2, seed=11)
    ref = _outputs(run_cycle_reference(args, m_chunk=32, p_chunk=8,
                                       exact_topk=True))
    names = [
        "task_node", "task_kind", "task_seq", "ready", "job_alloc",
        "queue_alloc", "idle", "releasing", "used", "dropped", "rounds",
    ]
    for n_dev in (1, 2, 4, 8):
        mesh = make_mesh(n_dev)
        fn, dev_args = make_sharded_cycle(
            args=args, mesh=mesh, m_chunk=32, p_chunk=8, exact_topk=True
        )
        got = _outputs(fn(dev_args))
        for name, r, g in zip(names, ref, got):
            np.testing.assert_array_equal(g, r, err_msg=f"{name}@{n_dev}dev")


def test_mesh_scheduler_conf_plumbs_through():
    """mesh: N in the scheduler-conf YAML reaches the deployed Scheduler:
    node-axis state shards over the mesh and the cycle's decisions match
    the single-device run (exactTopK pins the batch solve layout)."""
    from volcano_tpu.scheduler.conf import load_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from helpers import build_node, build_pod, build_podgroup, make_store

    def run(mesh_line):
        conf = load_conf(
            "backend: tpu\nsolveMode: batch\nexactTopK: true\n" + mesh_line
        )
        store = make_store(
            nodes=[build_node(f"n{i}", cpu="4") for i in range(16)],
            podgroups=[build_podgroup(f"pg{j}", min_member=2)
                       for j in range(4)],
            pods=[build_pod(f"p{j}-{i}", group=f"pg{j}", cpu="1")
                  for j in range(4) for i in range(2)],
        )
        sched = Scheduler(store, conf=conf)
        sched.run_once()
        return sched, dict(sched.cache.bind_log)

    sched8, binds8 = run("mesh: 8\n")
    assert sched8.mesh is not None and sched8.mesh.devices.size == 8
    _, binds1 = run("mesh: off\n")
    assert binds8 == binds1
    assert len(binds8) == 8


def test_mesh_auto_and_invalid():
    from volcano_tpu.scheduler.conf import load_conf
    from volcano_tpu.parallel.sharded import resolve_mesh

    assert load_conf("mesh: auto\n").mesh == "auto"
    assert resolve_mesh("auto").devices.size == len(jax.devices())
    assert resolve_mesh("off") is None
    assert resolve_mesh("1") is None
    with pytest.raises(ValueError):
        resolve_mesh(str(len(jax.devices()) + 1))
    with pytest.raises(ValueError):
        load_conf("mesh: sideways\n")


@pytest.mark.slow
def test_mesh_large_shape_parity_and_capacity():
    """The scale-axis mandate (SURVEY §5, VERDICT r3 next #7): one
    CPU-mesh run at 4096 nodes x 32k tasks over 8 devices, both top-k
    modes.  exact_topk: bind parity with the single-device run
    bit-for-bit; approx: capacity invariants (layout-dependent spill
    targets make bit parity out of contract)."""
    args = build_sim_args(n_nodes=4096, n_tasks=32768, n_jobs=2048,
                          n_queues=4, seed=13)
    mesh = make_mesh(8)
    names = [
        "task_node", "task_kind", "task_seq", "ready", "job_alloc",
        "queue_alloc", "idle", "releasing", "used", "dropped", "rounds",
    ]

    ref = _outputs(run_cycle_reference(args, m_chunk=256, p_chunk=16,
                                       exact_topk=True))
    fn, dev_args = make_sharded_cycle(
        args=args, mesh=mesh, m_chunk=256, p_chunk=16, exact_topk=True
    )
    got = _outputs(fn(dev_args))
    for name, r, g in zip(names, ref, got):
        np.testing.assert_array_equal(g, r, err_msg=f"{name}@8dev-exact")
    placed = int((got[1] > 0).sum())
    assert placed > 0

    fn, dev_args = make_sharded_cycle(
        args=args, mesh=mesh, m_chunk=256, p_chunk=16, exact_topk=False
    )
    out = _outputs(fn(dev_args))
    task_node, task_kind, used = out[0], out[1], out[8]
    eps = args["eps"]
    assert (used <= args["node_alloc"] + eps[None, :]).all()
    placed_rows = task_kind == 1
    assert placed_rows.any()
    assert args["node_valid"][task_node[placed_rows]].all()
    # no node exceeds its pod-count cap by more than the documented
    # per-round slack (idle+pipe same-round overshoot)
    counts = np.bincount(task_node[task_kind > 0],
                         minlength=args["node_valid"].shape[0])
    base = args["task_count"].astype(np.int64)
    cap = args["node_max_tasks"].astype(np.int64)
    assert (base + counts <= cap + 1).all()


def test_exact_topk_scheduler_conf_plumbs_through():
    """exactTopK in the scheduler-conf YAML reaches the batch solve."""
    from volcano_tpu.scheduler.conf import load_conf

    conf = load_conf("backend: tpu\nexactTopK: true\nsolveMode: batch\n")
    assert conf.exact_topk is True
    from volcano_tpu.scheduler.scheduler import Scheduler
    from helpers import build_node, build_pod, build_podgroup, make_store

    store = make_store(
        nodes=[build_node(f"n{i}") for i in range(2)],
        podgroups=[build_podgroup("pg", min_member=2)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(2)],
    )
    sched = Scheduler(store, conf=conf)
    sched.run_once()
    assert len(sched.cache.bind_log) == 2


def test_victim_step_mesh_sweep_matches_single_device():
    """The preempt/reclaim victim step under node-axis shardings: every
    mesh size (1/2/4/8 devices) reproduces the single-device solve's
    DECISIONS bit-for-bit — assigned flag, chosen node, victim mask,
    clean verdict — and the chained state within float tolerance (the
    PR-11 extension of the exact-topk parity sweep to the contention
    kernels)."""
    import jax.numpy as jnp

    from volcano_tpu.parallel.sharded import make_sharded_victim_step
    from volcano_tpu.scheduler.simargs import build_victim_sim
    from volcano_tpu.scheduler.victim_kernels import (
        VictimConsts, VictimState, victim_step,
    )

    c_np, s_np = build_victim_sim(64, 256, 16, n_queues=1, seed=5)
    t_req = jnp.asarray(np.array([2000.0, 2 * (1 << 30)], np.float32))
    kw = dict(mode="queue", use_gang=True, use_drf=False)

    ref_c = VictimConsts(**{k: jnp.asarray(v) for k, v in c_np.items()})
    ref_s = VictimState(**{k: jnp.asarray(v) for k, v in s_np.items()})
    ref = victim_step(ref_c, ref_s, t_req, 0, 0, 0, **kw)
    ref_state, ref_assigned, ref_nstar, ref_vmask, ref_clean = [
        jax.device_get(x) for x in
        (ref[0], ref[1], ref[2], ref[3], ref[4])
    ]

    for n_dev in (1, 2, 4, 8):
        mesh = make_mesh(n_dev)
        fn, dc, ds = make_sharded_victim_step(
            mesh, VictimConsts(**c_np), VictimState(**s_np), **kw
        )
        state, assigned, nstar, vmask, clean = fn(dc, ds, t_req, 0, 0, 0)
        assert bool(assigned) == bool(ref_assigned), f"{n_dev}dev"
        assert int(nstar) == int(ref_nstar), f"{n_dev}dev"
        assert bool(clean) == bool(ref_clean), f"{n_dev}dev"
        np.testing.assert_array_equal(
            jax.device_get(vmask), ref_vmask, err_msg=f"vmask@{n_dev}dev"
        )
        for name in state._fields:
            got = jax.device_get(getattr(state, name))
            want = jax.device_get(getattr(ref_state, name))
            np.testing.assert_allclose(
                got, want, rtol=1e-5, atol=1e-3,
                err_msg=f"state.{name}@{n_dev}dev",
            )


def test_shard_smoke_two_device_mesh_placement_parity():
    """Sub-second tier-1 smoke (`make bench-shard` preamble): the
    DEPLOYED fast cycle on a 2-device virtual CPU mesh places exactly
    what the single-device run places (exactTopK pins the batch solve's
    layout-dependent reduction)."""
    from volcano_tpu.scheduler.conf import load_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from helpers import build_node, build_pod, build_podgroup, make_store

    def run(mesh_line):
        conf = load_conf(
            "backend: tpu\nsolveMode: batch\nexactTopK: true\n" + mesh_line
        )
        store = make_store(
            nodes=[build_node(f"n{i}", cpu="4") for i in range(8)],
            podgroups=[build_podgroup(f"pg{j}", min_member=2)
                       for j in range(3)],
            pods=[build_pod(f"p{j}-{i}", group=f"pg{j}", cpu="1")
                  for j in range(3) for i in range(2)],
        )
        sched = Scheduler(store, conf=conf)
        sched.run_once()
        return sched, dict(sched.cache.bind_log)

    sched2, binds2 = run("mesh: 2\n")
    assert sched2.mesh is not None and sched2.mesh.devices.size == 2
    _, binds1 = run("mesh: off\n")
    assert binds2 == binds1
    assert len(binds2) == 6


# --- multi-controller mesh (PR 20: parallel/multihost) -----------------------

_NAMES = [
    "task_node", "task_kind", "task_seq", "ready", "job_alloc",
    "queue_alloc", "idle", "releasing", "used", "dropped", "rounds",
]


def test_multihost_degenerate_single_host_bitwise_parity():
    """``--mesh-hosts 1`` is the deployed mesh path, not a sibling: the
    degenerate single-host lockstep cycle reproduces the existing
    sharded-cycle outputs BIT-FOR-BIT — placements and the chained
    node state (idle/releasing/used fed back into a second cycle)."""
    from volcano_tpu.parallel import make_sharded_cycle, run_lockstep

    args = build_sim_args(n_nodes=512, n_tasks=2048, n_jobs=128,
                          n_queues=2, seed=11)
    mesh = make_mesh(8)

    def sharded(a):
        fn, dev_args = make_sharded_cycle(
            args=a, mesh=mesh, m_chunk=32, p_chunk=8, exact_topk=True
        )
        return _outputs(fn(dev_args))

    ref = sharded(args)
    got = run_lockstep(args, 1, m_chunk=32, p_chunk=8,
                       exact_topk=True)["outputs"]
    for name, r, g in zip(_NAMES, ref, got):
        np.testing.assert_array_equal(np.asarray(g), r,
                                      err_msg=f"{name}@1host")

    # chained state: the next cycle must agree bit-for-bit too — a
    # placement-only parity would hide a drifting node plane
    chained = dict(args)
    for name in ("idle", "releasing", "used"):
        chained[name] = np.asarray(got[_NAMES.index(name)])
    ref2 = sharded(chained)
    got2 = run_lockstep(chained, 1, m_chunk=32, p_chunk=8,
                        exact_topk=True)["outputs"]
    for name, r, g in zip(_NAMES, ref2, got2):
        np.testing.assert_array_equal(np.asarray(g), r,
                                      err_msg=f"{name}@1host-chained")


def test_multihost_two_host_lockstep_merges_to_single_host():
    """Two simulated hosts in lockstep over the same logical mesh:
    every host fetches only its owned output slice, and the MERGED
    slices equal the single-host run bit-for-bit — same binds, same
    node planes, nothing double- or un-fetched at the host seam."""
    from volcano_tpu.parallel import host_bounds, run_lockstep

    args = build_sim_args(n_nodes=512, n_tasks=2048, n_jobs=128,
                          n_queues=2, seed=11)
    one = run_lockstep(args, 1, m_chunk=32, p_chunk=8,
                       exact_topk=True)["outputs"]
    two = run_lockstep(args, 2, m_chunk=32, p_chunk=8,
                       exact_topk=True)["outputs"]
    for name, r, g in zip(_NAMES, one, two):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r),
                                      err_msg=f"{name}@2host")
    # the bind set specifically (the decision the cluster acts on)
    kind1, kind2 = np.asarray(one[1]), np.asarray(two[1])
    node1, node2 = np.asarray(one[0]), np.asarray(two[0])
    np.testing.assert_array_equal(kind2 == 1, kind1 == 1)
    np.testing.assert_array_equal(node2[kind2 == 1], node1[kind1 == 1])
    assert (kind1 == 1).sum() > 0
    # and the ownership split is a real partition of the task axis
    bounds = host_bounds(kind1.shape[0], 2)
    assert bounds[0][1] == bounds[1][0] and bounds[1][1] == kind1.shape[0]
