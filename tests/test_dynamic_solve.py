"""Device dynamic solve (host ports + pod-(anti)affinity as interned
bitsets, SURVEY §7c / VERDICT r4 missing #1): jobs whose dynamic
predicates are port/selector-expressible run the exact allocate kernel
with the portsel extension instead of the host residue sub-cycle, with
bind-for-bind parity against the pure host path.
"""

import random

import pytest

from tests.helpers import (
    FakeBinder,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)
from volcano_tpu.api.objects import Affinity
from volcano_tpu.api.types import PodPhase
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler


def _run(store, backend, fast=True):
    conf = default_conf(backend=backend)
    if backend == "tpu" and not fast:
        conf.fast_path = "off"
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder.binds


def _random_store(seed):
    rng = random.Random(seed)
    labels_pool = [{"app": "web"}, {"app": "db"}, {"tier": "gold"}, {}]
    nodes = [
        build_node(f"n{i:02d}", cpu=str(rng.choice([4, 8])),
                   memory=f"{rng.choice([8, 16])}Gi")
        for i in range(6)
    ]
    podgroups, pods = [], []
    # residents with labels/ports
    podgroups.append(build_podgroup("res", min_member=1))
    for i in range(rng.randint(2, 6)):
        p = build_pod(f"res-{i}", group="res", cpu="1", memory="1Gi",
                      labels=rng.choice(labels_pool))
        if rng.random() < 0.5:
            p.spec.host_ports = [rng.choice([80, 8080, 9090])]
        p.node_name = f"n{rng.randrange(6):02d}"
        p.phase = PodPhase.RUNNING
        pods.append(p)
    # pending jobs: express / ports / affinity mixtures
    for j in range(rng.randint(2, 5)):
        n_tasks = rng.randint(1, 3)
        podgroups.append(
            build_podgroup(f"j{j}", min_member=rng.randint(1, n_tasks))
        )
        kind = rng.choice(["express", "ports", "aff", "anti", "mixed"])
        for t in range(n_tasks):
            p = build_pod(f"j{j}-{t}", group=f"j{j}", cpu="1", memory="1Gi",
                          labels=rng.choice(labels_pool))
            if kind == "ports" or (kind == "mixed" and t == 0):
                p.spec.host_ports = [rng.choice([80, 8080, 9090])]
            elif kind == "aff":
                p.spec.affinity = Affinity(
                    pod_affinity=[rng.choice([{"app": "web"},
                                              {"tier": "gold"}])]
                )
            elif kind == "anti":
                p.spec.affinity = Affinity(
                    pod_anti_affinity=[rng.choice([{"app": "web"},
                                                   {"app": "db"}])]
                )
            pods.append(p)
    return make_store(nodes=nodes, queues=[build_queue("default")],
                      podgroups=podgroups, pods=pods)


@pytest.mark.parametrize("seed", range(8))
def test_ports_affinity_parity_randomized(seed):
    """Random residents + pending jobs carrying ports/affinity/anti
    mixtures: the fast cycle's DEVICE dynamic solve binds exactly what
    the object tensor path's HOST residue pass binds (both partition
    dynamic jobs after the express solve, so this isolates the device
    port/selector kernel against the host predicate walk; pure-host
    interleave parity holds only without cross-partition contention —
    test_partition.py's documented ordering note)."""
    _, obj = _run(_random_store(seed), "tpu", fast=False)
    sched, fast = _run(_random_store(seed), "tpu")
    assert sched.fast_cycle is not None and sched.fast_cycle.phases
    assert fast == obj


def test_expressible_jobs_skip_residue_subcycle(monkeypatch):
    """A ports/affinity job no longer pays the object residue sub-cycle
    (the device solve serves it), and since r6 neither does a
    non-constraining volume (no PVC object — emptyDir-style); only a
    count-INEXPRESSIBLE claim shape (here a static class whose pool
    mixes a node-pinned and a network PV) still does."""
    calls = []

    def spy(self, residue_keys, run_preempt):
        calls.append(set(residue_keys))

    monkeypatch.setattr(Scheduler, "run_object_residue", spy)

    store = _random_store(3)
    p = build_pod("ported", group="pg-port", cpu="1", memory="1Gi")
    p.spec.host_ports = [7777]
    store.create("PodGroup", build_podgroup("pg-port", min_member=1))
    store.create("Pod", p)
    sched, _ = _run(store, "tpu")
    assert sched.fast_cycle.phases.get("dyn_solve") is not None
    assert calls == []  # no residue sub-cycle ran

    store2 = _random_store(3)
    v = build_pod("vol", group="pg-vol", cpu="1", memory="1Gi")
    v.volumes = ["claim-a"]  # no PVC object: non-constraining, express
    store2.create("PodGroup", build_podgroup("pg-vol", min_member=1))
    store2.create("Pod", v)
    _run(store2, "tpu")
    assert calls == []

    from volcano_tpu.api.objects import (
        Metadata, PersistentVolume, PersistentVolumeClaim, StorageClass,
    )

    store3 = _random_store(3)
    store3.create("StorageClass", StorageClass(
        meta=Metadata(name="local", namespace=""), provisioner=""))
    store3.create("PV", PersistentVolume(
        meta=Metadata(name="pinned", namespace=""), capacity="20Gi",
        storage_class="local",
        node_affinity={"kubernetes.io/hostname": "n01"}))
    store3.create("PV", PersistentVolume(
        meta=Metadata(name="floating", namespace=""), capacity="20Gi",
        storage_class="local"))
    store3.create("PVC", PersistentVolumeClaim(
        meta=Metadata(name="claim-b", namespace="default"), size="5Gi",
        storage_class="local"))
    w = build_pod("vol2", group="pg-vol2", cpu="1", memory="1Gi")
    w.volumes = ["claim-b"]
    store3.create("PodGroup", build_podgroup("pg-vol2", min_member=1))
    store3.create("Pod", w)
    _run(store3, "tpu")
    assert calls and "default/pg-vol2" in calls[0]


def test_self_anti_affinity_spreads_within_cycle():
    """A gang whose pods anti-match their own labels must spread one per
    node — the in-solve node_sels update sees this cycle's placements,
    like the host walk seeing node.tasks."""
    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(3)]
    pg = build_podgroup("spread", min_member=3)
    pods = []
    for t in range(3):
        p = build_pod(f"s-{t}", group="spread", cpu="1", memory="1Gi",
                      labels={"app": "z"})
        p.spec.affinity = Affinity(pod_anti_affinity=[{"app": "z"}])
        pods.append(p)
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=[pg], pods=pods)
    sched, binds = _run(store, "tpu")
    assert len(binds) == 3
    assert len(set(binds.values())) == 3, binds  # one per node


def test_affinity_requires_resident_match():
    """Required affinity with no matching resident anywhere: nothing
    binds, identically on both paths; with a matching resident the pod
    co-locates on its node."""
    def mk(with_resident):
        nodes = [build_node("n0", cpu="8", memory="16Gi"),
                 build_node("n1", cpu="8", memory="16Gi")]
        podgroups = [build_podgroup("rg", min_member=1),
                     build_podgroup("want", min_member=1)]
        pods = []
        if with_resident:
            r = build_pod("res", group="rg", cpu="1", memory="1Gi",
                          labels={"app": "web"})
            r.node_name = "n1"
            r.phase = PodPhase.RUNNING
            pods.append(r)
        w = build_pod("w0", group="want", cpu="1", memory="1Gi")
        w.spec.affinity = Affinity(pod_affinity=[{"app": "web"}])
        pods.append(w)
        return make_store(nodes=nodes, queues=[build_queue("default")],
                          podgroups=podgroups, pods=pods)

    _, fast = _run(mk(False), "tpu")
    _, host = _run(mk(False), "host")
    assert fast == host and "default/w0" not in fast
    _, fast2 = _run(mk(True), "tpu")
    _, host2 = _run(mk(True), "host")
    assert fast2 == host2 and fast2["default/w0"] == "n1"


def _assert_hard_invariants(store):
    """Port disjointness, required/anti affinity, and capacity must hold
    over the final placement regardless of solve variant."""
    from collections import defaultdict

    by_node = defaultdict(list)
    for p in store.list("Pod"):
        if p.node_name:
            by_node[p.node_name].append(p)
    for node, pods in by_node.items():
        ports = []
        for p in pods:
            for port in p.spec.host_ports:
                assert port not in ports, f"port clash on {node}"
                ports.append(port)
        for p in pods:
            aff = p.spec.affinity
            if aff is None:
                continue
            others = [q for q in pods if q is not p]
            for sel in aff.pod_anti_affinity:
                assert not any(
                    all(q.meta.labels.get(k) == v for k, v in sel.items())
                    for q in others
                ), f"anti-affinity violated on {node}"


@pytest.mark.parametrize("seed", range(4))
def test_batched_dynamic_solve_invariants(seed):
    """solveMode batch routes the dynamic wave through the batched-rounds
    kernel (the intra-round conflict scans): placements may legally
    diverge from the exact solve — the approximate mode's contract — but
    every HARD predicate must hold, and gang-satisfiable work places."""
    store = _random_store(seed)
    conf = default_conf(backend="tpu")
    conf.solve_mode = "batch"
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    assert sched.fast_cycle is not None and sched.fast_cycle.phases
    for key, node in binder.binds.items():
        pod = store.get("Pod", key)
        pod.node_name = node  # FakeBinder doesn't write the store
    _assert_hard_invariants(store)


def test_batched_dynamic_solve_spreads_anti_self_gang():
    """Batch mode, a 6-task anti-self gang on 8 nodes: the spread cap +
    intra-round scan keep one task per node."""
    nodes = [build_node(f"n{i}", cpu="8", memory="16Gi") for i in range(8)]
    pg = build_podgroup("spread", min_member=6)
    pods = []
    for t in range(6):
        p = build_pod(f"s-{t}", group="spread", cpu="1", memory="1Gi",
                      labels={"app": "z"})
        p.spec.affinity = Affinity(pod_anti_affinity=[{"app": "z"}])
        pods.append(p)
    store = make_store(nodes=nodes, queues=[build_queue("default")],
                       podgroups=[pg], pods=pods)
    conf = default_conf(backend="tpu")
    conf.solve_mode = "batch"
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    assert len(binder.binds) == 6
    assert len(set(binder.binds.values())) == 6, binder.binds


def test_port_intern_overflow_falls_back_to_residue():
    """More distinct ports than the bitset cap: overflowing pods stay on
    the host residue path and still place correctly."""
    from volcano_tpu.scheduler.fastpath import ArrayMirror

    store = make_store(
        nodes=[build_node("n0", cpu="64", memory="128Gi")],
        queues=[build_queue("default")],
        podgroups=[build_podgroup("big", min_member=1)], pods=[],
    )
    m = ArrayMirror(store, "volcano-tpu", "default")
    m.drain()
    for i in range(130):  # cap is 128
        p = build_pod(f"p{i:03d}", group="big", cpu="100m", memory="64Mi")
        p.spec.host_ports = [10_000 + i]
        store.create("Pod", p)
    m.drain()
    assert len(m.port_ids) == 128
    overflowed = [
        m.pods.key_row[f"default/p{i:03d}"] for i in (128, 129)
    ]
    assert not m.p_dyn_expr[overflowed].any()
    interned = m.pods.key_row["default/p000"]
    assert m.p_dyn_expr[interned]
