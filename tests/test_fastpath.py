"""Array-native fast cycle (scheduler/fastpath.py): snapshot parity with
the object builder, decision parity with the object path, incremental
mirror maintenance, eligibility fallbacks, and status/condition writes.
"""

import numpy as np
import pytest

from volcano_tpu.api.types import PodGroupPhase, PodPhase
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import (
    FakeBinder,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)


def mixed_store(seed=0, n_nodes=5, n_jobs=6, running_jobs=2):
    """Queues + podgroups + pending pods + some already-running pods."""
    import random

    rng = random.Random(seed)
    nodes = [
        build_node(f"n{i:02d}", cpu=str(rng.choice([4, 8])),
                   memory=f"{rng.choice([8, 16])}Gi")
        for i in range(n_nodes)
    ]
    queues = [build_queue("qa", weight=2), build_queue("qb", weight=1),
              build_queue("default")]
    podgroups, pods = [], []
    for j in range(n_jobs):
        n_tasks = rng.randint(1, 4)
        pg = build_podgroup(f"job{j}", min_member=rng.randint(1, n_tasks),
                            queue=rng.choice(["qa", "qb"]))
        podgroups.append(pg)
        running = j < running_jobs
        for t in range(n_tasks):
            pod = build_pod(
                f"job{j}-{t}", group=f"job{j}",
                cpu=rng.choice(["500m", "1"]),
                memory=f"{rng.choice([512, 1024])}Mi",
                priority=rng.choice([0, 5]),
            )
            if running:
                pod.node_name = nodes[t % n_nodes].meta.name
                pod.phase = PodPhase.RUNNING
            pods.append(pod)
    return make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                      pods=pods)


def _object_snapshot(store):
    from volcano_tpu.scheduler.cache import SchedulerCache
    from volcano_tpu.scheduler.framework import open_session
    from volcano_tpu.scheduler.snapshot import build_tensor_snapshot

    cache = SchedulerCache(store)
    ssn = open_session(cache, default_conf("tpu").tiers)
    return build_tensor_snapshot(ssn)


def _fast_snapshot(store):
    from volcano_tpu.scheduler.fastpath import ArrayMirror, build_fast_snapshot

    m = ArrayMirror(store, "volcano-tpu", "default")
    m.drain()
    assert m.ineligible_reason() is None
    return build_fast_snapshot(m)


@pytest.mark.parametrize("seed", range(5))
def test_fast_snapshot_equals_object_builder(seed):
    store = mixed_store(seed)
    obj = _object_snapshot(store)
    fast, aux = _fast_snapshot(store)

    assert fast.dims == obj.dims
    assert fast.node_names == obj.node_names
    for field in (
        "node_idle", "node_releasing", "node_used", "node_alloc",
        "node_max_tasks", "node_task_count", "node_valid",
        "task_req", "task_job", "task_valid",
        "job_queue", "job_min_available", "job_priority", "job_ready_init",
        "job_alloc_init", "job_schedulable", "job_start", "job_ntasks",
        "queue_weight", "queue_alloc_init", "queue_request", "queue_valid",
        "queue_participates", "class_node_mask", "class_node_score",
        "total", "eps",
    ):
        np.testing.assert_array_equal(
            getattr(fast, field), getattr(obj, field), err_msg=field
        )
    assert fast.queue_names == obj.queue_names


def _binds(store, conf):
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder.binds


@pytest.mark.parametrize("seed", range(6))
def test_fast_cycle_binds_equal_object_path(seed):
    conf_fast = default_conf("tpu")
    conf_obj = default_conf("tpu")
    conf_obj.fast_path = "off"
    s1, fast = _binds(mixed_store(seed), conf_fast)
    assert s1.fast_cycle is not None and s1.fast_cycle.mirror is not None
    _, obj = _binds(mixed_store(seed), conf_obj)
    assert fast == obj


def test_fast_cycle_incremental_updates():
    store = mixed_store(1, running_jobs=0)
    sched = Scheduler(store, conf=default_conf("tpu"))
    sched.run_once()
    first = len(sched.cache.bind_log)
    assert first > 0
    # new job arrives: only watch deltas flow into the mirror
    store.create("PodGroup", build_podgroup("late", min_member=2,
                                            queue="qa"))
    for t in range(2):
        store.create("Pod", build_pod(f"late-{t}", group="late", cpu="500m",
                                      memory="256Mi"))
    sched.run_once()
    late_binds = [k for k, _ in sched.cache.bind_log[first:]]
    assert sorted(late_binds) == ["default/late-0", "default/late-1"]


def test_fast_cycle_updates_podgroup_status():
    store = make_store(
        nodes=[build_node("n0")],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(2)],
    )
    sched = Scheduler(store, conf=default_conf("tpu"))
    sched.run_once()
    pg = store.get("PodGroup", "default/pg")
    # strict allocated > min_member (session.go jobStatus parity)
    assert pg.status.phase == PodGroupPhase.RUNNING


def test_fast_cycle_unschedulable_condition_and_event():
    from volcano_tpu import events

    store = make_store(
        nodes=[build_node(f"n{i}", cpu="1", memory="2Gi") for i in range(2)],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg", cpu="4")],
    )
    sched = Scheduler(store, conf=default_conf("tpu"))
    assert sched.fast_cycle is not None
    sched.run_once()
    pg = store.get("PodGroup", "default/pg")
    cond = next(c for c in pg.status.conditions if c.kind == "Unschedulable")
    assert "tasks in gang unschedulable" in cond.message
    assert "insufficient cpu" in cond.message, cond.message
    evs = events.events_for(store, "PodGroup", "default/pg")
    assert any(e.reason == "Unschedulable" for e in evs)
    # steady state: the identical message must not rewrite the store
    rv = store.resource_version
    sched.run_once()
    assert store.resource_version == rv

    # capacity appears -> schedules, condition clears
    node = store.get("Node", "/n0")
    node.allocatable = node.allocatable.clone()
    node.allocatable.milli_cpu = 8000.0
    store.update("Node", node)
    sched.run_once()
    pg = store.get("PodGroup", "default/pg")
    assert not any(c.kind == "Unschedulable" for c in pg.status.conditions)


def _spy_fast(sched):
    calls = []
    orig = sched.fast_cycle.try_run

    def spy():
        r = orig()
        calls.append(r)
        return r

    sched.fast_cycle.try_run = spy
    return calls


def _dyn_store(seed):
    """mixed_store plus one host-port (resident-state-predicate) pod and a
    defined StorageClass — the partition scenario: everything express stays
    on the fast path, the dynamic job goes through the residue sub-cycle."""
    from volcano_tpu.api.objects import Metadata, StorageClass

    store = mixed_store(seed)
    p = build_pod("dyn-0", group="job0", cpu="500m")
    p.spec.host_ports = [8080]
    store.create("Pod", p)
    store.create("StorageClass", StorageClass(meta=Metadata(name="sc",
                                                            namespace="")))
    return store


@pytest.mark.parametrize("seed", range(4))
def test_partition_on_dynamic_pod_binds_equal_object_path(seed):
    """One host-port pod + a defined StorageClass must NOT evict the cycle
    from the fast path (VERDICT r2 weak #2): the express jobs solve
    array-native, the dynamic job host-solves in the residue sub-cycle,
    and the union of placements matches the pure object path."""
    conf_obj = default_conf("tpu")
    conf_obj.fast_path = "off"
    s1, fast = _binds(_dyn_store(seed), default_conf("tpu"))
    assert s1.fast_cycle is not None and s1.fast_cycle.mirror is not None
    _, obj = _binds(_dyn_store(seed), conf_obj)
    # FakeBinder.binds is {pod_key: node}: order-independent assignment map
    assert fast == obj
    assert ("default/dyn-0" in fast) == ("default/dyn-0" in obj)


def test_fast_path_survives_volume_objects():
    from volcano_tpu.api.objects import Metadata, StorageClass

    store = mixed_store(3)
    store.create("StorageClass", StorageClass(meta=Metadata(name="sc",
                                                            namespace="")))
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]  # volume objects alone never force the object path
    assert sched.cache.bind_log


def test_partition_unsafe_on_outranking_dynamic_job():
    """A dynamic job with HIGHER priority than an express contender in its
    queue must take the exact host path (device-first residue would invert
    priority under contention)."""
    from volcano_tpu.api.objects import Metadata, PriorityClass

    pg_hi = build_podgroup("hi", min_member=1, queue="default")
    pg_hi.priority_class_name = "urgent"
    store = make_store(
        nodes=[build_node("n0", cpu="2")],
        podgroups=[pg_hi,
                   build_podgroup("lo", min_member=1, queue="default")],
        pods=[],
    )
    store.create("PriorityClass", PriorityClass(
        meta=Metadata(name="urgent", namespace=""), value=10))
    hi = build_pod("hi-0", group="hi", cpu="1500m", priority=10)
    hi.spec.host_ports = [80]
    store.create("Pod", hi)
    store.create("Pod", build_pod("lo-0", group="lo", cpu="1500m",
                                  priority=0))
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [False]
    # the host path gave the contested node to the high-priority dynamic job
    assert [k for k, _ in sched.cache.bind_log] == ["default/hi-0"]


def _with_plain_pods(seed=4):
    """mixed_store plus group-less pods: one standalone, two sharing a
    controller owner, and a PodDisruptionBudget gang-ing the owned pair."""
    from volcano_tpu.api.objects import Metadata, PodDisruptionBudget

    store = mixed_store(seed)
    store.create("Pod", build_pod("plain", cpu="500m"))
    for i in range(2):
        p = build_pod(f"owned-{i}", cpu="250m", memory="256Mi")
        p.meta.owner = ("ReplicaSet", "rs-1")
        store.create("Pod", p)
    store.create("PodDisruptionBudget", PodDisruptionBudget(
        meta=Metadata(name="budget", namespace="default",
                      owner=("ReplicaSet", "rs-1")),
        min_available=2,
    ))
    return store


def test_plain_pods_stay_on_fast_path():
    """Group-less pods fold into shadow gang rows in the fast mirror
    (cache/util.go:36-60 semantics) instead of sending the whole cycle to
    the object path (VERDICT r4 missing #2); binds match the object path,
    PDB minimums included."""
    sched = Scheduler(_with_plain_pods(), conf=default_conf("tpu"))
    binder = FakeBinder()
    sched.cache.binder = binder
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    assert "default/plain" in binder.binds

    conf_obj = default_conf("tpu")
    conf_obj.fast_path = "off"
    obj = Scheduler(_with_plain_pods(), conf=conf_obj)
    obinder = FakeBinder()
    obj.cache.binder = obinder
    obj.run_once()
    assert binder.binds == obinder.binds


def test_plain_pod_snapshot_parity():
    """Field-for-field snapshot parity with the object builder when plain
    pods, owner-shadow gangs, and a PDB are present."""
    store = _with_plain_pods()
    obj = _object_snapshot(store)
    fast, aux = _fast_snapshot(store)
    # shadow rows sort last, in the same order (real jobs key by pg key on
    # the fast path vs pg uid on the object path — documented divergence)
    assert fast.job_uids[-2:] == obj.job_uids[-2:]
    assert all(u.startswith("shadow/") for u in fast.job_uids[-2:])
    for field in (
        "node_used", "node_idle", "node_task_count",
        "task_req", "task_job", "task_valid",
        "job_queue", "job_min_available", "job_priority", "job_ready_init",
        "job_alloc_init", "job_schedulable", "job_start", "job_ntasks",
        "queue_alloc_init", "queue_request", "queue_participates",
    ):
        np.testing.assert_array_equal(
            getattr(fast, field), getattr(obj, field), err_msg=field
        )


def test_pdb_gang_blocks_partial_placement_on_fast_path():
    """A PDB-configured shadow gang that cannot fully fit publishes
    nothing (gang semantics over plain pods) — and the cycle still runs
    on the fast path."""
    from volcano_tpu.api.objects import Metadata, PodDisruptionBudget

    store = make_store(
        nodes=[build_node("n0", cpu="2", memory="4Gi")],
        queues=[build_queue("default")],
        podgroups=[], pods=[],
    )
    store.create("PodDisruptionBudget", PodDisruptionBudget(
        meta=Metadata(name="budget", namespace="default",
                      owner=("ReplicaSet", "rs-b")),
        min_available=3,
    ))
    for i in range(3):  # 3 x 1cpu, only 2 fit
        p = build_pod(f"g{i}", cpu="1", memory="1Gi")
        p.meta.owner = ("ReplicaSet", "rs-b")
        store.create("Pod", p)
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    assert not sched.cache.bind_log

    # budget deleted -> the gang reverts to MinMember 1, pods bind singly
    store.delete("PodDisruptionBudget", "default/budget")
    sched.run_once()
    assert len(sched.cache.bind_log) == 2


def test_preempt_runs_as_object_subcycle_after_fast_passes():
    """Running evictable victims + a starving job in the same queue: the
    fast passes still run (allocate stays array-native) and the object
    preempt machinery takes over for the starving tail — victims are
    evicted and the preemptor pipelines, matching the object-path cycle."""
    def mk_store():
        nodes = [build_node("n0", cpu="2", memory="4Gi")]
        pg_run = build_podgroup("rich", min_member=1, queue="default")
        pods = []
        for t in range(2):
            p = build_pod(f"rich-{t}", group="rich", cpu="1", memory="1Gi")
            p.node_name = "n0"
            p.phase = PodPhase.RUNNING
            pods.append(p)
        pg_poor = build_podgroup("poor", min_member=1, queue="default")
        pods.append(build_pod("poor-0", group="poor", cpu="1", memory="1Gi",
                              priority=10))
        return make_store(nodes=nodes, podgroups=[pg_run, pg_poor],
                          pods=pods)

    sched = Scheduler(mk_store(), conf=full_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    fast_evicts = sorted(sched.cache.evict_log)
    assert fast_evicts, "preempt sub-cycle evicted nothing"

    conf_obj = full_conf("tpu")
    conf_obj.fast_path = "off"
    obj = Scheduler(mk_store(), conf=conf_obj)
    obj.run_once()
    assert fast_evicts == sorted(obj.cache.evict_log)


def test_full_conf_fast_when_no_preempt_work():
    """Full 5-action conf on a fresh cluster (no residents): prechecks
    prove preempt/reclaim vacuous and the fast path serves the cycle."""
    store = mixed_store(5, running_jobs=0)
    sched = Scheduler(store, conf=full_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    assert sched.cache.bind_log


def test_fast_enqueue_admits_pending_groups():
    store = make_store(
        nodes=[build_node("n0", cpu="4", memory="8Gi")],
        podgroups=[build_podgroup("pg", min_member=1,
                                  phase=PodGroupPhase.PENDING)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(2)],
    )
    conf = full_conf("tpu")
    sched = Scheduler(store, conf=conf)
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    assert len(sched.cache.bind_log) == 2  # enqueued AND scheduled in one cycle
    pg = store.get("PodGroup", "default/pg")
    assert pg.status.phase == PodGroupPhase.RUNNING


def test_fast_backfill_places_best_effort():
    store = make_store(
        nodes=[build_node("n0", cpu="1", memory="2Gi")],
        podgroups=[build_podgroup("pg", min_member=2)],
        pods=[
            build_pod("p0", group="pg", cpu="1"),
            build_pod("be-0", group="pg", cpu="0", memory="0"),
        ],
    )
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    binds = dict(sched.cache.bind_log)
    assert binds == {"default/p0": "n0", "default/be-0": "n0"}


# -- static predicate classes on the fast path -------------------------------

def classy_store(seed=0):
    """Selectors, node affinity, tolerations, tainted/cordoned nodes —
    everything the class system expresses."""
    import random

    from volcano_tpu.api.objects import Affinity, Taint, Toleration

    rng = random.Random(seed)
    nodes = []
    for i in range(6):
        n = build_node(f"n{i:02d}", cpu="8", memory="16Gi",
                       labels={"zone": "a" if i % 2 else "b",
                               "disk": "ssd" if i < 3 else "hdd"})
        if i == 4:
            n.taints.append(Taint(key="dedicated", value="infra",
                                  effect="NoSchedule"))
        if i == 5:
            n.unschedulable = True
        nodes.append(n)
    queues = [build_queue("default")]
    podgroups, pods = [], []
    for j in range(6):
        n_tasks = rng.randint(1, 3)
        podgroups.append(build_podgroup(f"job{j}", min_member=1))
        for t in range(n_tasks):
            pod = build_pod(f"job{j}-{t}", group=f"job{j}",
                            cpu=rng.choice(["500m", "1"]),
                            priority=rng.choice([0, 5]))
            if j % 3 == 0:
                pod.spec.node_selector = {"zone": "a"}
            elif j % 3 == 1:
                pod.spec.affinity = Affinity(
                    node_terms=[[("disk", "In", ("ssd",))]],
                    preferred_node_terms=[(7, [("zone", "In", ("a",))])],
                )
            else:
                pod.spec.tolerations = [
                    Toleration(key="dedicated", operator="Equal",
                               value="infra", effect="NoSchedule")
                ]
            pods.append(pod)
    return make_store(nodes=nodes, queues=queues, podgroups=podgroups,
                      pods=pods)


@pytest.mark.parametrize("seed", range(4))
def test_fast_snapshot_class_parity(seed):
    """Per-class masks/scores and task class indices match the object
    builder exactly for selector/affinity/toleration workloads."""
    store = classy_store(seed)
    obj = _object_snapshot(store)
    fast, aux = _fast_snapshot(store)
    for field in ("task_class", "class_node_mask", "class_node_score",
                  "task_req", "task_job", "job_start", "job_ntasks"):
        np.testing.assert_array_equal(
            getattr(fast, field), getattr(obj, field), err_msg=field
        )


@pytest.mark.parametrize("seed", range(4))
def test_fast_cycle_classy_binds_equal_object_path(seed):
    conf_obj = default_conf("tpu")
    conf_obj.fast_path = "off"
    s1, fast = _binds(classy_store(seed), default_conf("tpu"))
    assert s1.fast_cycle is not None and s1.fast_cycle.mirror is not None
    _, obj = _binds(classy_store(seed), conf_obj)
    assert fast == obj


def test_fast_cycle_class_cache_tracks_node_relabel():
    """A node label change must invalidate its class cells: a selector job
    that could not fit starts fitting after the relabel."""
    store = make_store(
        nodes=[build_node("n0", labels={"zone": "b"})],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg", cpu="1")],
    )
    store.get("Pod", "default/p0").spec.node_selector = {"zone": "a"}
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True] and not sched.cache.bind_log
    node = store.get("Node", "/n0")
    node.labels = {"zone": "a"}
    store.update("Node", node)
    sched.run_once()
    assert dict(sched.cache.bind_log) == {"default/p0": "n0"}


def test_fast_backfill_respects_classes():
    """Best-effort tasks only land on nodes passing their own class."""
    store = make_store(
        nodes=[build_node("n0", labels={"zone": "b"}),
               build_node("n1", labels={"zone": "a"})],
        podgroups=[build_podgroup("pg", min_member=1)],
        pods=[build_pod("p0", group="pg", cpu="1")],
    )
    be = build_pod("be0", group="pg", cpu="0", memory="0")
    be.spec.node_selector = {"zone": "a"}
    store.create("Pod", be)
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()
    assert calls == [True]
    assert dict(sched.cache.bind_log)["default/be0"] == "n1"


def test_class_cap_overflow_falls_back_not_recurses(monkeypatch):
    """Live classes beyond the cap must flag ineligibility (object path),
    not recurse through resyncs."""
    from volcano_tpu.scheduler import fastpath as fp

    monkeypatch.setattr(fp.ArrayMirror, "_MAX_CLASSES", 8)
    nodes = [build_node("n0", labels={"zone": "a"})]
    podgroups = [build_podgroup("pg", min_member=1)]
    pods = []
    for i in range(12):
        p = build_pod(f"p{i}", group="pg", cpu="100m", memory="64Mi")
        p.spec.node_selector = {"zone": "a", f"k{i}": "v"}  # distinct keys
        pods.append(p)
    for n in nodes:
        n.labels.update({f"k{i}": "v" for i in range(12)})
    store = make_store(nodes=nodes, podgroups=podgroups, pods=pods)
    sched = Scheduler(store, conf=default_conf("tpu"))
    calls = _spy_fast(sched)
    sched.run_once()  # must terminate, not RecursionError
    assert calls == [False]
    assert sched.fast_cycle.mirror.ineligible_reason() == (
        "predicate class cap exceeded"
    )
    assert len(sched.cache.bind_log) == 12  # object path scheduled them


def test_non_canonical_action_order_takes_object_path():
    """The fast passes assume enqueue->reclaim->allocate->backfill->preempt;
    any other conf order must run the object path (literal conf order)."""
    conf = full_conf("tpu")
    conf.actions = ["enqueue", "preempt", "allocate", "backfill"]
    sched = Scheduler(mixed_store(0), conf=conf)
    assert not sched.fast_cycle.conf_ok
    sched.run_once()
    assert sched.cache.bind_log  # object path still scheduled


def test_leadership_loss_resyncs_mirror():
    """A deposed leader drops its queued decisions (abort_pending) — the
    fast mirror's optimistic BOUND rows and status fingerprints must
    resync from the store so re-election schedules those pods again."""
    from volcano_tpu.leader import LeaderElector

    # takeovers use delete/release, never expiry; the clock still ADVANCES
    # (in sub-lease-duration hops) so the deposed leader's candidate-retry
    # backoff window (leader.py) elapses between elections
    now = [0.0]
    clock = lambda: now[0]
    store = make_store(
        nodes=[build_node("n0")],
        podgroups=[build_podgroup("pg", min_member=2)],
        pods=[build_pod(f"p{i}", group="pg", cpu="1") for i in range(2)],
    )
    conf = default_conf("tpu")
    conf.apply_mode = "async"
    sched = Scheduler(store, conf=conf,
                      elector=LeaderElector(store, "s", "a", clock=clock))
    # stop the applier thread so published decisions stay queued
    applier = sched.cache.applier
    applier.stop(flush=False)
    sched.run_once()  # leads, publishes 2 binds into the (dead) queue
    assert applier.pending >= 2
    m = sched.fast_cycle.mirror
    import volcano_tpu.scheduler.fastpath as fp

    assert (m.p_status[: 2] == fp._BOUND).all()  # optimistic rows

    # lease stolen: next run_once aborts the queue and resyncs the mirror
    store.delete("Lease", "/s")
    other = LeaderElector(store, "s", "b", clock=clock)
    assert other.try_acquire()
    sched.run_once()
    assert applier.pending == 0
    assert (m.p_status[: 2] == fp._PENDING).all()  # store truth restored

    # lease released -> re-election -> pods scheduled again
    other.release()
    now[0] += 10.0  # past the deposed leader's retry backoff, below expiry
    sched.cache.applier = None  # dead thread; bind synchronously now
    sched.run_once()
    assert sorted(k for k, _ in sched.cache.bind_log[2:]) == [
        "default/p0", "default/p1",
    ]
