"""The three binaries + CLI as real OS processes (reference process model).

Spawns `python -m volcano_tpu.cli apiserver/controller/scheduler/kubelet`
as subprocesses and drives a job to Running with `vtctl --server job run`,
mirroring how the reference e2e shells out to the real vkctl binary
(test/e2e/cli_util.go) against a live control plane.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

ENTRY = [sys.executable, "-m", "volcano_tpu.cli"]


def _spawn(args, **kw):
    return subprocess.Popen(
        ENTRY + args,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        **kw,
    )


def _vtctl(args, check=True):
    r = subprocess.run(
        ENTRY + args, capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if check and r.returncode != 0:
        raise AssertionError(f"vtctl {args} failed: {r.stdout} {r.stderr}")
    return r.stdout


@pytest.mark.slow
def test_daemon_processes_run_job_end_to_end(tmp_path):
    procs = []
    try:
        api = _spawn(["apiserver", "--port", "0"])
        procs.append(api)
        line = api.stdout.readline().strip()
        assert "listening on" in line, line
        url = line.rsplit(" ", 1)[-1]

        metrics_url = ""
        for comp in ("controller", "scheduler", "kubelet"):
            extra = (["--period", "0.1", "--metrics-port", "0"]
                     if comp == "scheduler" else ["--period", "0.05"])
            p = _spawn([comp, "--server", url] + extra)
            procs.append(p)
            assert url in p.stdout.readline()
            if comp == "scheduler":
                line = p.stdout.readline()
                assert "/metrics" in line, line
                metrics_url = line.strip().rsplit(" ", 1)[-1]

        _vtctl(["--server", url, "cluster", "init", "--nodes", "2"])
        _vtctl(["--server", url, "job", "run", "--name", "procjob",
                "--replicas", "2", "--min", "2"])

        deadline = time.monotonic() + 120
        table = ""
        while time.monotonic() < deadline:
            table = _vtctl(["--server", url, "job", "list"])
            row = next((ln for ln in table.splitlines() if ln.startswith("procjob")), "")
            if "Running" in row:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"job never ran; last table:\n{table}")

        # suspend -> Aborted, resume -> Running again (command.go round-trip)
        _vtctl(["--server", url, "job", "suspend", "--name", "procjob"])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if "Aborted" in _vtctl(["--server", url, "job", "list"]):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("job never aborted after suspend")

        _vtctl(["--server", url, "job", "resume", "--name", "procjob"])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if "Running" in _vtctl(["--server", url, "job", "list"]):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("job never resumed")

        # the scheduler daemon serves the reference's Prometheus series
        import urllib.request

        body = urllib.request.urlopen(metrics_url, timeout=10).read().decode()
        assert "volcano_e2e_scheduling_latency_milliseconds" in body

        # volume binding over the wire: StorageClass/PV/PVC round-trip
        # through the HTTP store codec, scheduler pins the job to the PV's
        # node, claim binds
        from volcano_tpu.api.job import Job, JobSpec, TaskSpec, VolumeSpec
        from volcano_tpu.api.objects import Metadata, PersistentVolume, PodSpec, StorageClass
        from volcano_tpu.api.resource import Resource
        from volcano_tpu.store.client import RemoteStore

        rs = RemoteStore(url)
        rs.create("StorageClass", StorageClass(
            meta=Metadata(name="local", namespace=""), provisioner=""))
        rs.create("PV", PersistentVolume(
            meta=Metadata(name="disk1", namespace=""), capacity="20Gi",
            storage_class="local",
            node_affinity={"kubernetes.io/hostname": "node-1"}))
        rs.create("Job", Job(
            meta=Metadata(name="voljob", namespace="default"),
            spec=JobSpec(
                min_available=1,
                tasks=[TaskSpec(name="main", replicas=1,
                                template=PodSpec(image="busybox",
                                    resources=Resource.from_resource_list(
                                    {"cpu": "1", "memory": "1Gi"})))],
                volumes=[VolumeSpec(mount_path="/x", size="5Gi",
                                    storage_class="local")],
                queue="default",
            )))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            pvc = rs.get("PVC", "default/voljob-pvc-0")
            if pvc is not None and pvc.phase == "Bound":
                break
            time.sleep(0.2)
        else:
            raise AssertionError("volume claim never bound over the wire")
        assert pvc.volume_name == "disk1"
        vol_pods = [p for p in rs.list("Pod") if "voljob" in p.meta.name]
        assert vol_pods and all(p.node_name == "node-1" for p in vol_pods)

        # admission over the wire: bad job rejected by the server
        out = subprocess.run(
            ENTRY + ["--server", url, "job", "run", "--name", "bad",
                     "--replicas", "1", "--min", "5"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 1 and "minAvailable" in out.stderr

    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_apiserver_restart_with_durable_state(tmp_path):
    """Kill the apiserver mid-workload and restart it from its --state file
    (the etcd-persistence analogue): the running job survives, the live
    daemons ride out the outage and relist, and new work schedules."""
    state = str(tmp_path / "state.json")
    procs = []
    try:
        api = _spawn(["apiserver", "--port", "0", "--state", state])
        procs.append(api)
        url = api.stdout.readline().strip().rsplit(" ", 1)[-1]
        port = url.rsplit(":", 1)[-1]
        for comp in ("controller", "scheduler", "kubelet"):
            extra = (["--period", "0.1", "--metrics-port", "0"]
                     if comp == "scheduler" else ["--period", "0.05"])
            p = _spawn([comp, "--server", url] + extra)
            procs.append(p)
            p.stdout.readline()
            if comp == "scheduler":
                p.stdout.readline()

        _vtctl(["--server", url, "cluster", "init", "--nodes", "2"])
        _vtctl(["--server", url, "job", "run", "--name", "durable",
                "--replicas", "2", "--min", "2"])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if "Running" in _vtctl(["--server", url, "job", "list"]):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("job never ran")

        api.send_signal(signal.SIGTERM)
        api.wait(timeout=10)
        time.sleep(1)  # daemons hit the outage path

        api2 = _spawn(["apiserver", "--port", port, "--state", state])
        procs.append(api2)
        assert "listening" in api2.stdout.readline()

        deadline = time.monotonic() + 120
        table = ""
        while time.monotonic() < deadline:
            table = _vtctl(["--server", url, "job", "list"])
            if "durable" in table and "Running" in table:
                break
            time.sleep(0.3)
        else:
            raise AssertionError(f"job lost after apiserver restart:\n{table}")

        _vtctl(["--server", url, "job", "run", "--name", "after",
                "--replicas", "1", "--min", "1"])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            t = _vtctl(["--server", url, "job", "list"])
            row = next((ln for ln in t.splitlines() if ln.startswith("after")), "")
            if "Running" in row:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("post-restart job never scheduled")
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_vtctl_up_one_command_control_plane(tmp_path):
    """`vtctl up` brings up the 4-daemon control plane with health checks
    (VERDICT r1 next #7 — the installer/ analogue); a gang job submitted
    against it reaches Running; `vtctl down` stops everything."""
    pidfile = str(tmp_path / "up.json")
    up = _spawn(["up", "--port", "0", "--detach", "--pidfile", pidfile,
                 "--state", str(tmp_path / "state.json")])
    try:
        url = ""
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = up.stdout.readline()
            if not line:
                break
            if "control plane up" in line:
                url = line.split("vtctl --server ", 1)[1].split()[0]
                break
        assert url, "vtctl up never reported readiness"
        assert up.wait(timeout=30) == 0  # detached: returns after startup

        _vtctl(["--server", url, "cluster", "init", "--nodes", "2"])
        _vtctl(["--server", url, "job", "run", "--name", "upjob",
                "--replicas", "3", "--min", "3"])
        deadline = time.monotonic() + 120
        table = ""
        while time.monotonic() < deadline:
            table = _vtctl(["--server", url, "job", "list"])
            row = next(
                (ln for ln in table.splitlines() if ln.startswith("upjob")),
                "",
            )
            if "Running" in row:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"job never ran; last table:\n{table}")

        out = _vtctl(["down", "--pidfile", pidfile])
        assert "stopped" in out
        # apiserver really gone
        import json as _json
        import urllib.request

        with pytest.raises(Exception):
            urllib.request.urlopen(url + "/rv", timeout=2)
    finally:
        if up.poll() is None:
            up.terminate()
        subprocess.run(ENTRY + ["down", "--pidfile", pidfile],
                       capture_output=True, text=True)


@pytest.mark.slow
def test_vtctl_up_tpu_backend_schedules(tmp_path):
    """The deployed default — tpu backend + fast cycle over RemoteStore —
    drives a gang job to Running through real processes (this exact path
    once hid a wire-codec bug the host-backend test could not see)."""
    pidfile = str(tmp_path / "up.json")
    env = {**os.environ, "VOLCANO_TPU_BACKEND": "tpu",
           "VOLCANO_TPU_XLA_CACHE": str(tmp_path / "xla")}
    up = subprocess.Popen(
        ENTRY + ["up", "--port", "0", "--detach", "--pidfile", pidfile],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        url = ""
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = up.stdout.readline()
            if not line:
                break
            if "control plane up" in line:
                url = line.split("vtctl --server ", 1)[1].split()[0]
                break
        assert url, "vtctl up never reported readiness"
        assert up.wait(timeout=30) == 0

        _vtctl(["--server", url, "cluster", "init", "--nodes", "2"])
        _vtctl(["--server", url, "job", "run", "--name", "tpujob",
                "--replicas", "2", "--min", "2"])
        # generous deadline: the scheduler subprocess compiles its solves
        # in prewarm before the first cycle
        deadline = time.monotonic() + 240
        table = ""
        while time.monotonic() < deadline:
            table = _vtctl(["--server", url, "job", "list"])
            row = next(
                (ln for ln in table.splitlines() if ln.startswith("tpujob")),
                "",
            )
            if "Running" in row:
                break
            time.sleep(0.5)
        else:
            log = open(pidfile + ".log").read()[-2000:]
            raise AssertionError(
                f"job never ran; table:\n{table}\nlog tail:\n{log}"
            )
    finally:
        if up.poll() is None:
            up.terminate()
        subprocess.run(ENTRY + ["down", "--pidfile", pidfile],
                       capture_output=True, text=True)
