"""Host-vs-tensor parity for preempt/reclaim victim selection.

The tensor path (victim_kernels.victim_step driven by tensor_actions)
must produce the same evictions/pipelines as the host object path for
identical snapshots (BASELINE config 4 semantics).
"""

import numpy as np
import pytest

from volcano_tpu.api.objects import Metadata, PriorityClass
from volcano_tpu.api.types import PodPhase
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import (
    FakeBinder,
    FakeEvictor,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)


def run_both(make_store_fn, actions):
    """Run host/tpu/native backends; assert tpu AND native match host and
    return (host, tpu) for the per-test shape assertions. When the native
    library is unavailable its backend falls back to the host path, so the
    comparison stays meaningful either way."""
    logs = {}
    for backend in ("host", "tpu", "native"):
        store = make_store_fn()
        conf = default_conf(backend=backend)
        conf.actions = list(actions)
        sched = Scheduler(store, conf=conf)
        binder, evictor = FakeBinder(), FakeEvictor()
        sched.cache.binder = binder
        sched.cache.evictor = evictor
        sched.run_once()
        logs[backend] = (dict(binder.binds), sorted(evictor.evicts))
    assert logs["native"] == logs["host"], "native backend diverged from host"
    return logs["host"], logs["tpu"]


def _priority_classes(store):
    store.create("PriorityClass", PriorityClass(Metadata(name="low", namespace=""), value=1))
    store.create("PriorityClass", PriorityClass(Metadata(name="high", namespace=""), value=100))


def test_preempt_parity_simple():
    def build():
        pg_low = build_podgroup("pg-low", min_member=1)
        pg_high = build_podgroup("pg-high", min_member=1)
        pg_high.priority_class_name = "high"
        store = make_store(
            nodes=[build_node("n0", cpu="2", memory="4Gi")],
            podgroups=[pg_low, pg_high],
            pods=[
                build_pod("low-0", group="pg-low", cpu="1", phase=PodPhase.RUNNING,
                          node_name="n0", priority=1),
                build_pod("low-1", group="pg-low", cpu="1", phase=PodPhase.RUNNING,
                          node_name="n0", priority=1),
                build_pod("high-0", group="pg-high", cpu="1", priority=100),
            ],
        )
        _priority_classes(store)
        return store

    host, tpu = run_both(build, ["preempt"])
    assert host == tpu
    assert len(tpu[1]) == 1


def test_preempt_parity_gang_blocked():
    # victim job's gang protects both pods -> statement discard on both paths
    def build():
        pg_low = build_podgroup("pg-low", min_member=2)
        pg_high = build_podgroup("pg-high", min_member=1)
        pg_high.priority_class_name = "high"
        store = make_store(
            nodes=[build_node("n0", cpu="2", memory="4Gi")],
            podgroups=[pg_low, pg_high],
            pods=[
                build_pod("low-0", group="pg-low", cpu="1", phase=PodPhase.RUNNING,
                          node_name="n0", priority=1),
                build_pod("low-1", group="pg-low", cpu="1", phase=PodPhase.RUNNING,
                          node_name="n0", priority=1),
                build_pod("high-0", group="pg-high", cpu="1", priority=100),
            ],
        )
        _priority_classes(store)
        return store

    host, tpu = run_both(build, ["preempt"])
    assert host == tpu
    assert tpu[1] == []


def test_preempt_parity_multi_node_gang():
    def build():
        pg_low = build_podgroup("pg-low", min_member=1)
        pg_high = build_podgroup("pg-high", min_member=2)
        pg_high.priority_class_name = "high"
        pods = []
        for i in range(2):
            for j in range(2):
                pods.append(
                    build_pod(f"low-{i}-{j}", group="pg-low", cpu="1",
                              phase=PodPhase.RUNNING, node_name=f"n{i}", priority=1)
                )
        pods += [build_pod(f"high-{k}", group="pg-high", cpu="2", priority=100)
                 for k in range(2)]
        store = make_store(
            nodes=[build_node(f"n{i}", cpu="2", memory="4Gi") for i in range(2)],
            podgroups=[pg_low, pg_high],
            pods=pods,
        )
        _priority_classes(store)
        return store

    host, tpu = run_both(build, ["preempt"])
    assert host == tpu
    assert len(tpu[1]) == 4


def test_reclaim_parity():
    def build():
        pods = []
        for i in range(2):
            for j in range(2):
                pods.append(
                    build_pod(f"q1-{i}-{j}", group="pg-q1", cpu="1",
                              phase=PodPhase.RUNNING, node_name=f"n{i}")
                )
        pods.append(build_pod("q2-0", group="pg-q2", cpu="1"))
        return make_store(
            nodes=[build_node(f"n{i}", cpu="2", memory="4Gi") for i in range(2)],
            queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
            podgroups=[
                build_podgroup("pg-q1", min_member=1, queue="q1"),
                build_podgroup("pg-q2", min_member=1, queue="q2"),
            ],
            pods=pods,
        )

    host, tpu = run_both(build, ["reclaim"])
    assert host == tpu
    assert len(tpu[1]) == 1


def test_preempt_parity_conformance_protects_critical():
    def build():
        pg_low = build_podgroup("pg-low", min_member=1)
        pg_high = build_podgroup("pg-high", min_member=1)
        pg_high.priority_class_name = "high"
        critical = build_pod("crit-0", group="pg-low", cpu="1",
                             phase=PodPhase.RUNNING, node_name="n0", priority=1)
        critical.spec.priority_class = "system-cluster-critical"
        store = make_store(
            nodes=[build_node("n0", cpu="2", memory="4Gi")],
            podgroups=[pg_low, pg_high],
            pods=[
                critical,
                build_pod("low-1", group="pg-low", cpu="1", phase=PodPhase.RUNNING,
                          node_name="n0", priority=1),
                build_pod("high-0", group="pg-high", cpu="2", priority=100),
            ],
        )
        _priority_classes(store)
        return store

    def run(backend):
        store = build()
        conf = full_conf(backend=backend)  # includes conformance
        conf.actions = ["preempt"]
        sched = Scheduler(store, conf=conf)
        evictor = FakeEvictor()
        sched.cache.evictor = evictor
        sched.run_once()
        return sorted(evictor.evicts)

    host, tpu = run("host"), run("tpu")
    assert host == tpu
    assert run("native") == host
    # the 2-cpu preemptor needs both pods; the critical one is protected,
    # so the single admissible victim cannot cover -> nothing evicts
    assert tpu == []


def test_reclaim_parity_same_tier_gang_proportion_intersection():
    # gang and proportion in ONE tier: vetoes intersect, and proportion's
    # hypothetical subtraction must run over every preemptee (including
    # gang-vetoed ones) — the host plugins subtract before any intersection.
    from volcano_tpu.scheduler.conf import PluginOption, SchedulerConf, Tier

    def build():
        pods = [
            # q1 job A: gang needs both -> gang vetoes its pods
            build_pod("a-0", group="pg-a", cpu="1", phase=PodPhase.RUNNING, node_name="n0"),
            build_pod("a-1", group="pg-a", cpu="1", phase=PodPhase.RUNNING, node_name="n0"),
            # q1 job B: single, gang-evictable
            build_pod("b-0", group="pg-b", cpu="1", phase=PodPhase.RUNNING, node_name="n0"),
            # q2 pending reclaimer
            build_pod("q2-0", group="pg-q2", cpu="1"),
        ]
        return make_store(
            nodes=[build_node("n0", cpu="4", memory="8Gi")],
            queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
            podgroups=[
                build_podgroup("pg-a", min_member=2, queue="q1"),
                build_podgroup("pg-b", min_member=1, queue="q1"),
                build_podgroup("pg-q2", min_member=1, queue="q2"),
            ],
            pods=pods,
        )

    results = {}
    for backend in ("host", "tpu", "native"):
        store = build()
        conf = SchedulerConf(
            actions=["reclaim"],
            tiers=[Tier(plugins=[PluginOption("gang"), PluginOption("proportion")])],
            backend=backend,
        )
        sched = Scheduler(store, conf=conf)
        evictor = FakeEvictor()
        sched.cache.evictor = evictor
        sched.run_once()
        results[backend] = sorted(evictor.evicts)
    assert results["host"] == results["tpu"]
    assert results["native"] == results["host"]


def test_preempt_parity_best_effort_preemptor_takes_one_victim():
    """An empty-request preemptor: the host DO-while loop evicts exactly
    one victim before its (trivially satisfied) cover check; the tensor
    and native kernels must reproduce that, not zero victims (the old
    while-shaped prefix) — 3-way parity through the real action."""
    def build():
        pg_low = build_podgroup("pg-low", min_member=1)
        pg_high = build_podgroup("pg-high", min_member=1)
        pg_high.priority_class_name = "high"
        store = make_store(
            nodes=[build_node("n0", cpu="2", memory="4Gi")],
            podgroups=[pg_low, pg_high],
            pods=[],
        )
        p = build_pod("low-0", group="pg-low", cpu="1", memory="1Gi",
                      priority=1)
        p.node_name = "n0"
        p.phase = PodPhase.RUNNING
        store.create("Pod", p)
        store.create("Pod", build_pod("hi-be", group="pg-high", cpu="0", memory="0", priority=100))
        _priority_classes(store)
        return store

    # no backfill in the conf: a feasible node would otherwise backfill
    # the BE task before preempt ever attempts it
    host, tpu = run_both(build, ["enqueue", "allocate", "preempt"])
    assert tpu == host
    assert len(host[1]) == 1, host  # exactly one victim


@pytest.mark.parametrize("seed", list(range(8)))
def test_victim_parity_random_clusters(seed):
    rng = np.random.default_rng(seed)

    def build():
        n_nodes = int(rng.integers(2, 5))
        n_queues = int(rng.integers(1, 3))
        queues = [build_queue(f"q{q}", weight=int(rng.integers(1, 4)))
                  for q in range(n_queues)]
        nodes = [build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(n_nodes)]
        pods, pgs = [], []
        # running jobs occupying the cluster (capacity-aware: a node may
        # never be oversubscribed — NodeInfo.add_task faults on that, the
        # reference's Resource.Sub panic)
        free = {f"n{i}": 4 for i in range(n_nodes)}
        for j in range(int(rng.integers(1, 4))):
            q = f"q{int(rng.integers(0, n_queues))}"
            pgs.append(build_podgroup(f"pg-run-{j}", min_member=1, queue=q))
            for k in range(int(rng.integers(1, 4))):
                node = f"n{int(rng.integers(0, n_nodes))}"
                cpu = int(rng.integers(1, 3))
                if free[node] < cpu:
                    continue
                free[node] -= cpu
                pods.append(
                    build_pod(f"run-{j}-{k}", group=f"pg-run-{j}",
                              cpu=str(cpu),
                              phase=PodPhase.RUNNING, node_name=node,
                              priority=int(rng.integers(0, 3)))
                )
        # pending high-priority jobs
        for j in range(int(rng.integers(1, 3))):
            q = f"q{int(rng.integers(0, n_queues))}"
            pg = build_podgroup(f"pg-pend-{j}", min_member=int(rng.integers(1, 3)),
                                queue=q)
            pg.priority_class_name = "high"
            pgs.append(pg)
            for k in range(int(rng.integers(1, 4))):
                pods.append(
                    build_pod(f"pend-{j}-{k}", group=f"pg-pend-{j}",
                              cpu=str(int(rng.integers(1, 3))), priority=100)
                )
        store = make_store(nodes=nodes, queues=queues, podgroups=pgs, pods=pods)
        _priority_classes(store)
        return store

    # odd seeds run the full five-action pipeline so victim selection is
    # exercised against allocate/backfill interleaving too
    actions = (
        ["enqueue", "reclaim", "allocate", "backfill", "preempt"]
        if seed % 2
        else ["reclaim", "preempt"]
    )
    # freeze the generated cluster: build once, snapshot the RNG state by
    # rebuilding from the same seed for each backend
    states = []
    for backend in ("host", "tpu", "native"):
        rng = np.random.default_rng(seed)
        store = build()
        conf = default_conf(backend=backend)
        conf.actions = actions
        sched = Scheduler(store, conf=conf)
        binder, evictor = FakeBinder(), FakeEvictor()
        sched.cache.binder = binder
        sched.cache.evictor = evictor
        sched.run_once()
        states.append((dict(binder.binds), sorted(evictor.evicts)))
    assert states[0] == states[1]
    assert states[2] == states[0], "native backend diverged from host"
