"""Multi-cycle churn soak on the tpu backend: random arrivals, failures,
completions, and node relabels over many scheduler cycles, with global
invariants checked after every step.

This exercises what single-scenario tests cannot: the persistent
SnapshotCache across epoch rolls, gang re-admission after failures, and the
interleaving of enqueue/reclaim/allocate/backfill/preempt under churn.

The churn runs one queue and no preempt action, because kube-batch v0
genuinely livelocks under sustained contention — reproduced here on our
faithful implementation, in two distinct ways:
  * preempt's victim filter has NO priority comparison, and the tier-1
    preemptable vetoes of the deployed config are gang-only (the priority
    plugin registers no preemptable callback), so two min=1 gangs evict
    each other every cycle regardless of priority (preempt.go:195-243);
  * cross-queue reclaim: Reclaimable dispatch is first-tier-wins
    (session_plugins.go:79) and the deployed config's tier 1 is
    priority/gang/conformance, so proportion's deserved-share veto in
    tier 2 is dead — two queues contending over capacity reclaim the same
    pod back and forth forever.
The reference schedules in endless 1s cycles, so this thrash is ambient
there; our sim's quiescence check surfaces it. Preempt/reclaim
correctness is covered by the dedicated parity suites on bounded
scenarios.

Invariants (the reference enforces these structurally — Resource.Sub
panics on oversubscription, gang counts via TaskStatusIndex):
  * no node is ever oversubscribed by resident pod requests;
  * every Running job has at least min_available running pods.
(Selector fit is asserted by the predicate suites; it is not a steady-state
invariant here because node relabels legitimately strand resident pods on
nodes their selector no longer matches — kubernetes does not evict on
label change.)
"""

import numpy as np
import pytest

from volcano_tpu.api.job import JOB_NAME_KEY, Job, JobSpec, LifecyclePolicy, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase, PodPhase
from volcano_tpu.scheduler.conf import full_conf
from volcano_tpu.sim import Cluster


def check_invariants(c: Cluster):
    nodes = {n.meta.name: n for n in c.store.list("Node")}
    used = {name: Resource() for name in nodes}
    for pod in c.store.list("Pod"):
        if not pod.node_name or pod.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
            continue
        used[pod.node_name].add(pod.spec.resources)
    for name, u in used.items():
        assert u.less_equal(nodes[name].allocatable), f"node {name} oversubscribed"

    running = {p.meta.key for p in c.store.list("Pod") if p.phase == PodPhase.RUNNING}
    for job in c.store.list("Job"):
        if job.status.state.phase == JobPhase.RUNNING:
            n_running = sum(
                1 for p in c.store.list("Pod")
                if p.meta.annotations.get(JOB_NAME_KEY) == job.meta.name
                and p.meta.key in running
            )
            assert n_running >= min(job.spec.min_available, 1), job.meta.name


@pytest.mark.slow
def test_churn_soak_tpu_backend():
    rng = np.random.default_rng(7)
    conf = full_conf("tpu")
    conf.actions = ["enqueue", "reclaim", "allocate", "backfill"]
    c = Cluster(scheduler_conf=conf)
    c.add_queue("default", weight=1)
    for i in range(6):
        c.add_node(f"n{i}", {"cpu": "8", "memory": "16Gi", "pods": 110},
                   labels={"zone": f"z{i % 2}"})
    for k in range(30):
        c.add_priority_class(f"p{k}", value=10 * (k + 1))

    live_jobs = []
    for step in range(30):
        action = rng.random()
        if action < 0.45 or not live_jobs:
            name = f"j{step}"
            replicas = int(rng.integers(1, 4))
            tmpl = PodSpec(image="busybox",
                           resources=Resource.from_resource_list(
                {"cpu": str(int(rng.integers(1, 3))), "memory": "1Gi"}))
            if rng.random() < 0.4:
                tmpl.node_selector = {"zone": f"z{int(rng.integers(0, 2))}"}
            job = Job(
                meta=Metadata(name=name, namespace="soak"),
                spec=JobSpec(
                    min_available=replicas,
                    tasks=[TaskSpec(name="w", replicas=replicas, template=tmpl)],
                    policies=[LifecyclePolicy(event=JobEvent.POD_FAILED,
                                              action=JobAction.RESTART_JOB)],
                    queue="default",
                    max_retry=5,
                    priority_class=f"p{step}",
                ),
            )
            c.store.create("Job", job)
            live_jobs.append(name)
        elif action < 0.65:
            # fail a random running pod (policy restarts its job)
            pods = [p for p in c.store.list("Pod") if p.phase == PodPhase.RUNNING]
            if pods:
                c.fail_pod(pods[int(rng.integers(0, len(pods)))].meta.key,
                           exit_code=137)
        elif action < 0.8:
            # complete every pod of a random running job
            names = [j.meta.name for j in c.store.list("Job")
                     if j.status.state.phase == JobPhase.RUNNING]
            if names:
                victim = names[int(rng.integers(0, len(names)))]
                for p in c.store.list("Pod"):
                    if p.meta.annotations.get(JOB_NAME_KEY) == victim \
                            and p.phase == PodPhase.RUNNING:
                        c.complete_pod(p.meta.key)
        else:
            # relabel a node (rolls the SnapshotCache epoch)
            node = c.store.get("Node", f"/n{int(rng.integers(0, 6))}")
            node.labels["zone"] = f"z{int(rng.integers(0, 2))}"
            c.store.update("Node", node)

        c.run_until_idle(max_steps=128)
        check_invariants(c)

    # the cluster ends quiescent and consistent
    check_invariants(c)
    phases = {j.meta.name: j.status.state.phase for j in c.store.list("Job")}
    assert phases, "no jobs survived the soak"
