"""vtrace: span runtime, flight recorder, propagation, neutrality.

Covers the tentpole contracts of volcano_tpu/trace.py:

* span ids / nesting / explicit trace joins / links, and the
  ``spans_for_trace`` reconstruction used by ``vtctl trace``;
* the bounded ring (flight recorder) + crash-dump artifacts;
* cross-daemon propagation: the X-Volcano-Trace header continues a
  client's context into the store server's request span;
* the arming discipline: a DISARMED run performs zero span-runtime work
  (spied), and an ARMED run is placement-neutral — bit-for-bit the same
  placements as a disarmed run, with the fast cycle's phase set
  unchanged (bench.py's breakdown gains no phase);
* the e2e scheduling-latency parity series emitted from bind spans.
"""

import json
import urllib.request

import pytest

from volcano_tpu import trace
from volcano_tpu.scheduler import metrics
from volcano_tpu.sim import Cluster


@pytest.fixture
def armed():
    tr = trace.arm(trace.Tracer(ring=8192))
    try:
        yield tr
    finally:
        trace.disarm()


def _gang_cluster(conf=None):
    c = Cluster(scheduler_conf=conf)
    c.add_queue("default")
    c.add_node("n0", {"cpu": "8", "memory": "16Gi", "pods": 110})
    return c


# -- span runtime --------------------------------------------------------------


def test_span_nesting_and_ids(armed):
    with trace.span("outer", kind="test") as outer:
        assert trace.current() == (outer.trace_id, outer.span_id)
        with trace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert trace.current() == ("", "")
    recs = armed.records()
    assert [r["name"] for r in recs] == ["inner", "outer"]  # exit order
    assert recs[0]["parent"] == recs[1]["span"]
    assert recs[1]["parent"] == ""
    assert recs[1]["attrs"] == {"kind": "test"}


def test_explicit_trace_join_and_link_reconstruction(armed):
    with trace.span("gang.root") as root:
        gang = root.trace_id
    # a cycle in its OWN trace links the gang; its children stay in the
    # cycle's trace but must be reconstructable from the gang's id
    with trace.span("cycle") as cyc:
        cyc.link(gang)
        with trace.span("action", action="allocate"):
            pass
    # an explicit join records directly in the gang's trace
    with trace.span("bind", trace_id=gang):
        pass
    sel = trace.spans_for_trace(armed.records(), gang)
    assert sorted(r["name"] for r in sel) == [
        "action", "bind", "cycle", "gang.root"]
    assert trace.render_tree(armed.records(), gang).count("~linked") == 1


def test_span_records_error_attr(armed):
    with pytest.raises(ValueError):
        with trace.span("boom"):
            raise ValueError("x")
    (rec,) = armed.records()
    assert rec["attrs"]["error"] == "ValueError"
    assert trace.current() == ("", "")  # context unwound


def test_ring_is_bounded():
    tr = trace.arm(trace.Tracer(ring=8))
    try:
        for i in range(20):
            with trace.span(f"s{i}"):
                pass
        names = [r["name"] for r in tr.records()]
        assert names == [f"s{i}" for i in range(12, 20)]
    finally:
        trace.disarm()


def test_env_parsing():
    assert trace._tracer_from_env("") is None
    assert trace._tracer_from_env("0") is None
    assert trace._tracer_from_env("off") is None
    assert trace._tracer_from_env("1").ring_size == trace.DEFAULT_RING
    tr = trace._tracer_from_env('{"ring": 16, "dir": "/tmp/x"}')
    assert tr.ring_size == 16 and tr.dump_dir == "/tmp/x"


def test_header_roundtrip():
    assert trace.parse_header(trace.format_header("t-1", "s-2")) == \
        ("t-1", "s-2")
    assert trace.parse_header("") == ("", "")
    assert trace.parse_header("t-only") == ("t-only", "")


def test_crash_dump_artifact(tmp_path, armed):
    armed.dump_dir = str(tmp_path)
    with trace.span("pre-crash"):
        pass
    path = trace.crash_dump("unit")
    assert path is not None
    data = json.load(open(path))
    assert data["reason"] == "unit"
    assert [s["name"] for s in data["spans"]] == ["pre-crash"]
    trace.disarm()
    assert trace.crash_dump("disarmed") is None


# -- arming discipline ---------------------------------------------------------


def test_disarmed_lifecycle_touches_span_runtime_zero_times(monkeypatch):
    """The overhead smoke: with tracing disarmed, a full gang lifecycle
    (submit -> schedule -> bind -> Running) constructs zero Span objects
    and records nothing — the hot path crosses only the ``TRACER is
    None`` guard."""
    assert trace.TRACER is None

    def explode(*a, **kw):
        raise AssertionError("span runtime touched while disarmed")

    monkeypatch.setattr(trace, "Span", explode)
    monkeypatch.setattr(trace.Tracer, "record", explode)
    c = _gang_cluster()
    from volcano_tpu.cli import cmd_run

    cmd_run(c.store, name="quiet", replicas=2, min_available=2)
    c.run_until_idle()
    from volcano_tpu.api.types import JobPhase

    assert c.store.get("Job", "default/quiet").status.state.phase == \
        JobPhase.RUNNING


def test_armed_run_is_placement_neutral_and_phase_set_unchanged():
    """Acceptance: armed vs disarmed runs produce bit-for-bit identical
    placements, and the fast cycle's phase breakdown (what bench.py
    reports) gains no new phase from tracing."""
    from volcano_tpu.scheduler.conf import full_conf

    known_phases = {"drain", "snapshot", "enqueue", "reclaim", "solve",
                    "backfill", "dyn_solve", "preempt", "publish",
                    "publish_build", "publish_ship", "subcycle"}

    def run(arm):
        if arm:
            trace.arm(trace.Tracer())
        try:
            c = _gang_cluster(conf=full_conf("tpu"))
            from volcano_tpu.cli import cmd_run

            for i in range(3):
                cmd_run(c.store, name=f"j{i}", replicas=2, min_available=2,
                        requests="cpu=1000m,memory=1Gi")
            c.run_until_idle()
            placements = sorted(
                (p.meta.key, p.node_name) for p in c.store.list("Pod"))
            phases = dict(getattr(c.scheduler.fast_cycle, "phases", None)
                          or {})
            return placements, phases
        finally:
            trace.disarm()

    base, base_phases = run(arm=False)
    armed_p, armed_phases = run(arm=True)
    assert armed_p == base
    assert set(armed_phases) == set(base_phases)
    assert set(armed_phases) <= known_phases


# -- cross-process propagation -------------------------------------------------


def test_header_continues_trace_into_store_server(armed):
    from volcano_tpu.api.objects import Metadata, Queue
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.server import StoreServer

    srv = StoreServer().start()
    try:
        client = RemoteStore(srv.url)
        with trace.span("client.op") as s:
            client.create("Queue", Queue(meta=Metadata(name="q",
                                                       namespace="")))
            tid, sid = s.trace_id, s.span_id
        # the handler thread records its span just after writing the
        # reply — give it a beat
        import time

        deadline = time.monotonic() + 5
        stored = []
        while time.monotonic() < deadline and not stored:
            stored = [r for r in armed.records()
                      if r["name"] == "store.POST"]
            if not stored:
                time.sleep(0.01)
        assert stored, "server recorded no request span"
        assert stored[0]["trace"] == tid
        assert stored[0]["parent"] == sid  # continued across the wire
        assert stored[0]["attrs"]["path"] == "/apis/Queue"
    finally:
        srv.stop()


def test_debug_trace_endpoint_serves_ring_and_is_chaos_exempt(armed):
    from volcano_tpu.store.server import StoreServer

    srv = StoreServer().start()
    try:
        with trace.span("visible"):
            pass
        payload = json.load(urllib.request.urlopen(
            srv.url + "/debug/trace", timeout=10))
        assert payload["armed"]
        assert any(s["name"] == "visible" for s in payload["spans"])
        # arm an everything-5xx plan: the admin endpoint must still serve
        req = urllib.request.Request(
            srv.url + "/chaos",
            data=json.dumps({"seed": 1, "rules": [
                {"point": "server.request", "action": "http_500"}]}).encode(),
            method="POST")
        urllib.request.urlopen(req, timeout=10)
        again = json.load(urllib.request.urlopen(
            srv.url + "/debug/trace", timeout=10))
        assert again["armed"]
        # serving the recorder never writes to it (no store.GET span for
        # the /debug/trace reads themselves)
        assert not any(
            s["attrs"].get("path", "").startswith("/debug/trace")
            for s in again["spans"])
    finally:
        srv.stop()


def test_metrics_server_serves_debug_trace(armed):
    from volcano_tpu.scheduler.metrics_server import MetricsServer

    with trace.span("daemon.work"):
        pass
    srv = MetricsServer(port=0).start()
    try:
        payload = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/trace", timeout=10))
        assert payload["armed"]
        assert any(s["name"] == "daemon.work" for s in payload["spans"])
    finally:
        srv.stop()


# -- lifecycle reconstruction + decision data ----------------------------------


def test_one_trace_id_covers_the_full_gang_lifecycle(armed):
    """The local-mode acceptance shape: one trace id stamped at job run
    is reconstructable into a tree spanning controller enqueue, the
    scheduler cycle (actions, plugins, session close), bind, and the
    kubelet Ready flip."""
    from volcano_tpu.cli import cmd_run

    c = _gang_cluster()
    job = cmd_run(c.store, name="lc", replicas=2, min_available=2)
    tid = trace.gang_trace(job.meta)
    assert tid
    c.run_until_idle()
    sel = trace.spans_for_trace(armed.records(), tid)
    names = {r["name"] for r in sel}
    assert "vtctl.job.run" in names
    assert "controller.EnqueueJob" in names
    assert "scheduler.cycle" in names
    assert "scheduler.bind" in names
    assert "kubelet.ready" in names
    actions = {r["attrs"].get("action") for r in sel if r["name"] == "action"}
    assert {"enqueue", "allocate"} <= actions
    plugins = {r["attrs"].get("plugin") for r in sel if r["name"] == "plugin"}
    assert {"gang", "proportion", "predicates"} <= plugins
    # the pods carried the annotation the whole way
    for pod in c.store.list("Pod"):
        assert trace.gang_trace(pod.meta) == tid


def test_statement_commit_span_in_preempt_storm(armed):
    """Contention path: a preempt storm's Statement settlement shows up
    as statement.commit spans inside the cycle's action span."""
    from volcano_tpu.api.objects import Metadata, PriorityClass
    from volcano_tpu.api.types import PodPhase
    from volcano_tpu.scheduler.conf import default_conf
    from volcano_tpu.scheduler.scheduler import Scheduler

    from helpers import build_node, build_pod, build_podgroup, make_store

    pg_low = build_podgroup("pg-low", min_member=1)
    pg_low.priority_class_name = "low-pri"
    pg_high = build_podgroup("pg-high", min_member=1)
    pg_high.priority_class_name = "high-pri"
    store = make_store(
        nodes=[build_node("n0", cpu="2", memory="4Gi")],
        podgroups=[pg_low, pg_high],
        pods=[
            build_pod("low-0", group="pg-low", cpu="1",
                      phase=PodPhase.RUNNING, node_name="n0", priority=1),
            build_pod("low-1", group="pg-low", cpu="1",
                      phase=PodPhase.RUNNING, node_name="n0", priority=1),
            build_pod("high-0", group="pg-high", cpu="1", priority=100),
        ],
    )
    store.create("PriorityClass", PriorityClass(
        Metadata(name="low-pri", namespace=""), value=1))
    store.create("PriorityClass", PriorityClass(
        Metadata(name="high-pri", namespace=""), value=100))
    conf = default_conf()
    conf.actions = ["preempt"]
    Scheduler(store, conf=conf).run_once()
    recs = armed.records()
    commits = [r for r in recs if r["name"] == "statement.commit"]
    assert commits, [r["name"] for r in recs]
    assert commits[0]["attrs"]["ops"] >= 1
    # nested inside the preempt action span of the cycle tree
    parents = {r["span"]: r for r in recs}
    parent = parents[commits[0]["parent"]]
    assert parent["name"] == "action" and \
        parent["attrs"]["action"] == "preempt"


@pytest.mark.slow
def test_real_daemons_expose_one_trace_on_all_debug_endpoints():
    """Acceptance, real process model: VOLCANO_TPU_TRACE=1 daemons, one
    trace id submitted at `vtctl job run`, recovered from /debug/trace on
    the controller, scheduler, kubelet AND the apiserver."""
    import os
    import signal
    import subprocess
    import sys
    import time

    from volcano_tpu.store.client import RemoteStore, wait_healthy

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VOLCANO_TPU_BACKEND": "host", "VOLCANO_TPU_TRACE": "1"}
    entry = [sys.executable, "-m", "volcano_tpu.cli"]
    procs = []

    def spawn(args):
        p = subprocess.Popen(entry + args, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True, env=env)
        procs.append(p)
        return p

    try:
        api = spawn(["apiserver", "--port", "0"])
        url = api.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert wait_healthy(url, timeout=30)
        ctl = spawn(["controller", "--server", url, "--debug-port", "0",
                     "--period", "0.05"])
        ctl_port = ctl.stdout.readline().strip().rsplit(":", 2)[-1].split("/")[0]
        kub = spawn(["kubelet", "--server", url, "--debug-port", "0",
                     "--period", "0.05"])
        kub_port = kub.stdout.readline().strip().rsplit(":", 2)[-1].split("/")[0]
        sched = spawn(["scheduler", "--server", url, "--period", "0.1",
                       "--metrics-port", "0"])
        sched_port = None
        deadline = time.time() + 60
        while time.time() < deadline and sched_port is None:
            line = sched.stdout.readline()
            if "metrics on" in line:
                sched_port = line.strip().rsplit(":", 1)[-1].split("/")[0]
        assert sched_port, "scheduler never announced its metrics port"

        subprocess.run(entry + ["--server", url, "cluster", "init",
                                "--nodes", "2"], env=env, check=True,
                       capture_output=True)
        subprocess.run(entry + ["--server", url, "job", "run", "--name",
                                "g1", "--replicas", "2", "--min", "2"],
                       env=env, check=True, capture_output=True)
        client = RemoteStore(url)
        deadline = time.time() + 90
        job = None
        while time.time() < deadline:
            job = client.get("Job", "default/g1")
            if job is not None and job.status.state.phase.value == "Running":
                break
            time.sleep(0.2)
        assert job is not None and job.status.state.phase.value == "Running"
        tid = trace.gang_trace(job.meta)
        assert tid
        time.sleep(1.0)  # let the last Ready-flip spans land in the rings

        def ring(port):
            return json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/trace", timeout=10))["spans"]

        expectations = {
            ctl_port: {"controller.EnqueueJob"},
            sched_port: {"scheduler.cycle", "scheduler.bind", "action",
                         "plugin"},
            kub_port: {"kubelet.ready"},
            url.rsplit(":", 1)[-1]: {"store.POST"},
        }
        for port, expect in expectations.items():
            names = {s["name"]
                     for s in trace.spans_for_trace(ring(port), tid)}
            assert expect <= names, (port, expect, names)
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_pod_e2e_latency_metric_exposition_and_monotonicity(armed):
    """Satellite: the reference-parity first-seen->bind series, emitted
    from bind spans — histogram exposition format (r8 bounded-bucket
    encoding: _bucket/_sum/_count lines) + monotone count/sum."""
    from volcano_tpu.cli import cmd_run

    metrics.reset()
    c = _gang_cluster()
    cmd_run(c.store, name="m1", replicas=2, min_available=2)
    c.run_until_idle()
    snap = metrics.get_histogram(
        "volcano_e2e_job_scheduling_latency_milliseconds")
    assert len(snap) == 2 and all(v >= 0 for v in snap)
    text = metrics.expose_text()
    assert "volcano_e2e_job_scheduling_latency_milliseconds_count 2" in text
    assert "volcano_e2e_job_scheduling_latency_milliseconds_sum" in text
    assert ('volcano_e2e_job_scheduling_latency_milliseconds_bucket'
            '{le="+Inf"} 2') in text
    assert "# TYPE volcano_e2e_job_scheduling_latency_milliseconds " \
           "histogram" in text
    cmd_run(c.store, name="m2", replicas=1, min_available=1)
    c.run_until_idle()
    snap2 = metrics.get_histogram(
        "volcano_e2e_job_scheduling_latency_milliseconds")
    assert len(snap2) == 3  # monotone: observations only accumulate
    assert snap2.sum >= snap.sum
    # cumulative bucket counts never shrink across the encoding
    before = dict(snap.buckets)
    after = dict(snap2.buckets)
    assert all(after.get(le, 0) >= c for le, c in before.items())
    assert "volcano_e2e_job_scheduling_latency_milliseconds_count 3" \
        in metrics.expose_text()
