"""Tensor-backend parity: the JAX allocate solve must reproduce the host
path's decisions bit-for-bit (same binds, same nodes, same pipelines).

This is the core correctness property of the TPU tier (SURVEY.md section 7
step 3: "validate bit-for-bit against the reference semantics"). Random
clusters exercise gang, priority, DRF, proportion and nodeorder together.
"""

import random

import pytest

from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import (
    FakeBinder,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)


def make_random_store(seed: int, n_nodes=6, n_jobs=8, n_queues=2):
    rng = random.Random(seed)
    nodes = [
        build_node(
            f"n{i:03d}",
            cpu=str(rng.choice([2, 4, 8])),
            memory=f"{rng.choice([4, 8, 16])}Gi",
        )
        for i in range(n_nodes)
    ]
    queues = [build_queue(f"q{i}", weight=rng.choice([1, 2, 3])) for i in range(n_queues)]
    queues.append(build_queue("default"))
    podgroups, pods = [], []
    for j in range(n_jobs):
        n_tasks = rng.randint(1, 5)
        minm = rng.randint(1, n_tasks)
        q = f"q{rng.randrange(n_queues)}"
        podgroups.append(build_podgroup(f"job{j:03d}", min_member=minm, queue=q))
        for t in range(n_tasks):
            pods.append(
                build_pod(
                    f"job{j:03d}-{t}",
                    group=f"job{j:03d}",
                    cpu=str(rng.choice(["250m", "500m", "1", "2"])),
                    memory=f"{rng.choice([256, 512, 1024, 2048])}Mi",
                    priority=rng.choice([0, 0, 5, 10]),
                )
            )
    return make_store(nodes=nodes, queues=queues, podgroups=podgroups, pods=pods)


def run_backend(seed: int, backend: str):
    store = make_random_store(seed)
    sched = Scheduler(store, conf=default_conf(backend=backend))
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return binder.binds


@pytest.mark.parametrize("seed", range(12))
def test_allocate_parity_random_clusters(seed):
    host = run_backend(seed, "host")
    tpu = run_backend(seed, "tpu")
    assert tpu == host


def test_parity_gang_with_best_effort_tasks():
    # regression: a gang job whose min_available counts BestEffort tasks
    # (valid for the gang gate, skipped by allocate) exhausts its allocate
    # queue without becoming ready — the kernel cursor must not run past
    # the job's task rows into other jobs'
    def run(backend):
        store = make_store(
            nodes=[build_node("n0", cpu="8", memory="16Gi")],
            podgroups=[
                build_podgroup("mixed", min_member=4),
                build_podgroup("other", min_member=1),
            ],
            pods=[
                build_pod("mixed-0", group="mixed", cpu="1"),
                build_pod("mixed-1", group="mixed", cpu="1"),
                build_pod("mixed-be0", group="mixed", cpu=0, memory=0),
                build_pod("mixed-be1", group="mixed", cpu=0, memory=0),
                build_pod("other-0", group="other", cpu="1"),
            ],
        )
        sched = Scheduler(store, conf=default_conf(backend=backend))
        binder = FakeBinder()
        sched.cache.binder = binder
        sched.run_once()
        return binder.binds

    host, tpu = run("host"), run("tpu")
    assert tpu == host
    # "other" must still get bound despite "mixed" never becoming ready
    # via allocate alone (its BestEffort tasks bind in backfill)
    assert "default/other-0" in host


def test_parity_oversubscribed():
    # heavy contention: many gangs, tiny cluster
    import random as _r

    rng = _r.Random(99)
    nodes = [build_node("n0", cpu="4", memory="8Gi"), build_node("n1", cpu="2", memory="4Gi")]
    queues = [build_queue("q0", weight=2), build_queue("q1", weight=1), build_queue("default")]
    podgroups, pods = [], []
    for j in range(10):
        n_tasks = rng.randint(1, 4)
        podgroups.append(
            build_podgroup(f"g{j}", min_member=n_tasks, queue=f"q{j % 2}")
        )
        for t in range(n_tasks):
            pods.append(build_pod(f"g{j}-{t}", group=f"g{j}", cpu="1", memory="1Gi"))
    def run(backend):
        store = make_store(nodes=nodes, queues=[build_queue(q.meta.name, q.weight) for q in queues],
                           podgroups=[build_podgroup(pg.meta.name, pg.min_member, pg.queue) for pg in podgroups],
                           pods=[build_pod(p.meta.name, group=p.meta.annotations.get("scheduling.volcano.tpu/group-name",""), cpu="1", memory="1Gi") for p in pods])
        sched = Scheduler(store, conf=default_conf(backend=backend))
        binder = FakeBinder()
        sched.cache.binder = binder
        sched.run_once()
        return binder.binds

    assert run("tpu") == run("host")


def _elastic_mix_store():
    """Cordoned + Provisioning + schedulable node mix (elastic capacity):
    both masked states must be excluded from placement identically by
    every backend — cordons via the unschedulable predicate, Provisioning
    via the Ready condition — with enough pending work to overflow onto
    the masked nodes if a backend ever leaked them into its mask."""
    from volcano_tpu.api.objects import Metadata, NodePool
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.elastic.lifecycle import make_pool_node

    nodes = [build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(3)]
    nodes[1].unschedulable = True  # cordoned mid-drain
    pool = NodePool(
        meta=Metadata(name="tp", namespace=""),
        resources=Resource.from_resource_list({"cpu": "4", "memory": "8Gi"}),
    )
    provisioning = make_pool_node(pool, 0, ready_at=1e18)  # never flips here
    pgs, pods = [], []
    for j in range(4):
        pgs.append(build_podgroup(f"g{j}", min_member=2))
        for t in range(2):
            pods.append(build_pod(f"g{j}-{t}", group=f"g{j}",
                                  cpu="2", memory="2Gi"))
    return make_store(nodes=nodes + [provisioning], podgroups=pgs, pods=pods)


def test_parity_cordoned_and_provisioning_mix():
    def run(backend):
        sched = Scheduler(_elastic_mix_store(), conf=default_conf(backend))
        binder = FakeBinder()
        sched.cache.binder = binder
        sched.run_once()
        return binder.binds

    host, tpu = run("host"), run("tpu")
    assert tpu == host
    # the masked nodes took nothing; the two schedulable nodes filled up
    assert host and set(host.values()) == {"n0", "n2"}
