"""vtrepl: WAL-shipping replication, follower-served watches, failover.

The gate for store/replica.py:

  * Group-commit watermark: the feed NEVER ships a record whose fsync
    has not landed — an unsynced append is invisible to followers until
    its shard's synced ticket covers it.
  * Follower replay determinism: a follower's watch stream and digest
    root are byte-identical to the leader's (frozen uid counter + clock,
    the PR-6 proof pattern), including across a torn feed reply
    (``repl.feed`` cut_body) — reconnect must re-ship exactly-once.
  * NotLeader redirects: a write against a follower 421s with the leader
    URL; RemoteStore refollows (hint first, then peer resolution) and
    the write lands on the leader.
  * Sync-ack mode: the leader's 2xx waits for >=1 follower append; with
    no follower connected the write times out into a 5xx (never a lying
    ACK).
  * Failover: on leader death the highest-applied follower promotes
    (exactly one — no double promotion), pre-failover watch cursors take
    exactly ONE StaleWatch relist and then stay incremental, and writers
    re-resolve onto the promoted leader.
  * THE acceptance storm (real subprocesses, real SIGKILL): a 3-replica
    control plane in ``--repl-ack sync`` loses its leader mid-cycle; a
    follower promotes and the run converges to placements bit-for-bit
    equal to a fault-free run, every acked job Running, ``vtctl audit``
    exit 0 against the promoted leader.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from volcano_tpu.api import objects as api_objects
from volcano_tpu.api.objects import Metadata, Node, Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobPhase
from volcano_tpu.backoff import Backoff
from volcano_tpu.scheduler import metrics
from volcano_tpu.store.client import (
    RemoteStore,
    RemoteStoreError,
    StaleWatch,
    resolve_leader,
    wait_healthy,
)
from volcano_tpu.store.replica import ReplicationAckTimeout  # noqa: F401
from volcano_tpu.store.server import StoreServer

from tests.helpers import build_pod
from tests.test_chaos_soak import (
    TRANSIENT,
    _check_invariants,
    _mk_job,
    _placements,
    _submit,
    _wait_running,
)


# -- in-process topology helpers ----------------------------------------------


def _repl(peers=(), leader=None, ack="async", lease=5.0, identity=None):
    return {"identity": identity, "peers": list(peers), "leader": leader,
            "ack": ack, "lease_duration": lease}


def _boot(tmp_path, name, leader=None, peers=(), ack="async", lease=5.0):
    return StoreServer(
        port=0, state_path=str(tmp_path / f"{name}.json"),
        save_interval=3600, wal=True,
        repl=_repl(peers=peers, leader=leader, ack=ack, lease=lease),
    ).start()


def _wait_caught_up(follower, leader, deadline=20.0):
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        if follower.seq >= leader.seq and follower.repl.epoch == \
                leader.repl.epoch:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"follower never caught up: {follower.seq} < {leader.seq}")


def _workload(rs):
    """A small but surface-complete workload: per-object creates,
    updates, patches, and one decision segment (EventLogBlock rows on
    the log — the lazy-expansion path followers must replay)."""
    from volcano_tpu.store.segment import DecisionSegment

    rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    rs.create("Node", Node(meta=Metadata(name="n0", namespace=""),
                           allocatable=Resource.from_resource_list(
                               {"cpu": "8", "memory": "16Gi"})))
    for i in range(6):
        rs.create("Pod", build_pod(f"p{i}"))
    n = rs.get("Node", "/n0")
    n.labels["zone"] = "z1"
    rs.update("Node", n)
    rs.patch("Pod", "default/p0", {"node_name": "n0"})
    seg = DecisionSegment.build(
        ["default/p1", "default/p2"], [0, 0], ["n0"],
        evicts=[("default/p3", "Preempted")])
    rs.apply_segment(seg)
    rs.delete("Pod", "default/p5")


# -- group-commit watermark ----------------------------------------------------


def test_feed_never_ships_an_unfsynced_record(tmp_path):
    """The shipping invariant, distilled: a record appended to the WAL
    but not yet fsynced must not appear on the feed; the commit makes it
    shippable."""
    srv = _boot(tmp_path, "l")
    try:
        rs = RemoteStore(srv.url)
        rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
        base = srv.seq
        epoch = srv.repl.epoch

        rec = {"op": "patch", "kind": "Queue", "key": "/q",
               "fields": {}, "seq": base + 1}
        ticket = srv.wal.append(rec)
        srv.repl.log_append(rec, ticket)
        out = srv.repl.feed(base, "", timeout=0, req_epoch=epoch)
        assert out["records"] == []  # appended, NOT fsynced: invisible

        srv.wal.commit()
        srv.repl.on_commit()
        out = srv.repl.feed(base, "", timeout=0, req_epoch=epoch)
        assert [r["seq"] for r in out["records"]] == [base + 1]
    finally:
        srv.stop()


# -- follower replay determinism (PR-6 frozen proof pattern) -------------------


def _leader_follower_streams(tmp_path, monkeypatch, feed_plan=None):
    """Run the controlled workload against a leader+follower pair (frozen
    uid counter + clock) and return both servers' full watch streams and
    digest roots.  ``feed_plan`` arms chaos on the leader first — the
    torn-feed arm."""
    monkeypatch.setattr(api_objects, "_uid_token", "t0")
    monkeypatch.setattr(api_objects, "_uid_next", 1000)
    monkeypatch.setattr(time, "time", lambda: 1234.5)
    L = _boot(tmp_path, "l")
    F = None
    try:
        if feed_plan is not None:
            data = json.dumps(feed_plan).encode()
            urllib.request.urlopen(urllib.request.Request(
                L.url + "/chaos", data=data, method="POST"), timeout=10)
        F = _boot(tmp_path, "f", leader=L.url, peers=[L.url])
        # first sync is a snapshot (fresh follower, epoch 0 vs leader's
        # 1): the byte-identity proof covers every record REPLAYED after
        # it — the whole workload — from the common post-sync cursor
        _wait_caught_up(F, L)
        cur = F.seq
        _workload(RemoteStore(L.url))
        _wait_caught_up(F, L)
        evs_l = L.watch_since(cur, set(), 0)["events"]
        evs_f = F.watch_since(cur, set(), 0)["events"]
        root_l = (L.store.digest_payload() or {}).get("root")
        root_f = (F.store.digest_payload() or {}).get("root")
        return json.dumps(evs_l), json.dumps(evs_f), root_l, root_f
    finally:
        if F is not None:
            F.stop()
        L.stop()


def test_follower_watch_stream_byte_identical(tmp_path, monkeypatch):
    evs_l, evs_f, root_l, root_f = _leader_follower_streams(
        tmp_path, monkeypatch)
    assert evs_f == evs_l
    assert root_f == root_l and root_l is not None
    assert '"type"' in evs_l  # the streams actually carried the workload


def test_follower_replay_survives_torn_feed_mid_stream(tmp_path, monkeypatch):
    """Feed replies cut mid-segment (repl.feed cut_body): the follower's
    reconnect must re-ship exactly-once — same byte-identical stream and
    root as the clean run."""
    plan = {"seed": 711, "rules": [
        {"point": "repl.feed", "action": "cut_body", "every": 2,
         "count": 4},
    ]}
    evs_l, evs_f, root_l, root_f = _leader_follower_streams(
        tmp_path, monkeypatch, feed_plan=plan)
    assert evs_f == evs_l
    assert root_f == root_l and root_l is not None


# -- NotLeader redirect + client refollow --------------------------------------


def test_write_to_follower_redirects_and_lands_on_leader(tmp_path):
    L = _boot(tmp_path, "l")
    F = _boot(tmp_path, "f", leader=L.url, peers=[L.url])
    try:
        # hint-following: even a peerless client chases the 421's leader
        # URL instead of failing the write
        hinted = RemoteStore(F.url)
        hinted.create("Queue", Queue(meta=Metadata(name="qa", namespace="")))
        assert hinted.url == L.url

        # peer resolution: a client with the replica set re-resolves
        rs = RemoteStore(F.url, peers=[L.url, F.url])
        rs.create("Queue", Queue(meta=Metadata(name="qb", namespace="")))
        assert rs.url == L.url
        _wait_caught_up(F, L)

        # follower-served reads: list/get locally, no redirect
        local = RemoteStore(F.url)
        assert {q.meta.name for q in local.list("Queue")} == {"qa", "qb"}
        assert local.url == F.url

        # the redirect counter moved (process-global registry)
        text = metrics.expose_text()
        assert "volcano_repl_follower_redirects_total" in text
    finally:
        F.stop()
        L.stop()


# -- sync-ack mode -------------------------------------------------------------


def test_sync_ack_blocks_until_a_follower_append(tmp_path):
    L = _boot(tmp_path, "l", ack="sync")
    L.repl.ack_timeout = 0.4  # fail fast: no follower will ever ack
    F = None
    try:
        rs = RemoteStore(L.url, timeout=10.0)
        with pytest.raises(RemoteStoreError):
            rs.create("Queue", Queue(meta=Metadata(name="q0", namespace="")))

        L.repl.ack_timeout = 10.0
        F = _boot(tmp_path, "f", leader=L.url, peers=[L.url])
        # with a live follower the 2xx waits for the ack and returns
        rs.create("Queue", Queue(meta=Metadata(name="q1", namespace="")))
        _wait_caught_up(F, L)
        # the acked record is on the follower AT ack time (sync contract)
        assert F.store.get("Queue", "/q1") is not None
    finally:
        if F is not None:
            F.stop()
        L.stop()


# -- in-process failover -------------------------------------------------------


def test_failover_promotes_one_follower_one_stalewatch(tmp_path):
    L = _boot(tmp_path, "l", lease=0.8)
    peers = [L.url]
    F1 = _boot(tmp_path, "f1", leader=L.url, peers=peers, lease=0.8)
    F2 = _boot(tmp_path, "f2", leader=L.url, peers=peers, lease=0.8)
    urls = [L.url, F1.url, F2.url]
    for s in (L, F1, F2):
        s.repl.peers = [u for u in urls if u != s.url]
    try:
        rs = RemoteStore(L.url, peers=urls)
        rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
        for i in range(4):
            rs.create("Pod", build_pod(f"p{i}"))
        _wait_caught_up(F1, L)
        _wait_caught_up(F2, L)

        watcher = RemoteStore(F1.url, peers=urls)
        wq = watcher.watch("Pod")
        watcher.poll()  # pin the cursor + epoch pre-failover

        L.kill()
        deadline = time.monotonic() + 20
        promoted = None
        while time.monotonic() < deadline and promoted is None:
            for s in (F1, F2):
                if s.repl.role == "leader":
                    promoted = s
            time.sleep(0.05)
        assert promoted is not None, "no follower promoted"
        other = F2 if promoted is F1 else F1

        # exactly one leader; the other follower re-follows the new one
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
                other.repl.role != "follower"
                or other.repl.leader_url != promoted.url):
            time.sleep(0.05)
        assert other.repl.role == "follower"
        assert other.repl.leader_url == promoted.url
        assert promoted.repl.epoch > 1

        # writer refollows onto the promoted leader
        rs.create("Pod", build_pod("after-failover"))
        assert rs.url == promoted.url
        _wait_caught_up(other, promoted)

        # pre-failover watch cursor: EXACTLY one StaleWatch (the epoch
        # fence), whose relist recovers the cursor-gap write
        stale = 0
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and stale == 0:
            try:
                watcher.poll(timeout=0.5)
            except StaleWatch:
                stale += 1
            except TRANSIENT:
                time.sleep(0.05)
        assert stale == 1, "the epoch fence never raised StaleWatch"
        assert "after-failover" in {
            p.meta.name for p in watcher.list("Pod")}
        # ...and stays incremental: the next write arrives as an event,
        # with no second relist (an escaping StaleWatch fails the test)
        rs.create("Pod", build_pod("post-relist"))
        deadline = time.monotonic() + 10
        seen = False
        while time.monotonic() < deadline and not seen:
            try:
                watcher.poll(timeout=0.5)
            except TRANSIENT:
                time.sleep(0.05)
                continue
            while wq:
                seen = seen or wq.popleft().obj.meta.name == "post-relist"
        assert seen and stale == 1
    finally:
        for s in (F1, F2):
            s.stop()
        # L was killed; reap its sockets
        try:
            L.stop()
        except Exception:
            pass


# -- metrics exposition --------------------------------------------------------


def test_repl_metrics_exposition(tmp_path):
    L = _boot(tmp_path, "l")
    F = _boot(tmp_path, "f", leader=L.url, peers=[L.url])
    try:
        rs = RemoteStore(L.url)
        rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
        _wait_caught_up(F, L)
        text = metrics.expose_text()
        for name in ("volcano_repl_lag_seconds",
                     "volcano_repl_shipped_segments_total",
                     "volcano_repl_applied_seq",
                     "volcano_repl_follower_redirects_total"):
            assert f"# HELP {name}" in text, name
            assert f"\n{name}" in text or text.startswith(name), name
    finally:
        F.stop()
        L.stop()


# -- THE acceptance storm: subprocess SIGKILL of the leader mid-cycle ----------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _repl_status(url):
    with urllib.request.urlopen(url + "/repl/status", timeout=10) as r:
        return json.load(r)


def _spawn_api(entry, env, tmp_path, name, port, peers, leader=None):
    args = entry + ["apiserver", "--port", str(port),
                    "--state", str(tmp_path / f"{name}.json"), "--wal",
                    "--peers", ",".join(peers), "--repl-ack", "sync",
                    "--lease-duration", "1.0"]
    if leader:
        args += ["--replica-of", leader]
    return subprocess.Popen(args, stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT, env=env)


def _spawn_daemon(entry, comp, url, peers, env):
    args = {"controller": ["--period", "0.05"],
            "scheduler": ["--period", "0.1", "--metrics-port", "-1"],
            "kubelet": ["--period", "0.05"]}[comp]
    return subprocess.Popen(
        entry + [comp, "--server", url, "--peers", ",".join(peers)] + args,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)


def _repl_storm(tmp_path, kill_leader, n_jobs=3):
    """A 3-replica sync-ack control plane under a real workload; when
    ``kill_leader`` the leader is SIGKILLed mid-cycle and NEVER
    restarted — the promotion path is the only way the run converges.
    Returns (placements, stale_count) from the surviving replicas."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    ports = _free_ports(3)
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VOLCANO_TPU_BACKEND": "host"}
    env.pop("VOLCANO_TPU_CHAOS", None)
    entry = [sys.executable, "-m", "volcano_tpu.cli"]

    procs = {}
    procs["api-0"] = _spawn_api(entry, env, tmp_path, "a", ports[0], urls)
    assert wait_healthy(urls[0], timeout=30)
    for i in (1, 2):
        procs[f"api-{i}"] = _spawn_api(entry, env, tmp_path, f"f{i}",
                                       ports[i], urls, leader=urls[0])
    # sync-ack leader: wait for a follower to connect before writing, or
    # the first creates burn ack-timeout windows
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if _repl_status(urls[0])["followers"]:
                break
        except OSError:
            pass
        time.sleep(0.1)
    else:
        raise AssertionError("no follower ever connected to the leader")

    try:
        for comp in ("controller", "scheduler", "kubelet"):
            procs[comp] = _spawn_daemon(entry, comp, urls[0], urls, env)

        client = RemoteStore(urls[0], peers=urls)
        for i in range(3):
            _submit(client, Node(
                meta=Metadata(name=f"n{i}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "4", "memory": "8Gi", "pods": 110})),
                kind="Node")

        # a pre-failover watch cursor on a follower replica: it must
        # survive the promotion with exactly one StaleWatch relist
        watcher = RemoteStore(urls[1], peers=urls)
        watcher.watch("Pod")
        watcher.poll()
        stale = 0

        acked = []
        killed = False
        for i in range(n_jobs):
            _submit(client, _mk_job(f"rj{i}", 2))
            acked.append(f"soak/rj{i}")
            if kill_leader and i == 1:
                # SIGKILL the leader mid-cycle: daemons are pumping, the
                # job's gang is mid-flight
                procs["api-0"].kill()
                procs["api-0"].wait()
                killed = True
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                # the harness supervises DAEMONS only — a dead leader
                # stays dead; promotion is the recovery path
                for comp in ("controller", "scheduler", "kubelet"):
                    if procs[comp].poll() is not None:
                        procs[comp] = _spawn_daemon(
                            entry, comp, urls[0] if not killed else urls[1],
                            urls, env)
                try:
                    watcher.poll()
                except StaleWatch:
                    stale += 1
                except TRANSIENT:
                    pass
                try:
                    job = client.get("Job", f"soak/rj{i}")
                    if job is not None and \
                            job.status.state.phase == JobPhase.RUNNING:
                        break
                except TRANSIENT:
                    pass
                time.sleep(0.1)
            _wait_running(client, f"soak/rj{i}", deadline=60)

        live = urls if not kill_leader else urls[1:]
        if kill_leader:
            # single promoted leader, no double promotion, epoch advanced
            roles = {}
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                roles = {u: _repl_status(u) for u in live}
                if sum(1 for s in roles.values()
                       if s["role"] == "leader") == 1:
                    break
                time.sleep(0.1)
            leaders = [u for u, s in roles.items() if s["role"] == "leader"]
            assert len(leaders) == 1, roles
            assert all(s["epoch"] >= 2 for s in roles.values()), roles
            assert roles[leaders[0]]["promotions"] >= 1
            leader_url = leaders[0]
            # the pre-failover watch survived via exactly one relist
            assert stale == 1, f"expected exactly one StaleWatch, saw {stale}"
        else:
            leader_url = urls[0]
            assert stale == 0

        # zero acked loss: every acked job Running on the (new) leader
        for key in acked:
            job = client.get("Job", key)
            assert job is not None
            assert job.status.state.phase == JobPhase.RUNNING
        _check_invariants(client)

        # vtctl audit exit 0 against the promoted leader (and replicas
        # agree on the root: mirror == store == shard rollups)
        from volcano_tpu.cli import vtctl

        assert vtctl.main(["audit", "--server", leader_url]) == 0

        # replica digest equality via the beacon surface: every live
        # replica's root matches at the same seq
        seqs_roots = {}
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            seqs_roots = {u: _repl_status(u) for u in live}
            if len({(s["applied"]) for s in seqs_roots.values()}) == 1:
                break
            time.sleep(0.1)
        assert len({s["applied"] for s in seqs_roots.values()}) == 1, \
            seqs_roots
        assert all(s["divergence"] == 0 for s in seqs_roots.values()), \
            seqs_roots

        return _placements(client)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def test_sigkill_leader_storm_zero_acked_loss(tmp_path):
    """THE gate: kill-the-leader-mid-cycle under --repl-ack sync.  The
    promoted follower must carry every acked write; final placements are
    bit-for-bit equal to a fault-free run of the same workload."""
    baseline = _repl_storm(tmp_path / "base", kill_leader=False)
    stormy = _repl_storm(tmp_path / "storm", kill_leader=True)
    assert stormy == baseline
    assert len(baseline) == 6  # 3 gangs x 2 replicas, all Running


# -- resolve_leader ------------------------------------------------------------


def test_resolve_leader_skips_followers_and_dead_peers(tmp_path):
    L = _boot(tmp_path, "l")
    F = _boot(tmp_path, "f", leader=L.url, peers=[L.url])
    try:
        dead = "http://127.0.0.1:1"
        assert resolve_leader([dead, F.url, L.url], timeout=15) == L.url
        with pytest.raises(RemoteStoreError):
            resolve_leader([dead], timeout=0.5)
    finally:
        F.stop()
        L.stop()
