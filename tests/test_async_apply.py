"""Async batched decision application (the reference's per-bind goroutines +
errTasks resync, KB cache.go:393-447,512-533) and the store bulk/patch verbs
it rides on."""

import threading

import pytest

from tests.helpers import build_node, build_pod, build_podgroup, make_store
from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.events import events_for
from volcano_tpu.scheduler.cache import SchedulerCache
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store import Store


# -- store verbs --------------------------------------------------------------


def test_store_patch_updates_fields_and_bumps_rv():
    store = Store()
    pod = build_pod("p1")
    store.create("Pod", pod)
    rv = pod.meta.resource_version
    out = store.patch("Pod", pod.meta.key, {"node_name": "n1"})
    assert out.node_name == "n1"
    assert out.meta.resource_version > rv
    assert store.get("Pod", pod.meta.key).node_name == "n1"


def test_store_patch_unknown_field_fails_loudly():
    store = Store()
    store.create("Pod", build_pod("p1"))
    with pytest.raises(AttributeError):
        store.patch("Pod", "default/p1", {"nodename_typo": "n1"})


def test_store_patch_missing_object_raises():
    store = Store()
    with pytest.raises(KeyError):
        store.patch("Pod", "default/nope", {"node_name": "n1"})


def test_store_bulk_applies_ops_in_order_with_per_op_errors():
    store = Store()
    store.create("Pod", build_pod("p1"))
    p2 = build_pod("p2")
    results = store.bulk([
        {"op": "create", "kind": "Pod", "object": p2},
        {"op": "patch", "kind": "Pod", "key": "default/p1",
         "fields": {"node_name": "n1"}},
        {"op": "patch", "kind": "Pod", "key": "default/ghost",
         "fields": {"node_name": "n1"}},
        {"op": "delete", "kind": "Pod", "key": "default/p2"},
    ])
    assert results[0] is None and results[1] is None and results[3] is None
    assert "ghost" in results[2]
    assert store.get("Pod", "default/p1").node_name == "n1"
    assert store.get("Pod", "default/p2") is None


# -- async applier ------------------------------------------------------------


def _async_scheduler(store):
    conf = default_conf(backend="host")
    conf.apply_mode = "async"
    return Scheduler(store, conf=conf)


def _gang_fixture(store, n_tasks=3):
    store.create("Node", build_node("n1", cpu="16", memory="32Gi"))
    pg = build_podgroup("pg1", min_member=n_tasks)
    pg.status.phase = PodGroupPhase.INQUEUE
    store.create("PodGroup", pg)
    for i in range(n_tasks):
        store.create("Pod", build_pod(f"p{i}", group="pg1", cpu="1"))


def test_async_binds_reach_store_after_flush():
    store = make_store([])
    _gang_fixture(store)
    sched = _async_scheduler(store)
    sched.run_once()
    assert sched.cache.applier.flush(timeout=10)
    bound = [p for p in store.list("Pod") if p.node_name == "n1"]
    assert len(bound) == 3
    # "Scheduled" events arrived via the bulk path
    evs = events_for(store, "Pod", "default/p0")
    assert [e.reason for e in evs] == ["Scheduled"]
    assert sched.cache.err_log == []


def test_inflight_bind_overlays_snapshot_as_bound():
    store = make_store([])
    _gang_fixture(store, n_tasks=1)
    cache = SchedulerCache(store, async_apply=True)
    # freeze the applier so the decision stays in flight deterministically
    gate = threading.Event()
    orig_bulk = store.bulk
    store.bulk = lambda ops: (gate.wait(10), orig_bulk(ops))[1]
    try:
        task = next(
            t for j in cache.snapshot().jobs.values() for t in j.tasks.values()
        )
        cache.bind(task, "n1")
        snap = cache.snapshot()  # store write still gated: overlay must cover
        t2 = next(t for j in snap.jobs.values() for t in j.tasks.values())
        assert t2.status == TaskStatus.BOUND
        assert t2.node_name == "n1"
        # node accounting charged the in-flight bind
        node = snap.nodes["n1"]
        assert t2.uid in node.tasks
        assert node.idle.milli_cpu < node.allocatable.milli_cpu
    finally:
        gate.set()
        assert cache.applier.flush(timeout=10)
    assert store.get("Pod", "default/p0").node_name == "n1"
    # confirmed: overlay marker gone, snapshot now reads pure store state
    assert cache.applier.inflight_binds == {}
    snap3 = cache.snapshot()
    t3 = next(t for j in snap3.jobs.values() for t in j.tasks.values())
    assert t3.status == TaskStatus.BOUND


def test_failed_async_bind_records_err_and_retries_next_cycle():
    store = make_store([])
    _gang_fixture(store, n_tasks=1)
    cache = SchedulerCache(store, async_apply=True)
    task = next(
        t for j in cache.snapshot().jobs.values() for t in j.tasks.values()
    )
    store.delete("Pod", task.key)  # pod vanishes between snapshot and bind
    cache.bind(task, "n1")
    assert cache.applier.flush(timeout=10)
    assert [(op, key) for op, key, _ in cache.err_log] == [("bind", task.key)]
    assert cache.applier.inflight_binds == {}  # marker dropped -> retry path


def test_async_evict_marks_deleting_and_overlays_releasing():
    from volcano_tpu.api.types import PodPhase

    store = make_store([])
    store.create("Node", build_node("n1"))
    pg = build_podgroup("pg1", min_member=1)
    pg.status.phase = PodGroupPhase.INQUEUE
    store.create("PodGroup", pg)
    store.create(
        "Pod",
        build_pod("p0", group="pg1", node_name="n1", phase=PodPhase.RUNNING),
    )
    cache = SchedulerCache(store, async_apply=True)
    gate = threading.Event()
    orig_bulk = store.bulk
    store.bulk = lambda ops: (gate.wait(10), orig_bulk(ops))[1]
    try:
        task = next(
            t for j in cache.snapshot().jobs.values() for t in j.tasks.values()
        )
        cache.evict(task, "preempt")
        snap = cache.snapshot()
        t2 = next(t for j in snap.jobs.values() for t in j.tasks.values())
        assert t2.status == TaskStatus.RELEASING
    finally:
        gate.set()
        assert cache.applier.flush(timeout=10)
    assert store.get("Pod", "default/p0").deleting
    assert [e.reason for e in events_for(store, "Pod", "default/p0")] == ["Evict"]


def test_async_second_cycle_does_not_double_schedule():
    """A cycle starting while last cycle's binds are in flight must see the
    pods as bound (no re-placement, no duplicate bind submissions)."""
    store = make_store([])
    _gang_fixture(store)
    sched = _async_scheduler(store)
    gate = threading.Event()
    orig_bulk = store.bulk
    store.bulk = lambda ops: (gate.wait(10), orig_bulk(ops))[1]
    try:
        sched.run_once()
        n_first = len(sched.cache.bind_log)
        assert n_first == 3
        sched.run_once()  # in-flight overlay: nothing new to place
        assert len(sched.cache.bind_log) == n_first
    finally:
        gate.set()
        assert sched.cache.applier.flush(timeout=10)
    assert sum(1 for p in store.list("Pod") if p.node_name == "n1") == 3


def test_load_conf_rejects_bad_apply_mode():
    from volcano_tpu.scheduler.conf import load_conf

    with pytest.raises(ValueError):
        load_conf("applyMode: Async\n")
    assert load_conf("applyMode: async\n").apply_mode == "async"
    assert load_conf("actions: allocate\n").apply_mode is None


def test_leadership_loss_purges_queued_decisions():
    """A deposed leader's queued (unapplied) decisions are dropped instead
    of landing on top of the new leader's placements."""

    class FlappingElector:
        def __init__(self):
            self.leader = True

        def try_acquire(self):
            return self.leader

    store = make_store([])
    _gang_fixture(store)
    conf = default_conf(backend="host")
    conf.apply_mode = "async"
    elector = FlappingElector()
    sched = Scheduler(store, conf=conf, elector=elector)
    gate = threading.Event()
    orig_bulk = store.bulk
    store.bulk = lambda ops: (gate.wait(10), orig_bulk(ops))[1]
    try:
        sched.run_once()
        assert len(sched.cache.bind_log) == 3
        elector.leader = False
        sched.run_once()  # deposed: purges whatever is still queued
        assert sched.cache.applier.pending <= sched.cache.applier.batch_max
    finally:
        gate.set()
        sched.cache.applier.flush(timeout=10)
    # whatever was already inside the store write may have landed (the
    # reference's goroutine window); everything queued behind it must not
    bound = sum(1 for p in store.list("Pod") if p.node_name)
    assert bound <= 3
