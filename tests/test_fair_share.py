"""DRF + proportion fair-share behavior in the allocate cycle (BASELINE
config 2: multi-queue weighted shares, drf job ordering).

Parity sources: KB/pkg/scheduler/plugins/drf/drf.go:60-177 (dominant share
job order), proportion/proportion.go:58-243 (water-filling, queue order,
overused gate).
"""

from volcano_tpu.api.types import PodPhase
from volcano_tpu.scheduler.conf import PluginOption, SchedulerConf, Tier
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import FakeBinder, build_node, build_pod, build_podgroup, build_queue, make_store


def run_cycle(store, tiers, actions=("allocate",)):
    conf = SchedulerConf(actions=list(actions), tiers=tiers)
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder


def test_drf_prefers_lower_dominant_share():
    # Job A already holds 2/3 of cluster cpu; job B holds nothing. With one
    # free cpu, drf's job order gives it to B.
    store = make_store(
        nodes=[build_node("n0", cpu="3", memory="6Gi")],
        podgroups=[
            build_podgroup("pg-a", min_member=1),
            build_podgroup("pg-b", min_member=1),
        ],
        pods=[
            build_pod("a-run-0", group="pg-a", cpu="1", phase=PodPhase.RUNNING, node_name="n0"),
            build_pod("a-run-1", group="pg-a", cpu="1", phase=PodPhase.RUNNING, node_name="n0"),
            build_pod("a-pend", group="pg-a", cpu="1"),
            build_pod("b-pend", group="pg-b", cpu="1"),
        ],
    )
    _, binder = run_cycle(store, tiers=[Tier(plugins=[PluginOption("drf")])])
    assert "default/b-pend" in binder.binds
    assert "default/a-pend" not in binder.binds


def test_drf_share_updates_as_allocation_progresses():
    # Two fresh jobs, 4 one-cpu tasks each, 4 cpus total: drf's event
    # handlers update shares after every bind, so capacity splits 2/2
    # instead of first-job-takes-all.
    store = make_store(
        nodes=[build_node("n0", cpu="4", memory="8Gi")],
        podgroups=[
            build_podgroup("pg-a", min_member=1),
            build_podgroup("pg-b", min_member=1),
        ],
        pods=[
            *[build_pod(f"a-{i}", group="pg-a", cpu="1") for i in range(4)],
            *[build_pod(f"b-{i}", group="pg-b", cpu="1") for i in range(4)],
        ],
    )
    _, binder = run_cycle(store, tiers=[Tier(plugins=[PluginOption("drf")])])
    a_bound = sum(1 for k in binder.binds if k.startswith("default/a-"))
    b_bound = sum(1 for k in binder.binds if k.startswith("default/b-"))
    assert (a_bound, b_bound) == (2, 2)


def test_proportion_overused_gate_splits_capacity_by_weight():
    # Equal-weight queues both demanding the whole 4-cpu cluster end up
    # with 2 cpus each: once a queue reaches its deserved share the
    # overused gate drops it from the cycle.
    store = make_store(
        nodes=[build_node("n0", cpu="4", memory="8Gi")],
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
        podgroups=[
            build_podgroup("pg-1", min_member=1, queue="q1"),
            build_podgroup("pg-2", min_member=1, queue="q2"),
        ],
        pods=[
            *[build_pod(f"q1-{i}", group="pg-1", cpu="1") for i in range(4)],
            *[build_pod(f"q2-{i}", group="pg-2", cpu="1") for i in range(4)],
        ],
    )
    _, binder = run_cycle(
        store,
        tiers=[Tier(plugins=[PluginOption("gang"), PluginOption("proportion")])],
    )
    q1_bound = sum(1 for k in binder.binds if k.startswith("default/q1-"))
    q2_bound = sum(1 for k in binder.binds if k.startswith("default/q2-"))
    assert (q1_bound, q2_bound) == (2, 2)


def test_proportion_weighted_split():
    # weight 3 : 1 over 4 cpus -> 3 and 1.
    store = make_store(
        nodes=[build_node("n0", cpu="4", memory="8Gi")],
        queues=[build_queue("q1", weight=3), build_queue("q2", weight=1)],
        podgroups=[
            build_podgroup("pg-1", min_member=1, queue="q1"),
            build_podgroup("pg-2", min_member=1, queue="q2"),
        ],
        pods=[
            *[build_pod(f"q1-{i}", group="pg-1", cpu="1", memory="2Gi") for i in range(4)],
            *[build_pod(f"q2-{i}", group="pg-2", cpu="1", memory="2Gi") for i in range(4)],
        ],
    )
    _, binder = run_cycle(
        store,
        tiers=[Tier(plugins=[PluginOption("gang"), PluginOption("proportion")])],
    )
    q1_bound = sum(1 for k in binder.binds if k.startswith("default/q1-"))
    q2_bound = sum(1 for k in binder.binds if k.startswith("default/q2-"))
    assert (q1_bound, q2_bound) == (3, 1)


def test_proportion_deserved_capped_at_request():
    # q1 asks for only 1 cpu; its unused entitlement flows to q2
    # (water-filling cap + re-spread, proportion.go:101-144).
    store = make_store(
        nodes=[build_node("n0", cpu="4", memory="8Gi")],
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
        podgroups=[
            build_podgroup("pg-1", min_member=1, queue="q1"),
            build_podgroup("pg-2", min_member=1, queue="q2"),
        ],
        pods=[
            build_pod("q1-0", group="pg-1", cpu="1"),
            *[build_pod(f"q2-{i}", group="pg-2", cpu="1") for i in range(4)],
        ],
    )
    _, binder = run_cycle(
        store,
        tiers=[Tier(plugins=[PluginOption("gang"), PluginOption("proportion")])],
    )
    q1_bound = sum(1 for k in binder.binds if k.startswith("default/q1-"))
    q2_bound = sum(1 for k in binder.binds if k.startswith("default/q2-"))
    assert q1_bound == 1
    assert q2_bound == 3
