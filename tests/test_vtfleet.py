"""vtfleet: the cross-process observability plane.

The gate for the fleet PR:

  * histogram federation is EXACT — merging K per-proc expositions
    bucket-wise produces byte-for-byte the histogram the union of the
    observations would have produced (the PR-8 fixed bucket universe is
    closed under merge), and the quantile error bound (one sub-bucket
    width, 9/SUBBUCKETS relative) survives the merge;
  * the merged /metrics exposition is conformant (HELP/TYPE once per
    family, monotone cumulative buckets, +Inf == count, every series
    proc-labelled) and byte-stable across harvest orders;
  * clock alignment follows the NTP midpoint rule: a proc's spans shift
    onto the harvester's clock by the harvest-RTT offset estimate, so a
    skewed remote interleaves correctly;
  * the ShardRouter passes ``?proc=`` through to every member debug
    surface (and its own) — regression per endpoint;
  * crash forensics: the FleetCollector's cached last-harvest snapshot
    becomes an atomic per-incident bundle directory for a process that
    is already dead;
  * the acceptance timeline: one gang trace id, submitted through the
    router over a 2-shard x 2-replica mesh, reconstructs from a single
    ``vtctl trace last --fleet`` an ordered timeline spanning
    vtctl -> router -> shard process -> replica, with the scheduler
    cycle linked in;
  * disarmed supervisor/router cycles construct ZERO collector objects
    (spied) — the arming discipline's cost contract.
"""

import json
import math
import os
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from volcano_tpu import timeseries, trace, vtfleet, vtprof
from volcano_tpu.api.objects import Metadata, Node, Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.cli import vtctl
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.metrics_server import MetricsServer
from volcano_tpu.store.client import RemoteStore

from tests.test_chaos_soak import ControlPlane, _mk_job, _submit, _wait_running
from tests.test_procmesh import NPROC, _mesh


@pytest.fixture(autouse=True)
def _clean_planes():
    metrics.reset()
    yield
    metrics.reset()
    trace.disarm()
    timeseries.disarm()
    vtprof.disarm()
    vtfleet.disarm()


def _get(url, timeout=10):
    return urllib.request.urlopen(url, timeout=timeout).read()


def _get_json(url, timeout=10):
    return json.loads(_get(url, timeout=timeout) or b"{}")


# -- histogram federation: exact merge + surviving quantile bound -------------

_FAM = "volcano_unit_merge_latency_seconds"


def _exposition_for(values):
    """One process's exposition containing exactly these observations."""
    metrics.reset()
    for v in values:
        metrics.observe(_FAM, v)
    text = metrics.expose_text()
    metrics.reset()
    return text


def _bucket_quantile(hist, q):
    """Quantile estimate off cumulative buckets: the upper edge of the
    bucket the q-th observation falls in (what dashboards compute)."""
    target = q * hist["count"]
    for le, cum in hist["buckets"]:
        if cum >= target and le != "+Inf":
            return float(le)
    return float("inf")


def test_histogram_merge_is_exactly_the_union():
    rng = random.Random(7)
    vals = [rng.lognormvariate(0.0, 2.0) for _ in range(600)]
    chunks = [vals[0::3], vals[1::3], vals[2::3]]
    texts = {f"p{i}": _exposition_for(c) for i, c in enumerate(chunks)}
    union = vtfleet.parse_exposition(_exposition_for(vals))
    merged = vtfleet.parse_exposition(vtfleet.merge_metrics(texts))
    fleet = merged[_FAM]["hist"][(("proc", "fleet"),)]
    truth = union[_FAM]["hist"][()]
    # bucket-for-bucket identical to the union-fed histogram: the fixed
    # log-linear universe makes the merge closed (see vtfleet docstring)
    assert fleet["buckets"] == truth["buckets"]
    assert fleet["count"] == truth["count"] == len(vals)
    assert float(fleet["sum"]) == pytest.approx(float(truth["sum"]),
                                                rel=1e-9)
    # ...and each proc's own series rides along, proc-labelled
    for i, c in enumerate(chunks):
        per = merged[_FAM]["hist"][(("proc", f"p{i}"),)]
        assert per["count"] == len(c)


def test_histogram_quantile_bound_survives_merge():
    rng = random.Random(11)
    vals = sorted(rng.lognormvariate(0.0, 2.0) for _ in range(900))
    chunks = [vals[0::3], vals[1::3], vals[2::3]]
    texts = {f"p{i}": _exposition_for(c) for i, c in enumerate(chunks)}
    merged = vtfleet.parse_exposition(vtfleet.merge_metrics(texts))
    fleet = merged[_FAM]["hist"][(("proc", "fleet"),)]
    bound = 9.0 / metrics.SUBBUCKETS  # one sub-bucket width, relative
    for q in (0.5, 0.9, 0.99):
        est = _bucket_quantile(fleet, q)
        # the bucket rule (first cum >= q*n) selects the bucket holding
        # the ceil(q*n)-th smallest observation
        true = vals[max(math.ceil(q * len(vals)) - 1, 0)]
        # the estimate is the bucket's upper edge: never below the true
        # sample, never more than one bucket width above it
        assert est >= true * (1.0 - 1e-9), (q, est, true)
        assert (est - true) / true <= bound + 1e-6, (q, est, true)


# -- merged exposition: conformance + byte stability --------------------------


def _three_proc_expositions():
    texts = {}
    for i, name in enumerate(("shard00", "shard01", "router")):
        metrics.reset()
        metrics.inc("volcano_unit_ops_total", float(i + 1), queue="q1")
        metrics.inc("volcano_unit_ops_total", 1.0, queue="q2")
        metrics.set_gauge("volcano_unit_depth", float(10 * i))
        for v in (0.001 * (i + 1), 0.5, 2.0 ** i):
            metrics.observe(_FAM, v)
        texts[name] = metrics.expose_text()
    metrics.reset()
    return texts


def test_merged_exposition_is_conformant():
    merged = vtfleet.merge_metrics(_three_proc_expositions())
    lines = merged.splitlines()
    helps = [ln for ln in lines if ln.startswith("# HELP ")]
    types = [ln for ln in lines if ln.startswith("# TYPE ")]
    fams = [ln.split(" ", 3)[2] for ln in types]
    # HELP/TYPE exactly once per family
    assert len(set(fams)) == len(fams)
    assert len(helps) == len(types) == len(fams)
    # every sample line carries a proc= label
    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        assert 'proc="' in ln, ln
    fam = vtfleet.parse_exposition(merged)[_FAM]
    assert fam["type"] == "histogram"
    assert set(dict(k)["proc"] for k in fam["hist"]) == {
        "shard00", "shard01", "router", "fleet"}
    for key, h in fam["hist"].items():
        cums = [c for _, c in sorted(h["buckets"],
                                     key=lambda b: vtfleet._le_key(b[0]))]
        assert cums == sorted(cums), key  # monotone cumulative
        assert cums[-1] == h["count"], key  # +Inf == count
    # the counter federates with per-proc provenance, labels preserved
    ops = vtfleet.parse_exposition(merged)["volcano_unit_ops_total"]
    got = {(dict(labels)["proc"], dict(labels)["queue"]): float(v)
           for labels, v in ops["scalar"]}
    assert got[("shard00", "q1")] == 1.0
    assert got[("router", "q1")] == 3.0
    assert got[("shard01", "q2")] == 1.0


def test_merged_exposition_is_byte_stable_across_harvest_orders():
    texts = _three_proc_expositions()
    a = vtfleet.merge_metrics(dict(sorted(texts.items())))
    b = vtfleet.merge_metrics(dict(sorted(texts.items(), reverse=True)))
    assert a == b
    # absent procs (a dead member's None exposition) merge as if never
    # harvested, not as an error
    c = vtfleet.merge_metrics(dict(texts, ghost=None))
    assert c == a


# -- clock alignment ----------------------------------------------------------


def _span(tid, sid, name, start, parent="", proc_extra=()):
    return dict({"trace": tid, "span": sid, "parent": parent,
                 "name": name, "start": start, "dur": 0.001,
                 "attrs": {}, "links": [], "component": ""}, **dict(proc_extra))


def test_merge_trace_aligns_skewed_remote_clock():
    snap = {
        "procs": {
            "a": {"offset": 5.0, "trace": {
                "armed": True, "pid": 11,
                "spans": [_span("t1", "s1", "remote.op", 105.0)]}},
            "b": {"offset": 0.0, "trace": {
                "armed": True, "pid": 22,
                "spans": [_span("t1", "s2", "local.op", 100.5)]}},
        },
        "unreachable": ["ghost"],
    }
    merged = vtfleet.merge_trace(snap)
    assert merged["armed"]
    # a's clock runs 5s fast: its span lands at 100.0 on the harvester's
    # clock and therefore sorts BEFORE b's 100.5 despite the raw stamps
    assert [(s["proc"], s["start"]) for s in merged["spans"]] == [
        ("a", 100.0), ("b", 100.5)]
    assert merged["procs"]["a"]["offset_s"] == 5.0
    assert merged["procs"]["b"]["spans"] == 1
    assert merged["unreachable"] == ["ghost"]


class _SkewedHandler(BaseHTTPRequestHandler):
    """A proc whose wall clock runs SKEW seconds fast, with one wedged
    surface (/debug/prof 500s) to exercise harvest degradation."""

    SKEW = 7.5

    def do_GET(self):  # noqa: N802 - http.server contract
        if self.path.startswith("/debug/prof"):
            self.send_error(500)
            return
        if self.path.startswith("/metrics"):
            body = b""
            self.send_response(200)
        else:
            body = json.dumps({"armed": False, "pid": os.getpid(),
                               "now": time.time() + self.SKEW,
                               "spans": []}).encode()
            self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


def test_harvest_proc_estimates_midpoint_offset_and_degrades():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _SkewedHandler)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        snap = vtfleet.harvest_proc("skewed", url)
        # NTP midpoint rule: offset ~= the injected skew (loopback RTT
        # is the only error term)
        assert snap["offset"] == pytest.approx(_SkewedHandler.SKEW,
                                               abs=0.5)
        assert snap["trace"] is not None
        assert snap["prof"] is None  # wedged surface degraded, not fatal
        assert snap["metrics"] == ""
        # a dead proc raises on the FIRST surface -> unreachable
        srv.shutdown()
        srv.server_close()
        with pytest.raises(Exception):
            vtfleet.harvest_proc("skewed", url, timeout=0.5)
    finally:
        srv.server_close()


# -- crash forensics: the incident bundle -------------------------------------


def test_incident_bundle_from_last_harvested_snapshot(tmp_path):
    trace.arm()
    with trace.span("unit.incident"):
        pass
    srv = MetricsServer(port=0).start()
    url = f"http://127.0.0.1:{srv.port}"
    col = vtfleet.FleetCollector(incident_dir=str(tmp_path))
    try:
        col.harvest_member("m0", url)
        snap = col.last("m0")
        assert snap and snap["trace"]["armed"]
    finally:
        srv.stop()
    # the member is dead now: a failed refresh KEEPS the last snapshot
    col.harvest_member("m0", url)
    assert col.last("m0") is snap
    bundle = col.incident("m0", {"pid": 123, "reason": "unit"})
    assert bundle and os.path.basename(bundle) == "incident-m0-123-0001"
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    assert set(os.listdir(bundle)) == {
        "meta.json", "trace.json", "prof.json", "timeseries.json",
        "digest.json"}
    with open(os.path.join(bundle, "meta.json")) as f:
        meta = json.load(f)
    assert meta == {"pid": 123, "reason": "unit", "proc": "m0"}
    with open(os.path.join(bundle, "trace.json")) as f:
        ring = json.load(f)
    assert ring["armed"]
    assert "unit.incident" in {s["name"] for s in ring["spans"]}
    # a member that was never harvested still yields a bundle — with a
    # null ring, because forensics must not mask the failure
    ghost = col.incident("ghost", {"pid": 0})
    assert ghost and os.path.basename(ghost) == "incident-ghost-0-0002"
    with open(os.path.join(ghost, "trace.json")) as f:
        assert json.load(f) is None


# -- router ?proc= passthrough: regression per endpoint -----------------------


def test_router_proc_passthrough_every_debug_endpoint():
    sup, router = _mesh(NPROC)
    try:
        member_pids = {m["shard"]: m["pid"]
                       for m in sup.status()["members"]}
        for path in ("/debug/trace", "/debug/timeseries", "/debug/prof"):
            mine = _get_json(f"{router.url}{path}?proc=router")
            p0 = _get_json(f"{router.url}{path}?proc=0")
            p1 = _get_json(f"{router.url}{path}?proc=1")
            # router answers from the ROUTER's process, shard selectors
            # from each member's own process
            assert mine["pid"] == os.getpid(), path
            assert p0["pid"] == member_pids[0], path
            assert p1["pid"] == member_pids[1], path
        # digest carries no pid: the passthrough must match the shard's
        # own surface instead of the router's cross-shard rollup
        direct = _get_json(sup.shard_map[0] + "/debug/digest")
        via = _get_json(router.url + "/debug/digest?proc=0")
        assert {k: v for k, v in via.items() if k != "now"} \
            == {k: v for k, v in direct.items() if k != "now"}
        # /metrics?proc=N is the RAW single-proc exposition (no proc=
        # labels) — the federated merge only runs unselected
        raw = _get(router.url + "/metrics?proc=0").decode()
        assert 'proc="' not in raw
        # unknown selectors 404 on every surface
        for path in vtfleet.DEBUG_PATHS + ("/metrics",):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{router.url}{path}?proc=9")
            assert e.value.code == 404, path
    finally:
        router.stop()
        sup.stop()


# -- the acceptance timeline --------------------------------------------------


def test_fleet_trace_reassembles_gang_timeline(tmp_path, monkeypatch,
                                               capsys):
    """One trace id, submitted through the router over a 2-shard x
    2-replica mesh, reconstructs an ordered cross-process timeline from
    a single ``vtctl trace last --fleet``: vtctl root -> router ->
    shard leader -> replica, with the scheduler cycle linked in."""
    # children arm via env, parent in-process; big rings — the control
    # plane's cycle machinery churns spans fast enough to evict the one
    # submit trace from the default ring before the harvest lands
    monkeypatch.setenv("VOLCANO_TPU_TRACE", '{"ring": 65536}')
    trace.arm(trace.Tracer(ring=65536))
    sched_srv = MetricsServer(port=0).start()
    sched_url = f"http://127.0.0.1:{sched_srv.port}"
    state = str(tmp_path / "state.json")
    sup, router = _mesh(2, state=state, wal=state + ".wal", replicas=2)
    cp = ControlPlane(router.url)
    try:
        client = RemoteStore(router.url)
        client.create("Queue", Queue(meta=Metadata(name="default",
                                                   namespace="")))
        client.create("Node", Node(
            meta=Metadata(name="n0", namespace=""),
            allocatable=Resource.from_resource_list(
                {"cpu": "4", "memory": "8Gi", "pods": 110})))
        cp.start(schedulers=1, controllers=1)
        job = _mk_job("fj0", 2)
        with trace.span("vtctl.job.run", job="soak/fj0"):
            trace.stamp(job.meta)
            _submit(client, job)
        tid = trace.gang_trace(job.meta)
        assert tid
        _wait_running(client, "soak/fj0")

        deadline = time.monotonic() + 30.0
        while True:
            snap = vtfleet.harvest(router.url,
                                   daemons=[("sched", sched_url)])
            merged = vtfleet.merge_trace(snap)
            sel = trace.spans_for_trace(merged["spans"], tid)
            procs = {s["proc"] for s in sel}
            names = {s["name"] for s in sel}
            leaders = {p for p in procs
                       if p.startswith("shard") and ".r" not in p}
            replicas = {p for p in procs if ".r" in p}
            if leaders and replicas and {
                    "vtctl.job.run", "router.post", "store.POST",
                    "replica.apply", "scheduler.cycle"} <= names:
                break
            if time.monotonic() > deadline:
                raise AssertionError((sorted(procs), sorted(names)))
            time.sleep(0.2)

        # structural order: the vtctl root parents the router request,
        # which parents the shard leader's store request.  (In this
        # harness the router thread shares the parent process, so its
        # spans surface under BOTH the "router" and "sched" harvest
        # targets and the dedup attributes each to one of them — the
        # parent/child chain is attribution-independent.)
        root = next(s for s in sel if s["name"] == "vtctl.job.run")
        rpost = min((s for s in sel if s["name"] == "router.post"),
                    key=lambda s: s["start"])
        spost = min((s for s in sel if s["name"] == "store.POST"),
                    key=lambda s: s["start"])
        rapply = min((s for s in sel if s["name"] == "replica.apply"),
                     key=lambda s: s["start"])
        assert rpost["parent"] == root["span"]
        assert rpost["proc"] in ("router", "sched")
        assert spost["parent"] == rpost["span"]
        assert spost["proc"] in leaders
        assert rapply["proc"] in replicas
        # temporal order on the ALIGNED clock, with midpoint-estimate
        # slack on every cross-snapshot edge
        assert rpost["start"] >= root["start"] - 0.05
        assert spost["start"] >= rpost["start"] - 0.05
        assert rapply["start"] >= spost["start"] - 0.05
        # the scheduler cycle serving the gang links the trace id
        cyc = next(s for s in sel if s["name"] == "scheduler.cycle")
        assert tid in cyc["links"]

        # ...and the single CLI invocation renders all of it
        rc = vtctl.main(["trace", "last", "--server", router.url,
                         "--fleet", "--daemon", f"sched={sched_url}",
                         "--trace", tid])
        out = capsys.readouterr().out
        assert rc == 0
        assert f"trace {tid}" in out
        for proc in ("router", sorted(leaders)[0], rapply["proc"],
                     "sched"):
            assert f"proc {proc} " in out, (proc, out)
        for name in ("vtctl.job.run", "router.post", "store.POST",
                     "replica.apply"):
            assert name in out, (name, out)
    finally:
        cp.shutdown()
        router.stop()
        sup.stop()
        sched_srv.stop()


# -- the arming discipline's cost contract ------------------------------------


def test_disarmed_cycles_construct_zero_collector_objects(monkeypatch):
    assert vtfleet.COLLECTOR is None  # disarmed default
    made = []
    orig = vtfleet.FleetCollector.__init__

    def spy(self, *a, **k):
        made.append((a, k))
        return orig(self, *a, **k)

    monkeypatch.setattr(vtfleet.FleetCollector, "__init__", spy)
    sup, router = _mesh(1)
    try:
        rs = RemoteStore(router.url)
        rs.create("Queue", Queue(meta=Metadata(name="default",
                                               namespace="")))
        time.sleep(0.5)  # several supervisor monitor ticks
        # the federated /metrics merge runs collector-free too
        assert b"volcano_" in _get(router.url + "/metrics")
    finally:
        router.stop()
        sup.stop()
    assert made == []
