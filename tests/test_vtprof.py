"""vtprof: device/host critical-path profiler, recompile sentinel,
memory watermarks.

Covers the tentpole contracts of volcano_tpu/vtprof.py:

* the arming discipline: a DISARMED lifecycle constructs zero Profiler
  objects (spied, the PR-4 trace-smoke pattern) and an ARMED run is
  placement-neutral with the cfg5 phase set unchanged;
* armed attribution: >= 95% of sampled cycle wall-clock lands in named
  host/dispatch/wait/transfer segments, and the per-kernel device totals
  sum consistently with the per-phase breakdown;
* the jit recompile sentinel: >= 20 post-warmup trickle cycles (varying
  task counts within a shape bucket) leave ``volcano_jit_compiles_total``
  flat, and a deliberately bucket-breaking shape advances it exactly
  once AND trips the steady-state anomaly (the sentinel fires, not just
  stays quiet);
* the leak sentinel: bounded under loadgen churn, trips once on a
  synthetic monotone device-bytes ramp;
* the surfaces: /debug/prof on both servers (chaos-exempt), vtrace span
  annotations at the fetch boundary, crash-dump anomalies/profile
  sections, the vtctl top device/host column + anomaly line.
"""

import json
import urllib.request

import pytest

from volcano_tpu import timeseries, trace, vtprof
from volcano_tpu.api import POD_GROUP_KEY, Resource
from volcano_tpu.api.objects import Metadata, Node, Pod, PodGroup, PodSpec, Queue
from volcano_tpu.api.types import PodGroupPhase
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.store import Store


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    vtprof.disarm()
    timeseries.disarm()
    trace.disarm()
    yield
    vtprof.disarm()
    timeseries.disarm()
    trace.disarm()
    metrics.reset()


def _mk_store(n_nodes=4, cpu=8000.0):
    store = Store()
    store.create("Queue", Queue(
        meta=Metadata(name="default", namespace=""), weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:03d}", namespace=""),
            allocatable=Resource(cpu, 16.0 * (1 << 30), max_task_num=200)))
    return store


def _submit_gang(store, name, n_pods, cpu=100.0):
    pg = PodGroup(meta=Metadata(name=name, namespace="default"),
                  min_member=n_pods, queue="default")
    pg.status.phase = PodGroupPhase.INQUEUE  # default_conf has no enqueue
    store.create("PodGroup", pg)
    for t in range(n_pods):
        store.create("Pod", Pod(
            meta=Metadata(name=f"{name}-{t}", namespace="default",
                          annotations={POD_GROUP_KEY: name}),
            spec=PodSpec(image="x", resources=Resource(cpu, 1 << 20))))


# -- arming discipline --------------------------------------------------------


def test_disarmed_lifecycle_constructs_zero_profiler_objects(monkeypatch):
    """The overhead smoke: with the profiler disarmed, full fast cycles
    (crossing the sanctioned fetch boundaries) construct zero Profiler
    objects and record nothing — the hot path crosses only the
    ``PROFILER is None`` guards."""
    assert vtprof.PROFILER is None

    def explode(*a, **kw):
        raise AssertionError("profiler runtime touched while disarmed")

    monkeypatch.setattr(vtprof, "Profiler", explode)
    monkeypatch.setattr(vtprof.Profiler, "record_fetch", explode,
                        raising=False)
    store = _mk_store()
    sched = Scheduler(store, conf=default_conf("tpu"))
    _submit_gang(store, "quiet", 3)
    sched.run_once()
    sched.run_once()
    assert sum(1 for p in store.items("Pod") if p.node_name) == 3


def test_armed_run_is_placement_neutral_and_phase_set_unchanged():
    """Acceptance: armed-vs-disarmed runs produce bit-for-bit identical
    placements, and the fast cycle's phase set (bench.py's breakdown)
    gains no phase from profiling."""
    def run(arm):
        if arm:
            vtprof.arm()
        try:
            store = _mk_store()
            sched = Scheduler(store, conf=default_conf("tpu"))
            for i in range(3):
                _submit_gang(store, f"j{i}", 2)
                sched.run_once()
            sched.run_once()
            placements = sorted(
                (p.meta.key, p.node_name) for p in store.list("Pod"))
            return placements, set(sched.fast_cycle.phases or {})
        finally:
            vtprof.disarm()

    base, base_phases = run(arm=False)
    armed_p, armed_phases = run(arm=True)
    assert armed_p == base
    assert armed_phases == base_phases


# -- attribution --------------------------------------------------------------


def test_armed_profile_attributes_95pct_and_kernel_totals_consistent():
    """Acceptance: the armed profile attributes >= 95% of sampled cycle
    wall-clock to named segments (no large unattributed bucket), and the
    per-kernel device totals equal the per-phase device segments — two
    groupings of the same records."""
    def one_run():
        vtprof.disarm()
        prof = vtprof.arm()
        store = _mk_store(n_nodes=6)
        sched = Scheduler(store, conf=default_conf("tpu"))
        # gangs big enough that per-cycle work dwarfs the fixed
        # scheduler-loop overhead even with fully warm jit caches
        for i in range(4):
            _submit_gang(store, f"g{i}", 60, cpu=10.0)
            sched.run_once()
        payload = prof.payload()
        return payload, vtprof.attribution(payload)

    # best-of-2, the bench methodology: one run can take a CPU-
    # contention hit in its between-phase gaps on a loaded test host
    payload, att = one_run()
    if att["coverage"] < 0.95:
        payload, att = one_run()
    assert payload["cycles"], "no cycles sampled"
    assert att["coverage"] >= 0.95, att
    # segment names are exactly the vtprof taxonomy
    assert set(att["segments"]) == {"host", "dispatch", "wait", "transfer"}
    # per-kernel device totals vs per-phase device segments
    kernel_dev = 0.0
    for cyc in payload["cycles"]:
        for kc in cyc["kernels"].values():
            kernel_dev += (kc.get("dispatch_s", 0.0) + kc.get("wait_s", 0.0)
                           + kc.get("transfer_s", 0.0))
    phase_dev = (att["segments"]["dispatch"] + att["segments"]["wait"]
                 + att["segments"]["transfer"])
    # per_phase rows are rounded to 1e-6 in the cycle records
    assert kernel_dev == pytest.approx(phase_dev, rel=1e-3, abs=1e-4)
    # the dispatch counter landed in the bounded metrics core
    assert metrics.get_counter(
        "volcano_kernel_dispatch_total", kernel="allocate_solve") > 0
    # memory watermark gauges exist for every component
    text = metrics.expose_text()
    for component in ("mirror", "snapshot", "solve_out", "device"):
        assert f'volcano_device_bytes{{component="{component}"}}' in text


def test_fetch_boundary_annotates_vtrace_span():
    """The fetch boundary's wait/transfer split rides the existing
    device span as annotations when both layers are armed."""
    tr = trace.arm()
    vtprof.arm()
    store = _mk_store()
    sched = Scheduler(store, conf=default_conf("tpu"))
    _submit_gang(store, "sp", 2)
    sched.run_once()
    spans = [r for r in tr.records() if r["name"] == "device.allocate_solve"]
    assert spans, "no device span recorded"
    assert "wait_s" in spans[-1]["attrs"]
    assert "transfer_s" in spans[-1]["attrs"]


# -- the jit recompile sentinel -----------------------------------------------


def _compiles(kernel):
    return metrics.get_counter("volcano_jit_compiles_total", kernel=kernel)


def test_steady_state_trickle_never_recompiles_and_bucket_break_fires():
    """The satellite regression: >= 20 trickle cycles after warmup
    (task counts varying 1-3 within the minimum shape bucket, a node
    added mid-stream inside the node bucket) advance
    ``volcano_jit_compiles_total`` by exactly zero; a deliberately
    bucket-breaking 9-pod gang advances it exactly once AND trips the
    steady-state-recompile anomaly."""
    prof = vtprof.arm()
    store = _mk_store(n_nodes=10)
    sched = Scheduler(store, conf=default_conf("tpu"))
    # initial batch sizes the job bucket high enough that the trickle
    # cannot cross it (40 jobs -> J bucket 64; 40+2+20+1 = 63 <= 64)
    for i in range(40):
        _submit_gang(store, f"w{i:03d}", 1)
    sched.run_once()
    # warm the trickle shape itself (T bucket = minimum) before the
    # handshake: its first dispatch is a legitimate warmup compile
    for i in range(2):
        _submit_gang(store, f"t{i:03d}", 1)
        sched.run_once()
    prof.warmup_handshake()
    sched.run_once()  # first compile-free cycle -> steady
    assert prof.steady
    before = dict(prof._cache_seen)
    total_before = prof.compiles_total
    counter_before = _compiles("allocate_solve")
    # >= 20 trickle cycles, 1-3 pending tasks per cycle, all within the
    # minimum task bucket; a node joins mid-stream (10 -> 11 nodes stays
    # inside the 16-node bucket)
    for i in range(20):
        _submit_gang(store, f"k{i:03d}", 1 + (i % 3), cpu=10.0)
        if i == 10:
            store.create("Node", Node(
                meta=Metadata(name="n-late", namespace=""),
                allocatable=Resource(8000.0, 16.0 * (1 << 30),
                                     max_task_num=200)))
        sched.run_once()
    assert prof.compiles_total == total_before, (
        "steady-state trickle recompiled", prof._cache_seen, before)
    assert _compiles("allocate_solve") == counter_before
    assert prof.anomalies_snapshot() == []
    # the bucket break: 9 pending tasks leave the minimum bucket -> ONE
    # new compile of the packed allocate solve, flagged as an anomaly
    _submit_gang(store, "breaker", 9, cpu=10.0)
    sched.run_once()
    assert prof.compiles_total == total_before + 1
    assert _compiles("allocate_solve") == counter_before + 1
    anomalies = prof.anomalies_snapshot()
    assert len(anomalies) == 1
    assert anomalies[0]["kind"] == "steady-state-recompile"
    assert "allocate_solve" in anomalies[0]["kernels"]
    # every submitted pod is bound: the trickle was real scheduling
    assert all(p.node_name for p in store.list("Pod"))


# -- the leak sentinel --------------------------------------------------------


def test_leak_sentinel_quiet_under_loadgen_churn():
    """Churn-bounded: an open-loop load with dwell departures (the
    existing loadgen) holds the device watermark bounded — the sentinel
    must stay quiet over >= 2 windows of cycles."""
    from volcano_tpu.loadgen import LoadSpec, run_open_loop

    prof = vtprof.arm()
    store = _mk_store(n_nodes=6)
    sched = Scheduler(store, conf=full_conf("tpu"))
    spec = LoadSpec(qps=30, duration_s=2.0, seed=3,
                    cpu_millis=(100,), mem_mb=(64,), dwell_s=0.4)
    # lockstep virtual time: a deterministic >= 2-window cycle count
    # regardless of CPU compile hiccups
    report = run_open_loop(store, spec, sched.run_once, settle_s=20.0,
                           tick_s=0.05)
    assert report.bound_pods == report.submitted_pods
    assert len(prof.payload()["cycles"]) >= 2 * vtprof.LEAK_WINDOW
    assert [a for a in prof.anomalies_snapshot()
            if a["kind"] == "device-bytes-leak"] == []


def test_leak_sentinel_trips_once_on_synthetic_ramp(monkeypatch):
    ramp = iter(range(1, 200))

    def fake_bytes():
        return next(ramp) * (64 << 20)  # +64MiB per cycle, forever

    monkeypatch.setattr(vtprof, "_live_device_bytes", fake_bytes)
    prof = vtprof.Profiler()
    for _ in range(3 * vtprof.LEAK_WINDOW):
        prof.begin_cycle()
        prof.end_cycle(0.001, {}, "fast")
    trips = [a for a in prof.anomalies_snapshot()
             if a["kind"] == "device-bytes-leak"]
    assert len(trips) == 1  # trips once, not every cycle
    assert trips[0]["recent_bytes"] > trips[0]["baseline_bytes"]


def test_leak_sentinel_baseline_is_anchored_across_ring_wrap(monkeypatch):
    """Review hardening: the baseline is captured ONCE from the first
    window — a sliding baseline would let a slow leak outrun the ring
    (recent/baseline tends to 1 as the footprint grows) and never
    trip."""
    i = iter(range(10_000))

    def slow_leak():  # +2MiB per cycle on a 256MiB footprint
        return (256 << 20) + next(i) * (2 << 20)

    monkeypatch.setattr(vtprof, "_live_device_bytes", slow_leak)
    prof = vtprof.Profiler(ring=4 * vtprof.LEAK_WINDOW)
    for _ in range(20 * vtprof.LEAK_WINDOW):  # far past the ring span
        prof.begin_cycle()
        prof.end_cycle(0.001, {}, "fast")
    trips = [a for a in prof.anomalies_snapshot()
             if a["kind"] == "device-bytes-leak"]
    assert len(trips) == 1, "slow leak must still trip after ring wrap"
    assert trips[0]["baseline_bytes"] < (300 << 20)  # first-window anchor


# -- surfaces -----------------------------------------------------------------


def test_debug_prof_endpoint_on_both_servers_and_chaos_exempt():
    from volcano_tpu.chaos import FaultPlan
    from volcano_tpu.scheduler.metrics_server import MetricsServer
    from volcano_tpu.store.server import StoreServer

    prof = vtprof.arm()
    prof.begin_cycle()
    prof.record_fetch("allocate_solve", "solve", 0.01, 0.002)
    prof.end_cycle(0.05, {"solve": 0.04}, "fast")
    srv = StoreServer()
    # a 100%-5xx storm must not block the admin endpoint
    srv.chaos = FaultPlan.from_dict({
        "seed": 1,
        "faults": [{"point": "server.request", "prob": 1.0,
                    "action": "http_500"}],
    })
    srv.start()
    msrv = MetricsServer(port=0).start()
    try:
        for url in (srv.url, f"http://127.0.0.1:{msrv.port}"):
            with urllib.request.urlopen(url + "/debug/prof", timeout=10) as r:
                body = json.load(r)
            assert body["armed"] is True
            assert body["totals"]["allocate_solve"]["wait_s"] > 0
        vtprof.disarm()
        with urllib.request.urlopen(srv.url + "/debug/prof", timeout=10) as r:
            assert json.load(r)["armed"] is False
    finally:
        srv.stop()
        msrv.stop()


def test_crash_dump_carries_anomalies_and_profile(tmp_path):
    tr = trace.arm(trace.Tracer(ring=64, dump_dir=str(tmp_path)))
    prof = vtprof.arm()
    prof.begin_cycle()
    prof.end_cycle(0.01, {"solve": 0.01}, "fast")
    with prof._mu:
        prof.anomalies.append({"kind": "steady-state-recompile",
                               "cycle": 7, "kernels": {"allocate_solve": 1}})
    with trace.span("pre-crash"):
        pass
    path = trace.crash_dump("unit")
    dump = json.load(open(path))
    assert dump["anomalies"][0]["kind"] == "steady-state-recompile"
    assert dump["profile"]["cycles"] == 1
    assert dump["profile"]["last_cycle"]["per_phase"]["solve"]
    del tr


def test_report_text_renders_flame_rows_kernels_and_anomalies():
    prof = vtprof.arm()
    prof.begin_cycle()
    tok = prof.dispatch_begin(lambda: None)
    prof.dispatch_end(tok, "allocate_solve", phase="solve")
    prof.record_fetch("allocate_solve", "solve", 0.02, 0.005)
    prof.note_bytes("snapshot", 3 << 20)
    prof.end_cycle(0.1, {"solve": 0.06, "publish": 0.03}, "fast")
    text = vtprof.report_text(prof.payload())
    assert "vtprof: 1 cycle(s) sampled" in text
    assert "solve" in text and "publish" in text
    assert "unattributed" in text
    assert "allocate_solve" in text and "dispatches=1" in text
    assert "snapshot=3.0MiB" in text
    assert "anomalies: none" in text
    vtprof.disarm()
    assert "no profile samples" in vtprof.report_text(vtprof.debug_payload())


def test_vtctl_top_renders_dev_host_column_and_anomaly_line():
    from volcano_tpu.cli import cmd_top

    rec = timeseries.arm()
    vtprof.arm()
    store = _mk_store()
    sched = Scheduler(store, conf=default_conf("tpu"))
    _submit_gang(store, "t0", 2)
    sched.run_once()
    timeseries.record("anomaly", anomaly="steady-state-recompile", cycle=0,
                      kernels={"allocate_solve": 1})
    text = cmd_top(rec.samples())
    assert "Dev/Host" in text
    row = [ln for ln in text.splitlines() if ln.startswith("0 ")][0]
    assert "/" in row.split()[3]  # the dev/host cell is populated
    assert "anomalies: steady-state-recompile" in text
    assert "cycle 0" in text


def test_background_prewarm_defers_warmup_handshake():
    """Review hardening: with background prewarm, the warmup handshake
    fires after the background warm thread finishes — its deferred
    compiles are warmup, never steady-state-recompile anomalies."""
    prof = vtprof.arm()
    store = _mk_store()
    _submit_gang(store, "w", 2)
    sched = Scheduler(store, conf=default_conf("tpu"))
    sched.prewarm(background=True)
    if sched.prewarm_background is not None:
        sched.prewarm_background.join()
    assert prof._warmed
    # no anomaly was recorded by prewarm's own compiles
    assert prof.anomalies_snapshot() == []
