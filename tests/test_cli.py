"""vtctl command surface, mirroring reference test/e2e/command.go."""

import pytest

from volcano_tpu.api.types import JobPhase
from volcano_tpu.cli import cmd_list, cmd_resume, cmd_run, cmd_suspend
from volcano_tpu.sim import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default")
    c.add_node("n0", {"cpu": "8", "memory": "16Gi", "pods": 110})
    return c


def test_run_and_list(cluster):
    cmd_run(cluster.store, name="cli-job", replicas=2, min_available=2)
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/cli-job")
    assert job.status.state.phase == JobPhase.RUNNING

    text = cmd_list(cluster.store)
    lines = text.splitlines()
    assert lines[0].split()[:3] == ["Name", "Creation", "Phase"]
    row = [ln for ln in lines if ln.startswith("cli-job")][0].split()
    assert row[2] == "Running"
    assert row[3] == "2"  # replicas


def test_list_empty(cluster):
    assert "No resources found" in cmd_list(cluster.store)


def test_suspend_resume_roundtrip(cluster):
    cmd_run(cluster.store, name="sr", replicas=2, min_available=2)
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/sr")
    assert job.status.state.phase == JobPhase.RUNNING

    cmd_suspend(cluster.store, "default", "sr")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED
    assert cluster.store.list("Pod") == []

    cmd_resume(cluster.store, "default", "sr")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING
    assert len(cluster.store.list("Pod")) == 2


def test_suspend_pending_job(cluster):
    # job too big to schedule stays pending; suspend still aborts it
    cmd_run(cluster.store, name="pend", replicas=4, min_available=4,
            requests="cpu=4000m,memory=1Gi")
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/pend")
    assert job.status.state.phase in (JobPhase.PENDING, JobPhase.INQUEUE)

    cmd_suspend(cluster.store, "default", "pend")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED


def test_run_rejected_by_admission(cluster):
    from volcano_tpu.admission import AdmissionError

    with pytest.raises(AdmissionError):
        cmd_run(cluster.store, name="bad", replicas=1, min_available=5)


def test_suspend_unknown_job(cluster):
    with pytest.raises(KeyError):
        cmd_suspend(cluster.store, "default", "ghost")


def test_main_entry_roundtrip(tmp_path):
    from volcano_tpu.cli.vtctl import main

    state = str(tmp_path / "state.pkl")
    assert main(["--state", state, "cluster", "init", "--nodes", "2"]) == 0
    assert main(["--state", state, "job", "run", "--name", "m1",
                 "--replicas", "2", "--min", "2"]) == 0
    assert main(["--state", state, "job", "list"]) == 0
    assert main(["--state", state, "job", "suspend", "--name", "m1"]) == 0
    assert main(["--state", state, "job", "resume", "--name", "m1"]) == 0
    assert main(["--state", state, "job", "suspend", "--name", "ghost"]) == 1
