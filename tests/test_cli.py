"""vtctl command surface, mirroring reference test/e2e/command.go."""

import pytest

from volcano_tpu.api.types import JobPhase
from volcano_tpu.cli import cmd_list, cmd_resume, cmd_run, cmd_suspend
from volcano_tpu.sim import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default")
    c.add_node("n0", {"cpu": "8", "memory": "16Gi", "pods": 110})
    return c


def test_run_and_list(cluster):
    cmd_run(cluster.store, name="cli-job", replicas=2, min_available=2)
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/cli-job")
    assert job.status.state.phase == JobPhase.RUNNING

    text = cmd_list(cluster.store)
    lines = text.splitlines()
    assert lines[0].split()[:3] == ["Name", "Creation", "Phase"]
    row = [ln for ln in lines if ln.startswith("cli-job")][0].split()
    assert row[2] == "Running"
    assert row[3] == "2"  # replicas


def test_list_empty(cluster):
    assert "No resources found" in cmd_list(cluster.store)


def test_suspend_resume_roundtrip(cluster):
    cmd_run(cluster.store, name="sr", replicas=2, min_available=2)
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/sr")
    assert job.status.state.phase == JobPhase.RUNNING

    cmd_suspend(cluster.store, "default", "sr")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED
    assert cluster.store.list("Pod") == []

    cmd_resume(cluster.store, "default", "sr")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING
    assert len(cluster.store.list("Pod")) == 2


def test_suspend_pending_job(cluster):
    # job too big to schedule stays pending; suspend still aborts it
    cmd_run(cluster.store, name="pend", replicas=4, min_available=4,
            requests="cpu=4000m,memory=1Gi")
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/pend")
    assert job.status.state.phase in (JobPhase.PENDING, JobPhase.INQUEUE)

    cmd_suspend(cluster.store, "default", "pend")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED


def test_run_rejected_by_admission(cluster):
    from volcano_tpu.admission import AdmissionError

    with pytest.raises(AdmissionError):
        cmd_run(cluster.store, name="bad", replicas=1, min_available=5)


def test_suspend_unknown_job(cluster):
    with pytest.raises(KeyError):
        cmd_suspend(cluster.store, "default", "ghost")


def test_main_entry_roundtrip(tmp_path):
    from volcano_tpu.cli.vtctl import main

    state = str(tmp_path / "state.pkl")
    assert main(["--state", state, "cluster", "init", "--nodes", "2"]) == 0
    assert main(["--state", state, "job", "run", "--name", "m1",
                 "--replicas", "2", "--min", "2"]) == 0
    assert main(["--state", state, "job", "list"]) == 0
    assert main(["--state", state, "job", "suspend", "--name", "m1"]) == 0
    assert main(["--state", state, "job", "resume", "--name", "m1"]) == 0
    assert main(["--state", state, "job", "suspend", "--name", "ghost"]) == 1


# -- node cordon/uncordon/drain + pool list (elastic capacity) ----------------


def test_node_cordon_shows_scheduling_disabled_and_masks(cluster):
    from volcano_tpu.cli import cmd_cordon, cmd_node_list, cmd_uncordon

    cluster.add_node("n1", {"cpu": "8", "memory": "16Gi", "pods": 110})
    cmd_cordon(cluster.store, "n0")
    text = cmd_node_list(cluster.store)
    row = [ln for ln in text.splitlines() if ln.startswith("n0")][0]
    assert "Ready,SchedulingDisabled" in row
    # new work lands on the remaining schedulable node only
    cmd_run(cluster.store, name="after", replicas=2, min_available=2)
    cluster.run_until_idle()
    assert {p.node_name for p in cluster.store.list("Pod")} == {"n1"}
    cmd_uncordon(cluster.store, "n0")
    assert "SchedulingDisabled" not in cmd_node_list(cluster.store)


def test_node_drain_is_cordon_plus_evict(cluster):
    from volcano_tpu.cli import cmd_drain, cmd_node_list

    cluster.add_node("n1", {"cpu": "8", "memory": "16Gi", "pods": 110})
    cmd_run(cluster.store, name="d1", replicas=2, min_available=2)
    cluster.run_until_idle()
    victims = [p for p in cluster.store.list("Pod") if p.node_name == "n0"]
    evicted = cmd_drain(cluster.store, "n0")
    assert sorted(evicted) == sorted(p.meta.key for p in victims)
    assert all(cluster.store.get("Pod", k).deleting for k in evicted)
    assert "SchedulingDisabled" in [
        ln for ln in cmd_node_list(cluster.store).splitlines()
        if ln.startswith("n0")][0]
    cluster.run_until_idle()
    # the job recovered entirely off the drained node
    pods = [p for p in cluster.store.list("Pod") if p.node_name]
    assert pods and all(p.node_name == "n1" for p in pods)


def test_node_verbs_unknown_node(cluster):
    from volcano_tpu.cli import cmd_cordon, cmd_drain

    with pytest.raises(KeyError):
        cmd_cordon(cluster.store, "ghost")
    with pytest.raises(KeyError):
        cmd_drain(cluster.store, "ghost")


def test_pool_list_table(cluster):
    from volcano_tpu.cli import cmd_pool_list

    assert "No resources found" in cmd_pool_list(cluster.store)
    cluster.add_node_pool("tp", {"cpu": "2", "memory": "4Gi"}, min_size=1,
                          max_size=4)
    cluster.run_until_idle()
    text = cmd_pool_list(cluster.store)
    assert text.splitlines()[0].split()[:5] == [
        "Name", "Min", "Max", "Size", "Ready"]
    row = [ln for ln in text.splitlines() if ln.startswith("tp")][0].split()
    assert row[1:5] == ["1", "4", "1", "1"]


def test_main_entry_node_and_pool_verbs(tmp_path, capsys):
    from volcano_tpu.cli.vtctl import main

    state = str(tmp_path / "state.pkl")
    assert main(["--state", state, "cluster", "init", "--nodes", "2"]) == 0
    assert main(["--state", state, "node", "cordon", "node-0"]) == 0
    assert main(["--state", state, "node", "list"]) == 0
    out = capsys.readouterr().out
    assert "SchedulingDisabled" in out
    assert main(["--state", state, "node", "uncordon", "node-0"]) == 0
    assert main(["--state", state, "node", "drain", "node-1"]) == 0
    assert main(["--state", state, "pool", "list"]) == 0
    assert "No resources found" in capsys.readouterr().out
    assert main(["--state", state, "node", "cordon", "ghost"]) == 1
