"""vtctl command surface, mirroring reference test/e2e/command.go."""

import pytest

from volcano_tpu.api.types import JobPhase
from volcano_tpu.cli import cmd_list, cmd_resume, cmd_run, cmd_suspend
from volcano_tpu.sim import Cluster


@pytest.fixture
def cluster():
    c = Cluster()
    c.add_queue("default")
    c.add_node("n0", {"cpu": "8", "memory": "16Gi", "pods": 110})
    return c


def test_run_and_list(cluster):
    cmd_run(cluster.store, name="cli-job", replicas=2, min_available=2)
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/cli-job")
    assert job.status.state.phase == JobPhase.RUNNING

    text = cmd_list(cluster.store)
    lines = text.splitlines()
    assert lines[0].split()[:3] == ["Name", "Creation", "Phase"]
    row = [ln for ln in lines if ln.startswith("cli-job")][0].split()
    assert row[2] == "Running"
    assert row[3] == "2"  # replicas


def test_list_empty(cluster):
    assert "No resources found" in cmd_list(cluster.store)


def test_suspend_resume_roundtrip(cluster):
    cmd_run(cluster.store, name="sr", replicas=2, min_available=2)
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/sr")
    assert job.status.state.phase == JobPhase.RUNNING

    cmd_suspend(cluster.store, "default", "sr")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED
    assert cluster.store.list("Pod") == []

    cmd_resume(cluster.store, "default", "sr")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.RUNNING
    assert len(cluster.store.list("Pod")) == 2


def test_suspend_pending_job(cluster):
    # job too big to schedule stays pending; suspend still aborts it
    cmd_run(cluster.store, name="pend", replicas=4, min_available=4,
            requests="cpu=4000m,memory=1Gi")
    cluster.run_until_idle()
    job = cluster.store.get("Job", "default/pend")
    assert job.status.state.phase in (JobPhase.PENDING, JobPhase.INQUEUE)

    cmd_suspend(cluster.store, "default", "pend")
    cluster.run_until_idle()
    assert job.status.state.phase == JobPhase.ABORTED


def test_run_rejected_by_admission(cluster):
    from volcano_tpu.admission import AdmissionError

    with pytest.raises(AdmissionError):
        cmd_run(cluster.store, name="bad", replicas=1, min_available=5)


def test_suspend_unknown_job(cluster):
    with pytest.raises(KeyError):
        cmd_suspend(cluster.store, "default", "ghost")


def test_main_entry_roundtrip(tmp_path):
    from volcano_tpu.cli.vtctl import main

    state = str(tmp_path / "state.pkl")
    assert main(["--state", state, "cluster", "init", "--nodes", "2"]) == 0
    assert main(["--state", state, "job", "run", "--name", "m1",
                 "--replicas", "2", "--min", "2"]) == 0
    assert main(["--state", state, "job", "list"]) == 0
    assert main(["--state", state, "job", "suspend", "--name", "m1"]) == 0
    assert main(["--state", state, "job", "resume", "--name", "m1"]) == 0
    assert main(["--state", state, "job", "suspend", "--name", "ghost"]) == 1


# -- node cordon/uncordon/drain + pool list (elastic capacity) ----------------


def test_node_cordon_shows_scheduling_disabled_and_masks(cluster):
    from volcano_tpu.cli import cmd_cordon, cmd_node_list, cmd_uncordon

    cluster.add_node("n1", {"cpu": "8", "memory": "16Gi", "pods": 110})
    cmd_cordon(cluster.store, "n0")
    text = cmd_node_list(cluster.store)
    row = [ln for ln in text.splitlines() if ln.startswith("n0")][0]
    assert "Ready,SchedulingDisabled" in row
    # new work lands on the remaining schedulable node only
    cmd_run(cluster.store, name="after", replicas=2, min_available=2)
    cluster.run_until_idle()
    assert {p.node_name for p in cluster.store.list("Pod")} == {"n1"}
    cmd_uncordon(cluster.store, "n0")
    assert "SchedulingDisabled" not in cmd_node_list(cluster.store)


def test_node_drain_is_cordon_plus_evict(cluster):
    from volcano_tpu.cli import cmd_drain, cmd_node_list

    cluster.add_node("n1", {"cpu": "8", "memory": "16Gi", "pods": 110})
    cmd_run(cluster.store, name="d1", replicas=2, min_available=2)
    cluster.run_until_idle()
    victims = [p for p in cluster.store.list("Pod") if p.node_name == "n0"]
    evicted = cmd_drain(cluster.store, "n0")
    assert sorted(evicted) == sorted(p.meta.key for p in victims)
    assert all(cluster.store.get("Pod", k).deleting for k in evicted)
    assert "SchedulingDisabled" in [
        ln for ln in cmd_node_list(cluster.store).splitlines()
        if ln.startswith("n0")][0]
    cluster.run_until_idle()
    # the job recovered entirely off the drained node
    pods = [p for p in cluster.store.list("Pod") if p.node_name]
    assert pods and all(p.node_name == "n1" for p in pods)


def test_node_verbs_unknown_node(cluster):
    from volcano_tpu.cli import cmd_cordon, cmd_drain

    with pytest.raises(KeyError):
        cmd_cordon(cluster.store, "ghost")
    with pytest.raises(KeyError):
        cmd_drain(cluster.store, "ghost")


def test_pool_list_table(cluster):
    from volcano_tpu.cli import cmd_pool_list

    assert "No resources found" in cmd_pool_list(cluster.store)
    cluster.add_node_pool("tp", {"cpu": "2", "memory": "4Gi"}, min_size=1,
                          max_size=4)
    cluster.run_until_idle()
    text = cmd_pool_list(cluster.store)
    assert text.splitlines()[0].split()[:5] == [
        "Name", "Min", "Max", "Size", "Ready"]
    row = [ln for ln in text.splitlines() if ln.startswith("tp")][0].split()
    assert row[1:5] == ["1", "4", "1", "1"]


def test_main_entry_node_and_pool_verbs(tmp_path, capsys):
    from volcano_tpu.cli.vtctl import main

    state = str(tmp_path / "state.pkl")
    assert main(["--state", state, "cluster", "init", "--nodes", "2"]) == 0
    assert main(["--state", state, "node", "cordon", "node-0"]) == 0
    assert main(["--state", state, "node", "list"]) == 0
    out = capsys.readouterr().out
    assert "SchedulingDisabled" in out
    assert main(["--state", state, "node", "uncordon", "node-0"]) == 0
    assert main(["--state", state, "node", "drain", "node-1"]) == 0
    assert main(["--state", state, "pool", "list"]) == 0
    assert "No resources found" in capsys.readouterr().out
    assert main(["--state", state, "node", "cordon", "ghost"]) == 1


# -- describe / events / trace (vtrace explainability) -------------------------


@pytest.fixture
def traced():
    from volcano_tpu import trace

    tr = trace.arm(trace.Tracer(ring=8192))
    try:
        yield tr
    finally:
        trace.disarm()


def test_describe_job_pending_why_verdict(cluster):
    """The "why is this gang pending" round-trip on the local store: the
    scheduler's Unschedulable verdict surfaces through describe."""
    from volcano_tpu.cli import cmd_describe_job, cmd_describe_pod

    # admitted past the enqueue gate (pods exist) but the gang can never
    # place both 4.5-cpu replicas on one 8-cpu node
    cmd_run(cluster.store, name="pend", replicas=2, min_available=2,
            requests="cpu=4500m,memory=1Gi")
    cluster.run_until_idle()
    text = cmd_describe_job(cluster.store, "default", "pend")
    assert "Conditions (why):" in text
    assert "Unschedulable" in text
    assert "0/1 nodes are available, 1 insufficient cpu" in text
    # per-pod view names the owning gang's verdict
    pod = sorted(p.meta.name for p in cluster.store.list("Pod"))[0]
    ptext = cmd_describe_pod(cluster.store, "default", pod)
    assert "Pending because (gang verdict):" in ptext
    assert "Unschedulable" in ptext


def test_describe_running_job_and_events_table(cluster):
    from volcano_tpu.cli import cmd_describe_job, cmd_events

    cmd_run(cluster.store, name="ok", replicas=2, min_available=2)
    cluster.run_until_idle()
    text = cmd_describe_job(cluster.store, "default", "ok")
    assert "Phase:     Running" in text
    assert "n0" in text
    ev = cmd_events(cluster.store)
    assert "Scheduled" in ev
    assert "Successfully assigned" in ev
    # namespace filter
    assert "Scheduled" not in cmd_events(cluster.store, namespace="other")


def test_describe_unknown_object_errors(cluster):
    from volcano_tpu.cli import cmd_describe_job, cmd_describe_pod

    with pytest.raises(KeyError):
        cmd_describe_job(cluster.store, "default", "ghost")
    with pytest.raises(KeyError):
        cmd_describe_pod(cluster.store, "default", "ghost")


def test_main_entry_local_trace_roundtrip(tmp_path, capsys, traced):
    """Local mode: an armed `job run` persists the flight recorder next
    to --state; `trace last` in a later invocation renders the tree and
    `describe job` shows the trace id."""
    from volcano_tpu.cli.vtctl import main

    state = str(tmp_path / "state.pkl")
    assert main(["--state", state, "cluster", "init", "--nodes", "1"]) == 0
    assert main(["--state", state, "job", "run", "--name", "tr1",
                 "--replicas", "2", "--min", "2"]) == 0
    import os

    assert os.path.exists(state + ".trace.json")
    assert main(["--state", state, "describe", "job", "--name", "tr1"]) == 0
    out = capsys.readouterr().out
    assert "Trace:     t-" in out
    # a fresh "process": drop the live ring, read the sidecar dump
    from volcano_tpu import trace

    trace.arm(trace.Tracer())  # empty ring; falls through to the file
    # an armed read-only command with an empty ring must NOT clobber the
    # sidecar recorder the job run wrote
    assert main(["--state", state, "describe", "job", "--name", "tr1"]) == 0
    capsys.readouterr()
    assert main(["--state", state, "trace", "last"]) == 0
    out = capsys.readouterr().out
    assert "vtctl.job.run" in out
    assert "scheduler.cycle" in out
    assert "kubelet.ready" in out
    assert main(["--state", state, "trace", "dump"]) == 0
    import json

    spans = json.loads(capsys.readouterr().out)
    assert any(s["name"] == "scheduler.bind" for s in spans)
    assert main(["--state", state, "events"]) == 0
    assert "Scheduled" in capsys.readouterr().out


def test_remote_describe_events_trace_roundtrip(tmp_path, capsys, traced):
    """Remote store coverage: pending-gang why verdict + events + the
    /debug/trace flight recorder, all through `vtctl --server`."""
    from volcano_tpu.cli.vtctl import main
    from volcano_tpu.controller import JobController
    from volcano_tpu.scheduler.conf import default_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.server import StoreServer

    srv = StoreServer().start()
    try:
        url = srv.url
        assert main(["--server", url, "cluster", "init", "--nodes", "1",
                     "--cpu", "2"]) == 0
        # an unschedulable gang: 4x2cpu on one 2-cpu node
        assert main(["--server", url, "job", "run", "--name", "big",
                     "--replicas", "4", "--min", "4",
                     "--requests", "cpu=2000m,memory=1Gi"]) == 0
        capsys.readouterr()
        # drive controller + scheduler in-process over the wire
        ctl = JobController(RemoteStore(url))
        sched = Scheduler(RemoteStore(url), conf=default_conf())
        for _ in range(4):
            ctl.pump()
            sched.run_once()
        assert main(["--server", url, "describe", "job",
                     "--name", "big"]) == 0
        out = capsys.readouterr().out
        assert "Conditions (why):" in out and "Unschedulable" in out
        assert "Trace:     t-" in out  # the run stamped the job
        assert main(["--server", url, "events"]) == 0
        assert "Unschedulable" in capsys.readouterr().out
        # the apiserver's flight recorder saw the traced writes
        assert main(["--server", url, "trace", "last"]) == 0
        assert "store." in capsys.readouterr().out
        assert main(["--server", url, "trace", "dump"]) == 0
        import json

        spans = json.loads(capsys.readouterr().out)
        assert any(s["name"].startswith("store.") for s in spans)
    finally:
        srv.stop()


# -- vtctl profile (vtprof critical-path report) ------------------------------


def test_vtctl_profile_local_renders_report_and_remote_fetch(capsys):
    """`vtctl profile` renders the in-process profiler's report; with
    --server it fetches /debug/prof from the remote daemon instead."""
    import json

    from volcano_tpu import vtprof
    from volcano_tpu.cli.vtctl import main
    from volcano_tpu.store.server import StoreServer

    # disarmed local mode: actionable hint, rc 0
    vtprof.disarm()
    assert main(["profile"]) == 0
    assert "VOLCANO_TPU_PROF=1" in capsys.readouterr().out

    prof = vtprof.arm()
    try:
        prof.begin_cycle()
        tok = prof.dispatch_begin(lambda: None)
        prof.dispatch_end(tok, "allocate_solve", phase="solve")
        prof.record_fetch("allocate_solve", "solve", 0.02, 0.004)
        prof.end_cycle(0.08, {"solve": 0.05, "publish": 0.02}, "fast")
        # local text report
        assert main(["profile"]) == 0
        out = capsys.readouterr().out
        assert "vtprof: 1 cycle(s) sampled" in out
        assert "allocate_solve" in out and "wait=0.0200s" in out
        # local raw payload
        assert main(["profile", "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["totals"]["allocate_solve"]["dispatches"] == 1
        # remote: the same ring served over /debug/prof
        srv = StoreServer().start()
        try:
            assert main(["profile", "--server", srv.url]) == 0
            out = capsys.readouterr().out
            assert "vtprof: 1 cycle(s) sampled" in out
            assert "allocate_solve" in out
        finally:
            srv.stop()
        # a dead server is a CLI error, not a traceback
        assert main(["profile", "--server", "http://127.0.0.1:9"]) == 1
        assert "error:" in capsys.readouterr().err
    finally:
        vtprof.disarm()


def test_vtctl_audit_local_remote_wal_and_corruption(tmp_path, capsys):
    """`vtctl audit`: the clean OK path against a local --state cluster
    and a remote server, exact-object localization output on a corrupted
    store (exit 2), and `audit wal` verifying a WAL lineage against the
    live server."""
    from volcano_tpu import vtaudit
    from volcano_tpu.cli import vtctl
    from volcano_tpu.cli.vtctl import main
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.server import StoreServer

    from tests.helpers import build_pod

    if not vtaudit.enabled():
        pytest.skip("digest auditing disarmed in env")

    # local --state: clean cluster audits OK
    state = str(tmp_path / "cluster.json")
    assert main(["--state", state, "cluster", "init", "--nodes", "2"]) == 0
    capsys.readouterr()
    assert main(["--state", state, "audit"]) == 0
    assert "state digest OK" in capsys.readouterr().out

    # corrupted local store: localization names the exact object (driven
    # in-process — a pickle roundtrip would rebuild the digest from the
    # corrupted objects and hide the flip)
    cluster = vtctl._load_cluster(state)
    cluster.store.create("Pod", build_pod("victim", namespace="ns"))
    cluster.store._objects["Pod"]["ns/victim"].node_name = "flipped"
    text = vtctl.cmd_audit_local(cluster.store)
    assert "STATE DIGEST DIVERGENCE" in text
    assert "Pod ns/victim" in text

    # remote: clean server audits OK over every tier, wal mode MATCHes
    srv = StoreServer(
        state_path=str(tmp_path / "state.json"), save_interval=3600,
        wal=True, shards=4,
    ).start()
    try:
        rs = RemoteStore(srv.url)
        for i in range(6):
            rs.create("Pod", build_pod(f"p{i}", namespace=f"team{i % 3}"))
        assert main(["audit", "--server", srv.url]) == 0
        assert "state digest OK" in capsys.readouterr().out
        assert main(["audit", "wal", str(tmp_path / "state.json.wal"),
                     "--server", srv.url]) == 0
        out = capsys.readouterr().out
        assert "WAL replay digest" in out and "MATCH" in out

        # flip one byte of one shard's state: detection + localization,
        # exit code 2
        srv.store._objects["Pod"]["team1/p4"].node_name = "flipped"
        assert main(["audit", "--server", srv.url]) == 2
        out = capsys.readouterr().out
        assert "STATE DIGEST DIVERGENCE" in out
        assert "Pod team1/p4" in out
    finally:
        srv.stop()

    # a dead server is a CLI error, not a traceback
    assert main(["audit", "--server", "http://127.0.0.1:9"]) == 1
    assert "error:" in capsys.readouterr().err


def test_vtctl_audit_remote_retries_when_state_moved_mid_walk(monkeypatch):
    """The audit walk is not seq-pinned: a write landing mid-walk (a
    replicated lease renewal is enough) makes a clean server look
    diverged.  cmd_audit_remote must retry such a pass and settle on
    the stable-seq verdict — clean if a later pass is clean, diverged
    only when the mismatch reproduces (or moved on every pass)."""
    from volcano_tpu.cli import vtctl

    moved = ("WIRE DIGEST DIVERGENCE  wire=aa  actual=bb\n"
             "  (state moved during audit: seq 5 -> 7; re-run to confirm)\n")
    stable_bad = "WIRE DIGEST DIVERGENCE  wire=aa  actual=bb\n"
    clean = "state digest OK  root=aa  seq=7  shards=1\n"

    passes = iter([moved, moved, clean])
    monkeypatch.setattr(vtctl, "_audit_remote_pass",
                        lambda url: next(passes))
    assert vtctl.cmd_audit_remote("http://x") == clean

    # stable-seq divergence reports immediately — no retry can launder
    # real corruption
    calls = []
    monkeypatch.setattr(
        vtctl, "_audit_remote_pass",
        lambda url: calls.append(1) or stable_bad)
    assert vtctl.cmd_audit_remote("http://x") == stable_bad
    assert len(calls) == 1

    # moved on every pass: bounded retries, the caveat survives so the
    # operator knows the verdict is unconfirmed
    monkeypatch.setattr(vtctl, "_audit_remote_pass", lambda url: moved)
    assert "state moved during audit" in vtctl.cmd_audit_remote("http://x")


# -- vtctl --fleet (vtfleet cross-process observability) -----------------------


def test_vtctl_fleet_local_mode_disarmed_hints_and_armed_render(capsys):
    """Without --server the fleet commands harvest the in-process rings:
    disarmed planes produce actionable arming hints at rc 0; armed ones
    render the same report shapes a live mesh produces."""
    from volcano_tpu import timeseries, trace, vtprof
    from volcano_tpu.cli.vtctl import main

    trace.disarm()
    timeseries.disarm()
    vtprof.disarm()
    try:
        assert main(["trace", "last", "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "proc local" in out and "(disarmed)" in out
        assert "VOLCANO_TPU_TRACE=1" in out
        assert main(["top", "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "fleet: 1 proc(s) harvested" in out
        assert "VOLCANO_TPU_TIMESERIES=1" in out
        assert main(["profile", "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "VOLCANO_TPU_PROF=1" in out
        assert "no cross-process drain attribution" in out

        # armed: the local rings feed the same merge/render path
        trace.arm()
        with trace.span("unit.fleet.local"):
            pass
        rec = timeseries.arm()
        rec.record("cycle", dur_s=0.01, binds=1)
        assert main(["trace", "last", "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "unit.fleet.local" in out
        assert "proc local" in out and "spans=1" in out
        assert main(["top", "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "VOLCANO_TPU_TIMESERIES=1" not in out
    finally:
        trace.disarm()
        timeseries.disarm()
        vtprof.disarm()


def test_vtctl_fleet_remote_plain_store_and_dead_daemon_degradation(
        capsys, traced):
    """--fleet against a plain (non-mesh) StoreServer falls back to one
    'store' proc; a dead --daemon degrades to an UNREACHABLE line at
    rc 0 (a partial harvest is a report, not an error); a malformed
    --daemon flag is a CLI error."""
    from volcano_tpu.cli.vtctl import main
    from volcano_tpu.store.server import StoreServer

    srv = StoreServer().start()
    try:
        assert main(["--server", srv.url, "cluster", "init",
                     "--nodes", "1"]) == 0
        assert main(["--server", srv.url, "job", "run", "--name", "fl1",
                     "--replicas", "1", "--min", "1"]) == 0
        capsys.readouterr()
        # the store server shares this process, so its ring carries the
        # traced writes; the harvest names the front proc "store"
        assert main(["trace", "last", "--server", srv.url, "--fleet",
                     "--daemon", "ghost=http://127.0.0.1:1"]) == 0
        out = capsys.readouterr().out
        assert "proc store" in out
        assert "proc ghost" in out and "UNREACHABLE" in out
        assert "vtctl.job.run" in out
        assert main(["top", "--server", srv.url, "--fleet"]) == 0
        assert "fleet: 1 proc(s) harvested" in capsys.readouterr().out
        assert main(["profile", "--server", srv.url, "--fleet",
                     "--daemon", "ghost=http://127.0.0.1:1"]) == 0
        out = capsys.readouterr().out
        assert "proc ghost" in out and "UNREACHABLE" in out
        # malformed --daemon: error, not a traceback
        assert main(["trace", "last", "--server", srv.url, "--fleet",
                     "--daemon", "nourl"]) == 1
        assert "bad --daemon entry" in capsys.readouterr().err
        # describe job --fleet appends the gang's fleet trace
        assert main(["--server", srv.url, "describe", "job", "--name",
                     "fl1", "--fleet"]) == 0
        out = capsys.readouterr().out
        assert "Fleet trace:" in out and "proc store" in out
    finally:
        srv.stop()
