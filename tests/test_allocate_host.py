"""Host-path allocate action behavior (BASELINE config 1 semantics).

Scenario sources: reference test/e2e/job_scheduling.go ("Schedule Job" :27,
"Gang scheduling" :82, "Gang Full-Occupied" :118) reduced to the hermetic
fake-binder pattern of KB/pkg/scheduler/util/test_utils.go.
"""

from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler

from helpers import FakeBinder, build_node, build_pod, build_podgroup, make_store


def run_cycle(store, backend="host"):
    sched = Scheduler(store, conf=default_conf(backend=backend))
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder


def test_simple_job_binds_all_tasks():
    store = make_store(
        nodes=[build_node("n1"), build_node("n2")],
        podgroups=[build_podgroup("pg1", min_member=3)],
        pods=[build_pod(f"p{i}", group="pg1") for i in range(3)],
    )
    _, binder = run_cycle(store)
    assert len(binder.binds) == 3
    assert set(binder.binds) == {"default/p0", "default/p1", "default/p2"}


def test_gang_insufficient_capacity_binds_nothing():
    # 3-task gang, cluster fits only 2 -> nothing binds (all-or-nothing)
    store = make_store(
        nodes=[build_node("n1", cpu="2", memory="4Gi")],
        podgroups=[build_podgroup("pg1", min_member=3)],
        pods=[build_pod(f"p{i}", group="pg1", cpu="1") for i in range(3)],
    )
    _, binder = run_cycle(store)
    assert binder.binds == {}


def test_gang_partial_min_available_binds():
    # 3 tasks, min_available=2, capacity 2 -> the 2 that fit all bind
    store = make_store(
        nodes=[build_node("n1", cpu="2", memory="4Gi")],
        podgroups=[build_podgroup("pg1", min_member=2)],
        pods=[build_pod(f"p{i}", group="pg1", cpu="1") for i in range(3)],
    )
    _, binder = run_cycle(store)
    assert len(binder.binds) == 2


def test_unschedulable_gang_gets_podgroup_condition():
    store = make_store(
        nodes=[build_node("n1", cpu="1", memory="2Gi")],
        podgroups=[build_podgroup("pg1", min_member=3)],
        pods=[build_pod(f"p{i}", group="pg1", cpu="1") for i in range(3)],
    )
    sched, binder = run_cycle(store)
    assert binder.binds == {}
    pg = store.get("PodGroup", "default/pg1")
    assert any(c.kind == "Unschedulable" for c in pg.status.conditions)


def test_higher_priority_job_wins_scarce_capacity():
    from volcano_tpu.api.objects import Metadata, PriorityClass

    pg_low = build_podgroup("pg-low", min_member=2)
    pg_high = build_podgroup("pg-high", min_member=2)
    pg_low.priority_class_name = "low-pri"
    pg_high.priority_class_name = "high-pri"
    store = make_store(
        nodes=[build_node("n1", cpu="2", memory="4Gi")],
        podgroups=[pg_low, pg_high],
        pods=[
            *[build_pod(f"low{i}", group="pg-low", cpu="1", priority=1) for i in range(2)],
            *[build_pod(f"high{i}", group="pg-high", cpu="1", priority=10) for i in range(2)],
        ],
    )
    store.create("PriorityClass", PriorityClass(Metadata(name="low-pri", namespace=""), value=1))
    store.create("PriorityClass", PriorityClass(Metadata(name="high-pri", namespace=""), value=10))
    _, binder = run_cycle(store)
    assert set(binder.binds) == {"default/high0", "default/high1"}


def test_invalid_gang_never_binds():
    """Fewer valid tasks than min_available: the job survives session open
    (reference ordering — the JobValid registry is empty at gate time,
    framework.go:30-50) but never reaches JobReady, so nothing dispatches
    and gang's OnSessionClose records the Unschedulable condition."""
    store = make_store(
        nodes=[build_node("n1")],
        podgroups=[build_podgroup("pg1", min_member=5)],
        pods=[build_pod("p0", group="pg1")],
    )
    _, binder = run_cycle(store)
    assert binder.binds == {}
    pg = store.get("PodGroup", "default/pg1")
    assert any(
        c.kind == "Unschedulable" and c.reason == "NotEnoughResources"
        for c in pg.status.conditions
    )


def test_best_effort_skipped_by_allocate_handled_by_backfill():
    store = make_store(
        nodes=[build_node("n1")],
        podgroups=[build_podgroup("pg1", min_member=1)],
        pods=[build_pod("p0", group="pg1", cpu=0, memory=0)],
    )
    _, binder = run_cycle(store)
    # default actions = allocate, backfill: backfill places the BestEffort pod
    assert binder.binds == {"default/p0": "n1"}
