"""Preempt/reclaim actions + Statement transactional semantics.

Scenario sources (reference e2e suite, reduced to the hermetic fake-seam
pattern): test/e2e/job_scheduling.go "Preemption" :149, "Multiple
Preemption" :181, "Statement" :252; test/e2e/queue.go "Reclaim" :27.
"""

from volcano_tpu.api.objects import Metadata, PriorityClass
from volcano_tpu.api.types import PodPhase, TaskStatus
from volcano_tpu.scheduler.conf import PluginOption, SchedulerConf, Tier, default_conf
from volcano_tpu.scheduler.framework import open_session
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.scheduler.statement import Statement

from helpers import (
    FakeBinder,
    FakeEvictor,
    build_node,
    build_pod,
    build_podgroup,
    build_queue,
    make_store,
)


def make_conf(*actions):
    conf = default_conf()
    conf.actions = list(actions)
    return conf


def run_cycle(store, conf):
    sched = Scheduler(store, conf=conf)
    binder, evictor = FakeBinder(), FakeEvictor()
    sched.cache.binder = binder
    sched.cache.evictor = evictor
    sched.run_once()
    return sched, binder, evictor


def occupied_cluster(n_nodes=1, pods_per_node=2, priority=1):
    """n nodes of 2 cpu, each fully occupied by running 1-cpu pods of the
    low-priority job pg-low."""
    nodes = [build_node(f"n{i}", cpu="2", memory="4Gi") for i in range(n_nodes)]
    pods = []
    for i in range(n_nodes):
        for j in range(pods_per_node):
            pods.append(
                build_pod(
                    f"low-{i}-{j}",
                    group="pg-low",
                    cpu="1",
                    phase=PodPhase.RUNNING,
                    node_name=f"n{i}",
                    priority=priority,
                )
            )
    return nodes, pods


def with_priority_classes(store):
    store.create("PriorityClass", PriorityClass(Metadata(name="low-pri", namespace=""), value=1))
    store.create("PriorityClass", PriorityClass(Metadata(name="high-pri", namespace=""), value=100))
    return store


def test_preemption_evicts_lower_priority_within_queue():
    # job_scheduling.go:149 — cluster full of low-pri pods; a high-pri job
    # preempts enough of them to pipeline its own task.
    nodes, low_pods = occupied_cluster(n_nodes=1, pods_per_node=2)
    pg_low = build_podgroup("pg-low", min_member=1)
    pg_low.priority_class_name = "low-pri"
    pg_high = build_podgroup("pg-high", min_member=1)
    pg_high.priority_class_name = "high-pri"
    store = make_store(
        nodes=nodes,
        podgroups=[pg_low, pg_high],
        pods=low_pods + [build_pod("high-0", group="pg-high", cpu="1", priority=100)],
    )
    with_priority_classes(store)

    _, _, evictor = run_cycle(store, make_conf("preempt"))
    # exactly one victim covers the 1-cpu preemptor request
    assert len(evictor.evicts) == 1
    assert evictor.evicts[0].startswith("default/low-")


def test_multiple_preemption_across_nodes():
    # job_scheduling.go:181 — a 2-task high-pri gang preempts on two nodes.
    nodes, low_pods = occupied_cluster(n_nodes=2, pods_per_node=2)
    pg_low = build_podgroup("pg-low", min_member=1)
    pg_high = build_podgroup("pg-high", min_member=2)
    pg_high.priority_class_name = "high-pri"
    store = make_store(
        nodes=nodes,
        podgroups=[pg_low, pg_high],
        pods=low_pods
        + [build_pod(f"high-{i}", group="pg-high", cpu="2", priority=100) for i in range(2)],
    )
    with_priority_classes(store)

    _, _, evictor = run_cycle(store, make_conf("preempt"))
    # each 2-cpu preemptor needs a whole node -> two victims per node
    assert len(evictor.evicts) == 4
    assert all(v.startswith("default/low-") for v in evictor.evicts)


def test_preemption_blocked_by_victim_gang_discards_statement():
    # Statement atomicity (job_scheduling.go:252): the victim job's gang
    # (min_member == its running count) refuses every victim, so the
    # preemptor's Statement is discarded — zero evictions reach the cache
    # and session state rolls back to Running.
    nodes, low_pods = occupied_cluster(n_nodes=1, pods_per_node=2)
    pg_low = build_podgroup("pg-low", min_member=2)  # gang needs both pods
    pg_high = build_podgroup("pg-high", min_member=1)
    pg_high.priority_class_name = "high-pri"
    store = make_store(
        nodes=nodes,
        podgroups=[pg_low, pg_high],
        pods=low_pods + [build_pod("high-0", group="pg-high", cpu="1", priority=100)],
    )
    with_priority_classes(store)

    _, _, evictor = run_cycle(store, make_conf("preempt"))
    assert evictor.evicts == []
    assert not any(p.deleting for p in store.items("Pod"))


def test_statement_discard_restores_session_state():
    # Direct Statement unit semantics (framework/statement.go:198-222).
    nodes, low_pods = occupied_cluster(n_nodes=1, pods_per_node=2)
    pg_low = build_podgroup("pg-low", min_member=1)
    pg_high = build_podgroup("pg-high", min_member=1)
    store = make_store(
        nodes=nodes,
        podgroups=[pg_low, pg_high],
        pods=low_pods + [build_pod("high-0", group="pg-high", cpu="1")],
    )
    sched = Scheduler(store, conf=default_conf())
    evictor = FakeEvictor()
    sched.cache.evictor = evictor
    ssn = open_session(sched.cache, sched.conf.tiers)

    node = ssn.nodes["n0"]
    idle_before = node.idle.clone()
    victim = next(
        t for j in ssn.jobs.values() for t in j.tasks.values()
        if t.status == TaskStatus.RUNNING
    )
    preemptor = next(
        t for j in ssn.jobs.values() for t in j.tasks.values()
        if t.status == TaskStatus.PENDING
    )

    stmt = Statement(ssn)
    stmt.evict(victim, "preempt")
    assert victim.status == TaskStatus.RELEASING
    stmt.pipeline(preemptor, "n0")
    assert preemptor.status == TaskStatus.PIPELINED

    stmt.discard()
    assert victim.status == TaskStatus.RUNNING
    assert preemptor.status == TaskStatus.PENDING
    assert preemptor.node_name == ""
    assert node.idle.less_equal(idle_before) and idle_before.less_equal(node.idle)
    assert evictor.evicts == []  # nothing committed


def test_statement_commit_replays_evictions():
    nodes, low_pods = occupied_cluster(n_nodes=1, pods_per_node=2)
    pg_low = build_podgroup("pg-low", min_member=1)
    store = make_store(nodes=nodes, podgroups=[pg_low], pods=low_pods)
    sched = Scheduler(store, conf=default_conf())
    evictor = FakeEvictor()
    sched.cache.evictor = evictor
    ssn = open_session(sched.cache, sched.conf.tiers)

    victim = next(
        t for j in ssn.jobs.values() for t in j.tasks.values()
        if t.status == TaskStatus.RUNNING
    )
    stmt = Statement(ssn)
    stmt.evict(victim, "preempt")
    stmt.commit()
    assert evictor.evicts == [victim.key]


def test_reclaim_cross_queue_restores_fair_share():
    # queue.go:27 — q1 occupies the whole cluster; q2's pending job reclaims
    # capacity up to its deserved share.
    nodes = [build_node(f"n{i}", cpu="2", memory="4Gi") for i in range(2)]
    q1_pods = []
    for i in range(2):
        for j in range(2):
            q1_pods.append(
                build_pod(
                    f"q1-{i}-{j}", group="pg-q1", cpu="1",
                    phase=PodPhase.RUNNING, node_name=f"n{i}",
                )
            )
    pg_q1 = build_podgroup("pg-q1", min_member=1, queue="q1")
    pg_q2 = build_podgroup("pg-q2", min_member=1, queue="q2")
    store = make_store(
        nodes=nodes,
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
        podgroups=[pg_q1, pg_q2],
        pods=q1_pods + [build_pod("q2-0", group="pg-q2", cpu="1")],
    )

    _, _, evictor = run_cycle(store, make_conf("reclaim"))
    assert len(evictor.evicts) == 1
    assert evictor.evicts[0].startswith("default/q1-")


def test_reclaim_refuses_when_victim_queue_at_deserved():
    # proportion's reclaimableFn keeps queues at/above deserved
    # (proportion.go:161-186): q1 sits exactly at its deserved share, so
    # nothing may be reclaimed from it. Proportion must share a tier with
    # gang for its veto to intersect (first tier returning non-None victims
    # wins, session_plugins.go Reclaimable) — same as putting proportion in
    # the reference conf's first tier.
    nodes = [build_node("n0", cpu="4", memory="8Gi")]
    q1_pods = [
        build_pod(
            f"q1-{j}", group="pg-q1", cpu="1",
            phase=PodPhase.RUNNING, node_name="n0",
        )
        for j in range(2)
    ]
    pg_q1 = build_podgroup("pg-q1", min_member=1, queue="q1")
    pg_q2 = build_podgroup("pg-q2", min_member=1, queue="q2")
    store = make_store(
        nodes=nodes,
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
        podgroups=[pg_q1, pg_q2],
        pods=q1_pods + [build_pod("q2-0", group="pg-q2", cpu="1")],
    )

    conf = SchedulerConf(
        actions=["reclaim"],
        tiers=[Tier(plugins=[PluginOption("gang"), PluginOption("proportion")])],
    )
    _, _, evictor = run_cycle(store, conf)
    assert evictor.evicts == []


def test_reclaim_protects_victim_gang():
    # gang's reclaimableFn refuses victims whose job would fall below
    # min_available (gang.go:71-94).
    nodes = [build_node("n0", cpu="2", memory="4Gi")]
    q1_pods = [
        build_pod(
            f"q1-{j}", group="pg-q1", cpu="1",
            phase=PodPhase.RUNNING, node_name="n0",
        )
        for j in range(2)
    ]
    pg_q1 = build_podgroup("pg-q1", min_member=2, queue="q1")  # needs both
    pg_q2 = build_podgroup("pg-q2", min_member=1, queue="q2")
    store = make_store(
        nodes=nodes,
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
        podgroups=[pg_q1, pg_q2],
        pods=q1_pods + [build_pod("q2-0", group="pg-q2", cpu="1")],
    )

    _, _, evictor = run_cycle(store, make_conf("reclaim"))
    assert evictor.evicts == []
