"""Elastic capacity: demand estimator, node lifecycle, the elastic soak
acceptance, and cordon/drain churn parity between the fastpath mirror and
a fresh host-backend run.

The acceptance soak (tier-1): a 3-gang burst against a pool at min_size
scales up to exactly the estimator's bin-pack minimum, converges to the
same final placements as a run started fully provisioned, then drains back
to min_size after the hysteresis window — with zero non-drain evictions of
Running pods and no oversubscription at any step.
"""

import pytest

from volcano_tpu.api.job import JOB_NAME_KEY, Job, JobSpec, TaskSpec
from volcano_tpu.api.objects import Metadata, NodePool, PodSpec, Taint, Toleration
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobPhase, PodPhase
from volcano_tpu.elastic import (
    DRAINING,
    POOL_LABEL,
    PROVISIONING,
    READY,
    ElasticController,
    node_state,
    plan_pools,
    pool_nodes,
    unschedulable_gangs,
)
from volcano_tpu.elastic.demand import GangDemand
from volcano_tpu.scheduler import metrics
from volcano_tpu.scheduler.conf import default_conf, full_conf
from volcano_tpu.sim import Cluster

from helpers import build_node, build_pod, build_podgroup, build_queue, make_store


def _pool(name="tp", cpu="2", mem="4Gi", min_size=0, max_size=8, **kw):
    return NodePool(
        meta=Metadata(name=name, namespace=""),
        resources=Resource.from_resource_list(
            {"cpu": cpu, "memory": mem, "pods": 110}),
        min_size=min_size,
        max_size=max_size,
        **kw,
    )


def _gang(key, n, cpu=2000, mem=4 << 30, queue="default", priority=0,
          selector=None, tolerations=None):
    return GangDemand(
        key=key, queue=queue, priority=priority,
        requests=[Resource(milli_cpu=cpu, memory=mem) for _ in range(n)],
        selector=dict(selector or {}), tolerations=list(tolerations or []),
    )


def mk_job(name, replicas=2, cpu="2", mem="4Gi", namespace="el",
           queue="default"):
    return Job(
        meta=Metadata(name=name, namespace=namespace),
        spec=JobSpec(
            min_available=replicas, queue=queue,
            tasks=[TaskSpec(name="w", replicas=replicas,
                            template=PodSpec(
                                image="busybox",
                                resources=Resource.from_resource_list(
                                    {"cpu": cpu, "memory": mem})))],
        ),
    )


# -- demand estimator ---------------------------------------------------------


def _plan_store(pools, queues=("default",)):
    store = make_store(nodes=[], queues=[build_queue(q) for q in queues])
    for p in pools:
        store.create("NodePool", p)
    return store


def test_estimator_binpacks_whole_gangs():
    """Two 2-pod full-node gangs need 4 nodes; a gang that cannot fully
    fit under max_size contributes NOTHING (never half a gang)."""
    pool = _pool(max_size=5)
    store = _plan_store([pool])
    plans = plan_pools(store, [pool],
                       gangs=[_gang("a/g1", 2), _gang("a/g2", 2),
                              _gang("a/g3", 2)])
    plan = plans["tp"]
    assert plan.demand_nodes == 6          # unclipped bin-pack minimum
    assert plan.new_nodes == 4             # g3 would need 2 > remaining 1
    assert plan.admitted == ["a/g1", "a/g2"]


def test_estimator_uses_existing_free_capacity_first():
    """Free capacity on Ready members (and full Provisioning templates)
    absorbs demand before new bins open."""
    pool = _pool()
    store = _plan_store([pool])
    ready = build_node("tp-0", cpu="2", memory="4Gi",
                       labels={POOL_LABEL: "tp"})
    store.create("Node", ready)
    plans = plan_pools(store, [pool], gangs=[_gang("a/g1", 2)])
    assert plans["tp"].demand_nodes == 1  # one pod rides the free node


def test_estimator_skips_unservable_gangs():
    """A request larger than the template can never be served — no nodes
    are provisioned for it (they could only host a forever-partial gang)."""
    pool = _pool(cpu="2")
    store = _plan_store([pool])
    plans = plan_pools(store, [pool], gangs=[_gang("a/big", 2, cpu=4000)])
    assert plans["tp"].demand_nodes == 0
    assert plans["tp"].new_nodes == 0


def test_estimator_respects_selector_and_taints():
    pool = _pool()
    pool.labels = {"zone": "z1"}
    pool.taints = [Taint(key="tpu", value="v5e", effect="NoSchedule")]
    store = _plan_store([pool])
    # wrong selector: not eligible
    plans = plan_pools(store, [pool],
                       gangs=[_gang("a/g", 2, selector={"zone": "z2"})])
    assert plans["tp"].new_nodes == 0
    # matching selector but untolerated taint: not eligible
    plans = plan_pools(store, [pool],
                       gangs=[_gang("a/g", 2, selector={"zone": "z1"})])
    assert plans["tp"].new_nodes == 0
    # selector + toleration: served
    plans = plan_pools(store, [pool], gangs=[
        _gang("a/g", 2, selector={"zone": "z1"},
              tolerations=[Toleration(key="tpu", operator="Exists")])])
    assert plans["tp"].new_nodes == 2


def test_estimator_queue_clip_loans_idle_quota():
    """Aryl-style: a lone demanding queue takes the whole pool (idle quota
    is loaned); under contention each queue is clipped to its weighted
    share of the headroom, whole gangs at a time."""
    pool = _pool(max_size=4)
    store = _plan_store([pool], queues=("qa", "qb"))
    # qa alone: loan lets it take all 4 nodes despite qb's idle quota
    plans = plan_pools(store, [pool], gangs=[
        _gang("a/g1", 2, queue="qa"), _gang("a/g2", 2, queue="qa")])
    assert plans["tp"].new_nodes == 4
    # contention (demand 8 > headroom 4): equal weights -> 2 nodes each,
    # one whole gang per queue
    plans = plan_pools(store, [pool], gangs=[
        _gang("a/g1", 2, queue="qa"), _gang("a/g2", 2, queue="qa"),
        _gang("b/g1", 2, queue="qb"), _gang("b/g2", 2, queue="qb")])
    plan = plans["tp"]
    assert plan.demand_nodes == 8
    assert plan.new_nodes == 4
    assert sorted(plan.admitted) == ["a/g1", "b/g1"]


def test_estimator_pools_absorb_by_priority():
    hi = _pool("fast", priority=10, max_size=2)
    lo = _pool("slow", priority=0, max_size=8)
    store = _plan_store([hi, lo])
    plans = plan_pools(store, [lo, hi],
                       gangs=[_gang("a/g1", 2), _gang("a/g2", 2)])
    assert plans["fast"].new_nodes == 2   # g1 lands on the priority pool
    assert plans["slow"].new_nodes == 2   # g2 overflows to the next pool


def test_gang_signal_from_unschedulable_condition():
    """unschedulable_gangs reads the PodGroup condition the gang plugin
    publishes — including the from-zero case where the enqueue gate held
    the group Pending and no pods exist (requests derived from the Job)."""
    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.store.create("Job", mk_job("cj0", replicas=2))
    for _ in range(2):
        c.step()
    gangs = unschedulable_gangs(c.store)
    assert [g.key for g in gangs] == ["el/cj0"]
    assert len(gangs[0].requests) == 2
    assert gangs[0].requests[0].milli_cpu == 2000.0


# -- lifecycle ----------------------------------------------------------------


def test_provisioning_node_turns_ready_after_delay():
    c = Cluster(with_scheduler=False, with_controller=False)
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi"}, min_size=1,
                    provision_delay=3)
    c.pump_elastic()
    (node,) = pool_nodes(c.store, "tp")
    assert node_state(node) == PROVISIONING and not node.ready()
    for _ in range(2):
        c.step()
    assert not c.store.get("Node", "/tp-0").ready()
    for _ in range(2):
        c.step()
    node = c.store.get("Node", "/tp-0")
    assert node.ready() and node_state(node) == READY


def test_cordoned_and_provisioning_nodes_masked_from_placement():
    """A cordoned node and a Provisioning node both reject placement on
    the next cycle — existing predicate masks, no scheduler changes."""
    from volcano_tpu.cli import cmd_cordon, cmd_uncordon

    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.add_node("n0", {"cpu": "4", "memory": "8Gi", "pods": 110})
    c.add_node("n1", {"cpu": "4", "memory": "8Gi", "pods": 110})
    cmd_cordon(c.store, "n0")
    c.store.create("Job", mk_job("cj0", replicas=2, cpu="1", mem="1Gi"))
    c.run_until_idle()
    placements = {p.node_name for p in c.store.list("Pod") if p.node_name}
    assert placements == {"n1"}
    cmd_uncordon(c.store, "n0")
    c.store.create("Job", mk_job("cj1", replicas=2, cpu="2", mem="2Gi"))
    c.run_until_idle()
    assert any(p.node_name == "n0" for p in c.store.list("Pod"))


def test_drain_evicts_through_releasing_and_node_empties():
    from volcano_tpu.cli import cmd_drain

    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.add_node("n0", {"cpu": "4", "memory": "8Gi", "pods": 110})
    c.add_node("n1", {"cpu": "4", "memory": "8Gi", "pods": 110})
    c.store.create("Job", mk_job("cj0", replicas=1, cpu="1", mem="1Gi"))
    c.run_until_idle()
    (pod,) = [p for p in c.store.list("Pod")]
    victim = pod.node_name
    evicted = cmd_drain(c.store, victim)
    assert evicted == [pod.meta.key]
    assert c.store.get("Node", f"/{victim}").unschedulable
    c.run_until_idle()
    # the evicted pod was reaped and the controller recreated it on the
    # OTHER node (drain = cordon + the existing eviction/Releasing path)
    pods = [p for p in c.store.list("Pod")
            if p.phase in (PodPhase.PENDING, PodPhase.RUNNING)]
    assert pods and all(p.node_name != victim for p in pods)


# -- the elastic soak (tier-1 acceptance) -------------------------------------


def _soak_invariants(c: Cluster, pool_name: str):
    nodes = {n.meta.name: n for n in c.store.list("Node")}
    used = {name: Resource() for name in nodes}
    for pod in c.store.list("Pod"):
        if pod.node_name and pod.phase in (PodPhase.PENDING, PodPhase.RUNNING):
            if pod.node_name in used:
                used[pod.node_name].add(pod.spec.resources)
    for name, u in used.items():
        assert u.less_equal(nodes[name].allocatable), f"{name} oversubscribed"
    pool = c.store.get("NodePool", f"/{pool_name}")
    size = len(pool_nodes(c.store, pool_name))
    assert pool.min_size <= size <= pool.max_size, (
        f"pool size {size} outside [{pool.min_size}, {pool.max_size}]")


def test_elastic_soak_burst_scales_converges_and_drains():
    """The acceptance scenario end to end, invariants checked every step."""
    metrics.reset()
    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi", "pods": 110},
                    min_size=1, max_size=8, provision_delay=2, hysteresis=3)
    for _ in range(3):
        c.step()
        _soak_invariants(c, "tp")
    assert [n.meta.name for n in pool_nodes(c.store, "tp")] == ["tp-0"]

    # 3-gang burst; each pod fills a template node -> bin-pack minimum 6
    for i in range(3):
        c.store.create("Job", mk_job(f"cj{i}"))
    deleting_seen = []
    for _ in range(25):
        c.step()
        _soak_invariants(c, "tp")
        deleting_seen.extend(
            p.meta.key for p in c.store.list("Pod")
            if p.deleting and p.phase == PodPhase.RUNNING
        )
    assert all(j.status.state.phase == JobPhase.RUNNING
               for j in c.store.list("Job"))
    pool = c.store.get("NodePool", "/tp")
    assert pool.status.size == 6, "scaled to exactly the bin-pack minimum"
    assert pool.status.ready == 6 and pool.status.provisioning == 0
    assert pool.status.scale_ups == 6
    elastic_placements = sorted(
        (p.meta.key, p.node_name) for p in c.store.list("Pod") if p.node_name)

    # a run started fully provisioned lands the same placements
    b = Cluster(scheduler_conf=full_conf("host"))
    b.add_queue("default")
    for i in range(6):
        b.add_node(f"tp-{i}", {"cpu": "2", "memory": "4Gi", "pods": 110},
                   labels={POOL_LABEL: "tp"})
    for i in range(3):
        b.store.create("Job", mk_job(f"cj{i}"))
    b.run_until_idle()
    baseline = sorted(
        (p.meta.key, p.node_name) for p in b.store.list("Pod") if p.node_name)
    assert elastic_placements == baseline

    # workloads finish; after the hysteresis window the pool drains back
    # to min_size with zero non-drain evictions of Running pods
    for p in c.store.list("Pod"):
        if p.phase == PodPhase.RUNNING:
            c.complete_pod(p.meta.key)
    for _ in range(15):
        c.step()
        _soak_invariants(c, "tp")
        deleting_seen.extend(
            p.meta.key for p in c.store.list("Pod")
            if p.deleting and p.phase == PodPhase.RUNNING
        )
    assert sorted(n.meta.name for n in c.store.list("Node")) == ["tp-0"]
    pool = c.store.get("NodePool", "/tp")
    assert pool.status.size == 1 and pool.status.scale_downs == 5
    assert deleting_seen == [], "a Running pod was evicted outside a drain"
    assert c.scheduler.cache.evict_log == []
    assert metrics.get_counter(
        "volcano_elastic_scale_events_total", pool="tp", direction="up") == 6
    assert metrics.get_counter(
        "volcano_elastic_scale_events_total", pool="tp", direction="down") == 5
    assert metrics.get_counter(
        "volcano_elastic_drain_evictions_total", pool="tp") == 0


def test_elastic_provision_chaos_fail_retries_and_converges():
    """elastic.provision 'fail' rules starve early attempts; demand
    persists, the controller retries, and the pool still converges with
    no orphan Provisioning nodes and size within bounds throughout."""
    from volcano_tpu.chaos import FaultPlan

    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi", "pods": 110},
                    min_size=0, max_size=4, provision_delay=1, hysteresis=50)
    c.elastic.chaos = FaultPlan.from_dict({"seed": 7, "rules": [
        {"point": "elastic.provision", "action": "fail", "count": 3},
        {"point": "elastic.provision", "action": "delay", "arg": 2.0,
         "count": 1},
    ]})
    c.store.create("Job", mk_job("cj0"))
    for _ in range(20):
        c.step()
        _soak_invariants(c, "tp")
    assert c.store.get("Job", "el/cj0").status.state.phase == JobPhase.RUNNING
    members = pool_nodes(c.store, "tp")
    assert len(members) == 2
    assert all(node_state(n) == READY for n in members), "orphan Provisioning"
    plan = c.elastic.chaos.stats()
    assert plan[0]["fires"] == 3  # the injected failures really happened


def test_estimator_ignores_demand_unservable_at_cap():
    """A gang whose remainder alone needs more bins than max_size can
    never run in the pool — it must not count as demand, or it would pin
    the scale-down hysteresis clock forever while idle nodes leak."""
    pool = _pool(max_size=4)
    store = _plan_store([pool])
    plans = plan_pools(store, [pool], gangs=[_gang("a/huge", 6)])
    assert plans["tp"].demand_nodes == 0
    assert plans["tp"].eligible_gangs == 0
    # end to end: idle nodes above min_size still drain back with the
    # unservable gang pending
    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi", "pods": 110},
                    min_size=1, max_size=4, provision_delay=0, hysteresis=2)
    c.store.create("Job", mk_job("fit", replicas=2))
    c.run_until_idle()
    assert len(pool_nodes(c.store, "tp")) == 2
    c.store.create("Job", mk_job("huge", replicas=6))  # > max_size forever
    for p in c.store.list("Pod"):
        if p.phase == PodPhase.RUNNING:
            c.complete_pod(p.meta.key)
    for _ in range(10):
        c.step()
    assert len(pool_nodes(c.store, "tp")) == 1, (
        "unservable demand pinned the hysteresis clock")


def test_uncordon_cancels_autoscaler_drain():
    """`vtctl node uncordon` of a Draining member returns it to service:
    the lifecycle state clears in the same write, so the controller stops
    treating it as Draining (no eviction fight, no surprise deletion)."""
    from volcano_tpu.cli import cmd_uncordon
    from volcano_tpu.elastic import begin_drain

    c = Cluster(with_scheduler=False, with_controller=False)
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi"}, min_size=1,
                    max_size=4, hysteresis=50)
    c.pump_elastic()
    node = c.store.get("Node", "/tp-0")
    begin_drain(c.store, node)
    assert node_state(c.store.get("Node", "/tp-0")) == DRAINING
    cmd_uncordon(c.store, "tp-0")
    fresh = c.store.get("Node", "/tp-0")
    assert not fresh.unschedulable and node_state(fresh) == READY
    c.pump_elastic()
    assert c.store.get("Node", "/tp-0") is not None, (
        "controller deleted an uncordoned node")


def test_fresh_controller_finishes_persisted_drain():
    """Leader failover mid-drain: a node atomically marked Draining
    (begin_drain's single write) is finished — emptied and deleted — by a
    REPLACEMENT controller that never saw the original decision."""
    from volcano_tpu.elastic import ElasticController, begin_drain

    c = Cluster(with_scheduler=False, with_controller=False)
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi"}, min_size=0,
                    max_size=4, hysteresis=0)
    # two members; one goes Draining, then the old leader "crashes"
    c.store.create("Job", mk_job("seed", replicas=2))  # no scheduler: ignored
    c.elastic.pump()  # nothing yet (min_size 0, no demand signal)
    from volcano_tpu.elastic.lifecycle import make_pool_node

    pool = c.store.get("NodePool", "/tp")
    for i in range(2):
        n = make_pool_node(pool, i, ready_at=0.0)
        c.store.create("Node", n)
    from volcano_tpu.elastic import kubelet_provisioning_step

    kubelet_provisioning_step(c.store, 1.0)
    begin_drain(c.store, c.store.get("Node", "/tp-1"))
    takeover = ElasticController(c.store, clock=lambda: 100.0)
    takeover.pump()
    assert c.store.get("Node", "/tp-1") is None, (
        "replacement leader never finished the persisted drain")
    assert c.store.get("Node", "/tp-0") is not None


def test_run_until_idle_waits_out_provision_delay():
    """A wait-only step (clock ticking toward a Provisioning node's
    ready-at) counts as movement: run_until_idle must not report
    quiescence with a gang pending on nodes mid-provision."""
    c = Cluster(scheduler_conf=full_conf("host"))
    c.add_queue("default")
    c.add_node_pool("tp", {"cpu": "2", "memory": "4Gi", "pods": 110},
                    min_size=0, max_size=4, provision_delay=3, hysteresis=50)
    c.store.create("Job", mk_job("cj0", replicas=2))
    c.run_until_idle(max_steps=64)
    assert c.store.get("Job", "el/cj0").status.state.phase == JobPhase.RUNNING


def test_status_patch_preserves_concurrent_spec_edits():
    """_publish_status patches status only: a spec edit (max_size bump)
    an operator commits between elasticd's pump-start list and its status
    write must survive.  Driven over RemoteStore — the wire path where
    the controller holds decoded COPIES and a full-object write-back
    would really clobber."""
    from volcano_tpu.store.client import RemoteStore, wait_healthy
    from volcano_tpu.store.server import StoreServer

    srv = StoreServer().start()
    try:
        assert wait_healthy(srv.url, timeout=10)
        admin = RemoteStore(srv.url)
        admin.create("NodePool", _pool("tp", min_size=1, max_size=2))
        client = RemoteStore(srv.url)
        ctl = ElasticController(client)
        orig = client.patch

        def racing_patch(kind, key, fields, **kw):
            if kind == "NodePool":
                # the operator's edit lands mid-pump, before the
                # controller's status write
                live = admin.get("NodePool", key)
                if live is not None and live.max_size == 2:
                    live.max_size = 6
                    admin.update("NodePool", live)
            return orig(kind, key, fields, **kw)

        client.patch = racing_patch
        ctl.pump()
        pool = admin.get("NodePool", "/tp")
        assert pool.max_size == 6, "status write clobbered the spec edit"
        assert pool.status.size == 1  # and the status still landed
    finally:
        srv.stop()


# -- cordon/drain churn parity: fastpath mirror vs fresh host run -------------


def _storm_ops(seed):
    """A seeded storm of node cordons/uncordons/deletes/re-adds and gang
    arrivals — pure data, so both backends replay the identical tape."""
    import random

    rng = random.Random(seed)
    ops = []
    for step in range(14):
        r = rng.random()
        if r < 0.3:
            ops.append(("job", f"j{step}", rng.randint(1, 2),
                        rng.choice(["500m", "1"])))
        elif r < 0.5:
            ops.append(("cordon", rng.randrange(4)))
        elif r < 0.65:
            ops.append(("uncordon", rng.randrange(4)))
        elif r < 0.8:
            ops.append(("delete", rng.randrange(4)))
        else:
            ops.append(("readd", rng.randrange(4)))
    return ops


def _run_storm(backend, ops, fast_off=False):
    conf = default_conf(backend)
    if fast_off:
        conf.fast_path = "off"
    store = make_store(
        nodes=[build_node(f"n{i}", cpu="4", memory="8Gi") for i in range(4)],
        queues=[build_queue("default")],
    )
    from volcano_tpu.scheduler.scheduler import Scheduler

    sched = Scheduler(store, conf=conf)
    fast_calls = []
    if sched.fast_cycle is not None:
        orig = sched.fast_cycle.try_run

        def spy():
            r = orig()
            fast_calls.append(r)
            return r

        sched.fast_cycle.try_run = spy
    sched.fast_calls = fast_calls
    history = []
    jobs = 0
    for op in ops:
        kind = op[0]
        if kind == "job":
            _, name, replicas, cpu = op
            store.create("PodGroup", build_podgroup(name, min_member=replicas))
            for t in range(replicas):
                store.create("Pod", build_pod(f"{name}-{t}", group=name,
                                              cpu=cpu, memory="512Mi"))
            jobs += 1
        elif kind == "cordon":
            node = store.get("Node", f"/n{op[1]}")
            if node is not None and not node.unschedulable:
                store.patch("Node", f"/n{op[1]}", {"unschedulable": True})
        elif kind == "uncordon":
            node = store.get("Node", f"/n{op[1]}")
            if node is not None and node.unschedulable:
                store.patch("Node", f"/n{op[1]}", {"unschedulable": False})
        elif kind == "delete":
            store.delete("Node", f"/n{op[1]}")
        elif kind == "readd":
            if store.get("Node", f"/n{op[1]}") is None:
                store.create("Node", build_node(f"n{op[1]}", cpu="4",
                                                memory="8Gi"))
        sched.run_once()
        # sim kubelet: bound pods start Running before the next cycle
        for pod in store.list("Pod"):
            if pod.node_name and pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                store.update("Pod", pod)
        history.append(sorted(
            (p.meta.key, p.node_name)
            for p in store.list("Pod") if p.node_name))
    return sched, history


@pytest.mark.parametrize("seed", range(3))
def test_cordon_churn_parity_fastpath_vs_host(seed):
    """Seeded cordon/uncordon/delete/re-add storm mid-cycles: the fastpath
    mirror's placements match a fresh host-backend run bit-for-bit after
    EVERY cycle — _on_node row retire/rebirth and cls_valid invalidation
    under unschedulable flips."""
    ops = _storm_ops(seed)
    fast_sched, fast_hist = _run_storm("tpu", ops)
    assert fast_sched.fast_cycle is not None
    assert fast_sched.fast_cycle.mirror is not None
    # the mirror really served every cycle — a silent object-path fallback
    # would make this parity check vacuous
    assert fast_sched.fast_calls and all(fast_sched.fast_calls)
    _, host_hist = _run_storm("host", ops)
    assert fast_hist == host_hist


# -- elasticd daemon (real processes) -----------------------------------------


@pytest.mark.slow
def test_elasticd_daemon_scales_pool_over_http():
    import json
    import signal
    import subprocess
    import sys
    import time

    from volcano_tpu.store.client import RemoteStore, wait_healthy

    entry = [sys.executable, "-m", "volcano_tpu.cli"]
    import os

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "VOLCANO_TPU_BACKEND": "host"}
    procs = []
    try:
        api = subprocess.Popen(entry + ["apiserver", "--port", "0"],
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT, text=True, env=env)
        procs.append(api)
        url = api.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert wait_healthy(url, timeout=30)
        for comp, extra in (("controller", []), ("scheduler", ["--period", "0.1",
                                                               "--metrics-port", "-1"]),
                            ("kubelet", ["--period", "0.05"]),
                            ("elastic", ["--period", "0.05",
                                         "--metrics-port", "-1"])):
            procs.append(subprocess.Popen(
                entry + [comp, "--server", url] + extra,
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env))
        client = RemoteStore(url)  # the apiserver creates the default queue
        client.create("NodePool", _pool("tp", min_size=1, max_size=4,
                                        provision_delay=0.1, hysteresis=60))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            nodes = [n for n in client.list("Node")
                     if n.labels.get(POOL_LABEL) == "tp"]
            if nodes and all(n.ready() for n in nodes):
                break
            time.sleep(0.2)
        assert nodes and nodes[0].meta.name == "tp-0" and nodes[0].ready()

        client.create("Job", mk_job("cj0", replicas=2))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            job = client.get("Job", "el/cj0")
            if job is not None and job.status.state.phase == JobPhase.RUNNING:
                break
            time.sleep(0.2)
        assert client.get("Job", "el/cj0").status.state.phase == JobPhase.RUNNING
        pool = client.get("NodePool", "/tp")
        assert 2 <= pool.status.size <= 4
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
