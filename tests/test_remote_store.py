"""Multi-process parity: codec, store server, RemoteStore, daemons.

The reference's components are separate binaries meeting at the K8s API
server (SURVEY.md §1); these tests prove the same property for the
framework: every component runs against the HTTP store server through
RemoteStore with no code changes, admission gates Job writes server-side
(the webhook path, §3.3), and leader election works across clients.
"""

import threading
import time

import pytest

from volcano_tpu.api.job import Job, JobSpec, LifecyclePolicy, TaskSpec, VolumeSpec
from volcano_tpu.api.objects import (
    Affinity,
    Command,
    Metadata,
    Node,
    Pod,
    PodGroup,
    PodSpec,
    Queue,
    Toleration,
)
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent, JobPhase, PodPhase
from volcano_tpu.store.client import RemoteStore
from volcano_tpu.store.codec import KIND_CLASSES, decode, encode
from volcano_tpu.store.server import StoreServer


@pytest.fixture()
def server():
    srv = StoreServer().start()
    yield srv
    srv.stop()


def make_job(name="j1", namespace="default", replicas=2, min_available=2):
    return Job(
        meta=Metadata(name=name, namespace=namespace),
        spec=JobSpec(
            min_available=min_available,
            queue="default",
            tasks=[
                TaskSpec(
                    name="task",
                    replicas=replicas,
                    template=PodSpec(image="busybox",
                                     resources=Resource(1000, 1 << 30)),
                )
            ],
        ),
    )


# -- codec --------------------------------------------------------------------


def test_codec_round_trips_every_kind():
    import json

    samples = {
        "Job": Job(
            meta=Metadata(name="j", labels={"a": "b"}, owner=("Queue", "q")),
            spec=JobSpec(
                min_available=2,
                tasks=[
                    TaskSpec(
                        name="t",
                        replicas=3,
                        policies=[
                            LifecyclePolicy(
                                action=JobAction.RESTART_JOB,
                                event=JobEvent.POD_FAILED,
                            )
                        ],
                    )
                ],
                volumes=[VolumeSpec(mount_path="/data", size="1Gi")],
            ),
        ),
        "Pod": Pod(
            meta=Metadata(name="p"),
            spec=PodSpec(
                resources=Resource(2000, 4 << 30, {"tpu.dev/v5e": 4.0}),
                affinity=Affinity(
                    node_terms=[[("zone", "In", ("a", "b"))]],
                    preferred_node_terms=[(5, [("ssd", "Exists", ())])],
                    pod_anti_affinity=[{"app": "web"}],
                ),
                tolerations=[Toleration(key="k", value="v", effect="NoSchedule")],
                host_ports=[8080],
            ),
            phase=PodPhase.RUNNING,
            node_name="n1",
        ),
        "Node": Node(
            meta=Metadata(name="n", namespace=""),
            allocatable=Resource(8000, 16 << 30),
        ),
        "Queue": Queue(meta=Metadata(name="q", namespace=""), weight=4),
        "PodGroup": PodGroup(meta=Metadata(name="pg"), min_member=3),
        "Command": Command(
            meta=Metadata(name="c"), action="AbortJob", target=("Job", "j")
        ),
    }
    for kind, obj in samples.items():
        wire = json.loads(json.dumps(encode(obj)))
        back = decode(KIND_CLASSES[kind], wire)
        assert back == obj, f"{kind} did not round-trip"


# -- CRUD + watch over HTTP ---------------------------------------------------


def test_remote_crud_and_watch(server):
    a = RemoteStore(server.url)
    b = RemoteStore(server.url)
    watch_q = b.watch("Node")

    node = Node(meta=Metadata(name="n1", namespace=""), allocatable=Resource(4000, 8 << 30))
    a.create("Node", node)
    assert node.meta.resource_version > 0  # server-stamped, propagated back

    got = b.get("Node", "/n1")
    assert got is not None and got.allocatable == node.allocatable
    assert [n.meta.name for n in b.list("Node")] == ["n1"]

    got.unschedulable = True
    b.update("Node", got)
    assert a.get("Node", "/n1").unschedulable

    ev = watch_q.popleft()
    assert (ev.type.value, ev.obj.meta.name) == ("Added", "n1")
    ev = watch_q.popleft()
    assert ev.type.value == "Updated" and ev.obj.unschedulable
    assert ev.old is not None and not ev.old.unschedulable  # shadowed old state

    assert a.delete("Node", "/n1") is not None
    assert a.get("Node", "/n1") is None
    assert watch_q.popleft().type.value == "Deleted"
    assert not watch_q


def test_create_conflict_and_update_missing(server):
    s = RemoteStore(server.url)
    s.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    with pytest.raises(KeyError):
        s.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    with pytest.raises(KeyError):
        s.update("Queue", Queue(meta=Metadata(name="ghost", namespace="")))


def test_server_side_admission(server):
    from volcano_tpu.admission import AdmissionError

    s = RemoteStore(server.url)
    bad = make_job("bad")
    bad.spec.min_available = 5  # > total replicas: admit_job.go rejection
    with pytest.raises(AdmissionError):
        s.create("Job", bad)
    assert s.get("Job", "default/bad") is None

    ok = make_job("ok")
    ok.spec.queue = ""  # webhook mutation fills the default
    ok.spec.tasks[0].name = ""
    s.create("Job", ok)
    assert ok.spec.queue == "default"  # mutation propagated to the caller
    assert ok.spec.tasks[0].name == "default0"

    # spec is frozen on update (admit_job.go specDeepEqual)
    stored = s.get("Job", "default/ok")
    stored.spec.min_available = 1
    with pytest.raises(AdmissionError):
        s.update("Job", stored)


def test_update_cas_rejects_stale_writes(server):
    from volcano_tpu.store.store import Conflict

    s = RemoteStore(server.url)
    node = Node(meta=Metadata(name="n1", namespace=""), allocatable=Resource(1000, 1 << 30))
    s.create("Node", node)

    stale = s.get("Node", "/n1")
    fresh = s.get("Node", "/n1")
    fresh.unschedulable = True
    s.update("Node", fresh)

    stale.labels["x"] = "y"
    with pytest.raises(Conflict):
        s.update_cas("Node", stale, stale.meta.resource_version)
    # the concurrent write survived
    assert s.get("Node", "/n1").unschedulable


def test_leader_election_create_race_does_not_crash_loser(server):
    """Two fresh candidates both see no lease; the create loser must stand
    by, not crash (409 path in RemoteStore.create)."""
    from volcano_tpu.leader import LeaderElector

    e1 = LeaderElector(RemoteStore(server.url), "vk-scheduler", "a")
    e2 = LeaderElector(RemoteStore(server.url), "vk-scheduler", "b")
    # both electors read "no lease" before either creates
    r1, r2 = e1.try_acquire(), e2.try_acquire()
    assert (r1, r2) == (True, False)
    assert e1.is_leader() and not e2.is_leader()


def test_controller_seeds_existing_objects_on_start(server):
    """A controller started against a store with live jobs must reconcile
    them (informer list+watch warm-up), not wait for new events."""
    from volcano_tpu.controller import JobController

    submit = RemoteStore(server.url)
    server.store.create("Queue", Queue(meta=Metadata(name="default", namespace="")))
    submit.create("Job", make_job("preexisting", replicas=1, min_available=1))

    ctl = JobController(RemoteStore(server.url))
    ctl.pump()
    # seeding produced the OutOfSync request: the job got its PodGroup
    assert submit.get("PodGroup", "default/preexisting") is not None

    # a scheduler cycle enqueues the PodGroup; the next pump creates pods
    # (§3.3: pods appear only after PodGroup goes Inqueue)
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler

    server.store.create(
        "Node",
        Node(meta=Metadata(name="n0", namespace=""),
             allocatable=Resource.from_resource_list(
                 {"cpu": "4", "memory": "8Gi", "pods": 110})),
    )
    Scheduler(RemoteStore(server.url), conf=full_conf()).run_once()
    ctl.pump()
    pods = [p for p in submit.list("Pod") if "preexisting" in p.meta.name]
    assert len(pods) == 1


def test_leader_election_across_clients(server):
    from volcano_tpu.leader import LeaderElector

    clock = [0.0]
    e1 = LeaderElector(RemoteStore(server.url), "vk-controllers", "a",
                       clock=lambda: clock[0])
    e2 = LeaderElector(RemoteStore(server.url), "vk-controllers", "b",
                       clock=lambda: clock[0])
    assert e1.try_acquire()
    assert not e2.try_acquire()
    clock[0] += 20.0  # lease expires without renewal
    assert e2.try_acquire()
    assert not e1.try_acquire()
    assert e2.is_leader() and not e1.is_leader()


def test_watch_relist_after_log_overflow(server):
    from volcano_tpu.store.client import StaleWatch
    from volcano_tpu.store.server import LOG_CAP

    s = RemoteStore(server.url)
    s.watch("Queue")
    s.poll()
    server.log[:] = []  # simulate cap eviction of everything we missed
    server.seq += LOG_CAP + 1
    with pytest.raises(StaleWatch):
        s.poll()
    # cursor resynced to the server head: next poll is clean
    assert s.poll() == 0


# -- the full control plane as separate "processes" over HTTP ----------------


def test_multiprocess_control_plane_runs_job(server):
    """Controller, scheduler, and kubelet each on their own RemoteStore,
    driven concurrently in threads over real HTTP; a job submitted through
    a fourth client reaches Running — SURVEY.md §3.3 end to end across the
    process boundary."""
    from volcano_tpu.controller import JobController
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.api.types import PodPhase

    server.store.create("Queue", Queue(meta=Metadata(name="default", namespace="")))
    for i in range(2):
        server.store.create(
            "Node",
            Node(meta=Metadata(name=f"n{i}", namespace=""),
                 allocatable=Resource.from_resource_list(
                     {"cpu": "4", "memory": "8Gi", "pods": 110})),
        )

    stop = threading.Event()

    def controller_loop():
        ctl = JobController(RemoteStore(server.url))
        while not stop.is_set():
            ctl.pump()
            time.sleep(0.02)

    def scheduler_loop():
        sched = Scheduler(RemoteStore(server.url), conf=full_conf())
        while not stop.is_set():
            sched.run_once()
            time.sleep(0.02)

    def kubelet_loop():
        from volcano_tpu.store.store import Conflict

        store = RemoteStore(server.url)
        while not stop.is_set():
            for pod in store.list("Pod"):
                if pod.deleting:
                    store.delete("Pod", pod.meta.key)
                elif pod.node_name and pod.phase == PodPhase.PENDING:
                    rv = pod.meta.resource_version
                    pod.phase = PodPhase.RUNNING
                    try:
                        store.update_cas("Pod", pod, rv)
                    except (Conflict, KeyError):
                        pass
            time.sleep(0.02)

    threads = [
        threading.Thread(target=f, daemon=True)
        for f in (controller_loop, scheduler_loop, kubelet_loop)
    ]
    for t in threads:
        t.start()
    try:
        client = RemoteStore(server.url)
        client.create("Job", make_job("mpjob", replicas=2, min_available=2))

        deadline = time.monotonic() + 30
        job = None
        while time.monotonic() < deadline:
            job = client.get("Job", "default/mpjob")
            if job and job.status.state.phase == JobPhase.RUNNING:
                break
            time.sleep(0.05)
        assert job is not None and job.status.state.phase == JobPhase.RUNNING, (
            job and job.status
        )
        running = [p for p in client.list("Pod") if p.phase == PodPhase.RUNNING]
        assert len(running) == 2
        assert all(p.node_name for p in running)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)


def test_server_state_survives_restart(tmp_path):
    """The state-file persistence (etcd analogue): a restarted StoreServer
    resumes with every object, continues the version sequence, and stale
    clients are told to relist."""
    from volcano_tpu.api.objects import Metadata, Node, Queue
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.server import StoreServer

    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state, save_interval=0.0).start()
    rs = RemoteStore(srv.url)
    rs.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    rs.create("Node", Node(meta=Metadata(name="n0", namespace=""),
                           allocatable=Resource.from_resource_list(
                               {"cpu": "4", "memory": "8Gi"})))
    node_rv = rs.get("Node", "/n0").meta.resource_version
    seq_before = srv.seq
    srv.stop()

    srv2 = StoreServer(state_path=state, save_interval=0.0).start()
    try:
        rs2 = RemoteStore(srv2.url)
        node = rs2.get("Node", "/n0")
        assert node is not None
        assert node.meta.resource_version == node_rv
        assert rs2.get("Queue", "/q") is not None
        # version sequence continues, not restarts
        node.labels["zone"] = "z1"
        updated = rs2.update("Node", node)
        assert updated.meta.resource_version > node_rv
        # a watch cursor from before the restart must be told to relist
        # (the event log is not persisted)
        out = srv2.watch_since(seq_before + 100, set(), 0)
        assert out.get("relist")
    finally:
        srv2.stop()


def test_state_kinds_survive_double_restart(tmp_path):
    """Regression: the incremental flush builds the file from the encoded
    cache, which must be seeded at load — otherwise the first post-restart
    flush silently drops every kind that wasn't re-dirtied."""
    from volcano_tpu.api.objects import Metadata, Node, Queue
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.store.server import StoreServer

    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state).start()
    srv.store.create("Queue", Queue(meta=Metadata(name="q", namespace="")))
    srv.store.create("Node", Node(meta=Metadata(name="n0", namespace=""),
                                  allocatable=Resource.from_resource_list(
                                      {"cpu": "4", "memory": "8Gi"})))
    with srv.lock:
        srv._pump_log()
    srv.stop()

    srv2 = StoreServer(state_path=state).start()
    # dirty ONE kind only, then flush and restart again
    q = srv2.store.get("Queue", "/q")
    q.weight = 7
    srv2.store.update("Queue", q)
    with srv2.lock:
        srv2._pump_log()
    srv2.stop()

    srv3 = StoreServer(state_path=state).start()
    try:
        assert srv3.store.get("Node", "/n0") is not None, "Node dropped from state"
        assert srv3.store.get("Queue", "/q").weight == 7
    finally:
        srv3.stop()


# -- patch / bulk over the wire ----------------------------------------------


def test_remote_patch_and_bulk_round_trip(server):
    from tests.helpers import build_pod

    s = RemoteStore(server.url)
    s.create("Pod", build_pod("bp1"))
    s.create("Pod", build_pod("bp2"))

    out = s.patch("Pod", "default/bp1", {"node_name": "n7"})
    assert out.node_name == "n7"
    assert s.get("Pod", "default/bp1").node_name == "n7"
    with pytest.raises(KeyError):
        s.patch("Pod", "default/ghost", {"node_name": "n7"})

    results = s.bulk([
        {"op": "patch", "kind": "Pod", "key": "default/bp2",
         "fields": {"node_name": "n8", "deleting": True}},
        {"op": "patch", "kind": "Pod", "key": "default/ghost",
         "fields": {"node_name": "n8"}},
        {"op": "create", "kind": "Pod", "object": build_pod("bp3")},
        {"op": "delete", "kind": "Pod", "key": "default/bp1"},
    ])
    assert results[0] is None and results[2] is None and results[3] is None
    assert results[1] is not None and "ghost" in results[1]
    p2 = s.get("Pod", "default/bp2")
    assert p2.node_name == "n8" and p2.deleting
    assert s.get("Pod", "default/bp3") is not None
    assert s.get("Pod", "default/bp1") is None


def test_conditional_dotted_patch_local_and_remote(server):
    """Dotted-path patch with a precondition — the fast cycle's bulk
    enqueue shipping verb: status.phase flips Pending -> Inqueue in one
    call, siblings preserved, precondition misses skip without writing —
    identical semantics in-process and over HTTP."""
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.store import Store
    from volcano_tpu.store.store import PreconditionFailed
    from tests.helpers import build_podgroup

    def drive(s):
        pg = build_podgroup("cp1", min_member=3)
        pg.status.phase = PodGroupPhase.PENDING
        pg.status.running = 2
        s.create("PodGroup", pg)
        out = s.patch(
            "PodGroup", "default/cp1",
            {"status.phase": PodGroupPhase.INQUEUE},
            when={"status.phase": PodGroupPhase.PENDING},
        )
        assert out.status.phase == PodGroupPhase.INQUEUE
        got = s.get("PodGroup", "default/cp1")
        assert got.status.phase == PodGroupPhase.INQUEUE
        assert got.status.running == 2  # sibling fields preserved
        rv = got.meta.resource_version
        # precondition miss: nothing written, no version bump
        with pytest.raises(PreconditionFailed):
            s.patch(
                "PodGroup", "default/cp1",
                {"status.phase": PodGroupPhase.RUNNING},
                when={"status.phase": PodGroupPhase.PENDING},
            )
        got = s.get("PodGroup", "default/cp1")
        assert got.status.phase == PodGroupPhase.INQUEUE
        assert got.meta.resource_version == rv
        # bulk: ok + precondition-miss + bad path, per-op isolation
        pg2 = build_podgroup("cp2", min_member=1)
        pg2.status.phase = PodGroupPhase.PENDING
        s.create("PodGroup", pg2)
        res = s.bulk([
            {"op": "patch", "kind": "PodGroup", "key": "default/cp2",
             "fields": {"status.phase": PodGroupPhase.INQUEUE},
             "when": {"status.phase": PodGroupPhase.PENDING}},
            {"op": "patch", "kind": "PodGroup", "key": "default/cp1",
             "fields": {"status.phase": PodGroupPhase.RUNNING},
             "when": {"status.phase": PodGroupPhase.PENDING}},
            {"op": "patch", "kind": "PodGroup", "key": "default/cp2",
             "fields": {"status.nope": 1}},
        ])
        assert res[0] is None
        assert res[1] is not None and res[1].startswith("PreconditionFailed")
        assert res[2] is not None and "nope" in res[2]
        assert s.get("PodGroup", "default/cp2").status.phase == (
            PodGroupPhase.INQUEUE
        )

    drive(Store())
    drive(RemoteStore(server.url))


def test_remote_bulk_events_flow_to_watchers(server):
    from tests.helpers import build_pod

    writer = RemoteStore(server.url)
    watcher = RemoteStore(server.url)
    writer.create("Pod", build_pod("wp1"))
    q = watcher.watch("Pod")
    writer.bulk([
        {"op": "patch", "kind": "Pod", "key": "default/wp1",
         "fields": {"node_name": "n1"}},
    ])
    deadline = time.monotonic() + 5
    seen = []
    while time.monotonic() < deadline and not seen:
        watcher.poll()
        while q:
            seen.append(q.popleft())
    assert any(
        ev.obj.meta.key == "default/wp1" and ev.obj.node_name == "n1"
        for ev in seen
    )


def test_remote_patch_on_job_rejected_by_admission(server):
    from volcano_tpu.admission import AdmissionError

    s = RemoteStore(server.url)
    s.create("Job", make_job("patchjob"))
    with pytest.raises(AdmissionError):
        s.patch("Job", "default/patchjob", {"max_retry": 5})


def test_flush_state_picks_up_direct_store_writes(tmp_path):
    """Objects created directly on srv.store (no API request) must reach the
    state file: flush_state pumps the watch log itself."""
    from volcano_tpu.api.objects import Metadata, Queue
    from volcano_tpu.store.server import StoreServer

    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state)  # never started, no API traffic
    srv.store.create("Queue", Queue(meta=Metadata(name="direct", namespace="")))
    srv.flush_state()
    srv2 = StoreServer(state_path=state)
    assert srv2.store.get("Queue", "/direct") is not None


def test_sync_persist_mode_is_durable_before_ack(tmp_path):
    """save_interval <= 0: a mutation is persisted before the client's
    request returns — killing the server right after an ack loses nothing."""
    import json as _json

    from tests.helpers import build_pod
    from volcano_tpu.store.client import RemoteStore
    from volcano_tpu.store.server import StoreServer

    state = str(tmp_path / "state.json")
    srv = StoreServer(state_path=state, save_interval=0).start()
    try:
        rs = RemoteStore(srv.url)
        rs.create("Pod", build_pod("dur1"))
        rs.bulk([{"op": "patch", "kind": "Pod", "key": "default/dur1",
                  "fields": {"node_name": "n1"}}])
        # state file reflects both writes NOW, with the server still live
        # (no stop-flush involved)
        data = _json.load(open(state))
        pods = data["kinds"]["Pod"]
        assert len(pods) == 1 and pods[0]["node_name"] == "n1"
    finally:
        srv.stop()
