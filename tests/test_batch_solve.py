"""Batched-rounds (throughput-mode) solve: invariant tests.

Batch mode may interleave placements differently from the exact solve
(documented divergence), so these tests check policy invariants rather than
bit-for-bit equality: no node overcommit, gang all-or-nothing binds,
full placement when capacity is ample, and predicate respect.
"""

import numpy as np

from volcano_tpu.api.resource import MIN_MEMORY, MIN_MILLI_CPU
from volcano_tpu.scheduler.conf import default_conf
from volcano_tpu.scheduler.scheduler import Scheduler


from helpers import FakeBinder, build_node, build_pod, build_podgroup, build_queue, make_store
from test_tensor_parity import make_random_store


def run_batch(store):
    conf = default_conf(backend="tpu")
    conf.solve_mode = "batch"
    sched = Scheduler(store, conf=conf)
    binder = FakeBinder()
    sched.cache.binder = binder
    sched.run_once()
    return sched, binder


def test_batch_no_overcommit_and_gang_atomicity():
    for seed in range(6):
        store = make_random_store(seed)
        sched, binder = run_batch(store)

        # no node overcommitted
        per_node_cpu, per_node_mem = {}, {}
        for pod_key, node in binder.binds.items():
            pod = store.get("Pod", pod_key)
            per_node_cpu[node] = per_node_cpu.get(node, 0) + pod.spec.resources.get("cpu")
            per_node_mem[node] = per_node_mem.get(node, 0) + pod.spec.resources.get("memory")
        for node in store.items("Node"):
            name = node.meta.name
            assert per_node_cpu.get(name, 0) < node.allocatable.get("cpu") + MIN_MILLI_CPU
            assert per_node_mem.get(name, 0) < node.allocatable.get("memory") + MIN_MEMORY

        # gang atomicity: bound tasks per job either 0 or >= min_member
        by_group = {}
        for pod_key in binder.binds:
            pod = store.get("Pod", pod_key)
            group = pod.meta.annotations["scheduling.volcano.tpu/group-name"]
            by_group[group] = by_group.get(group, 0) + 1
        for group, count in by_group.items():
            pg = store.get("PodGroup", f"default/{group}")
            assert count >= pg.min_member, f"{group}: {count} < {pg.min_member}"


def test_batch_full_placement_when_capacity_ample():
    store = make_store(
        nodes=[build_node(f"n{i}", cpu="16", memory="32Gi") for i in range(8)],
        podgroups=[build_podgroup(f"g{j}", min_member=4) for j in range(10)],
        pods=[
            build_pod(f"g{j}-{t}", group=f"g{j}", cpu="1", memory="1Gi")
            for j in range(10)
            for t in range(4)
        ],
    )
    _, binder = run_batch(store)
    assert len(binder.binds) == 40


def test_batch_placement_volume_vs_exact_under_contention():
    # Throughput mode may order whole-gang commitments differently from the
    # strict greedy walk, so under adversarial contention (tiny cluster,
    # heterogeneous gangs) bound counts can differ — auto mode uses the
    # exact solve at this scale. The batch solve must still land within a
    # reasonable band of the exact placement volume in both directions.
    for seed in (3, 7):
        store_a = make_random_store(seed, n_nodes=4, n_jobs=12)
        store_b = make_random_store(seed, n_nodes=4, n_jobs=12)
        _, batch_binder = run_batch(store_a)

        sched = Scheduler(store_b, conf=default_conf(backend="tpu"))
        exact_binder = FakeBinder()
        sched.cache.binder = exact_binder
        sched.run_once()

        assert len(batch_binder.binds) >= 0.6 * len(exact_binder.binds)
