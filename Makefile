# Analogue of the reference Makefile targets (Makefile:15-63):
# unit-test -> test, e2e-test-kind -> e2e (simulator), images -> native lib.

PY ?= python
DOCKER ?= docker

.PHONY: test e2e parity bench bench-residue bench-wire bench-shard bench-delta bench-repl bench-procs bench-multihost fleet loadtest native examples install clean images image image-tpu lint sanitize chaos crash-soak elastic trace profile perfgate audit

# vtlint: the project-native static analyzer (see ANALYSIS.md); `test`
# runs it as a preamble so tier-1 runs can't pass with lint findings.
# Time budget: <=15s on one core for the whole tree (currently ~7s: ~2s
# interprocedural project-context build + ~5s file rules; `--stats`
# prints the per-rule breakdown when the budget needs re-auditing).
lint:
	$(PY) -m volcano_tpu.analysis --json

test: lint
	$(PY) -m pytest tests/ -q

# seeded chaos soak (volcano_tpu/chaos.py + tests/test_chaos_soak.py):
# deterministic fault plans on the store bus — 5xx bursts, mid-body cuts,
# watch-log truncation, lease clock skew — each must converge to the same
# final placements as a fault-free run, invariants intact.  The smoke
# variant is slow-exempt and runs in tier-1; this target runs every plan.
chaos:
	$(PY) -m pytest tests/test_chaos_soak.py -q

# crash-kill chaos + the zero-acked-loss gate (store/wal.py +
# tests/test_crash_recovery.py): WAL framing/torn-tail/group-commit
# units, acked-durability-after-kill, segment atomicity + idempotent
# resubmit, and the seeded crash.* storms — in-process InjectedCrash
# aborts run in tier-1; this target adds the real-subprocess SIGKILL
# storms (server pre/post-fsync, scheduler mid-drain, controller
# mid-gang), each asserting placements bit-for-bit equal a fault-free
# run after recovery.
crash-soak:
	$(PY) -m pytest tests/test_crash_recovery.py -q

# elastic capacity (volcano_tpu/elastic/ + tests/test_elastic.py): the
# demand estimator, the cordon/drain lifecycle, the elasticd daemon, the
# fastpath churn-parity storm, and the chaos-soak elastic storm.  The
# fast smoke (scale-up -> placement parity -> drain-back) is tier-1.
elastic:
	$(PY) -m pytest tests/test_elastic.py \
	  tests/test_chaos_soak.py::test_chaos_soak_elastic_provision_failures -q

# two sanitizer legs, each the runtime twin of a static rule:
#   1. lock order — every lock acquisition in the multi-process control
#      plane is order-checked against the acyclic graph the static
#      `lock-order` rule proves (volcano_tpu/locksan.py)
#   2. effect order — the store/replica hot paths record the
#      (mutate, append, beacon, ship, ack) sequence per request and any
#      observable effect over an un-appended mutation raises at the
#      offending site (volcano_tpu/effectsan.py, static twin
#      `wal-effect-order`), exercised under the replication + daemons
#      suites where the windows actually open
#   the procmesh leg re-runs the multi-process shard-store suite with
#   the effect sanitizer armed (the env var rides into the spawned
#   shard-server processes): every verb path, WAL append, and the
#   500-abandon rule are checked ACROSS the router hop
sanitize:
	VOLCANO_TPU_LOCK_SANITIZER=1 $(PY) -m pytest tests/test_daemons.py -q
	VOLCANO_TPU_EFFECT_SANITIZER=1 $(PY) -m pytest \
	  tests/test_replication.py tests/test_daemons.py -q
	VOLCANO_TPU_EFFECT_SANITIZER=1 $(PY) -m pytest tests/test_procmesh.py -q

# vtrace (volcano_tpu/trace.py + tests/test_trace.py): the span runtime,
# flight recorder, cross-daemon propagation, the armed-vs-disarmed
# placement-neutrality + zero-overhead smokes, the describe/events/trace
# CLI, and the traced chaos storm (one trace id across three daemons).
trace:
	$(PY) -m pytest tests/test_trace.py tests/test_cli.py \
	  tests/test_chaos_soak.py::test_chaos_smoke_traced_storm_neutral_and_reconstructs_gang -q

e2e:
	$(PY) -m pytest tests/test_e2e_policies.py tests/test_e2e_mpi.py \
	  tests/test_e2e_recovery.py tests/test_controller.py tests/test_volumes.py \
	  tests/test_daemons.py tests/test_churn_soak.py -q

parity:
	$(PY) -m pytest tests/test_tensor_parity.py tests/test_victim_parity.py \
	  tests/test_native_backend.py tests/test_batch_solve.py \
	  tests/test_fastpath.py tests/test_parallel.py -q

bench:
	$(PY) bench.py

# the host-residue cliff (BASELINE.md r5: 64.6 s / 500 volume tasks):
# cfg5v runs config 5 + 500/2000 volume-constrained gangs through the
# device volume solve (volsolve.py) with the vectorized residue engine
# (scheduler/residue.py) behind it; parity in tests/test_volume_parity.py
bench-residue:
	$(PY) bench.py --config 9

# vtload (volcano_tpu/loadgen/): cfg8 sustains a seeded open-loop
# arrival process (Poisson gang arrivals, resource/queue mix, dwell
# churn) through the real Scheduler + Store, reports p50/p99/p999 pod
# first-seen→bind latency from the bounded metric histograms, then
# doubles QPS on fresh clusters until p99 breaches the band (saturation
# search).  The tier-1 smoke + SLO chaos gate live in
# tests/test_loadgen.py; `vtctl top` renders the per-cycle time series.
loadtest:
	$(PY) bench.py --open-loop

# vtprof (volcano_tpu/vtprof.py + tests/test_vtprof.py): the critical-
# path profiler suite — disarmed-zero-overhead + placement-parity
# smokes, the >=95% attribution bar, the steady-state recompile
# sentinel, the leak sentinel, /debug/prof, and `vtctl profile`.
profile:
	$(PY) -m pytest tests/test_vtprof.py tests/test_perfgate.py -q

# the continuous perf-regression gate: fresh capture of the gated
# headline configs (cfg5/cfg7/cfg8 — same-device bands derived from the
# BENCH_r0*.json trajectory via `bench.py --history`) with a per-config,
# per-phase attribution diff and a nonzero exit on breach.  The
# sub-second machinery smoke lives in tier-1 (tests/test_perfgate.py).
perfgate:
	$(PY) bench.py --check

# vtaudit (volcano_tpu/vtaudit.py + tests/test_vtaudit.py): the
# incremental state-digest auditor — digest algebra invariants, the
# flipped-byte corruption drill with exact (kind, namespace, name)
# localization, mirror-vs-partitioned-server beacon-pinned equality,
# WAL-replay digest verification, and the `vtctl audit` walk; the
# digest-maintenance lint rule fences the store's mutation verbs.
audit:
	$(PY) -m pytest tests/test_vtaudit.py -q
	$(PY) -m volcano_tpu.analysis --select digest-maintenance volcano_tpu

# the columnar store wire (store/segment.py): cfg7 runs config 5 against
# the HTTP apiserver in its own OS process — publish + off-cycle drain of
# 102k binds/Events as ONE segment per cycle, with the per-kind drain
# breakdown (drain_binds_s / drain_events_s / drain_pg_s) in extra;
# parity in tests/test_columnar_wire.py, fenced by the columnar-publish
# lint rule
bench-wire:
	$(PY) bench.py --config 7

# the mesh-sharded deployed cycle + partitioned store bus (ROADMAP item
# 1, PR 11): the tier-1 smoke first proves 2-device-mesh placement
# parity with the single-device run (sub-second, virtual CPU mesh),
# then cfg9 runs 1M tasks x 100k nodes end-to-end — mesh from
# VOLCANO_TPU_CFG9_MESH (auto), store shards from
# VOLCANO_TPU_CFG9_SHARDS (4), vtprof armed (>=95% attribution bar),
# plus the cfg7-shaped sharded-vs-single-shard drain comparison.
# CPU containers: set VOLCANO_TPU_CFG9_SCALE (e.g. 0.01) to shrink.
bench-shard:
	$(PY) -m pytest tests/test_parallel.py -q \
	  -k "shard_smoke or victim_step_mesh" -p no:cacheprovider
	$(PY) bench.py --config 11

# vtdelta (volcano_tpu/scheduler/delta/ + tests/test_delta.py, ROADMAP
# item 2): event-driven incremental micro-cycles with admission control
# and backlog shedding.  The tier-1 suite proves bit-for-bit
# micro-vs-full parity (the snapshot-incremental oracle), jit-flat
# steady state over >=50 micro-cycles, the Backlogged shed/readmit
# lifecycle, and the chaos-storm/crash-kill gates composed with delta
# mode on; cfg10 (`--config 12`) measures micro vs full pump latency on
# a resident cluster plus the lockstep saturation search.
# CPU containers: set VOLCANO_TPU_CFG10_SCALE (e.g. 0.1) to shrink.
bench-delta:
	$(PY) -m pytest tests/test_delta.py -q -p no:cacheprovider
	$(PY) bench.py --config 12

# vtrepl (store/replica.py + tests/test_replication.py): WAL-shipping
# replication, follower-served watches, leader failover.  The tier-1
# suite proves the group-commit ship watermark, byte-identical follower
# replay, NotLeader redirects, sync-ack, and the SIGKILL-the-leader
# storm (zero acked loss); cfg11 (`--config 13`) measures follower-
# served watch fan-out read throughput scaling 1->2->4 follower
# subprocesses.  CPU containers: set VOLCANO_TPU_CFG11_SCALE to shrink.
bench-repl:
	$(PY) -m pytest tests/test_replication.py -q -p no:cacheprovider
	$(PY) bench.py --config 13

# vtproc (store/procmesh/ + tests/test_procmesh.py): the multi-process
# shard store — per-shard OS processes under a ShardSupervisor behind a
# ShardRouter, one SeqBus seq/rv line.  The tier-1 suite proves merged-
# /watch byte identity vs a single-process server, the SIGKILL-a-shard
# storm (restart, zero acked loss, placement parity, `vtctl audit` 0),
# router decomposition of cross-shard segments/columnar patches, and
# procNN_s drain attribution; cfg9c (`--config 14`) measures the drain
# critical path (slowest shard's ship wall) scaling 2 -> 4 shard
# processes.  CPU containers: set VOLCANO_TPU_CFG9C_SCALE to shrink.
bench-procs:
	$(PY) -m pytest tests/test_procmesh.py -q -p no:cacheprovider
	$(PY) bench.py --config 14

# vtmesh (parallel/multihost.py + tests/test_multihost.py): the
# multi-controller mesh solve — one process per host over one logical
# device mesh, per-host snapshot shards in, owned output slices out.
# The tier-1 suite proves --mesh-hosts 1 bit-for-bit parity with the
# sharded path, the 2-host lockstep merge, the 2-process coordinator
# cycle (clean shutdown) and the coordinator-death fallback; the
# sub-second sweep here shows the per-host critical path at CI scale,
# then cfg9e (`--check --configs 16`) gates ≤0.7x per host doubling +
# ≥0.95 vtprof attribution at bench scale and cfg9f (`--configs 17`)
# runs the env-scaled 10M x 1M stretch shape
# (VOLCANO_TPU_CFG9E_SCALE / VOLCANO_TPU_CFG9F_SCALE shrink further).
bench-multihost:
	$(PY) -m pytest tests/test_multihost.py -q -p no:cacheprovider
	env JAX_PLATFORMS=cpu \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PY) -m volcano_tpu.parallel.multihost --sweep 1,2,4 \
	  --nodes 512 --tasks 2048 --jobs 128 --reps 3 --prof

# vtfleet (volcano_tpu/vtfleet.py + tests/test_vtfleet.py): the
# cross-process observability plane — fleet trace reassembly (per-proc
# /debug/trace rings clock-aligned onto one timeline), federated
# /metrics with proc= labels + exact bucket-wise histogram rollups,
# `vtctl top/trace/profile/describe --fleet`, router ?proc= passthrough,
# and the supervisor's crash-forensics incident bundles (the SIGKILL
# storm in tests/test_procmesh.py asserts bundle contents + restart
# counters).  cfg9d (`--check --configs 15`) gates the armed-vs-
# disarmed procmesh drain ratio at an absolute 1.05x band so fleet
# harvesting can never tax the drain path.  CPU containers: set
# VOLCANO_TPU_CFG9C_SCALE to shrink.
fleet:
	$(PY) -m pytest tests/test_vtfleet.py -q -p no:cacheprovider
	$(PY) -m pytest tests/test_procmesh.py -q -p no:cacheprovider \
	  -k "storm or fleet or collector"
	$(PY) bench.py --check --configs 15

# container images (reference Makefile:40-48 / installer/dockerfile/):
# `image` = CPU-jax control plane, `image-tpu` = jax[tpu]+libtpu wheel
# baked in (build needs no TPU; running the scheduler on chips does)
images: image image-tpu

image:
	$(DOCKER) build -f installer/Dockerfile -t volcano-tpu .

image-tpu:
	$(DOCKER) build -f installer/Dockerfile.tpu -t volcano-tpu:tpu .

native: volcano_tpu/native/libvtsolver.so

volcano_tpu/native/libvtsolver.so: volcano_tpu/native/solver.cc
	g++ -O3 -shared -fPIC -fopenmp -std=c++17 volcano_tpu/native/solver.cc \
	  -o volcano_tpu/native/libvtsolver.so

install:
	$(PY) -m pip install .

examples:
	$(PY) examples/job_gang.py
	$(PY) examples/mpi_hello.py
	$(PY) examples/tensorflow_benchmark.py
	$(PY) examples/job_with_volumes.py

clean:
	rm -f volcano_tpu/native/libvtsolver.so
	find . -name __pycache__ -type d -exec rm -rf {} +
