"""Benchmarks for the BASELINE.md target configs.

Default (no arguments): the HEADLINE SUITE — the five headline configs,
one compact JSON line each, in this order:
  cfg5   e2e_schedule_cycle_100k_tasks_10k_nodes   (best-of-2 full runs)
  cfg5d  cfg5d_e2e_cycle_10pct_dynamic_predicates  (1 run)
  cfg5v  cfg5v_e2e_cycle_volume_constrained        (500 + 2000 vol tasks)
  cfg6   cfg6_contended_preempt_storm_100k_x_10k   (storm only, no cfg6b)
  cfg7   e2e_http_schedule_cycle_100k_tasks_10k_nodes
  cfg8   cfg8_open_loop_first_seen_to_bind         (short open-loop run)
so one driver invocation captures the plain, dynamic-predicate,
volume-constrained, contended, HTTP-process-model, and open-loop-SLO
numbers (~5 min
total on a v5e; a
failed config prints an {"metric": ..., "error": ...} line and the suite
continues, rc stays 0).  Each line reports
  {"metric": ..., "value": run_once_seconds, "unit": "s", "vs_baseline": x}
with vs_baseline = 60 s / seconds (the reference's Go CPU path takes
>60 s for one allocate cycle at this scale on 16 goroutines; BASELINE.md —
and that 60 s is the Go path's *solve alone*, not its end-to-end cycle).

`--config N` runs one of the BASELINE configs (full methodology:
best-of-3 for cfg5, storm + best-effort-storm lines for cfg6), `--all`
runs all of them plus the kernel-only cycle (one JSON line each).
`--config 11` is cfg9 (`make bench-shard`): the mesh-sharded deployed
cycle against the partitioned store bus — 1M tasks × 100k nodes at full
scale (VOLCANO_TPU_CFG9_SCALE shrinks it for CPU containers), vtprof
armed (the ≥95% attribution bar), plus the cfg7-shaped sharded-vs-
single-shard drain comparison line:
  1  gang+priority, allocate only (single queue, no fair share)
  2  drf+proportion multi-queue fair share
  3  predicates+nodeorder (per-class node masks + affinity scores)
  4  preempt/reclaim victim selection (overcommitted cluster)
  5  end-to-end 5-action pipeline through Scheduler+Store (the default)
  6  contended end-to-end cycle: 100k running x 10k nodes fully occupied
     plus a 2000-task urgent preemption storm through the real Scheduler
     (a second line, cfg6b, adds one best-effort preemptor to the storm)
  7  config 5 through the real HTTP apiserver (StoreServer) + RemoteStore
`--kernel` times the device decision kernel alone over sim arrays.
`--open-loop` (also `--config 10`) runs cfg8: the vtload open-loop SLO
harness — seeded Poisson gang arrivals at a target QPS through the real
Scheduler + Store, p50/p99/p999 pod first-seen→bind latency from the
bounded metric histograms, then a saturation search raising QPS until
p99 breaches the band (`make loadtest`).

Configs 1-4 and --kernel are post-compile steady-state kernel solves;
config 5 pays the real cycle: watch drain, array snapshot, device solve,
decision publish (async drain reported separately).
"""

import argparse
import json
import os
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 100_000
N_JOBS = 5_000
N_QUEUES = 2
BASELINE_SECONDS = 60.0  # reference Go CPU path at this scale (BASELINE.md)

#: every metric payload printed this invocation, in order — the perf
#: gate (--check) reads the fresh capture from here instead of scraping
#: its own stdout
LAST_RESULTS = []


def _print_json(payload):
    LAST_RESULTS.append(payload)
    print(json.dumps(payload))


def build_sim_snapshot(seed=0, **kw):
    from volcano_tpu.scheduler.simargs import build_sim_args

    return build_sim_args(N_NODES, N_TASKS, N_JOBS, N_QUEUES, seed=seed, **kw)


def _time_cycle(args_host, reps=7, **cycle_kw):
    import jax
    import jax.numpy as jnp

    from volcano_tpu.parallel.sharded import run_cycle_reference

    args = {k: jnp.asarray(v) for k, v in args_host.items()}
    # warm-up / compile (twice: the second run also warms the device
    # allocator and any tunnel-side caching)
    for _ in range(2):
        out = run_cycle_reference(args, **cycle_kw)
        jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_cycle_reference(args, **cycle_kw)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times), out


def _emit(metric, cycle, placed, extra=None):
    import jax

    payload = {
        "metric": metric,
        "value": round(cycle, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / cycle, 1),
        "extra": {
            "pods_placed": placed,
            "pods_per_sec": int(placed / cycle),
            "device": str(jax.devices()[0]),
            **(extra or {}),
        },
    }
    _print_json((payload))


def config1():
    """Gang+priority allocate only: one queue, no fair-share keys."""
    host = build_sim_snapshot(seed=1)
    host["queue_weight"][:] = 0
    host["queue_weight"][0] = 1
    host["job_queue"][host["job_queue"] >= 0] = 0
    cycle, out = _time_cycle(
        host, job_key_order=("priority", "gang"), use_proportion=False
    )
    _emit("cfg1_gang_priority_allocate", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def config2():
    """DRF + proportion water-filling across weighted queues."""
    host = build_sim_snapshot(seed=2)
    cycle, out = _time_cycle(
        host, job_key_order=("priority", "gang", "drf"), use_proportion=True
    )
    _emit("cfg2_drf_proportion_fair_share", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def config3():
    """Predicates + nodeorder: 32 per-class node masks, 60% fill, affinity
    scores in the weighted sum."""
    host = build_sim_snapshot(seed=3, n_classes=32, class_fill=0.6)
    cycle, out = _time_cycle(host)
    _emit("cfg3_predicates_nodeorder", cycle,
          int((np.asarray(out[1]) > 0).sum()),
          extra={"classes": 32, "class_fill": 0.6})


def config4():
    """Victim selection on an occupied cluster: one victim_step per
    preemptor over a 100k-victim pool (the per-preemptor decision the host
    path takes O(nodes x victims) Python for)."""
    import jax
    import jax.numpy as jnp

    from volcano_tpu.scheduler.simargs import build_victim_sim
    from volcano_tpu.scheduler.victim_kernels import (
        VictimConsts, VictimState, victim_step,
    )

    c_np, s_np = build_victim_sim(N_NODES, N_TASKS, N_JOBS, seed=4)
    consts = VictimConsts(**{k: jnp.asarray(v) for k, v in c_np.items()})
    state = VictimState(**{k: jnp.asarray(v) for k, v in s_np.items()})
    t_req = jnp.asarray(np.array([2000.0, 4 * (1 << 30)], np.float32))

    def solve(s, jt):
        return victim_step(consts, s, t_req, 0, jt, 0, mode="queue",
                           use_gang=True, use_drf=True)

    out = solve(state, jnp.int32(0))
    jax.block_until_ready(out)
    # 16 INDEPENDENT solves from the same snapshot (job 0 is the reserved
    # empty preemptor job — a lower-share job preempting resident ones, the
    # deployed preempt shape; states from clean=False solves are
    # contractually discarded, so chaining would time solves over invalid
    # state), each individually blocked; min-of-reps, same methodology as
    # the cycle configs.
    times = []
    assigned_n = clean_n = 0
    for _ in range(16):
        t0 = time.perf_counter()
        s2, assigned, nstar, vmask, clean = solve(state, jnp.int32(0))
        jax.block_until_ready(s2)
        times.append(time.perf_counter() - t0)
        assigned_n += int(bool(assigned))
        clean_n += int(bool(clean))
    assert assigned_n > 0, "victim solve never assigned at bench scale"
    times.sort()
    per_min = times[0]
    per_mean = sum(times) / len(times)
    per_p50 = times[len(times) // 2]
    # pipelined shape: dispatch all 16, block once.  Each BLOCK on the
    # tunneled device pays a ~0.1 s completion RTT regardless of work
    # (dispatch itself is ~0.2 ms), so per-solve blocking measures the
    # tunnel, not the kernel; the deployed paths never block per
    # preemptor (the storm kernels run a whole pass per dispatch).
    t0 = time.perf_counter()
    outs = [solve(state, jnp.int32(0)) for _ in range(16)]
    jax.block_until_ready(outs[-1][0])
    per_pipelined = (time.perf_counter() - t0) / 16
    # own payload: this is s/preemptor, not a placement-cycle metric —
    # reusing pods_placed/pods_per_sec here would silently change those
    # fields' meaning across configs.  mean/p50 are reported alongside min
    # because each independent solve pays a host<->device round trip whose
    # tunnel latency the min hides (VERDICT r3 weak #2); a real contended
    # cycle amortizes dispatch via the storm kernels, so storm throughput
    # comes from config 6, never from this number.
    _print_json(({
        "metric": "cfg4_preempt_victim_solve",
        "value": round(per_min, 5),
        "unit": "s/preemptor",
        "vs_baseline": None,
        "extra": {
            "victim_pool": N_TASKS,
            "mean_s": round(per_mean, 5),
            "p50_s": round(per_p50, 5),
            "pipelined_s": round(per_pipelined, 5),
            "assigned": assigned_n,
            "clean": clean_n,
            "methodology": (
                "min/mean/p50 over 16 independent individually blocked "
                "solves — each block pays the tunnel's ~0.1s completion "
                "RTT (dispatch is ~0.2ms), so blocked numbers measure the "
                "tunnel; pipelined_s amortizes one block over 16 "
                "dispatches (the deployed dispatch shape — storm kernels "
                "block once per PASS); see cfg6 for storm throughput"
            ),
            "device": str(jax.devices()[0]),
        },
    }))


def kernel_cycle():
    """Kernel-only cycle (water-fill + batched allocate solve) over
    pre-built sim arrays at 100k x 10k — the device decision kernel in
    isolation, without store/snapshot/publish. The headline end-to-end
    number is config 5."""
    host = build_sim_snapshot()
    cycle, out = _time_cycle(host)
    _emit("kernel_cycle_100k_tasks_10k_nodes", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def _build_e2e_store(n_best_effort=2000, dynamic_frac=0.0, volume_tasks=0):
    """Real Store at bench scale: 10k nodes, 5k gang jobs x 20 tasks
    (100k), plus best-effort tasks for backfill. Capacity covers demand so
    the pipeline's preempt/reclaim passes correctly find no starving work
    (an overcommitted preemption storm is config 4's domain).

    ``dynamic_frac``: that fraction of the jobs carries resident-state
    predicates — alternating host-port gangs (64-port pool) and
    self-anti-affinity gangs (48 shared labels) — exercising the device
    dynamic solve at scale (VERDICT r4 missing #1).  Best-effort pods
    attach only to non-dynamic jobs (a BE pod of a dynamic job routes
    the job through the host residue path by design).

    ``volume_tasks``: that many extra VOLUME-CONSTRAINED tasks (20-task
    gangs, tiny requests) — alternating bound-PVC-pinned gangs (the
    claim's PV carries single-node affinity, so the gang must colocate)
    and static-class gangs (one shared WaitForFirstConsumer claim per
    gang drawing from a node-pinned PV pool, exercising the attach-
    capacity tensor).  The r5 residue path paid ~0.13 s/task x nodes for
    exactly this class (BASELINE.md host-residue cost curve)."""
    from volcano_tpu.api import POD_GROUP_KEY, Resource
    from volcano_tpu.api.objects import (
        Affinity, Metadata, Node, Pod, PodGroup, PodSpec, Queue,
    )
    from volcano_tpu.api.objects import (
        PersistentVolume, PersistentVolumeClaim, StorageClass,
    )
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.store import Store

    rng = np.random.default_rng(0)
    tasks_per_job = N_TASKS // N_JOBS
    node_cpu = rng.choice([8000, 16000, 32000], N_NODES)
    node_mem = rng.choice([16, 32, 64], N_NODES) * (1 << 30)
    cpus = rng.choice([250, 500, 1000, 2000], N_TASKS)
    mems = rng.choice([256, 512, 1024, 2048], N_TASKS) * (1 << 20)
    n_dynamic = int(N_JOBS * dynamic_frac)

    store = Store()
    for q in range(N_QUEUES):
        store.create("Queue", Queue(meta=Metadata(name=f"q{q}", namespace=""),
                                    weight=N_QUEUES - q))
    store.create("Queue", Queue(meta=Metadata(name="default", namespace=""),
                                weight=1))
    for i in range(N_NODES):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:05d}", namespace=""),
            allocatable=Resource(float(node_cpu[i]), float(node_mem[i]),
                                 max_task_num=110)))
    k = 0
    for j in range(N_JOBS):
        pg = PodGroup(meta=Metadata(name=f"pg{j:05d}", namespace="default"),
                      min_member=tasks_per_job, queue=f"q{j % N_QUEUES}")
        pg.status.phase = PodGroupPhase.PENDING  # enqueue admits them
        store.create("PodGroup", pg)
        ann = {POD_GROUP_KEY: f"pg{j:05d}"}
        dyn_kind = None
        if j < n_dynamic:
            dyn_kind = "ports" if j % 2 == 0 else "anti"
        for t in range(tasks_per_job):
            spec = PodSpec(image="bench",
                           resources=Resource(float(cpus[k]),
                                              float(mems[k])))
            labels = {}
            if dyn_kind == "ports":
                spec.host_ports = [20000 + (j % 64)]
            elif dyn_kind == "anti":
                labels = {"grp": f"g{j % 48}"}
                spec.affinity = Affinity(
                    pod_anti_affinity=[{"grp": f"g{j % 48}"}]
                )
            store.create("Pod", Pod(
                meta=Metadata(name=f"p{j:05d}-{t}", namespace="default",
                              annotations=dict(ann), labels=labels),
                spec=spec))
            k += 1
        if dyn_kind is None and j < n_dynamic + n_best_effort:
            store.create("Pod", Pod(
                meta=Metadata(name=f"be{j:05d}", namespace="default",
                              annotations=dict(ann)),
                spec=PodSpec(image="bench", resources=Resource())))
    n_vol_jobs = volume_tasks // tasks_per_job
    if n_vol_jobs:
        store.create("StorageClass", StorageClass(
            meta=Metadata(name="volb", namespace=""), provisioner=""))
        for v in range(n_vol_jobs):
            pin = f"n{(v * 97) % N_NODES:05d}"
            if v % 2 == 0:
                # bound-PVC gang: the claim's PV pins the whole gang
                store.create("PV", PersistentVolume(
                    meta=Metadata(name=f"vpv{v:04d}", namespace=""),
                    capacity="50Gi", storage_class="net",
                    node_affinity={"kubernetes.io/hostname": pin},
                    claim_ref=f"default/vc{v:04d}"))
                store.create("PVC", PersistentVolumeClaim(
                    meta=Metadata(name=f"vc{v:04d}", namespace="default"),
                    size="5Gi", storage_class="net",
                    volume_name=f"vpv{v:04d}", phase="Bound"))
            else:
                # static-class gang: one pending claim per gang, drawing
                # from the shared node-pinned pool (attach-capacity tensor)
                store.create("PV", PersistentVolume(
                    meta=Metadata(name=f"vpv{v:04d}", namespace=""),
                    capacity="50Gi", storage_class="volb",
                    node_affinity={"kubernetes.io/hostname": pin}))
                store.create("PVC", PersistentVolumeClaim(
                    meta=Metadata(name=f"vc{v:04d}", namespace="default"),
                    size="5Gi", storage_class="volb"))
            pg = PodGroup(
                meta=Metadata(name=f"vol{v:04d}", namespace="default"),
                min_member=tasks_per_job, queue=f"q{v % N_QUEUES}")
            pg.status.phase = PodGroupPhase.PENDING
            store.create("PodGroup", pg)
            ann = {POD_GROUP_KEY: f"vol{v:04d}"}
            for t in range(tasks_per_job):
                pod = Pod(
                    meta=Metadata(name=f"v{v:04d}-{t}", namespace="default",
                                  annotations=dict(ann)),
                    spec=PodSpec(image="bench",
                                 resources=Resource(100.0, 64 * (1 << 20))))
                pod.volumes = [f"vc{v:04d}"]
                store.create("Pod", pod)
    return store


def _build_contended_store(n_best_effort=0):
    """Fully-occupied bench-scale cluster + a high-priority pending storm:
    10k nodes with 100k RUNNING low-priority tasks (zero idle), then 100
    urgent 20-task gangs (2000 preemptors) in the same queue — allocate
    finds nothing, the array-native preempt pass must evict to serve them.
    One queue only, so reclaim (cross-queue) correctly prechecks to no
    work.  ``n_best_effort`` adds empty-request pods to the first storm
    gangs — the formerly kernel-inexpressible preemptor class that used to
    route the whole pass through the O(cluster) object session."""
    from volcano_tpu.api import POD_GROUP_KEY, Resource
    from volcano_tpu.api.objects import (
        Metadata, Node, Pod, PodGroup, PodSpec, PriorityClass, Queue,
    )
    from volcano_tpu.api.types import PodGroupPhase, PodPhase
    from volcano_tpu.store import Store

    tasks_per_job = N_TASKS // N_JOBS  # 20
    store = Store()
    store.create("Queue", Queue(meta=Metadata(name="q0", namespace=""),
                                weight=1))
    store.create("Queue", Queue(meta=Metadata(name="default", namespace=""),
                                weight=1))
    store.create("PriorityClass", PriorityClass(
        meta=Metadata(name="urgent", namespace=""), value=100))
    for i in range(N_NODES):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:05d}", namespace=""),
            allocatable=Resource(8000.0, 16.0 * (1 << 30), max_task_num=110)))
    # residents: 10 per node x 800m cpu / 1.2Gi = node exactly full on cpu
    k = 0
    for j in range(N_JOBS):
        pg = PodGroup(meta=Metadata(name=f"run{j:05d}", namespace="default"),
                      min_member=1, queue="q0")
        pg.status.phase = PodGroupPhase.RUNNING
        store.create("PodGroup", pg)
        ann = {POD_GROUP_KEY: f"run{j:05d}"}
        for t in range(tasks_per_job):
            pod = Pod(
                meta=Metadata(name=f"r{j:05d}-{t}", namespace="default",
                              annotations=dict(ann)),
                spec=PodSpec(image="bench",
                             resources=Resource(800.0, 1.2 * (1 << 30))))
            pod.node_name = f"n{k % N_NODES:05d}"
            pod.phase = PodPhase.RUNNING
            store.create("Pod", pod)
            k += 1
    # the storm: 100 urgent gangs x 20 tasks, each task needs 2 victims
    for j in range(100):
        pg = PodGroup(meta=Metadata(name=f"hot{j:03d}", namespace="default"),
                      min_member=tasks_per_job, queue="q0",
                      priority_class_name="urgent")
        pg.status.phase = PodGroupPhase.INQUEUE
        store.create("PodGroup", pg)
        ann = {POD_GROUP_KEY: f"hot{j:03d}"}
        for t in range(tasks_per_job):
            store.create("Pod", Pod(
                meta=Metadata(name=f"h{j:03d}-{t}", namespace="default",
                              annotations=dict(ann)),
                spec=PodSpec(image="bench",
                             resources=Resource(1500.0, 2.0 * (1 << 30)))))
        if j < n_best_effort:
            # unsatisfiable node selector: backfill cannot place it, so it
            # genuinely reaches the preempt pass as an empty-request
            # preemptor (it finds no feasible node there either — the
            # point is that attempting it stays array-native)
            store.create("Pod", Pod(
                meta=Metadata(name=f"hbe{j:03d}", namespace="default",
                              annotations=dict(ann)),
                spec=PodSpec(image="bench", resources=Resource(),
                             node_selector={"zone": "nowhere"})))
    return store


def config6(include_best_effort=True):
    """Contended cycle (VERDICT r2 weak #1): the preemption storm at
    100k x 10k through the real Scheduler — run_once wall-clock for the
    full pipeline where preempt actually finds work, array-native.  A
    second line re-runs the storm with one best-effort preemptor mixed in
    (VERDICT r3 next #6): the formerly kernel-inexpressible class must
    stay array-native instead of paying the O(cluster) object session.
    The default headline suite passes ``include_best_effort=False`` to
    emit only the base storm line."""
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler

    variants = [("cfg6_contended_preempt_storm_100k_x_10k", 0)]
    if include_best_effort:
        variants.append(
            ("cfg6b_contended_storm_with_best_effort_preemptor", 1))
    for metric, n_be in variants:
        store = _build_contended_store(n_best_effort=n_be)
        conf = full_conf("tpu")
        conf.apply_mode = "async"
        sched = Scheduler(store, conf=conf)
        warm = sched.prewarm()
        t1 = time.perf_counter()
        if sched.prewarm_background is not None:
            sched.prewarm_background.join()
        warm_bg = time.perf_counter() - t1

        t0 = time.perf_counter()
        sched.run_once()
        cycle = time.perf_counter() - t0
        while sched.cache.applier.pending > 0:
            time.sleep(0.005)
        drain = time.perf_counter() - t0 - cycle
        evicted = len(sched.cache.evict_log)

        import jax

        _print_json(({
            "metric": metric,
            "value": round(cycle, 4),
            "unit": "s",
            "vs_baseline": round(BASELINE_SECONDS / cycle, 1),
            "extra": {
                "preemptor_tasks": 2000 + n_be,
                "victims_evicted": evicted,
                "preemptors_per_sec": int((2000 + n_be) / cycle),
                "phases_s": _phases_of(sched),
                "async_drain_s": round(drain, 2),
                "prewarm_s": round(warm, 1),
                "prewarm_bg_s": round(warm_bg, 1),
                "path": "fastpath" if (
                    sched.fast_cycle and sched.fast_cycle.mirror is not None
                ) else "object",
                "device": str(jax.devices()[0]),
            },
        }))


def _phases_of(sched):
    fc = sched.fast_cycle
    if fc is None or not fc.phases:
        return {}
    return {k: round(v, 4) for k, v in fc.phases.items()}


def _e2e_run(store, conf):
    """One full e2e run: fresh Scheduler on ``store``, prewarm (joined),
    timed first cycle, async drain, steady cycle.  Returns a stats dict
    including the fast cycle's per-phase breakdown."""
    from volcano_tpu.scheduler.scheduler import Scheduler

    sched = Scheduler(store, conf=conf)
    warm = sched.prewarm()
    t1 = time.perf_counter()
    if sched.prewarm_background is not None:
        sched.prewarm_background.join()
    warm_bg = time.perf_counter() - t1

    t0 = time.perf_counter()
    sched.run_once()
    publish = time.perf_counter() - t0
    phases = _phases_of(sched)
    while sched.cache.applier.pending > 0:
        time.sleep(0.005)
    drain = time.perf_counter() - t0 - publish
    bound = sum(1 for p in store.items("Pod") if p.node_name)

    # steady-state cycle: everything placed, watch backlog drained
    sched.run_once()
    t1 = time.perf_counter()
    sched.run_once()
    steady = time.perf_counter() - t1
    # scalars only: holding the Scheduler (and through it the 100k-pod
    # store + mirror) across reps would triple the bench's peak memory
    return {
        "publish": publish, "phases": phases,
        "drain": drain, "bound": bound, "steady": steady,
        "warm": warm, "warm_bg": warm_bg,
        "fastpath": bool(
            sched.fast_cycle and sched.fast_cycle.mirror is not None
        ),
    }


def config5(reps=3, dynamic_frac=0.0,
            metric="e2e_schedule_cycle_100k_tasks_10k_nodes"):
    """THE headline: the full 5-action pipeline (enqueue, reclaim,
    allocate, backfill, preempt) through the real Scheduler + Store at
    100k x 10k with best-effort tasks — run_once wall-clock from watch
    drain through device solve to decision publish (async applier;
    store-drain time reported separately, the reference's per-bind
    goroutines have the same asynchrony).  Best-of-``reps`` FULL runs
    (fresh store + fresh Scheduler each; the jit caches persist in
    process, as they do for a deployed scheduler), same methodology as
    the kernel configs' min-of-7; the reported phase breakdown is the
    best run's.  ``dynamic_frac`` > 0 gives that fraction of the jobs
    resident-state predicates (config 8's scenario)."""
    from volcano_tpu.scheduler.conf import full_conf

    conf = full_conf("tpu")
    conf.apply_mode = "async"
    runs = []
    for _ in range(reps):
        runs.append(_e2e_run(
            _build_e2e_store(dynamic_frac=dynamic_frac), conf
        ))
    best = min(runs, key=lambda r: r["publish"])
    publish = best["publish"]

    import jax

    extra = {
        "pods_bound": best["bound"],
        "pods_per_sec": int(best["bound"] / publish),
        "phases_s": best["phases"],
        "all_runs_s": [round(r["publish"], 4) for r in runs],
        "async_drain_s": round(best["drain"], 2),
        "steady_cycle_s": round(best["steady"], 4),
        "prewarm_s": round(runs[0]["warm"], 1),
        "prewarm_bg_s": round(runs[0]["warm_bg"], 1),
        "path": "fastpath" if best["fastpath"] else "object",
        "actions": ",".join(conf.actions),
        "device": str(jax.devices()[0]),
    }
    if dynamic_frac:
        extra["dynamic_tasks"] = int(N_TASKS * dynamic_frac)
    _print_json(({
        "metric": metric,
        "value": round(publish, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / publish, 1),
        "extra": extra,
    }))


def config5_volumes(sizes=(500, 2000)):
    """cfg5v: config 5 plus volume-constrained gangs — the r5 host-
    residue cost cliff (64.6 s for 500 volume-constrained tasks, 316.6 s
    for 2,000; BASELINE.md).  The device volume solve (claim feasible-
    node bitsets + the attach-capacity tensor in the exact allocate
    kernel, volsolve.py) now serves the count-expressible shapes after
    the express pass, with publish-time allocate/bind as validation.
    Targets: 500 tasks < 2 s, 2,000 < 5 s, placements bit-for-bit equal
    to the host oracle (tests/test_volume_parity.py).  One headline line:
    value = the 500-task cycle; the 2,000-task cycle and both phase
    breakdowns (incl. the vol_solve phase) ride in extra."""
    from volcano_tpu.scheduler.conf import full_conf

    conf = full_conf("tpu")
    conf.apply_mode = "async"
    runs = {}
    for n_vol in sizes:
        runs[n_vol] = _e2e_run(
            _build_e2e_store(volume_tasks=n_vol), conf
        )
    import jax

    head = runs[sizes[0]]
    payload = {
        "metric": "cfg5v_e2e_cycle_volume_constrained",
        "value": round(head["publish"], 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / head["publish"], 1),
        "extra": {
            "vol_tasks": sizes[0],
            "pods_bound": head["bound"],
            "phases_s": head["phases"],
            "async_drain_s": round(head["drain"], 2),
            "steady_cycle_s": round(head["steady"], 4),
            "prewarm_s": round(head["warm"], 1),
            "path": "fastpath" if head["fastpath"] else "object",
            "r5_host_residue_s": {"500": 64.6, "2000": 316.6},
            "device": str(jax.devices()[0]),
            **{
                f"cycle_{n}_s": round(r["publish"], 4)
                for n, r in runs.items() if n != sizes[0]
            },
            **{
                f"phases_{n}_s": r["phases"]
                for n, r in runs.items() if n != sizes[0]
            },
        },
    }
    _print_json((payload))


def config5_dynamic(reps=3):
    """Config 5 with 10% of the jobs carrying resident-state predicates
    (host-port gangs + self-anti-affinity gangs, ~10k dynamic tasks): the
    device dynamic solve — the allocate kernels' interned port/selector
    bitset extension — serves them after the express pass instead of the
    host residue sub-cycle (VERDICT r4 missing #1).  Target: < 1.5 s."""
    config5(reps=reps, dynamic_frac=0.10,
            metric="cfg5d_e2e_cycle_10pct_dynamic_predicates")


def _apiserver_proc(q, state="", wal=False, save_interval=0.25, shards=1):
    """Child-process entry: a StoreServer on a free port, url via queue.
    ``state``/``wal`` arm the durable tier (segment WAL, store/wal.py)
    for the WAL-on drain comparison; the comparison passes a long
    ``save_interval`` so it measures the ACK path's fsync overhead, not
    background snapshot serialization (the WAL alone already guarantees
    zero acked loss — checkpoints only bound replay length).
    ``shards`` arms the partitioned decision bus (store/partition.py)."""
    import time as _time

    from volcano_tpu.store.server import StoreServer

    srv = StoreServer(state_path=state or None, wal=wal,
                      save_interval=save_interval, shards=shards).start()
    q.put(srv.url)
    while True:
        _time.sleep(3600)


def config7():
    """Config 5 through the REAL process model: the HTTP apiserver
    (StoreServer) in its OWN OS process with the scheduler on a
    RemoteStore client — every watch drain, bulk bind publish, and
    enqueue admission pays the wire (VERDICT r3 missing #2: every
    published number was in-process).  The separate server process is
    the deployed topology; an in-process server thread shares the
    GIL with the scheduler/applier and inflates the drain 2-5x."""
    import multiprocessing as mp

    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.store.client import RemoteStore

    ctx = mp.get_context("spawn")

    def one_run(state="", wal=False, prewarm=True, steady_cycles=True,
                save_interval=0.25):
        """One full cfg7 pass against a fresh apiserver process; returns
        the measurements as plain data (the server dies on return)."""
        import urllib.request as _rq

        q = ctx.Queue()
        srv_proc = ctx.Process(target=_apiserver_proc,
                               args=(q, state, wal, save_interval),
                               daemon=True)
        srv_proc.start()
        try:
            url = q.get(timeout=60)
            remote = RemoteStore(url)
            local = _build_e2e_store()
            t0 = time.perf_counter()
            ops = []
            for kind in ("Queue", "PriorityClass", "Node", "PodGroup", "Pod"):
                for obj in local.items(kind):
                    ops.append({"op": "create", "kind": kind, "object": obj})
            for i in range(0, len(ops), 4000):
                errs = [e for e in remote.bulk(ops[i:i + 4000]) if e]
                assert not errs, errs[:3]
            load_s = time.perf_counter() - t0

            conf = full_conf("tpu")
            conf.apply_mode = "async"
            sched = Scheduler(remote, conf=conf)
            warm = warm_bg = 0.0
            if prewarm:
                warm = sched.prewarm()
                t1 = time.perf_counter()
                if sched.prewarm_background is not None:
                    sched.prewarm_background.join()
                warm_bg = time.perf_counter() - t1
            t0 = time.perf_counter()
            sched.run_once()
            publish = time.perf_counter() - t0
            phases = _phases_of(sched)
            while sched.cache.applier.pending > 0:
                time.sleep(0.005)
            drain = time.perf_counter() - t0 - publish
            # per-kind drain attribution (server-measured segment
            # sections + client-side op batches) so a wire regression
            # localizes by kind
            drain_kinds = dict(sched.cache.applier.drain_stats)
            bound = sum(1 for p in remote.items("Pod") if p.node_name)
            steady = 0.0
            if steady_cycles:
                sched.run_once()
                t1 = time.perf_counter()
                sched.run_once()
                steady = time.perf_counter() - t1
            wal_stats = None
            if wal:
                with _rq.urlopen(url + "/healthz", timeout=10) as resp:
                    wal_stats = json.load(resp).get("wal")
            return {
                "publish": publish, "drain": drain, "phases": phases,
                "drain_kinds": drain_kinds, "bound": bound,
                "steady": steady, "warm": warm, "warm_bg": warm_bg,
                "load_s": load_s, "wal": wal_stats,
                "fastpath": bool(sched.fast_cycle
                                 and sched.fast_cycle.mirror is not None),
            }
        finally:
            srv_proc.terminate()
            srv_proc.join(timeout=5)

    run = one_run()
    publish, drain = run["publish"], run["drain"]
    drain_kinds, phases = run["drain_kinds"], run["phases"]
    bound = run["bound"]

    # WAL-on comparison: the SAME workload against an apiserver with
    # the segment write-ahead log armed (store/wal.py) — every ACK
    # waits on a group-committed fsync, the whole cycle is one WAL
    # record, and the drain delta IS the durability overhead the
    # 25%-band acceptance tracks.  The prewarm runs again on purpose:
    # skipping it pushes an inline recompile into run_once (~20 s of
    # "publish" that is really XLA), corrupting the comparison.
    import tempfile

    with tempfile.TemporaryDirectory() as wal_dir:
        wal_run = one_run(state=os.path.join(wal_dir, "state.json"),
                          wal=True, steady_cycles=False,
                          save_interval=3600.0)

    import jax

    _print_json(({
        "metric": "e2e_http_schedule_cycle_100k_tasks_10k_nodes",
        "value": round(publish, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / publish, 1),
        "extra": {
            "transport": (
                "http+json, apiserver in its own OS process "
                "(StoreServer / RemoteStore); columnar segment "
                "publish (store/segment.py)"
            ),
            "pods_bound": bound,
            "pods_per_sec": int(bound / publish),
            "phases_s": phases,
            "async_drain_s": round(drain, 2),
            "drain_binds_s": round(drain_kinds.get("binds_s", 0.0), 3),
            "drain_events_s": round(drain_kinds.get("events_s", 0.0), 3),
            "drain_evicts_s": round(drain_kinds.get("evicts_s", 0.0), 3),
            "drain_pg_s": round(drain_kinds.get("pg_s", 0.0), 3),
            "drain_wire_s": round(drain_kinds.get("wire_s", 0.0), 3),
            "steady_cycle_s": round(run["steady"], 4),
            "prewarm_s": round(run["warm"], 1),
            "prewarm_bg_s": round(run["warm_bg"], 1),
            "store_load_s": round(run["load_s"], 1),
            "path": "fastpath" if run["fastpath"] else "object",
            "device": str(jax.devices()[0]),
            # durability overhead (segment WAL armed): the off-cycle
            # drain re-measured with ACK-after-fsync, plus the
            # server's own fsync accounting — wal_records shows the
            # whole 102k-bind cycle was a handful of records
            "wal_drain_s": round(wal_run["drain"], 2),
            "wal_publish_s": round(wal_run["publish"], 4),
            "wal_fsync_s": (wal_run["wal"] or {}).get("fsync_s"),
            "wal_fsync_total": (wal_run["wal"] or {}).get("fsync_total"),
            "wal_records": (wal_run["wal"] or {}).get("records"),
        },
    }))
    # the WAL-on vs WAL-off comparison line: ratio > 1.25 breaks the
    # acceptance band (group commit must amortize fsync per segment)
    _print_json(({
        "metric": "cfg7_wal_on_vs_off_drain",
        "value": round(wal_run["drain"], 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / max(
            wal_run["publish"], 1e-9), 1),
        "extra": {
            "wal_off_drain_s": round(drain, 4),
            "ratio": round(wal_run["drain"] / max(drain, 1e-9), 3),
            "wal": wal_run["wal"],
        },
    }))

    # digest-off comparison: the SAME workload with the vtaudit state
    # digest disarmed (VOLCANO_TPU_AUDIT=0 rides os.environ into the
    # spawned apiserver AND disarms the client-side mirror audit) — the
    # headline run above already paid for digest-ON, so one extra run
    # prices the incremental per-mutation hash.  ratio = on/off > 1.05
    # breaks the acceptance band (with an absolute noise floor — fast
    # containers drain in microseconds, where a ratio is meaningless):
    # the O(1) splitmix64 fold per verb must stay inside measurement
    # noise of the drain.
    _env_prev = os.environ.get("VOLCANO_TPU_AUDIT")
    os.environ["VOLCANO_TPU_AUDIT"] = "0"
    try:
        off_run = one_run(steady_cycles=False)
    finally:
        if _env_prev is None:
            os.environ.pop("VOLCANO_TPU_AUDIT", None)
        else:
            os.environ["VOLCANO_TPU_AUDIT"] = _env_prev
    _print_json(({
        "metric": "cfg7_digest_on_vs_off_drain",
        "value": round(drain, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / max(publish, 1e-9), 1),
        "extra": {
            "digest_off_drain_s": round(off_run["drain"], 4),
            "digest_off_publish_s": round(off_run["publish"], 4),
            "ratio": round(drain / max(off_run["drain"], 1e-9), 3),
        },
    }))


def _build_open_loop_store(n_nodes=200):
    """Small-but-real cluster for the open-loop SLO runs: latency under
    sustained arrivals is a cycle-cadence property, not a 10k-node one
    (cfg5/cfg7 own the scale axis)."""
    from volcano_tpu.api import Resource
    from volcano_tpu.api.objects import Metadata, Node, Queue
    from volcano_tpu.store import Store

    store = Store()
    store.create("Queue", Queue(meta=Metadata(name="default", namespace=""),
                                weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:04d}", namespace=""),
            allocatable=Resource(8000.0, 16.0 * (1 << 30),
                                 max_task_num=110)))
    return store


def config8_open_loop(duration_s=8.0, qps=25.0, band_p99_ms=1000.0,
                      max_doublings=3):
    """cfg8: the OPEN-LOOP SLO harness (volcano_tpu/loadgen/) — a seeded
    Poisson arrival process (gang-size/resource mix, exponential dwell
    churn) sustained at ``qps`` gang arrivals/s against the real
    Scheduler + Store, reporting p50/p99/p999 pod first-seen→bind
    latency from the bounded metric histograms, then a saturation
    search: double QPS on a fresh cluster until p99 breaches the band.
    This is the measurement half of ROADMAP item 2 — the gate the
    incremental-scheduler work will be judged against."""
    import jax

    from volcano_tpu.loadgen import LoadSpec, run_open_loop, saturation_search
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler

    def run_at(q, dur):
        store = _build_open_loop_store()
        conf = full_conf("tpu")
        conf.apply_mode = "async"
        sched = Scheduler(store, conf=conf)
        sched.prewarm()
        if sched.prewarm_background is not None:
            sched.prewarm_background.join()
        # prewarm compiles against the EMPTY store (zero pending → no
        # solve shapes); an unmeasured warmup burst populates the
        # pending-task bucket compiles the way `_time_cycle`'s warm-up
        # reps do — otherwise every arrival during the first ~1.5 s CPU
        # compile stalls behind it and the tail measures XLA, not the
        # scheduler (post-compile steady state, the configs-1–4 rule)
        warm = LoadSpec(qps=300.0, duration_s=0.15, seed=1,
                        gang_sizes=((1, 5.0), (2, 3.0), (4, 2.0)),
                        cpu_millis=(250, 500), mem_mb=(256, 512),
                        dwell_s=0.05, namespace="warm", prefix="wm")
        run_open_loop(store, warm, sched.run_once, settle_s=30.0)
        spec = LoadSpec(
            qps=q, duration_s=dur, seed=8,
            gang_sizes=((1, 5.0), (2, 3.0), (4, 2.0)),
            cpu_millis=(250, 500), mem_mb=(256, 512),
            dwell_s=6.0, namespace="load",
        )
        return run_open_loop(store, spec, sched.run_once, settle_s=30.0)

    # best-of-2 full runs, the cfg5 methodology: the first run in a
    # fresh process still amortizes storm-kernel/bucket compiles that
    # later runs reuse (in-process jit caches persist, as they do for a
    # deployed scheduler); the reported percentiles are the best run's
    base = min((run_at(qps, duration_s) for _ in range(2)),
               key=lambda r: r.p99_ms)
    sat = saturation_search(
        lambda q: run_at(q, max(duration_s / 2.0, 3.0)),
        base_qps=qps * 2, band_p99_ms=band_p99_ms,
        max_doublings=max_doublings,
    )
    _print_json(({
        "metric": "cfg8_open_loop_first_seen_to_bind",
        "value": round(base.p50_ms / 1e3, 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "target_qps_gangs": qps,
            "p50_ms": round(base.p50_ms, 2),
            "p99_ms": round(base.p99_ms, 2),
            "p999_ms": round(base.p999_ms, 2),
            "report": base.as_dict(),
            "band_p99_ms": band_p99_ms,
            "saturation": sat.as_dict(),
            "series": "volcano_e2e_job_scheduling_latency_milliseconds",
            "device": str(jax.devices()[0]),
        },
    }))


# -- cfg9: mesh-sharded fast cycle + partitioned store bus --------------------
#
# ROADMAP item 1's headline: 1M pending tasks × 100k nodes END TO END —
# watch mirror, array snapshot, mesh-sharded batched solve (conf
# `mesh:`, parallel/sharded.py NamedShardings), columnar publish split
# by namespace shard, partitioned StoreServer drain (per-shard apply
# locks + per-shard WAL-ready watch logs; store/partition.py).  The
# headline capture runs on a real device mesh (v5e); CI and the CPU
# container scale down with VOLCANO_TPU_CFG9_SCALE (the same store
# shape at fraction of the size — machinery proof, not a perf claim).
# vtprof runs ARMED by design: the acceptance bar is ≥95% wall-clock
# attribution of where the sharded cycle spends.

N_NODES9 = 100_000
N_TASKS9 = 1_000_000
#: namespaces the cfg9 workload spreads over — the partitioned bus
#: shards by namespace hash, so one-namespace workloads cannot scale
CFG9_NAMESPACES = 16


def _build_shard_e2e_store(n_nodes, n_tasks, tasks_per_job=20,
                           n_namespaces=CFG9_NAMESPACES, n_queues=2):
    """cfg5-shaped store at cfg9 scale, spread over namespaces so the
    partitioned decision bus actually shards (store/partition.py hashes
    the namespace)."""
    from volcano_tpu.api import POD_GROUP_KEY, Resource
    from volcano_tpu.api.objects import (
        Metadata, Node, Pod, PodGroup, PodSpec, Queue,
    )
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.store import Store

    rng = np.random.default_rng(9)
    n_jobs = max(n_tasks // tasks_per_job, 1)
    node_cpu = rng.choice([16000, 32000], n_nodes)
    node_mem = rng.choice([32, 64], n_nodes) * (1 << 30)
    cpus = rng.choice([250, 500, 1000, 2000], n_tasks)
    mems = rng.choice([256, 512, 1024, 2048], n_tasks) * (1 << 20)

    store = Store()
    for q in range(n_queues):
        store.create("Queue", Queue(meta=Metadata(name=f"q{q}", namespace=""),
                                    weight=n_queues - q))
    store.create("Queue", Queue(meta=Metadata(name="default", namespace=""),
                                weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:06d}", namespace=""),
            allocatable=Resource(float(node_cpu[i]), float(node_mem[i]),
                                 max_task_num=110)))
    k = 0
    for j in range(n_jobs):
        ns = f"team{j % n_namespaces}"
        pg = PodGroup(meta=Metadata(name=f"pg{j:06d}", namespace=ns),
                      min_member=min(tasks_per_job, n_tasks - k),
                      queue=f"q{j % n_queues}")
        pg.status.phase = PodGroupPhase.PENDING
        store.create("PodGroup", pg)
        ann = {POD_GROUP_KEY: f"pg{j:06d}"}
        for _t in range(min(tasks_per_job, n_tasks - k)):
            store.create("Pod", Pod(
                meta=Metadata(name=f"p{k:07d}", namespace=ns,
                              annotations=dict(ann)),
                spec=PodSpec(image="bench",
                             resources=Resource(float(cpus[k]),
                                                float(mems[k])))))
            k += 1
        if k >= n_tasks:
            break
    return store


def _cfg9_run(n_nodes, n_tasks, shards, mesh_setting, prof=True, procs=0):
    """One end-to-end cfg9 pass: partitioned apiserver in its own OS
    process, the store loaded over the wire, a mesh-conf'd Scheduler on
    a RemoteStore, one timed cycle + off-cycle drain.  Returns plain
    measurement data (the server dies on return).  ``procs > 0`` swaps
    the single partitioned server for the procmesh deployment: that
    many shard-server OS processes under a ShardSupervisor, fronted by
    a ShardRouter — the client learns the shard map from ``/healthz``
    and ships sub-segments straight to the shard processes."""
    import multiprocessing as mp

    from volcano_tpu import vtprof
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler
    from volcano_tpu.store.client import RemoteStore

    sup = router = srv_proc = None
    if procs > 0:
        from volcano_tpu.store.procmesh import ShardRouter, ShardSupervisor

        sup = ShardSupervisor(procs).start()
        router = ShardRouter(sup.shard_map, supervisor=sup).start()
    else:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        srv_proc = ctx.Process(target=_apiserver_proc,
                               args=(q, "", False, 0.25, shards),
                               daemon=True)
        srv_proc.start()
    try:
        url = router.url if router is not None else q.get(timeout=120)
        remote = RemoteStore(url)
        local = _build_shard_e2e_store(n_nodes, n_tasks)
        t0 = time.perf_counter()
        ops = []
        for kind in ("Queue", "Node", "PodGroup", "Pod"):
            for obj in local.items(kind):
                ops.append({"op": "create", "kind": kind, "object": obj})
        for i in range(0, len(ops), 4000):
            errs = [e for e in remote.bulk(ops[i:i + 4000]) if e]
            assert not errs, errs[:3]
        load_s = time.perf_counter() - t0

        conf = full_conf("tpu")
        conf.apply_mode = "async"
        conf.mesh = mesh_setting
        sched = Scheduler(remote, conf=conf)
        profiler = vtprof.arm() if prof else None
        try:
            warm = sched.prewarm()
            t1 = time.perf_counter()
            if sched.prewarm_background is not None:
                sched.prewarm_background.join()
            warm_bg = time.perf_counter() - t1
            t0 = time.perf_counter()
            sched.run_once()
            publish = time.perf_counter() - t0
            phases = _phases_of(sched)
            while sched.cache.applier.pending > 0:
                time.sleep(0.005)
            drain = time.perf_counter() - t0 - publish
            coverage = None
            if profiler is not None:
                att = vtprof.attribution(profiler.payload())
                coverage = round(att["coverage"], 4)
        finally:
            if prof:
                vtprof.disarm()
        drain_kinds = dict(sched.cache.applier.drain_stats)
        bound = sum(1 for p in remote.items("Pod") if p.node_name)
        mesh_devices = (
            sched.mesh.devices.size if sched.mesh is not None else 1
        )
        return {
            "publish": publish, "drain": drain, "phases": phases,
            "drain_kinds": drain_kinds, "bound": bound, "load_s": load_s,
            "warm": warm, "warm_bg": warm_bg, "coverage": coverage,
            "mesh_devices": mesh_devices, "shards": shards,
            "fastpath": bool(sched.fast_cycle
                             and sched.fast_cycle.mirror is not None),
        }
    finally:
        if router is not None:
            router.stop()
        if sup is not None:
            sup.stop()
        if srv_proc is not None:
            srv_proc.terminate()
            srv_proc.join(timeout=5)


def config9_shard(scale=None):
    """cfg9: the mesh-sharded deployed cycle against the partitioned
    store bus — 1M × 100k at full scale (VOLCANO_TPU_CFG9_SCALE shrinks
    it for CPU containers/CI), mesh from VOLCANO_TPU_CFG9_MESH (default
    `auto`), shard count from VOLCANO_TPU_CFG9_SHARDS (default 4).  Two
    lines: the headline cycle, and the cfg7-shaped sharded-vs-single
    drain comparison (the partitioning claim, isolated)."""
    import jax

    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG9_SCALE", "1.0"))
    shards = int(os.environ.get("VOLCANO_TPU_CFG9_SHARDS", "4"))
    mesh_setting = os.environ.get("VOLCANO_TPU_CFG9_MESH", "auto")
    n_nodes = max(int(N_NODES9 * scale), 64)
    n_tasks = max(int(N_TASKS9 * scale), 640)

    run = _cfg9_run(n_nodes, n_tasks, shards, mesh_setting)
    shard_attr = {
        k: round(v, 3)
        for k, v in sorted(run["drain_kinds"].items())
        if k.startswith(("shard", "proc"))
    }
    _print_json({
        "metric": "cfg9_mesh_sharded_1m_x_100k",
        "value": round(run["publish"], 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": n_tasks, "n_nodes": n_nodes, "scale": scale,
            "mesh": mesh_setting, "mesh_devices": run["mesh_devices"],
            "store_shards": shards,
            "pods_bound": run["bound"],
            "pods_per_sec": int(run["bound"] / max(run["publish"], 1e-9)),
            "phases_s": run["phases"],
            "async_drain_s": round(run["drain"], 2),
            "drain_shards_s": shard_attr,
            "drain_wire_s": round(
                run["drain_kinds"].get("wire_s", 0.0), 3),
            "prof_attribution": run["coverage"],
            "prewarm_s": round(run["warm"], 1),
            "prewarm_bg_s": round(run["warm_bg"], 1),
            "store_load_s": round(run["load_s"], 1),
            "path": "fastpath" if run["fastpath"] else "object",
            "namespaces": CFG9_NAMESPACES,
            "device": str(jax.devices()[0]),
        },
    })

    # the partitioning claim isolated: the SAME cfg7-shaped workload's
    # off-cycle drain against >=4 shards vs one shard — the sharded
    # drain must measurably beat the single-shard reading (per-shard
    # attribution shows where each shard's ship spent).  Own scale knob:
    # the win comes from pipelining client encode against server
    # decode/apply across shards, which needs a drain big enough to
    # pipeline — sub-second toy drains pay the split overhead instead,
    # so CI smokes keep cfg9b at the shape the claim is about.
    cmp_scale = float(os.environ.get("VOLCANO_TPU_CFG9B_SCALE", str(scale)))
    cmp_nodes = max(int(N_NODES * cmp_scale), 64)
    cmp_tasks = max(int(N_TASKS * cmp_scale), 640)
    sharded = _cfg9_run(cmp_nodes, cmp_tasks, shards, "off", prof=False)
    single = _cfg9_run(cmp_nodes, cmp_tasks, 1, "off", prof=False)
    _print_json({
        "metric": "cfg9b_sharded_drain_vs_single_shard",
        "value": round(sharded["drain"], 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": cmp_tasks, "n_nodes": cmp_nodes,
            "store_shards": shards,
            "single_shard_drain_s": round(single["drain"], 4),
            "ratio": round(
                sharded["drain"] / max(single["drain"], 1e-9), 3),
            "drain_shards_s": {
                k: round(v, 3)
                for k, v in sorted(sharded["drain_kinds"].items())
                if k.startswith(("shard", "proc"))
            },
            "sharded_wire_s": round(
                sharded["drain_kinds"].get("wire_s", 0.0), 3),
            "single_wire_s": round(
                single["drain_kinds"].get("wire_s", 0.0), 3),
            "device": str(jax.devices()[0]),
        },
    })


def config9_procs(scale=None):
    """cfg9c: the cfg9b drain comparison re-measured against the
    MULTI-PROCESS shard store (store/procmesh): N shard-server OS
    processes under a ShardSupervisor behind a ShardRouter, the applier
    shipping sub-segments straight to the shard processes (drain
    attribution under ``procNN_s`` keys).  Sweeps 1 -> 2 -> 4 shard
    processes over the cfg7-shaped workload; the partitioning claim
    across the process seam is the per-doubling drain scaling.
    VOLCANO_TPU_CFG9C_SCALE shrinks for CPU containers/CI;
    VOLCANO_TPU_CFG9C_PROCS caps the sweep (default 4)."""
    import jax

    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG9C_SCALE", "1.0"))
    max_procs = int(os.environ.get("VOLCANO_TPU_CFG9C_PROCS", "4"))
    n_nodes = max(int(N_NODES * scale), 64)
    n_tasks = max(int(N_TASKS * scale), 640)

    # the headline is the drain CRITICAL PATH: sub-segments ship to the
    # shard processes concurrently, so the cycle's drain completes when
    # the SLOWEST shard's ship wall does — max(procNN_s).  (The post-
    # publish wait the cfg9 headline uses reads 0 here: the async drain
    # overlaps publish entirely at CI scales.)  The 1-process baseline
    # is cfg9b's claim; this sweep doubles PROCESSES: 2 -> 4.
    sweep = [2]
    while sweep[-1] * 2 <= max_procs:
        sweep.append(sweep[-1] * 2)
    runs = {}
    walls = {}
    for nprocs in sweep:
        run = _cfg9_run(n_nodes, n_tasks, 1, "off",
                        prof=(nprocs == sweep[-1]), procs=nprocs)
        shard_walls = [v for k, v in run["drain_kinds"].items()
                       if k.startswith("proc")]
        assert shard_walls, (
            f"procmesh drain produced no procNN_s keys: "
            f"{sorted(run['drain_kinds'])}")
        runs[nprocs] = run
        walls[nprocs] = max(shard_walls)
    head = runs[sweep[-1]]
    scaling = {
        f"{sweep[i]}->{sweep[i + 1]}": round(
            walls[sweep[i + 1]] / max(walls[sweep[i]], 1e-9), 3)
        for i in range(len(sweep) - 1)
    }
    _print_json({
        "metric": "cfg9c_procmesh_drain",
        "value": round(walls[sweep[-1]], 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": n_tasks, "n_nodes": n_nodes, "scale": scale,
            "shard_procs": sweep[-1],
            "slowest_shard_ship_s": {n: round(w, 4)
                                     for n, w in walls.items()},
            "scaling_per_doubling": scaling,
            "publish_s": round(head["publish"], 4),
            "pods_bound": head["bound"],
            "drain_shards_s": {
                k: round(v, 3)
                for k, v in sorted(head["drain_kinds"].items())
                if k.startswith(("shard", "proc"))
            },
            "drain_wire_s": round(
                head["drain_kinds"].get("wire_s", 0.0), 3),
            "prof_attribution": head["coverage"],
            "store_load_s": round(head["load_s"], 1),
            "path": "fastpath" if head["fastpath"] else "object",
            "publish_build_s": round(
                head["phases"].get("publish_build", 0.0), 4),
            "publish_split_s": round(
                head["drain_kinds"].get("split_s", 0.0), 4),
            "publish_ship_s": round(
                head["phases"].get("publish_ship", 0.0), 4),
            "device": str(jax.devices()[0]),
        },
    })

    # cfg9c_publish: the publish wall from the same head run with its
    # internal attribution.  BENCH_r12 showed publish at 6.575 s against
    # a 0.15 s drain critical path — the drain stopped being the story;
    # build (decision->segment), split (segment->per-shard sub-segments)
    # and ship (wire fan-out) say where the publish wall actually goes.
    _print_json({
        "metric": "cfg9c_publish",
        "value": round(head["publish"], 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": n_tasks, "n_nodes": n_nodes, "scale": scale,
            "shard_procs": sweep[-1],
            "phases_s": {
                "publish_build": round(
                    head["phases"].get("publish_build", 0.0), 4),
                "publish_split": round(
                    head["drain_kinds"].get("split_s", 0.0), 4),
                "publish_ship": round(
                    head["phases"].get("publish_ship", 0.0), 4),
            },
            "drain_critical_path_s": round(walls[sweep[-1]], 4),
            "pods_bound": head["bound"],
            "device": str(jax.devices()[0]),
        },
    })


def config9_fleet(scale=None):
    """cfg9d: the vtfleet arming-overhead gate.  The cfg9c procmesh
    drain measured twice over the SAME workload — fully disarmed, then
    with the whole observability plane armed (child trace/timeseries
    rings via env, the parent FleetCollector harvesting every member on
    each supervisor monitor tick) — and reported as a ratio.  The
    fleet plane's contract is that harvesting rides debug endpoints on
    server threads the drain path never waits on, so armed/disarmed
    must hold ≤1.05x (`bench.py --check --configs 15`); the bit-for-bit
    placement identity half of that claim lives in the procmesh storm
    test."""
    import shutil
    import tempfile

    import jax

    from volcano_tpu import timeseries, trace, vtfleet

    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG9C_SCALE", "1.0"))
    n_nodes = max(int(N_NODES * scale), 64)
    n_tasks = max(int(N_TASKS * scale), 640)
    procs = 2

    def wall(run):
        shard_walls = [v for k, v in run["drain_kinds"].items()
                       if k.startswith("proc")]
        assert shard_walls, sorted(run["drain_kinds"])
        return max(shard_walls)

    base = _cfg9_run(n_nodes, n_tasks, 1, "off", prof=False, procs=procs)
    incident_dir = tempfile.mkdtemp(prefix="vtfleet-bench-")
    saved = {k: os.environ.get(k) for k in
             ("VOLCANO_TPU_TRACE", "VOLCANO_TPU_TIMESERIES")}
    try:
        # children inherit the env at spawn; the parent arms in-process
        os.environ["VOLCANO_TPU_TRACE"] = "1"
        os.environ["VOLCANO_TPU_TIMESERIES"] = "1"
        trace.arm()
        timeseries.arm()
        vtfleet.arm(incident_dir=incident_dir)
        armed = _cfg9_run(n_nodes, n_tasks, 1, "off", prof=False,
                          procs=procs)
    finally:
        vtfleet.disarm()
        timeseries.disarm()
        trace.disarm()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(incident_dir, ignore_errors=True)
    assert armed["bound"] == base["bound"], (armed["bound"], base["bound"])
    base_w, armed_w = wall(base), wall(armed)
    _print_json({
        "metric": "cfg9d_fleet_armed_vs_disarmed_drain",
        "value": round(armed_w, 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": n_tasks, "n_nodes": n_nodes, "scale": scale,
            "shard_procs": procs,
            "ratio": round(armed_w / max(base_w, 1e-9), 3),
            "disarmed_s": round(base_w, 4),
            "armed_s": round(armed_w, 4),
            "pods_bound": armed["bound"],
            "device": str(jax.devices()[0]),
        },
    })


def _multihost_sweep(hosts, n_nodes, n_tasks, n_jobs, reps, timeout=570):
    """Run the multi-controller lockstep host sweep in a FRESH
    subprocess and parse its one-line JSON payload.  A subprocess, not
    in-process: the bench process's jax is already initialized by the
    earlier configs without the forced 8-device CPU topology the host
    mesh needs (`--xla_force_host_platform_device_count`), and jax
    device topology cannot change after init."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-m", "volcano_tpu.parallel.multihost",
           "--sweep", ",".join(str(h) for h in hosts),
           "--nodes", str(n_nodes), "--tasks", str(n_tasks),
           "--jobs", str(n_jobs), "--reps", str(reps), "--prof"]
    out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=timeout, check=False)
    lines = [ln for ln in out.stdout.splitlines()
             if ln.strip().startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"multihost sweep rc={out.returncode}: "
            f"{(out.stderr or out.stdout)[-400:]}")
    return json.loads(lines[-1])


def config9_multihost(scale=None):
    """cfg9e: the multi-controller mesh solve — the lockstep host sweep
    at 1 -> 2 -> 4 simulated hosts over one 8-device CPU mesh.  Each
    host builds ONLY its snapshot shard, dispatches only its mesh row,
    and fetches ONLY its owned output slice; the headline is the
    per-host critical path (build+dispatch+fetch) at the top host
    count, the claim is the per-doubling scaling of that path
    (`--check`: ≤0.7x per doubling, vtprof attribution ≥0.95, and
    bitwise cross-host-count output parity).  VOLCANO_TPU_CFG9E_SCALE
    shrinks for CPU containers/CI."""
    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG9E_SCALE", "1.0"))
    n_nodes = max(int(4096 * scale) // 8 * 8, 64)
    # tasks stay a multiple of the job count (sim gangs divide evenly)
    # — 256 jobs, and 256 is a multiple of 8 so the host/device blocking
    # stays even too
    n_tasks = max(int(65536 * scale) // 256 * 256, 1024)
    hosts = [1, 2, 4]
    run = _multihost_sweep(hosts, n_nodes, n_tasks, n_jobs=256, reps=5)
    top = str(hosts[-1])
    _print_json({
        "metric": "cfg9e_multihost_solve",
        "value": round(run["sweep"][top]["critical_path_s"], 6),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": n_tasks, "n_nodes": n_nodes, "scale": scale,
            "hosts": hosts[-1],
            "critical_path_s": {
                h: run["sweep"][str(h)]["critical_path_s"] for h in hosts
            },
            "scaling_per_doubling": run["scaling_per_doubling"],
            "parity": run["parity"],
            "prof_attribution": run["prof_attribution"],
            "per_host": run["sweep"][top]["per_host"],
            "solve_wait_s": run["sweep"][top]["solve_wait_s"],
            "binds": run["binds"],
            "n_devices": run["n_devices"],
            "device": run["device"],
        },
    })


def config9_stretch(scale=None):
    """cfg9f: the 10M-task x 1M-node stretch shape through the same
    multi-controller sweep, env-scaled (VOLCANO_TPU_CFG9F_SCALE,
    default 0.01 -> 100k x 10k on CPU containers; 1.0 is the full
    deployment shape on a real pod).  Hosts 1 -> 2 only — the stretch
    claim is that the owned-slice path keeps scaling when the planes
    stop fitting comfortably per host, not a 4-way ladder."""
    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG9F_SCALE", "0.01"))
    n_nodes = max(int(1_000_000 * scale) // 8 * 8, 64)
    n_tasks = max(int(10_000_000 * scale) // 512 * 512, 1024)
    hosts = [1, 2]
    run = _multihost_sweep(hosts, n_nodes, n_tasks, n_jobs=512, reps=2)
    top = str(hosts[-1])
    _print_json({
        "metric": "cfg9f_stretch_10m_x_1m",
        "value": round(run["sweep"][top]["critical_path_s"], 6),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_tasks": n_tasks, "n_nodes": n_nodes, "scale": scale,
            "hosts": hosts[-1],
            "critical_path_s": {
                h: run["sweep"][str(h)]["critical_path_s"] for h in hosts
            },
            "scaling_per_doubling": run["scaling_per_doubling"],
            "parity": run["parity"],
            "prof_attribution": run["prof_attribution"],
            "binds": run["binds"],
            "n_devices": run["n_devices"],
            "device": run["device"],
        },
    })


# -- cfg10: vtdelta steady-state trickle (scheduler/delta/) -------------------
#
# ROADMAP item 2's measurement: the event-driven incremental core under
# the workload it exists for — a big RESIDENT cluster (cfg5-shaped:
# running gangs pinned to nodes) receiving a steady trickle of small
# gang arrivals with periodic batched departures.  Reports micro-cycle
# vs full-cycle pump latency side by side (departure pumps are
# structural `job-remove` fallbacks — the honest mix, not a micro-only
# showcase), then the lockstep saturation search with delta mode on.
# CPU containers: VOLCANO_TPU_CFG10_SCALE shrinks the resident set.

#: resident gangs kept live during the trickle before a departure wave
CFG10_POPULATION = 64
#: gangs reaped per departure wave (one structural pump amortizes all)
CFG10_WAVE = 8


def _build_delta_store(n_nodes, n_tasks, tasks_per_job=20):
    """cfg5-shaped resident cluster: RUNNING gangs pinned round-robin —
    the steady state a trickle arrives on top of."""
    from volcano_tpu.api import POD_GROUP_KEY, Resource
    from volcano_tpu.api.objects import (
        Metadata, Node, Pod, PodGroup, PodSpec, Queue,
    )
    from volcano_tpu.api.types import PodGroupPhase, PodPhase
    from volcano_tpu.store import Store

    store = Store()
    store.create("Queue", Queue(meta=Metadata(name="default", namespace=""),
                                weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:05d}", namespace=""),
            allocatable=Resource(32000.0, 64.0 * (1 << 30),
                                 max_task_num=200)))
    n_jobs = max(n_tasks // tasks_per_job, 1)
    for j in range(n_jobs):
        pg = PodGroup(meta=Metadata(name=f"res{j:05d}", namespace="default"),
                      min_member=tasks_per_job, queue="default")
        pg.status.phase = PodGroupPhase.RUNNING
        store.create("PodGroup", pg)
        for t in range(tasks_per_job):
            store.create("Pod", Pod(
                meta=Metadata(
                    name=f"res{j:05d}-{t}", namespace="default",
                    annotations={POD_GROUP_KEY: f"res{j:05d}"}),
                spec=PodSpec(resources=Resource(250.0, 256.0 * (1 << 20))),
                phase=PodPhase.RUNNING,
                node_name=f"n{(j * tasks_per_job + t) % n_nodes:05d}",
            ))
    return store


def config10_delta(scale=None, trickle_cycles=200, duration_s=4.0,
                   sat_base_qps=250.0, band_p99_ms=1000.0,
                   max_doublings=3):
    """cfg10: vtdelta micro-cycles vs full cycles on a resident cluster
    plus the lockstep saturation search (`make bench-delta`)."""
    import collections

    import jax

    from volcano_tpu.api import POD_GROUP_KEY, Resource
    from volcano_tpu.api.objects import Metadata, Pod, PodGroup, PodSpec
    from volcano_tpu.loadgen import LoadSpec, run_open_loop, saturation_search
    from volcano_tpu.scheduler.conf import full_conf
    from volcano_tpu.scheduler.scheduler import Scheduler

    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG10_SCALE", "1.0"))
    n_nodes = max(int(N_NODES * scale), 64)
    n_tasks = max(int(N_TASKS * scale), 640)

    def delta_conf():
        conf = full_conf("tpu")
        conf.delta = "on"  # oracle stays OFF: this is the timed path
        return conf

    store = _build_delta_store(n_nodes, n_tasks)
    sched = Scheduler(store, conf=delta_conf())
    fc = sched.fast_cycle

    def submit(name, size=2):
        pg = PodGroup(meta=Metadata(name=name, namespace="default"),
                      min_member=size, queue="default")
        store.create("PodGroup", pg)
        for t in range(size):
            store.create("Pod", Pod(
                meta=Metadata(name=f"{name}-{t}", namespace="default",
                              annotations={POD_GROUP_KEY: name}),
                spec=PodSpec(resources=Resource(100.0, 64.0 * (1 << 20)))))

    def reap(name):
        for t in range(2):
            store.delete("Pod", f"default/{name}-{t}")
        store.delete("PodGroup", f"default/{name}")

    # unmeasured warmup: arm + the trickle shape's solve compiles (the
    # cfg8 rule — steady state measures the scheduler, not XLA)
    sched.run_once()
    for i in range(8):
        submit(f"wm{i:03d}")
        sched.run_once()

    lat = {"micro": [], "full": []}
    reasons = collections.Counter()
    live = collections.deque(f"wm{i:03d}" for i in range(8))
    for i in range(trickle_cycles):
        submit(f"tk{i:04d}")
        live.append(f"tk{i:04d}")
        if len(live) > CFG10_POPULATION:
            # one departure wave: CFG10_WAVE gangs leave before this
            # pump — a single structural job-remove fallback amortizes
            # the whole batch
            for _ in range(CFG10_WAVE):
                reap(live.popleft())
        t0 = time.perf_counter()
        sched.run_once()
        dt_ms = (time.perf_counter() - t0) * 1e3
        mode = fc.delta.last["mode"]
        lat[mode].append(dt_ms)
        if mode == "full":
            reasons[fc.delta.last["fallback_reason"]] += 1

    def pct(xs, q):
        if not xs:
            return None
        return round(float(np.percentile(np.asarray(xs), q)), 3)

    # lockstep saturation with delta mode on: fresh clusters per step,
    # virtual-time arrivals (wall-clock-independent QPS), same-process
    # jit caches — the ROADMAP item 2 gate (>= 10x the cfg8 r08 breach)
    def run_at(q, dur):
        sat_store = _build_delta_store(max(n_nodes // 10, 16),
                                       max(n_tasks // 10, 160))
        sat_sched = Scheduler(sat_store, conf=delta_conf())
        spec = LoadSpec(qps=q, duration_s=dur, seed=10,
                        gang_sizes=((1, 6.0), (2, 3.0)),
                        cpu_millis=(100,), mem_mb=(64,), namespace="sat")
        return run_open_loop(sat_store, spec, sat_sched.run_once,
                             tick_s=0.05, settle_s=60.0)

    run_at(sat_base_qps, 1.0)  # warm the saturation shapes, unmeasured
    sat = saturation_search(
        lambda q: run_at(q, duration_s), base_qps=sat_base_qps,
        band_p99_ms=band_p99_ms, max_doublings=max_doublings,
    )

    micro_p50 = pct(lat["micro"], 50)
    _print_json({
        "metric": "cfg10_delta_steady_state_micro_cycle",
        "value": round((micro_p50 or 0.0) / 1e3, 5),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "n_nodes": n_nodes, "resident_tasks": n_tasks, "scale": scale,
            "trickle_cycles": trickle_cycles,
            "micro_cycles": len(lat["micro"]),
            "full_cycles": len(lat["full"]),
            "micro_p50_ms": micro_p50,
            "micro_p99_ms": pct(lat["micro"], 99),
            "full_p50_ms": pct(lat["full"], 50),
            "full_p99_ms": pct(lat["full"], 99),
            "full_reasons": dict(reasons),
            "speedup_p50": (
                round(pct(lat["full"], 50) / micro_p50, 2)
                if micro_p50 and lat["full"] else None),
            "saturation": sat.as_dict(),
            "device": str(jax.devices()[0]),
        },
    })


# -- cfg11: follower-served watch fan-out (store/replica.py) ------------------
#
# The replication PR's read-scaling claim: watch/list traffic served by
# follower replicas scales with the follower count because each follower
# is its own PROCESS (own GIL, own event log copy) serving the same
# replicated stream.  The bench spawns a leader + 4 follower apiservers
# as real subprocesses (in-process followers would serialize on this
# process's GIL and measure nothing), seeds a resident event-log window
# through the leader, waits for the followers to mirror it, then hammers
# the first 1 / 2 / 4 followers with a fixed reader fleet replaying the
# window via raw long-polls (loadgen/harness.fanout_watch_pass).  The
# headline value is seconds per 10k events served at 4 followers (lower
# is better, so the trajectory gate's max_s band fences regressions);
# the 1->2->4 aggregate throughputs and their scaling ratios ride in
# `extra`.  Gated alongside cfg7 (`--check --configs 7,13`): the drain
# bands must hold while the fan-out tier scales.

CFG11_EVENTS = 400    # resident event rows the readers replay per pass
CFG11_READERS = 24    # reader threads (fixed fleet, split across followers)
CFG11_WINDOW_S = 4.0  # measurement window per follower count


def _cfg11_spawn(args, env):
    import subprocess

    return subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)


def config11_repl(scale=None, readers=None, n_events=None, window_s=None,
                  follower_counts=(1, 2, 4)):
    """cfg11: follower-served watch fan-out read throughput 1->2->4
    followers (VOLCANO_TPU_CFG11_SCALE shrinks readers/window for CI)."""
    import shutil
    import signal
    import sys
    import tempfile
    import threading

    import jax

    from volcano_tpu.loadgen.harness import fanout_watch_pass
    from volcano_tpu.store.client import RemoteStore, wait_healthy

    if scale is None:
        scale = float(os.environ.get("VOLCANO_TPU_CFG11_SCALE", "1.0"))
    readers = readers or max(int(CFG11_READERS * scale), 4)
    n_events = n_events or max(int(CFG11_EVENTS * scale), 60)
    window_s = window_s or max(CFG11_WINDOW_S * scale, 1.0)
    n_followers = max(follower_counts)

    env = {k: v for k, v in os.environ.items() if k != "VOLCANO_TPU_CHAOS"}
    env.update({"JAX_PLATFORMS": "cpu", "VOLCANO_TPU_BACKEND": "host"})
    entry = [sys.executable, "-m", "volcano_tpu.cli", "apiserver",
             "--port", "0", "--wal"]
    workdir = tempfile.mkdtemp(prefix="cfg11-")
    procs = []

    def status(url):
        import urllib.request

        with urllib.request.urlopen(url + "/repl/status", timeout=10) as r:
            return json.load(r)

    try:
        leader = _cfg11_spawn(
            entry + ["--state", f"{workdir}/L.json",
                     "--repl-ack", "async"], env)
        procs.append(leader)
        leader_url = leader.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert wait_healthy(leader_url, timeout=60)
        follower_urls = []
        for i in range(n_followers):
            p = _cfg11_spawn(
                entry + ["--state", f"{workdir}/f{i}.json",
                         "--replica-of", leader_url], env)
            procs.append(p)
            follower_urls.append(
                p.stdout.readline().strip().rsplit(" ", 1)[-1])
        for u in follower_urls:
            assert wait_healthy(u, timeout=60)

        # followers past their bootstrap snapshot BEFORE the window is
        # written, so every event row lands in their local logs live
        def synced(target):
            return all(status(u).get("applied", -1) >= target
                       for u in follower_urls)

        deadline = time.monotonic() + 60
        while not synced(status(leader_url)["ship_seq"]):
            assert time.monotonic() < deadline, "followers never synced"
            time.sleep(0.1)
        base_cursor = int(status(leader_url)["ship_seq"])

        from volcano_tpu.api.objects import Metadata, Node
        from volcano_tpu.api.resource import Resource

        rs = RemoteStore(leader_url)
        for i in range(n_events):
            rs.create("Node", Node(
                meta=Metadata(name=f"bn{i:05d}", namespace=""),
                allocatable=Resource.from_resource_list(
                    {"cpu": "1", "memory": "1Gi"})))
        target = int(status(leader_url)["ship_seq"])
        deadline = time.monotonic() + 60
        while not synced(target):
            assert time.monotonic() < deadline, "window never replicated"
            time.sleep(0.1)

        def measure(urls):
            """Fixed reader fleet split across ``urls``; aggregate events
            served in the window."""
            stop = time.monotonic() + window_s
            counts = [0] * readers

            def read_loop(idx):
                url = urls[idx % len(urls)]
                cur = base_cursor
                while time.monotonic() < stop:
                    try:
                        ev, nxt, relist = fanout_watch_pass(
                            url, cur, timeout_s=0.25)
                    except OSError:
                        continue
                    counts[idx] += ev
                    cur = base_cursor if (relist or ev == 0) else nxt
            threads = [threading.Thread(target=read_loop, args=(i,),
                                        daemon=True)
                       for i in range(readers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=window_s + 30)
            return sum(counts) / window_s

        measure(follower_urls[:1])  # warm connections/caches, unmeasured
        thr = {}
        for k in follower_counts:
            thr[k] = measure(follower_urls[:k])
        top = max(follower_counts)
        base = max(thr[min(follower_counts)], 1e-9)
        _print_json({
            "metric": "cfg11_repl_fanout_watch_reads",
            "value": round(10_000.0 / max(thr[top], 1e-9), 4),
            "unit": "s",  # seconds per 10k follower-served events
            "vs_baseline": None,
            "extra": {
                "events_per_s": {str(k): int(v) for k, v in thr.items()},
                "scaling_vs_1_follower": {
                    str(k): round(thr[k] / base, 2) for k in thr},
                "readers": readers, "window_s": window_s,
                "resident_events": n_events, "scale": scale,
                "followers": n_followers,
                "device": str(jax.devices()[0]),
            },
        })
    finally:
        for p in procs:
            p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        shutil.rmtree(workdir, ignore_errors=True)


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7, 8: config5_dynamic, 9: config5_volumes,
           10: config8_open_loop, 11: config9_shard, 12: config10_delta,
           13: config11_repl, 14: config9_procs, 15: config9_fleet,
           16: config9_multihost, 17: config9_stretch}


# -- bench trajectory + continuous perf-regression gate (vtprof PR) -----------
#
# `--history` collates every BENCH_r0*.json driver capture into ONE
# machine-readable artifact (BENCH_TRAJECTORY.json) plus a markdown
# table appended to BASELINE.md, so the gate and humans read one file
# instead of nine.  `--check` runs a fresh capture of the headline
# configs and compares value + per-phase attribution against bands —
# derived from the same-device trajectory by default, or an explicit
# `--bands` JSON file — and exits nonzero with a per-config, per-phase
# diff on any breach (`make perfgate`).

TRAJECTORY_FILE = "BENCH_TRAJECTORY.json"
#: headline metrics the gate fences (cfg5 / cfg7 / cfg8)
GATED_METRICS = (
    "e2e_schedule_cycle_100k_tasks_10k_nodes",
    "e2e_http_schedule_cycle_100k_tasks_10k_nodes",
    "cfg8_open_loop_first_seen_to_bind",
    "cfg9_mesh_sharded_1m_x_100k",
    "cfg10_delta_steady_state_micro_cycle",
    "cfg11_repl_fanout_watch_reads",
    "cfg9c_procmesh_drain",
    "cfg9c_publish",
    "cfg9e_multihost_solve",
    "cfg9f_stretch_10m_x_1m",
)
#: band slack over the best same-device trajectory reading: headline
#: values breathe ±15% run-to-run on the tunnel (BASELINE.md), phases
#: more — the gate catches regressions, not noise
VALUE_SLACK = 1.8
PHASE_SLACK = 2.5
PHASE_FLOOR_S = 0.05


def _synthesize_payloads(payload):
    """Yield ``payload`` plus any first-class metrics older captures
    only carried inside ``extra``: r12-era cfg9c lines report the
    publish wall as ``extra.publish_s`` — surfacing it as a
    ``cfg9c_publish`` payload lets the publish-attribution band derive
    from history.  A real cfg9c_publish line in the same round (printed
    after the drain line) overrides the synthetic one on merge."""
    yield payload
    extra = payload.get("extra") or {}
    if payload.get("metric") == "cfg9c_procmesh_drain" \
            and extra.get("publish_s") is not None:
        yield {
            "metric": "cfg9c_publish",
            "value": extra["publish_s"],
            "unit": "s",
            "extra": {"device": extra.get("device")},
        }


def _payloads_from_doc(doc):
    """Every metric payload a BENCH_r0*.json driver capture carries:
    the bare payload form (r08), the ``parsed*`` fields, and every JSON
    line embedded in the driver's ``tail`` transcript."""
    if not isinstance(doc, dict):
        return
    if "metric" in doc and "value" in doc:
        yield from _synthesize_payloads(doc)
        return
    for key in sorted(doc):
        if key.startswith("parsed") and isinstance(doc[key], dict) \
                and "metric" in doc[key]:
            yield from _synthesize_payloads(doc[key])
    for line in str(doc.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            payload = json.loads(line)
        except ValueError:
            continue
        if isinstance(payload, dict) and "metric" in payload \
                and "value" in payload:
            yield from _synthesize_payloads(payload)


def load_bench_rounds(directory="."):
    """[(round_number, {metric: payload})] from BENCH_r*.json AND
    MULTICHIP_r*.json, ascending; captures for the same round merge
    (BENCH wins ties — MULTICHIP rounds carry the mesh/cfg9 lines),
    and within one file the last occurrence of a metric wins (the
    driver tail repeats headline lines across sweeps)."""
    import glob
    import re

    by_round = {}
    # MULTICHIP first so a same-round BENCH reading overrides on ties
    paths = sorted(glob.glob(os.path.join(directory, "MULTICHIP_r*.json")))
    paths += sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")))
    for path in paths:
        m = re.search(r"(?:BENCH|MULTICHIP)_r0*(\d+)\.json$",
                      os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        metrics = {}
        for payload in _payloads_from_doc(doc):
            if payload.get("value") is not None:
                metrics[payload["metric"]] = payload
        if metrics:
            by_round.setdefault(int(m.group(1)), {}).update(metrics)
    return sorted(by_round.items())


def build_trajectory(rounds):
    return {
        "source": "bench.py --history (BENCH_r0*.json collation)",
        "rounds": [
            {
                "round": n,
                "metrics": {
                    metric: {
                        "value": p.get("value"),
                        "unit": p.get("unit"),
                        "vs_baseline": p.get("vs_baseline"),
                        "device": (p.get("extra") or {}).get("device"),
                        "phases_s": (p.get("extra") or {}).get("phases_s"),
                        "p99_ms": (p.get("extra") or {}).get("p99_ms"),
                    }
                    for metric, p in sorted(m.items())
                },
            }
            for n, m in rounds
        ],
    }


_TRAJ_BEGIN = "<!-- bench-trajectory:begin -->"
_TRAJ_END = "<!-- bench-trajectory:end -->"


def trajectory_markdown(traj):
    rounds = traj["rounds"]
    metrics = sorted({m for r in rounds for m in r["metrics"]})
    head = ("| metric | " + " | ".join(f"r{r['round']:02d}" for r in rounds)
            + " |")
    sep = "|---" * (len(rounds) + 1) + "|"
    lines = [
        _TRAJ_BEGIN,
        "## Bench trajectory (generated by `python bench.py --history`)",
        "",
        "Headline `value` per metric per driver round (seconds unless the "
        "metric says otherwise); `—` = not captured that round.  "
        "Machine-readable twin: `BENCH_TRAJECTORY.json` — what "
        "`bench.py --check` derives its default bands from.",
        "",
        head, sep,
    ]
    for metric in metrics:
        cells = []
        for r in rounds:
            p = r["metrics"].get(metric)
            cells.append("—" if p is None else f"{p['value']}")
        lines.append(f"| `{metric}` | " + " | ".join(cells) + " |")
    lines.append(_TRAJ_END)
    return "\n".join(lines) + "\n"


def cmd_history(directory=".", out_path=None, baseline_md=None):
    """Collate BENCH_r0*.json into BENCH_TRAJECTORY.json + the BASELINE.md
    table (replacing a previous generated section in place)."""
    rounds = load_bench_rounds(directory)
    traj = build_trajectory(rounds)
    out_path = out_path or os.path.join(directory, TRAJECTORY_FILE)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(traj, f, indent=1)
    print(f"wrote {out_path}: {len(traj['rounds'])} round(s), "
          f"{sum(len(r['metrics']) for r in traj['rounds'])} metric line(s)")
    md = trajectory_markdown(traj)
    if baseline_md:
        try:
            with open(baseline_md, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            text = ""
        if _TRAJ_BEGIN in text and _TRAJ_END in text:
            pre = text.split(_TRAJ_BEGIN)[0]
            post = text.split(_TRAJ_END, 1)[1].lstrip("\n")
            text = pre + md + post
        else:
            text = text.rstrip("\n") + "\n\n" + md
        with open(baseline_md, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"updated {baseline_md} trajectory table")
    return traj


def _same_device_class(a, b):
    """CPU-container readings must not gate against v5e readings and
    vice versa — compare by cpu-ness of the recorded device string.  A
    missing device on either side matches NOTHING: a device-less
    trajectory reading must never slip into the accelerator band pool
    just because '' contains no 'cpu'."""
    if not a or not b:
        return False
    a, b = a.lower(), b.lower()
    return ("cpu" in a) == ("cpu" in b)


def derive_bands(traj, device_str):
    """Default bands from the best same-device trajectory reading per
    gated metric: value band = best × VALUE_SLACK, per-phase bands from
    the best round's attribution × PHASE_SLACK (+ an absolute floor so
    a 1 ms phase cannot fail on scheduler jitter)."""
    bands = {}
    for metric in GATED_METRICS:
        best = None
        best_round = None
        for r in traj.get("rounds", ()):
            p = r["metrics"].get(metric)
            if p is None or p.get("value") is None:
                continue
            if not _same_device_class(p.get("device"), device_str):
                continue
            if best is None or p["value"] < best["value"]:
                best, best_round = p, r["round"]
        if best is None:
            continue
        band = {
            "max_s": round(best["value"] * VALUE_SLACK, 4),
            "source_round": best_round,
            "source_value": best["value"],
        }
        if best.get("phases_s"):
            band["phases_max_s"] = {
                k: round(v * PHASE_SLACK + PHASE_FLOOR_S, 4)
                for k, v in best["phases_s"].items()
            }
        if best.get("p99_ms") is not None:
            band["max_p99_ms"] = round(best["p99_ms"] * VALUE_SLACK, 2)
        bands[metric] = band
    return bands


def check_results(results, bands):
    """Compare a fresh capture against bands.  Returns (ok, lines):
    every gated metric gets a verdict line, breaches get a per-phase
    attribution diff so the regression localizes from the gate output
    alone."""
    ok = True
    lines = []
    by_metric = {p["metric"]: p for p in results if isinstance(p, dict)}
    for metric, band in sorted(bands.items()):
        p = by_metric.get(metric)
        if p is None or p.get("value") is None:
            ok = False
            err = (p or {}).get("error", "no result captured")
            lines.append(f"FAIL {metric}: {err}")
            continue
        extra = p.get("extra") or {}
        breaches = []
        if band.get("max_s") is not None and p["value"] > band["max_s"]:
            breaches.append(
                f"value {p['value']:.4f}s > band {band['max_s']:.4f}s")
        phases = extra.get("phases_s") or {}
        for phase, cap in sorted((band.get("phases_max_s") or {}).items()):
            got = phases.get(phase)
            if got is not None and got > cap:
                breaches.append(f"phase {phase} {got:.4f}s > {cap:.4f}s")
        if band.get("max_p99_ms") is not None \
                and extra.get("p99_ms") is not None \
                and extra["p99_ms"] > band["max_p99_ms"]:
            breaches.append(
                f"p99 {extra['p99_ms']:.1f}ms > {band['max_p99_ms']:.1f}ms")
        if band.get("max_ratio") is not None:
            ratio = extra.get("ratio")
            if ratio is None:
                ok = False
                lines.append(f"FAIL {metric}: no ratio in capture")
                continue
            # noise floor: a ratio over a sub-second base is measurement
            # noise (fast containers drain in microseconds) — a breach
            # needs the absolute delta too
            base = p["value"] / max(ratio, 1e-9)
            delta = p["value"] - base
            if ratio > band["max_ratio"] \
                    and delta > band.get("min_delta_s", 0.0):
                breaches.append(
                    f"ratio {ratio:.3f} > band {band['max_ratio']:.3f} "
                    f"(delta {delta:.3f}s)")
        if band.get("max_scaling_per_doubling") is not None:
            scaling = extra.get("scaling_per_doubling")
            if not scaling:
                ok = False
                lines.append(
                    f"FAIL {metric}: no scaling_per_doubling in capture")
                continue
            # noise floor: per-doubling ratios over a sub-millisecond
            # critical path are scheduler jitter, not a scaling claim
            if p["value"] > band.get("min_base_s", 0.0):
                for leg, ratio in sorted(scaling.items()):
                    if ratio > band["max_scaling_per_doubling"]:
                        breaches.append(
                            f"scaling {leg} {ratio:.3f} > band "
                            f"{band['max_scaling_per_doubling']:.3f}")
            if extra.get("parity") is False:
                breaches.append("cross-host output parity violated")
        if band.get("min_coverage") is not None:
            cov = extra.get("prof_attribution")
            if cov is None or cov < band["min_coverage"]:
                breaches.append(
                    f"attribution {cov} < floor {band['min_coverage']}")
        if breaches:
            ok = False
            lines.append(f"FAIL {metric}: " + "; ".join(breaches))
            # the attribution diff: every measured phase vs its band
            for phase, got in sorted(phases.items()):
                cap = (band.get("phases_max_s") or {}).get(phase)
                mark = " BREACH" if cap is not None and got > cap else ""
                cap_txt = f"{cap:.4f}" if cap is not None else "—"
                lines.append(
                    f"  phase {phase:<12} {got:.4f}s / band {cap_txt}s{mark}")
        elif band.get("max_s") is None \
                and band.get("max_scaling_per_doubling") is not None:
            legs = ", ".join(
                f"{leg} {r:.3f}"
                for leg, r in sorted(
                    (extra.get("scaling_per_doubling") or {}).items()))
            lines.append(
                f"ok   {metric}: scaling [{legs}] <= "
                f"{band['max_scaling_per_doubling']:.3f}/doubling, "
                f"attribution {extra.get('prof_attribution')}")
        elif band.get("max_s") is None and band.get("max_ratio") is not None:
            if extra["ratio"] > band["max_ratio"]:
                lines.append(
                    f"ok   {metric}: ratio {extra['ratio']:.3f} > "
                    f"{band['max_ratio']:.3f} but delta under the "
                    f"{band.get('min_delta_s', 0.0):.2f}s noise floor")
            else:
                lines.append(
                    f"ok   {metric}: ratio {extra['ratio']:.3f} <= "
                    f"{band['max_ratio']:.3f}")
        else:
            lines.append(
                f"ok   {metric}: {p['value']:.4f}s <= "
                f"{band.get('max_s', float('inf')):.4f}s "
                f"(band from r{band.get('source_round', '?')})")
    if not bands:
        ok = False
        lines.append("FAIL: no bands resolved (no same-device trajectory "
                     "history and no --bands file)")
    return ok, lines


def _build_small_e2e_store(n_nodes=50, n_jobs=40, tasks_per_job=5):
    """Scaled-down cfg5-shaped cluster for the perf-gate smoke."""
    from volcano_tpu.api import POD_GROUP_KEY, Resource
    from volcano_tpu.api.objects import Metadata, Node, Pod, PodGroup, PodSpec, Queue
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.store import Store

    store = Store()
    store.create("Queue", Queue(meta=Metadata(name="q0", namespace=""),
                                weight=1))
    store.create("Queue", Queue(meta=Metadata(name="default", namespace=""),
                                weight=1))
    for i in range(n_nodes):
        store.create("Node", Node(
            meta=Metadata(name=f"n{i:03d}", namespace=""),
            allocatable=Resource(8000.0, 16.0 * (1 << 30), max_task_num=110)))
    for j in range(n_jobs):
        pg = PodGroup(meta=Metadata(name=f"pg{j:03d}", namespace="default"),
                      min_member=tasks_per_job, queue="q0")
        pg.status.phase = PodGroupPhase.PENDING
        store.create("PodGroup", pg)
        for t in range(tasks_per_job):
            store.create("Pod", Pod(
                meta=Metadata(name=f"p{j:03d}-{t}", namespace="default",
                              annotations={POD_GROUP_KEY: f"pg{j:03d}"}),
                spec=PodSpec(image="bench",
                             resources=Resource(250.0, 256 * (1 << 20)))))
    return store


def config_smoke():
    """Perf-gate smoke capture: the cfg5 pipeline at toy scale (one run,
    full 5-action conf) — proves the capture→bands→verdict machinery end
    to end without the 100k×10k cost.  Gated by generous absolute bands
    (SMOKE_BANDS), not the trajectory."""
    from volcano_tpu.scheduler.conf import full_conf

    conf = full_conf("tpu")
    conf.apply_mode = "async"
    run = _e2e_run(_build_small_e2e_store(), conf)
    import jax

    _print_json({
        "metric": "perfgate_smoke_small_cycle",
        "value": round(run["publish"], 4),
        "unit": "s",
        "vs_baseline": None,
        "extra": {
            "pods_bound": run["bound"],
            "phases_s": run["phases"],
            "steady_cycle_s": round(run["steady"], 4),
            "path": "fastpath" if run["fastpath"] else "object",
            "device": str(jax.devices()[0]),
        },
    })


#: absolute smoke bands: the toy cycle finishing at all inside these is
#: the machinery proof; a doctored band file is the failure proof
SMOKE_BANDS = {
    "perfgate_smoke_small_cycle": {"max_s": 60.0},
}


#: which headline metric each gated config captures
CONFIG_METRIC = {
    5: "e2e_schedule_cycle_100k_tasks_10k_nodes",
    7: "e2e_http_schedule_cycle_100k_tasks_10k_nodes",
    8: "cfg8_open_loop_first_seen_to_bind",
    10: "cfg8_open_loop_first_seen_to_bind",
    11: "cfg9_mesh_sharded_1m_x_100k",
    12: "cfg10_delta_steady_state_micro_cycle",
    13: "cfg11_repl_fanout_watch_reads",
    14: "cfg9c_procmesh_drain",
    15: "cfg9d_fleet_armed_vs_disarmed_drain",
    16: "cfg9e_multihost_solve",
    17: "cfg9f_stretch_10m_x_1m",
}


def cmd_check(configs=(5,), bands_path=None, smoke=False, directory="."):
    """The continuous perf-regression gate: fresh capture vs bands;
    returns the process exit code (nonzero on breach)."""
    import jax

    device = str(jax.devices()[0])
    if bands_path:
        with open(bands_path, encoding="utf-8") as f:
            bands = json.load(f)
    elif smoke:
        bands = dict(SMOKE_BANDS)
    else:
        traj_path = os.path.join(directory, TRAJECTORY_FILE)
        if os.path.exists(traj_path):
            with open(traj_path, encoding="utf-8") as f:
                traj = json.load(f)
        else:
            traj = build_trajectory(load_bench_rounds(directory))
        bands = derive_bands(traj, device)
    if not smoke:
        # gate only what this invocation captures — a cfg7 band (derived
        # OR from a --bands file) must not fail a cfg5-only run as
        # "missing" — and don't burn a capture there is no band for
        # (e.g. cfg5 on the CPU container: the only cfg5 trajectory
        # readings are v5e)
        # cfg9d's band is absolute, not trajectory-derived — a ratio is
        # device-invariant, so the fleet-overhead gate works on any
        # machine with no history (set BEFORE the wanted filter: the
        # ratio IS this config's headline metric)
        if 15 in configs:
            bands.setdefault("cfg9d_fleet_armed_vs_disarmed_drain",
                             {"max_ratio": 1.05, "min_delta_s": 0.25})
        # cfg9e/cfg9f gate on per-doubling SCALING of the per-host
        # critical path plus the attribution floor — both ratios, both
        # device-invariant, so the bands are absolute like cfg9d's (set
        # BEFORE the wanted filter: they ARE these configs' headline
        # metrics).  min_base_s keeps sub-ms paths from gating on
        # scheduler jitter.
        if 16 in configs:
            bands.setdefault("cfg9e_multihost_solve",
                             {"max_scaling_per_doubling": 0.7,
                              "min_coverage": 0.95, "min_base_s": 0.002})
        if 17 in configs:
            bands.setdefault("cfg9f_stretch_10m_x_1m",
                             {"max_scaling_per_doubling": 0.9,
                              "min_coverage": 0.95, "min_base_s": 0.002})
        # cfg9c captures the publish-attribution line alongside its
        # drain headline — keep its trajectory band through the
        # one-metric-per-config filter below
        publish_band = bands.get("cfg9c_publish")
        wanted = {CONFIG_METRIC.get(n) for n in configs}
        bands = {m: b for m, b in bands.items() if m in wanted}
        if 14 in configs and publish_band is not None:
            bands["cfg9c_publish"] = publish_band
        skipped = [n for n in configs if CONFIG_METRIC.get(n) not in bands]
        if skipped:
            print(f"perfgate: skipping config(s) {skipped} — no band "
                  f"for this capture (device {device})")
        configs = tuple(n for n in configs
                        if CONFIG_METRIC.get(n) in bands)
        # cfg7 captures the digest on/off drain comparison alongside its
        # headline; the absolute 1.05x band gates the auditor's overhead
        # (no trajectory needed — a ratio is device-invariant)
        if 7 in configs:
            bands["cfg7_digest_on_vs_off_drain"] = {
                "max_ratio": 1.05, "min_delta_s": 0.25}
    start = len(LAST_RESULTS)
    if smoke:
        runners = {0: config_smoke}
        configs = (0,)
    else:
        runners = {
            5: lambda: config5(reps=1),
            7: config7,
            8: lambda: config8_open_loop(duration_s=5.0, max_doublings=1),
            10: lambda: config8_open_loop(duration_s=5.0, max_doublings=1),
            11: config9_shard,
            12: lambda: config10_delta(trickle_cycles=60, duration_s=2.0,
                                       max_doublings=1),
            # full shape, not a shrink: the headline (s / 10k events at
            # the top follower count) scales with the reader fleet and
            # the window amortizes per-pass overhead — a cut-down run
            # would breach a band captured from the real configuration
            13: config11_repl,
            14: config9_procs,
            15: config9_fleet,
            16: config9_multihost,
            17: config9_stretch,
        }
    for n in configs:
        fn = runners.get(n)
        if fn is None:
            print(json.dumps({"metric": f"config{n}",
                              "error": "not a gated config (5/7/8)"}))
            continue
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — a crash is a gate failure
            # record the crash under the GATED metric name so the
            # verdict line carries the actual exception
            _print_json({"metric": CONFIG_METRIC.get(n, f"config{n}"),
                         "value": None, "unit": "s", "error": repr(e)})
    ok, lines = check_results(LAST_RESULTS[start:], bands)
    print(f"perfgate: device={device} bands="
          + (bands_path or ("smoke" if smoke else "trajectory")))
    for line in lines:
        print(line)
    print("perfgate: PASS" if ok else "perfgate: FAIL")
    return 0 if ok else 1


def default_suite():
    """The four headline lines in one invocation (cfg5, cfg5d, cfg6,
    cfg7), time-boxed variants: cfg5 best-of-2 (vs best-of-3 under
    --config 5), cfg5d/cfg6/cfg7 one run each, no cfg6b.  A failing
    config emits an error line and the suite continues — the driver's
    capture must always get all four metrics it can."""
    suite = (
        ("e2e_schedule_cycle_100k_tasks_10k_nodes",
         lambda: config5(reps=2)),
        ("cfg5d_e2e_cycle_10pct_dynamic_predicates",
         lambda: config5_dynamic(reps=1)),
        ("cfg5v_e2e_cycle_volume_constrained",
         config5_volumes),
        ("cfg6_contended_preempt_storm_100k_x_10k",
         lambda: config6(include_best_effort=False)),
        ("e2e_http_schedule_cycle_100k_tasks_10k_nodes",
         config7),
        ("cfg8_open_loop_first_seen_to_bind",
         lambda: config8_open_loop(duration_s=5.0, max_doublings=2)),
    )
    for metric, fn in suite:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — per-config isolation
            _print_json(({"metric": metric, "value": None,
                              "unit": "s", "error": repr(e)}))


def main():
    ap = argparse.ArgumentParser()
    group = ap.add_mutually_exclusive_group()
    group.add_argument("--config", type=int, choices=sorted(CONFIGS))
    group.add_argument("--all", action="store_true")
    group.add_argument("--e2e", action="store_true",
                       help="alias for --config 5 (the cfg5 headline alone, "
                            "best-of-3)")
    group.add_argument("--kernel", action="store_true",
                       help="kernel-only solve cycle over sim arrays")
    group.add_argument("--open-loop", action="store_true",
                       help="cfg8: sustained open-loop QPS with "
                            "p50/p99/p999 first-seen->bind latency + "
                            "saturation search (volcano_tpu/loadgen)")
    group.add_argument("--check", action="store_true",
                       help="continuous perf-regression gate: fresh "
                            "capture of the gated configs vs the "
                            "trajectory/--bands bands; exits nonzero "
                            "with a per-config per-phase diff on breach "
                            "(make perfgate)")
    group.add_argument("--history", action="store_true",
                       help="collate BENCH_r0*.json into "
                            "BENCH_TRAJECTORY.json + the BASELINE.md "
                            "trajectory table")
    ap.add_argument("--configs", default="5,7,8",
                    help="--check: comma-separated gated configs "
                         "(5,7,8,11; default 5,7,8 — configs without a "
                         "same-device band are skipped; 11 = cfg9 "
                         "mesh+partitioned-store, scaled by "
                         "VOLCANO_TPU_CFG9_SCALE; 15 = cfg9d vtfleet "
                         "armed-vs-disarmed drain overhead, absolute "
                         "1.05x ratio band; 16 = cfg9e multi-controller "
                         "mesh solve, absolute 0.7x-per-host-doubling + "
                         "0.95 attribution band; 17 = cfg9f 10Mx1M "
                         "stretch shape, VOLCANO_TPU_CFG9F_SCALE)")
    ap.add_argument("--bands", default="",
                    help="--check: explicit band JSON file instead of "
                         "the trajectory-derived defaults")
    ap.add_argument("--smoke", action="store_true",
                    help="--check: toy-scale capture against absolute "
                         "bands (machinery proof, not a perf claim)")
    ns = ap.parse_args()
    if ns.history:
        cmd_history(baseline_md="BASELINE.md")
        return
    # amortize XLA compiles across bench invocations
    from volcano_tpu.scheduler.scheduler import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache(
        default_dir="/tmp/volcano-tpu-xla-cache"
    )
    if ns.check:
        import sys

        configs = tuple(
            int(c) for c in str(ns.configs).split(",") if c.strip()
        )
        sys.exit(cmd_check(configs=configs, bands_path=ns.bands or None,
                           smoke=ns.smoke))
    elif ns.all:
        for n in sorted(CONFIGS):
            CONFIGS[n]()
        kernel_cycle()
    elif ns.kernel:
        kernel_cycle()
    elif ns.open_loop:
        config8_open_loop()
    elif ns.e2e or ns.config is not None:
        CONFIGS[ns.config or 5]()
    else:
        default_suite()


if __name__ == "__main__":
    main()
