"""Benchmark: schedule-cycle wall-clock @ 100k pending tasks x 10k nodes.

BASELINE.md config 5: the reference's Go scheduler takes >60 s for one
allocate cycle at this scale on CPU (16-goroutine task x node loops); the
north-star target is <1 s on a single TPU chip. This bench builds the
simulated tensor snapshot (BASELINE "10k-node / 100k-task simulated
snapshot"), runs proportion water-filling + the batched allocate solve on
device, and reports the steady-state cycle wall-clock (post-compile; XLA
caches the compilation across cycles of the same bucketed shape).

Prints ONE JSON line:
  {"metric": ..., "value": cycle_seconds, "unit": "s", "vs_baseline": speedup}
with vs_baseline = 60 s / cycle_seconds (the Go-path lower bound).
"""

import json
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 100_000
N_JOBS = 5_000
N_QUEUES = 2
BASELINE_SECONDS = 60.0  # reference Go CPU path at this scale (BASELINE.md)


def build_sim_snapshot(seed=0):
    from volcano_tpu.scheduler.simargs import build_sim_args

    return build_sim_args(N_NODES, N_TASKS, N_JOBS, N_QUEUES, seed=seed)


def main():
    import jax
    import jax.numpy as jnp

    from volcano_tpu.parallel.sharded import run_cycle_reference

    host_args = build_sim_snapshot()
    # device-resident once; run_cycle_reference's jnp.asarray is then a no-op
    args = {k: jnp.asarray(v) for k, v in host_args.items()}

    # warm-up / compile (twice: the second run also warms the device
    # allocator and any tunnel-side caching, which otherwise inflates the
    # first timed repetition)
    for _ in range(2):
        out = run_cycle_reference(args)
        jax.block_until_ready(out)

    # min over more reps: the remote-device tunnel adds multi-10ms jitter,
    # and the steady-state cycle cost is the quantity under test
    times = []
    for _ in range(7):
        t0 = time.perf_counter()
        out = run_cycle_reference(args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)

    cycle = min(times)
    task_kind = np.asarray(out[1])
    placed = int((task_kind > 0).sum())

    print(
        json.dumps(
            {
                "metric": "schedule_cycle_100k_tasks_10k_nodes",
                "value": round(cycle, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_SECONDS / cycle, 1),
                "extra": {
                    "pods_placed": placed,
                    "pods_per_sec": int(placed / cycle),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
