"""Benchmark: schedule-cycle wall-clock @ 100k pending tasks x 10k nodes.

BASELINE.md config 5: the reference's Go scheduler takes >60 s for one
allocate cycle at this scale on CPU (16-goroutine task x node loops); the
north-star target is <1 s on a single TPU chip. This bench builds the
simulated tensor snapshot (BASELINE "10k-node / 100k-task simulated
snapshot"), runs proportion water-filling + the batched allocate solve on
device, and reports the steady-state cycle wall-clock (post-compile; XLA
caches the compilation across cycles of the same bucketed shape).

Prints ONE JSON line:
  {"metric": ..., "value": cycle_seconds, "unit": "s", "vs_baseline": speedup}
with vs_baseline = 60 s / cycle_seconds (the Go-path lower bound).
"""

import json
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 100_000
N_JOBS = 5_000
N_QUEUES = 2
BASELINE_SECONDS = 60.0  # reference Go CPU path at this scale (BASELINE.md)


def build_sim_snapshot(seed=0):
    from volcano_tpu.scheduler.snapshot import _bucket

    rng = np.random.default_rng(seed)
    R = 2
    N, T, J, Q = (_bucket(N_NODES), _bucket(N_TASKS), _bucket(N_JOBS), _bucket(N_QUEUES, 4))

    node_alloc = np.zeros((N, R), np.float32)
    node_alloc[:N_NODES, 0] = rng.choice([8000, 16000, 32000], N_NODES)
    node_alloc[:N_NODES, 1] = rng.choice([16, 32, 64], N_NODES) * (1 << 30)
    node_valid = np.zeros(N, bool)
    node_valid[:N_NODES] = True

    tasks_per_job = N_TASKS // N_JOBS
    task_req = np.zeros((T, R), np.float32)
    task_req[:N_TASKS, 0] = rng.choice([250, 500, 1000, 2000], N_TASKS)
    task_req[:N_TASKS, 1] = rng.choice([256, 512, 1024, 2048], N_TASKS) * (1 << 20)
    task_valid = np.zeros(T, bool)
    task_valid[:N_TASKS] = True
    task_job = np.zeros(T, np.int32)
    task_job[:N_TASKS] = np.repeat(np.arange(N_JOBS, dtype=np.int32), tasks_per_job)

    job_start = np.zeros(J, np.int32)
    job_ntasks = np.zeros(J, np.int32)
    job_start[:N_JOBS] = np.arange(N_JOBS, dtype=np.int32) * tasks_per_job
    job_ntasks[:N_JOBS] = tasks_per_job
    job_min = np.zeros(J, np.int32)
    job_min[:N_JOBS] = rng.integers(1, tasks_per_job + 1, N_JOBS)
    job_queue = np.full(J, -1, np.int32)
    job_queue[:N_JOBS] = rng.integers(0, N_QUEUES, N_JOBS)
    job_prio = np.zeros(J, np.int32)
    job_prio[:N_JOBS] = rng.choice([0, 0, 5, 10], N_JOBS)
    job_schedulable = np.zeros(J, bool)
    job_schedulable[:N_JOBS] = True

    queue_weight = np.zeros(Q, np.float32)
    queue_weight[:N_QUEUES] = [2.0, 1.0]
    queue_request = np.zeros((Q, R), np.float32)
    for q in range(N_QUEUES):
        mask = task_job[:N_TASKS][job_queue[task_job[:N_TASKS]] == q]
        sel = job_queue[task_job[:N_TASKS]] == q
        queue_request[q] = task_req[:N_TASKS][sel].sum(0)
    queue_participates = np.zeros(Q, bool)
    queue_participates[:N_QUEUES] = True

    eps = np.array([10.0, 10 * 1024 * 1024], np.float32)
    total = node_alloc[node_valid].sum(0)

    return dict(
        idle=node_alloc.copy(), releasing=np.zeros((N, R), np.float32),
        used=np.zeros((N, R), np.float32), node_alloc=node_alloc,
        node_max_tasks=np.full(N, 2**31 - 1, np.int32),
        task_count=np.zeros(N, np.int32), node_valid=node_valid,
        task_req=task_req, task_job=task_job,
        task_class=np.zeros(T, np.int32), task_valid=task_valid,
        job_queue=job_queue, job_min=job_min, job_prio=job_prio,
        job_ready_init=np.zeros(J, np.int32),
        job_alloc_init=np.zeros((J, R), np.float32),
        job_schedulable=job_schedulable, job_start=job_start,
        job_ntasks=job_ntasks,
        queue_alloc_init=np.zeros((Q, R), np.float32),
        class_mask=np.ones((1, N), bool),
        class_score=np.zeros((1, N), np.float32),
        total=total, eps=eps,
        queue_weight=queue_weight, queue_request=queue_request,
        queue_participates=queue_participates,
    )


def run_cycle(args, jnp, water_fill, allocate_solve_batch):
    """One full decision cycle on device: water-fill + allocate solve."""
    deserved = water_fill(
        args["queue_weight"], args["queue_request"], args["total"],
        args["eps"], args["queue_participates"],
    )
    out = allocate_solve_batch(
        args["idle"], args["releasing"], args["used"], args["node_alloc"],
        args["node_max_tasks"], args["task_count"], args["node_valid"],
        args["task_req"], args["task_job"], args["task_class"], args["task_valid"],
        args["job_queue"], args["job_min"], args["job_prio"],
        args["job_ready_init"], args["job_alloc_init"], args["job_schedulable"],
        args["job_start"], args["job_ntasks"],
        args["queue_alloc_init"], deserved,
        args["class_mask"], args["class_score"],
        args["total"], args["eps"],
        jnp.float32(1.0), jnp.float32(1.0),
    )
    return out


def main():
    import jax
    import jax.numpy as jnp

    from volcano_tpu.scheduler.kernels import allocate_solve_batch, water_fill

    host_args = build_sim_snapshot()
    args = {k: jnp.asarray(v) for k, v in host_args.items()}

    # warm-up / compile
    out = run_cycle(args, jnp, water_fill, allocate_solve_batch)
    jax.block_until_ready(out)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = run_cycle(args, jnp, water_fill, allocate_solve_batch)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)

    cycle = min(times)
    task_kind = np.asarray(out[1])
    placed = int((task_kind > 0).sum())

    print(
        json.dumps(
            {
                "metric": "schedule_cycle_100k_tasks_10k_nodes",
                "value": round(cycle, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_SECONDS / cycle, 1),
                "extra": {
                    "pods_placed": placed,
                    "pods_per_sec": int(placed / cycle),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
