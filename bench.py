"""Benchmarks for the five BASELINE.md target configs.

Default (no arguments): config 5, the headline 100k-task x 10k-node
allocate cycle — prints ONE JSON line
  {"metric": ..., "value": cycle_seconds, "unit": "s", "vs_baseline": x}
with vs_baseline = 60 s / cycle_seconds (the reference's Go CPU path takes
>60 s for one allocate cycle at this scale on 16 goroutines; BASELINE.md).

`--config N` runs one of the BASELINE configs, `--all` runs all five (one
JSON line each):
  1  gang+priority, allocate only (single queue, no fair share)
  2  drf+proportion multi-queue fair share
  3  predicates+nodeorder (per-class node masks + affinity scores)
  4  preempt/reclaim victim selection (overcommitted cluster)
  5  full pipeline at bench scale (the headline; default)

All solves are post-compile steady-state: XLA compilations are cached
across cycles of the same bucketed shape, matching the deployed scheduler
(SnapshotCache + bucketed shapes).
"""

import argparse
import json
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 100_000
N_JOBS = 5_000
N_QUEUES = 2
BASELINE_SECONDS = 60.0  # reference Go CPU path at this scale (BASELINE.md)


def build_sim_snapshot(seed=0, **kw):
    from volcano_tpu.scheduler.simargs import build_sim_args

    return build_sim_args(N_NODES, N_TASKS, N_JOBS, N_QUEUES, seed=seed, **kw)


def _time_cycle(args_host, reps=7, **cycle_kw):
    import jax
    import jax.numpy as jnp

    from volcano_tpu.parallel.sharded import run_cycle_reference

    args = {k: jnp.asarray(v) for k, v in args_host.items()}
    # warm-up / compile (twice: the second run also warms the device
    # allocator and any tunnel-side caching)
    for _ in range(2):
        out = run_cycle_reference(args, **cycle_kw)
        jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_cycle_reference(args, **cycle_kw)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times), out


def _emit(metric, cycle, placed, extra=None):
    import jax

    payload = {
        "metric": metric,
        "value": round(cycle, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / cycle, 1),
        "extra": {
            "pods_placed": placed,
            "pods_per_sec": int(placed / cycle),
            "device": str(jax.devices()[0]),
            **(extra or {}),
        },
    }
    print(json.dumps(payload))


def config1():
    """Gang+priority allocate only: one queue, no fair-share keys."""
    host = build_sim_snapshot(seed=1)
    host["queue_weight"][:] = 0
    host["queue_weight"][0] = 1
    host["job_queue"][host["job_queue"] >= 0] = 0
    cycle, out = _time_cycle(
        host, job_key_order=("priority", "gang"), use_proportion=False
    )
    _emit("cfg1_gang_priority_allocate", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def config2():
    """DRF + proportion water-filling across weighted queues."""
    host = build_sim_snapshot(seed=2)
    cycle, out = _time_cycle(
        host, job_key_order=("priority", "gang", "drf"), use_proportion=True
    )
    _emit("cfg2_drf_proportion_fair_share", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def config3():
    """Predicates + nodeorder: 32 per-class node masks, 60% fill, affinity
    scores in the weighted sum."""
    host = build_sim_snapshot(seed=3, n_classes=32, class_fill=0.6)
    cycle, out = _time_cycle(host)
    _emit("cfg3_predicates_nodeorder", cycle,
          int((np.asarray(out[1]) > 0).sum()),
          extra={"classes": 32, "class_fill": 0.6})


def config4():
    """Victim selection on an occupied cluster: one victim_step per
    preemptor over a 100k-victim pool (the per-preemptor decision the host
    path takes O(nodes x victims) Python for)."""
    import jax
    import jax.numpy as jnp

    from volcano_tpu.scheduler.simargs import build_victim_sim
    from volcano_tpu.scheduler.victim_kernels import (
        VictimConsts, VictimState, victim_step,
    )

    c_np, s_np = build_victim_sim(N_NODES, N_TASKS, N_JOBS, seed=4)
    consts = VictimConsts(**{k: jnp.asarray(v) for k, v in c_np.items()})
    state = VictimState(**{k: jnp.asarray(v) for k, v in s_np.items()})
    t_req = jnp.asarray(np.array([2000.0, 4 * (1 << 30)], np.float32))

    def solve(s, jt):
        return victim_step(consts, s, t_req, 0, jt, 0, mode="queue",
                           use_gang=True, use_drf=True)

    out = solve(state, jnp.int32(0))
    jax.block_until_ready(out)
    # 16 INDEPENDENT solves from the same snapshot (job 0 is the reserved
    # empty preemptor job — a lower-share job preempting resident ones, the
    # deployed preempt shape; states from clean=False solves are
    # contractually discarded, so chaining would time solves over invalid
    # state), each individually blocked; min-of-reps, same methodology as
    # the cycle configs.
    times = []
    assigned_n = clean_n = 0
    for _ in range(16):
        t0 = time.perf_counter()
        s2, assigned, nstar, vmask, clean = solve(state, jnp.int32(0))
        jax.block_until_ready(s2)
        times.append(time.perf_counter() - t0)
        assigned_n += int(bool(assigned))
        clean_n += int(bool(clean))
    assert assigned_n > 0, "victim solve never assigned at bench scale"
    per_preemptor = min(times)
    # own payload: this is s/preemptor, not a placement-cycle metric —
    # reusing pods_placed/pods_per_sec here would silently change those
    # fields' meaning across configs
    print(json.dumps({
        "metric": "cfg4_preempt_victim_solve",
        "value": round(per_preemptor, 5),
        "unit": "s/preemptor",
        "vs_baseline": None,
        "extra": {
            "victim_pool": N_TASKS,
            "preemptors_per_sec": int(1 / per_preemptor),
            "assigned": assigned_n,
            "clean": clean_n,
            "methodology": "min over 16 independent individually blocked solves",
            "device": str(jax.devices()[0]),
        },
    }))


def config5():
    """The headline: full pipeline at 100k x 10k (the driver's metric)."""
    host = build_sim_snapshot()
    cycle, out = _time_cycle(host)
    _emit("schedule_cycle_100k_tasks_10k_nodes", cycle,
          int((np.asarray(out[1]) > 0).sum()))


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main():
    ap = argparse.ArgumentParser()
    group = ap.add_mutually_exclusive_group()
    group.add_argument("--config", type=int, choices=sorted(CONFIGS))
    group.add_argument("--all", action="store_true")
    ns = ap.parse_args()
    if ns.all:
        for n in sorted(CONFIGS):
            CONFIGS[n]()
    else:
        CONFIGS[ns.config or 5]()


if __name__ == "__main__":
    main()
