"""Benchmarks for the five BASELINE.md target configs.

Default (no arguments): config 5, the headline 100k-task x 10k-node
allocate cycle — prints ONE JSON line
  {"metric": ..., "value": cycle_seconds, "unit": "s", "vs_baseline": x}
with vs_baseline = 60 s / cycle_seconds (the reference's Go CPU path takes
>60 s for one allocate cycle at this scale on 16 goroutines; BASELINE.md).

`--config N` runs one of the BASELINE configs, `--all` runs all five (one
JSON line each):
  1  gang+priority, allocate only (single queue, no fair share)
  2  drf+proportion multi-queue fair share
  3  predicates+nodeorder (per-class node masks + affinity scores)
  4  preempt/reclaim victim selection (overcommitted cluster)
  5  full pipeline at bench scale (the headline; default)

All solves are post-compile steady-state: XLA compilations are cached
across cycles of the same bucketed shape, matching the deployed scheduler
(SnapshotCache + bucketed shapes).
"""

import argparse
import json
import time

import numpy as np

N_NODES = 10_000
N_TASKS = 100_000
N_JOBS = 5_000
N_QUEUES = 2
BASELINE_SECONDS = 60.0  # reference Go CPU path at this scale (BASELINE.md)


def build_sim_snapshot(seed=0, **kw):
    from volcano_tpu.scheduler.simargs import build_sim_args

    return build_sim_args(N_NODES, N_TASKS, N_JOBS, N_QUEUES, seed=seed, **kw)


def _time_cycle(args_host, reps=7, **cycle_kw):
    import jax
    import jax.numpy as jnp

    from volcano_tpu.parallel.sharded import run_cycle_reference

    args = {k: jnp.asarray(v) for k, v in args_host.items()}
    # warm-up / compile (twice: the second run also warms the device
    # allocator and any tunnel-side caching)
    for _ in range(2):
        out = run_cycle_reference(args, **cycle_kw)
        jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_cycle_reference(args, **cycle_kw)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times), out


def _emit(metric, cycle, placed, extra=None):
    import jax

    payload = {
        "metric": metric,
        "value": round(cycle, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / cycle, 1),
        "extra": {
            "pods_placed": placed,
            "pods_per_sec": int(placed / cycle),
            "device": str(jax.devices()[0]),
            **(extra or {}),
        },
    }
    print(json.dumps(payload))


def config1():
    """Gang+priority allocate only: one queue, no fair-share keys."""
    host = build_sim_snapshot(seed=1)
    host["queue_weight"][:] = 0
    host["queue_weight"][0] = 1
    host["job_queue"][host["job_queue"] >= 0] = 0
    cycle, out = _time_cycle(
        host, job_key_order=("priority", "gang"), use_proportion=False
    )
    _emit("cfg1_gang_priority_allocate", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def config2():
    """DRF + proportion water-filling across weighted queues."""
    host = build_sim_snapshot(seed=2)
    cycle, out = _time_cycle(
        host, job_key_order=("priority", "gang", "drf"), use_proportion=True
    )
    _emit("cfg2_drf_proportion_fair_share", cycle,
          int((np.asarray(out[1]) > 0).sum()))


def config3():
    """Predicates + nodeorder: 32 per-class node masks, 60% fill, affinity
    scores in the weighted sum."""
    host = build_sim_snapshot(seed=3, n_classes=32, class_fill=0.6)
    cycle, out = _time_cycle(host)
    _emit("cfg3_predicates_nodeorder", cycle,
          int((np.asarray(out[1]) > 0).sum()),
          extra={"classes": 32, "class_fill": 0.6})


def config4():
    """Victim selection on an overcommitted cluster: one victim_step per
    preemptor over a 100k-victim pool (the per-preemptor decision the host
    path takes O(nodes x victims) Python for)."""
    import jax
    import jax.numpy as jnp

    from volcano_tpu.scheduler.snapshot import _bucket
    from volcano_tpu.scheduler.victim_kernels import (
        VictimConsts, VictimState, victim_step,
    )

    rng = np.random.default_rng(4)
    R = 2
    N, V, J, Q = _bucket(N_NODES), _bucket(N_TASKS), _bucket(N_JOBS), 4

    node_alloc = np.zeros((N, R), np.float32)
    node_alloc[:N_NODES, 0] = 16000
    node_alloc[:N_NODES, 1] = 32 * (1 << 30)
    run_req = np.zeros((V, R), np.float32)
    run_req[:N_TASKS, 0] = rng.choice([250, 500, 1000], N_TASKS)
    run_req[:N_TASKS, 1] = rng.choice([256, 512, 1024], N_TASKS) * (1 << 20)
    run_node = np.zeros(V, np.int32)
    run_node[:N_TASKS] = rng.integers(0, N_NODES, N_TASKS)
    run_job = np.zeros(V, np.int32)
    run_job[:N_TASKS] = rng.integers(0, N_JOBS, N_TASKS)
    job_queue = rng.integers(0, 2, J).astype(np.int32)

    used = np.zeros((N, R), np.float32)
    np.add.at(used, run_node[:N_TASKS], run_req[:N_TASKS])
    idle = np.maximum(node_alloc - used, 0.0)
    job_alloc = np.zeros((J, R), np.float32)
    np.add.at(job_alloc, run_job[:N_TASKS], run_req[:N_TASKS])
    occupied = np.zeros(J, np.int32)
    np.add.at(occupied, run_job[:N_TASKS], 1)
    task_count = np.zeros(N, np.int32)
    np.add.at(task_count, run_node[:N_TASKS], 1)
    queue_alloc = np.zeros((Q, R), np.float32)
    np.add.at(queue_alloc, job_queue[run_job[:N_TASKS]], run_req[:N_TASKS])

    eps = np.array([10.0, 10 * 1024 * 1024], np.float32)
    total = node_alloc[:N_NODES].sum(0)
    consts = VictimConsts(
        run_req=jnp.asarray(run_req),
        run_node=jnp.asarray(run_node),
        run_job=jnp.asarray(run_job),
        run_prio=jnp.asarray(rng.integers(0, 3, V).astype(np.int32)),
        run_rank=jnp.asarray(np.argsort(np.argsort(rng.random(V))).astype(np.int32)),
        run_evictable=jnp.ones(V, bool),
        job_queue=jnp.asarray(job_queue),
        job_min=jnp.ones(J, jnp.int32),
        node_alloc=jnp.asarray(node_alloc),
        node_max_tasks=jnp.full(N, 2**31 - 1, jnp.int32),
        node_valid=jnp.asarray(np.arange(N) < N_NODES),
        class_mask=jnp.ones((1, N), bool),
        class_score=jnp.zeros((1, N), jnp.float32),
        queue_deserved=jnp.asarray(np.tile(total / 2, (Q, 1)).astype(np.float32)),
        total=jnp.asarray(total.astype(np.float32)),
        eps=jnp.asarray(eps),
        w_least=jnp.float32(1.0),
        w_balanced=jnp.float32(1.0),
    )
    state = VictimState(
        run_live=jnp.asarray(np.arange(V) < N_TASKS),
        idle=jnp.asarray(idle),
        releasing=jnp.zeros((N, R), jnp.float32),
        used=jnp.asarray(used),
        task_count=jnp.asarray(task_count),
        job_alloc=jnp.asarray(job_alloc),
        job_occupied=jnp.asarray(occupied),
        queue_alloc=jnp.asarray(queue_alloc),
    )
    t_req = jnp.asarray(np.array([2000.0, 4 * (1 << 30)], np.float32))

    def solve(s, jt):
        return victim_step(consts, s, t_req, 0, jt, 0, mode="queue",
                           use_gang=True, use_drf=True)

    out = solve(state, jnp.int32(0))
    jax.block_until_ready(out)
    # per-solve blocking + min-of-reps, same methodology as the cycle
    # configs (chained async dispatch under the remote-device tunnel times
    # mostly pipelining, not the solve)
    times = []
    s = state
    for i in range(16):
        t0 = time.perf_counter()
        s, assigned, nstar, vmask, clean = solve(s, jnp.int32(i % N_JOBS))
        jax.block_until_ready(s)
        times.append(time.perf_counter() - t0)
    per_preemptor = min(times)
    # own payload: this is s/preemptor, not a placement-cycle metric —
    # reusing pods_placed/pods_per_sec here would silently change those
    # fields' meaning across configs
    print(json.dumps({
        "metric": "cfg4_preempt_victim_solve",
        "value": round(per_preemptor, 5),
        "unit": "s/preemptor",
        "vs_baseline": None,
        "extra": {
            "victim_pool": N_TASKS,
            "preemptors_per_sec": int(1 / per_preemptor),
            "methodology": "min over 16 individually blocked victim_step solves",
            "device": str(jax.devices()[0]),
        },
    }))


def config5():
    """The headline: full pipeline at 100k x 10k (the driver's metric)."""
    host = build_sim_snapshot()
    cycle, out = _time_cycle(host)
    _emit("schedule_cycle_100k_tasks_10k_nodes", cycle,
          int((np.asarray(out[1]) > 0).sum()))


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, choices=sorted(CONFIGS))
    ap.add_argument("--all", action="store_true")
    ns = ap.parse_args()
    if ns.all:
        for n in sorted(CONFIGS):
            CONFIGS[n]()
    else:
        CONFIGS[ns.config or 5]()


if __name__ == "__main__":
    main()
