"""Volume-bound gang job: node-local storage pins and colocates the gang.

Reference analogue: Job.spec.volumes -> PVC creation by the controller
(pkg/controllers/job/job_controller_actions.go:333) and scheduler-side
volume binding through the VolumeBinder seam
(KB/pkg/scheduler/cache/interface.go:83-89). Here a static `local` class
with one node-pinned PV forces the whole gang onto the volume's node,
while a second dynamic claim provisions wherever the pod lands.

Run: python examples/job_with_volumes.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from volcano_tpu.api.job import Job, JobSpec, TaskSpec, VolumeSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.sim import Cluster


def main():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(3):
        c.add_node(f"n{i}", {"cpu": "8", "memory": "16Gi", "pods": 110})

    # a static-only storage class with one 100Gi volume local to n2
    c.add_storage_class("local", provisioner="")
    c.add_pv("scratch-n2", capacity="100Gi", storage_class="local",
             node_affinity={"kubernetes.io/hostname": "n2"})

    job = Job(
        meta=Metadata(name="trainer", namespace="demo"),
        spec=JobSpec(
            min_available=2,
            tasks=[TaskSpec(
                name="worker", replicas=2,
                template=PodSpec(
                    image="busybox",
                    resources=Resource.from_resource_list(
                        {"cpu": "2", "memory": "4Gi"})),
            )],
            volumes=[
                VolumeSpec(mount_path="/scratch", size="50Gi",
                           storage_class="local"),   # pins to n2
                VolumeSpec(mount_path="/output", size="10Gi"),  # dynamic
            ],
        ),
    )
    c.submit_job(job)
    c.run_until_idle()

    print(f"job phase: {job.status.state.phase.value}")
    for pod in c.store.list("Pod"):
        print(f"  {pod.meta.key} -> {pod.node_name}")
    for pvc in c.store.list("PVC"):
        print(f"  claim {pvc.meta.name}: {pvc.phase} on {pvc.volume_name}")
    assert all(p.node_name == "n2" for p in c.store.list("Pod"))
    assert all(pvc.phase == "Bound" for pvc in c.store.list("PVC"))
    print("gang colocated on n2 with the local volume; output claim "
          "dynamically provisioned")


if __name__ == "__main__":
    main()
