"""OpenMPI-shaped job with ssh/svc/env plugins — the analogue of the
reference's example/integrations/mpi/openmpi-hello.yaml.

Run: python examples/mpi_hello.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from volcano_tpu.api.job import Job, JobSpec, LifecyclePolicy, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import JobAction, JobEvent
from volcano_tpu.sim import Cluster


def main():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(2):
        c.add_node(f"node-{i}", {"cpu": "8", "memory": "16Gi", "pods": 110})

    req = Resource.from_resource_list({"cpu": "1", "memory": "1Gi"})
    job = Job(
        meta=Metadata(name="openmpi-hello", namespace="default"),
        spec=JobSpec(
            min_available=3,
            plugins={"ssh": [], "svc": [], "env": []},
            tasks=[
                TaskSpec(
                    name="mpimaster", replicas=1,
                    template=PodSpec(image="openmpi-hello", resources=req.clone()),
                    policies=[LifecyclePolicy(action=JobAction.COMPLETE_JOB,
                                              event=JobEvent.TASK_COMPLETED)],
                ),
                TaskSpec(
                    name="mpiworker", replicas=2,
                    template=PodSpec(image="openmpi-hello", resources=req.clone()),
                ),
            ],
        ),
    )
    c.submit_job(job)
    c.run_until_idle()

    print(f"job phase: {job.status.state.phase.value}")
    hostfile = c.store.get("ConfigMap", "default/openmpi-hello-svc")
    print("hostfile (mpiworker.host):")
    for line in hostfile.data["mpiworker.host"].splitlines():
        print(f"  {line}")
    ssh = c.store.get("ConfigMap", "default/openmpi-hello-ssh")
    print(f"ssh keypair keys: {sorted(ssh.data)}")

    # master finishes -> TaskCompleted policy completes the job
    c.complete_pod("default/openmpi-hello-mpimaster-0")
    c.run_until_idle()
    print(f"after master completion: {job.status.state.phase.value}")


if __name__ == "__main__":
    main()
