"""Three-task gang job — the analogue of the reference's example/job.yaml.

Run: python examples/job_gang.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from volcano_tpu.api.job import Job, JobSpec, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.sim import Cluster


def main():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(2):
        c.add_node(f"node-{i}", {"cpu": "8", "memory": "16Gi", "pods": 110})

    job = Job(
        meta=Metadata(name="test-job", namespace="default"),
        spec=JobSpec(
            min_available=3,
            tasks=[
                TaskSpec(
                    name="nginx",
                    replicas=3,
                    template=PodSpec(
                        image="nginx",
                        resources=Resource.from_resource_list(
                            {"cpu": "1", "memory": "2Gi"}
                        ),
                    ),
                )
            ],
        ),
    )
    c.submit_job(job)
    steps = c.run_until_idle()

    print(f"quiesced in {steps} steps; job phase: {job.status.state.phase.value}")
    for pod in sorted(c.store.list("Pod"), key=lambda p: p.meta.name):
        print(f"  {pod.meta.name:20s} -> {pod.node_name:10s} [{pod.phase.value}]")


if __name__ == "__main__":
    main()
