"""PS/worker TensorFlow-benchmark-shaped job — the analogue of the
reference's example/tensorflow-benchmark.yaml (2 ps + 3 workers, env+svc
plugins for TF_CONFIG-style discovery).

Run: python examples/tensorflow_benchmark.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from volcano_tpu.api.job import Job, JobSpec, TaskSpec
from volcano_tpu.api.objects import Metadata, PodSpec
from volcano_tpu.api.resource import Resource
from volcano_tpu.sim import Cluster


def main():
    c = Cluster()
    c.add_queue("default", weight=1)
    for i in range(3):
        c.add_node(
            f"node-{i}",
            {"cpu": "16", "memory": "32Gi", "pods": 110, "accelerator": 4},
        )

    job = Job(
        meta=Metadata(name="tf-benchmark", namespace="default"),
        spec=JobSpec(
            min_available=5,
            plugins={"env": [], "svc": []},
            tasks=[
                TaskSpec(
                    name="ps", replicas=2,
                    template=PodSpec(
                        image="tf-benchmarks",
                        resources=Resource.from_resource_list(
                            {"cpu": "2", "memory": "4Gi"}),
                    ),
                ),
                TaskSpec(
                    name="worker", replicas=3,
                    template=PodSpec(
                        image="tf-benchmarks",
                        resources=Resource.from_resource_list(
                            {"cpu": "4", "memory": "8Gi", "accelerator": 1}),
                    ),
                ),
            ],
        ),
    )
    c.submit_job(job)
    c.run_until_idle()

    print(f"job phase: {job.status.state.phase.value}")
    for pod in sorted(c.store.list("Pod"), key=lambda p: p.meta.name):
        print(
            f"  {pod.meta.name:26s} -> {pod.node_name:10s}"
            f" VT_TASK_INDEX={pod.env.get('VT_TASK_INDEX')}"
        )
    hostfile = c.store.get("ConfigMap", "default/tf-benchmark-svc")
    print("discovery rows:", sorted(hostfile.data))


if __name__ == "__main__":
    main()
